"""VoteVerifier: the interface between protocol logic and the verify/tally kernel.

The reference verifies one vote at a time inside ``TxVoteSet.AddVote``
(reference types/vote_set.go:117-119 -> types/tx_vote.go:110-119), serialized
through one goroutine (txflow/service.go:123-166). Here the same decision —
"is this signature valid, and does the tx now have >2/3 stake" — is computed
for a whole batch of in-flight (tx, validator) votes at once:

- ``ScalarVoteVerifier``  — the golden model: host ed25519 (audited port of
  Go crypto/ed25519 semantics) + int64 stake accumulation. Slow, correct,
  and the parity oracle for every other implementation.
- ``DeviceVoteVerifier``  — batched JAX kernel (ops.ed25519_batch +
  ops.tally), bucketed padding so in-flight count variation does not cause
  recompilation storms, optional shard_map over a device mesh with the
  stake tally psum-combined over ICI (parallel.mesh).

Both return bit-identical accept/reject masks and quorum decisions; the
engine (engine.txflow) feeds accepted votes into the authoritative host
``TxVoteSet`` so duplicate/conflict bookkeeping stays first-signature-wins
exactly like the reference.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

import numpy as np

from .analysis.lockgraph import make_lock, note_blocking
from .analysis.racegraph import shared_field
from .crypto import ed25519 as host_ed
from .ops import ed25519_batch, tally
from .types.validator import ValidatorSet
from .utils.clock import monotonic

# Batch-size buckets: in-flight vote counts vary wildly (SURVEY.md §7 hard
# part 4); padding to the next bucket keeps the number of distinct compiled
# shapes small and bounded.
DEFAULT_BUCKETS = (64, 256, 1024, 4096, 16384, 65536)


def bucket_size(n: int, buckets=DEFAULT_BUCKETS, multiple: int = 1) -> int:
    """Smallest bucket >= n after rounding buckets up to `multiple`.

    Each ladder rung is rounded up for mesh divisibility BEFORE the
    comparison, so a non-power-of-two mesh (e.g. 6 devices) still yields
    one stable shape per bucket instead of a fresh shape per batch size —
    and a drain sized exactly at a rounded rung (the coalescer's
    shard-rounded full-bucket targets) pads zero instead of spilling to
    the next rung up.
    """
    for b in buckets:
        bb = ((b + multiple - 1) // multiple) * multiple
        if bb >= n:
            return bb
    # beyond the largest bucket: round up to a multiple
    return ((n + multiple - 1) // multiple) * multiple


class VerifyCache:
    """Cross-engine signature-verification result cache.

    Verification is a pure function of (message, signature, public key):
    when several engines are co-located in one process (LocalNet; several
    validators on one host sharing one chip), full-mesh gossip hands every
    engine the same votes, and each engine re-verifying them multiplies
    the device work by the engine count for zero information (measured r4:
    the 4-node bench ran 4x the kernel work of the 1-node case). The first
    engine to see a vote pays the device verify; the rest hit this cache.

    Keys bind ALL inputs — sha256(len(msg) ‖ msg ‖ len(sig) ‖ sig ‖
    pubkey) — so a byzantine validator re-using one signature across
    different payloads can never alias a cached verdict, and (r4 advisor)
    the key survives validator-set changes: it binds the *resolved public
    key*, not the validator index, so a cache outliving an END_BLOCK
    validator update can never replay a verdict against a different key
    that now occupies the same index. Fields are length-prefixed so no
    (msg, sig) split ambiguity exists either. The reference has no
    analog: its validators are one-process-per-node, so the question
    never arises (txflow/service.go:123-166 verifies serially per node).
    """

    def __init__(self, capacity: int = 1 << 17, claim_ttl: float = 3.0):
        import threading
        from collections import OrderedDict

        self.capacity = capacity
        self.claim_ttl = claim_ttl
        self._mtx = make_lock("verifier.VerifyCache._mtx")
        # verdicts + in-flight claims: every co-located engine's verify
        # path races through these tables
        self._sh_claims = shared_field("verifier.VerifyCache.claims")  # txlint: shared(self._mtx)
        self._d: OrderedDict[bytes, bool] = OrderedDict()
        # in-flight claims: key -> monotonic claim time. Without claims,
        # co-located engines that miss on the SAME votes all ship them to
        # the device in the same beat — N redundant verifies AND (worse,
        # measured r5 on TPU: 580 votes/s vs 12k without the cache) each
        # engine pays a full padded device call for its tiny private miss
        # set. A claim hands each vote to exactly one engine; the others
        # defer the vote to their next step, by which time it is a hit.
        self._inflight: dict[bytes, float] = {}
        self.hits = 0
        self.misses = 0
        self.deferrals = 0

    @staticmethod
    def key(msg: bytes, sig: bytes, pub_key: bytes) -> bytes:
        from .crypto.hash import sha256

        return sha256(
            len(msg).to_bytes(4, "little")
            + msg
            + len(sig).to_bytes(4, "little")
            + sig
            + pub_key
        )

    def lookup_or_claim_many(
        self, keys: list[bytes | None]
    ) -> tuple[list[bool | None], np.ndarray]:
        """One lock hold: resolve hits, CLAIM unclaimed misses for this
        caller, and flag misses already in flight elsewhere.

        Returns (vals, pending): vals[i] is the cached verdict or None for
        a miss; pending[i] is True when the miss is owned by another
        caller — the caller must NOT verify it (defer/re-offer instead)
        and None-vals with pending False are claimed by THIS caller, which
        must eventually store_many or release_many them. Claims older than
        claim_ttl are treated as abandoned (owner died mid-verify) and
        handed to the next asker.
        """
        n = len(keys)
        vals: list[bool | None] = [None] * n
        pending = np.zeros(n, dtype=bool)
        now = time.monotonic()
        stale = now - self.claim_ttl
        with self._mtx:
            self._sh_claims.note_write()
            d = self._d
            infl = self._inflight
            for i, k in enumerate(keys):
                if k is None:
                    continue
                v = d.get(k)
                if v is not None:
                    d.move_to_end(k)
                    vals[i] = v
                    self.hits += 1
                    continue
                t = infl.get(k)
                if t is not None and t > stale:
                    # another caller's verify is in flight: a deferral,
                    # not a miss — misses counts actual claimed verifies
                    pending[i] = True
                    self.deferrals += 1
                else:
                    self.misses += 1
                    infl[k] = now  # claimed by this caller
        return vals, pending

    def release_many(self, keys: list[bytes]) -> None:
        """Drop claims without storing results (verify failed/raised)."""
        with self._mtx:
            self._sh_claims.note_write()
            for k in keys:
                self._inflight.pop(k, None)

    def store_many(self, pairs: list[tuple[bytes, bool]]) -> None:
        with self._mtx:
            self._sh_claims.note_write()
            d = self._d
            infl = self._inflight
            for k, v in pairs:
                d[k] = v
                d.move_to_end(k)
                infl.pop(k, None)
            while len(d) > self.capacity:
                d.popitem(last=False)

    def heartbeat_many(self, keys: list[bytes]) -> None:
        """Re-stamp still-live claims: the owner's verify call is in
        flight but slow. Claims already released/stored are left alone."""
        now = time.monotonic()
        with self._mtx:
            self._sh_claims.note_write()
            infl = self._inflight
            for k in keys:
                if k in infl:
                    infl[k] = now

    def claim_keepalive(self, keys: list[bytes]) -> "_ClaimKeepalive":
        """Context manager that heartbeats the given claims every
        claim_ttl/2 until exit. The TTL (3 s) is sized for a warm verify
        step, but the owner's device call can exceed it by orders of
        magnitude — a cold-shape compile runs minutes on TPU — and once a
        claim goes stale every other engine re-claims the same votes and
        launches its own compile of the same cold shape (N concurrent
        compiles for one shape). The heartbeat keeps ownership exactly as
        long as the owner is actually working."""
        return _ClaimKeepalive(self, keys)


class _ClaimKeepalive:
    """Background heartbeat for VerifyCache claims (claim_keepalive)."""

    def __init__(self, cache: VerifyCache, keys: list[bytes]):
        self._cache = cache
        self._keys = keys
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def __enter__(self) -> "_ClaimKeepalive":
        if self._keys:
            self._thread = threading.Thread(
                target=self._run, name="verify-claim-keepalive", daemon=True
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        interval = max(self._cache.claim_ttl / 2, 0.01)
        # first beat immediately: the claims were stamped at lookup time,
        # possibly a while before this thread got scheduled — with a short
        # TTL (tests, aggressive configs) waiting a full interval first
        # leaves a window where the claims are already re-claimable
        self._cache.heartbeat_many(self._keys)
        while not self._stop.wait(interval):
            self._cache.heartbeat_many(self._keys)

    def __exit__(self, *exc) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None


@dataclass
class TallyResult:
    """Outcome of one verify+tally step over a vote batch."""

    valid: np.ndarray  # bool[B]  per-vote signature validity (False for dropped)
    stake: np.ndarray  # int[n_slots] cumulative stake per tx slot (incl. prior)
    maj23: np.ndarray  # bool[n_slots] quorum reached (latched via prior stake)
    dropped: np.ndarray  # bool[B] in-batch (slot, validator) repeat: not processed


class VerifyTicket:
    """Handle to an in-flight verify+tally call (submit/collect split).

    ``submit(...)`` dispatches the work — for the device verifier that
    means the XLA program is launched but the ``np.asarray`` readback has
    NOT been forced, so host code (batch prep for the next drain, commit
    routing for the previous one) runs while the device computes.
    ``result()`` blocks for the readback and returns the ``TallyResult``;
    it may be called exactly once per ticket from any thread, and any
    cache claims the call took are settled (stored or released) by the
    time it returns or raises — a ticket never leaks claims.
    """

    def result(self) -> TallyResult:
        raise NotImplementedError


class ReadyTicket(VerifyTicket):
    """Already-completed ticket: eager paths (scalar verifier, fallbacks)
    present the same submit/collect surface with the work done inline."""

    __slots__ = ("_result",)

    def __init__(self, result: TallyResult):
        self._result = result

    def result(self) -> TallyResult:
        return self._result


class _RingHandle:
    """A dispatched device array whose readback rides the staging ring.

    Stands in for the raw device array inside tickets: the ring's side
    thread is (or soon will be) pulling the bytes to host, and ``get()``
    waits on that slot with overlap accounting instead of issuing the
    transfer itself."""

    __slots__ = ("_ring", "_slot")

    def __init__(self, ring, slot):
        self._ring = ring
        self._slot = slot

    def get(self) -> np.ndarray:
        return self._ring.result(self._slot)


def _force_readback(packed) -> np.ndarray:
    """The ONE blocking device->host readback, ring-aware: staged handles
    wait on their slot (transfer already in flight off-thread), raw
    device arrays take the historical synchronous np.asarray. Same device
    array, same host bytes either way — certificate parity is untouched
    by WHERE the transfer runs."""
    if isinstance(packed, _RingHandle):
        return packed.get()
    return np.asarray(packed)


class _FusedDeviceTicket(VerifyTicket):
    """Dispatched fused kernel (no cache): readback + unpack at result()."""

    __slots__ = ("_packed", "_n", "_n_slots", "_n_shards", "_b", "_b_slots",
                 "_keep", "_done")

    def __init__(self, packed, n, n_slots, n_shards, b, b_slots, keep):
        self._packed = packed  # device array, readback not yet forced
        self._n = n
        self._n_slots = n_slots
        self._n_shards = n_shards
        self._b = b
        self._b_slots = b_slots
        self._keep = keep
        self._done: TallyResult | None = None

    def result(self) -> TallyResult:
        if self._done is not None:
            return self._done
        note_blocking("verifier.device-readback")
        packed = _force_readback(self._packed)  # the ONE blocking readback
        self._packed = None
        rows = packed.reshape(self._n_shards, -1)
        bs = self._b // self._n_shards
        valid = rows[:, :bs].reshape(-1).astype(bool)
        stake = rows[0, bs : bs + self._b_slots]
        maj23 = rows[0, bs + self._b_slots :].astype(bool)
        self._done = TallyResult(
            valid[: self._n],
            stake[: self._n_slots].astype(np.int64),
            maj23[: self._n_slots],
            ~self._keep,
        )
        return self._done


class _CachedDeviceTicket(VerifyTicket):
    """Dispatched miss-set verify (cache path): the caller's claims stay
    held (keepalive running) until result() stores or releases them."""

    __slots__ = ("_cache", "_packed", "_keepalive", "_miss_idx", "_miss_keys",
                 "_keys", "_valid", "_tx_slot", "_n_slots", "_prior",
                 "_quorum", "_keep", "_pending", "_powers", "_val_idx",
                 "_n_shards", "_b", "_done")

    def __init__(self, cache, packed, keepalive, miss_idx, miss_keys, keys,
                 valid, tx_slot, n_slots, prior, quorum, keep, pending,
                 powers, val_idx, n_shards, b):
        self._cache = cache
        self._packed = packed
        self._keepalive = keepalive
        self._miss_idx = miss_idx
        self._miss_keys = miss_keys
        self._keys = keys
        self._valid = valid
        self._tx_slot = tx_slot
        self._n_slots = n_slots
        self._prior = prior
        self._quorum = quorum
        self._keep = keep
        self._pending = pending
        self._powers = powers
        self._val_idx = val_idx
        self._n_shards = n_shards
        self._b = b
        self._done: TallyResult | None = None

    def result(self) -> TallyResult:
        if self._done is not None:
            return self._done
        # re-stamp the claims from the collecting thread before blocking:
        # the keepalive thread normally covers this window, but the
        # readback can start arbitrarily long after dispatch (pipelined
        # engine) and a missed keepalive beat must not cost ownership
        self._cache.heartbeat_many(self._miss_keys)
        note_blocking("verifier.device-readback")
        try:
            packed = _force_readback(self._packed)  # blocking readback
        except BaseException:
            # claims must not outlive a failed readback (waiters would
            # stall until the TTL) — hand them to the next asker
            self._keepalive.__exit__(None, None, None)
            self._cache.release_many(self._miss_keys)
            raise
        self._packed = None
        self._keepalive.__exit__(None, None, None)
        rows = packed.reshape(self._n_shards, -1)
        bs = self._b // self._n_shards
        sub_valid = rows[:, :bs].reshape(-1).astype(bool)[: len(self._miss_idx)]
        self._cache.store_many(
            [(self._keys[i], bool(v)) for i, v in zip(self._miss_idx, sub_valid)]
        )
        valid = self._valid
        valid[self._miss_idx] = sub_valid
        # host tally (int64 — no overflow constraint on this path)
        stake = (
            np.zeros(self._n_slots, dtype=np.int64)
            if self._prior is None
            else np.asarray(self._prior, dtype=np.int64).copy()
        )
        ok = valid & (self._tx_slot >= 0) & (self._tx_slot < self._n_slots)
        np.add.at(
            stake,
            self._tx_slot[ok],
            self._powers[self._val_idx[ok]].astype(np.int64),
        )
        self._done = TallyResult(
            valid, stake, stake >= self._quorum, ~self._keep | self._pending
        )
        return self._done


def first_occurrence_mask(tx_slot, val_idx) -> np.ndarray:
    """bool[B]: True for the first occurrence of each (tx_slot, val_idx) pair.

    The reference can never count one validator's stake twice for one tx
    (first-signature-wins under a mutex, types/vote_set.go:109-131); a batch
    containing the same (tx, validator) pair twice would double-count in the
    segment-sum tally. Both verifier implementations therefore process only
    the first occurrence, in batch (arrival) order; callers re-offer dropped
    votes in a later batch if the validator still hasn't been tallied.
    """
    slot = np.asarray(tx_slot, dtype=np.int64)
    val = np.asarray(val_idx, dtype=np.int64)
    n = len(slot)
    if n == 0:
        return np.zeros(0, dtype=bool)
    # 1-D combined key: shift both axes non-negative, multiply past the
    # validator range — distinct pairs <-> distinct keys
    vmin, vmax = int(val.min()), int(val.max())
    smin = int(slot.min())
    m = vmax - vmin + 2
    combined = (slot - smin) * m + (val - vmin)
    nb = int(combined.max()) + 1
    mask = np.zeros(n, dtype=bool)
    if nb <= 4 * n + 1024:
        # dense key space (the engine's case: compact slots × small val
        # range): scatter-min of positions — ~5x faster than the sort
        # paths (r5 microbench: 38 µs vs 215 µs np.unique at B=3072)
        firstpos = np.full(nb, n, dtype=np.int64)
        np.minimum.at(firstpos, combined, np.arange(n))
        mask[firstpos[firstpos < n]] = True
    else:
        # sparse keys: stable sort + neighbor-compare (np.unique minus its
        # second key sort)
        order = np.argsort(combined, kind="stable")
        sc = combined[order]
        firsts = np.empty(n, dtype=bool)
        firsts[0] = True
        np.not_equal(sc[1:], sc[:-1], out=firsts[1:])
        mask[order[firsts]] = True
    return mask


class ScalarVoteVerifier:
    """Golden model: per-vote host verify + int64 tally (reference semantics).

    shared_cache: optional VerifyCache for co-located engines (see
    VerifyCache) — pure memoization; decisions are unchanged."""

    def __init__(self, val_set: ValidatorSet, shared_cache=None):
        self.val_set = val_set
        self._pub_keys = [v.pub_key for v in val_set]
        self._powers = val_set.powers_array()
        # one-tuple epoch stage: verify paths read it ONCE per call so a
        # concurrent restage() can never mix one epoch's keys with
        # another's powers (tuple assignment is atomic)
        self._stage = (val_set, self._pub_keys, self._powers)
        if shared_cache is True:
            shared_cache = VerifyCache()
        self.cache: VerifyCache | None = shared_cache or None

    def restage(self, new_val_set: ValidatorSet) -> bool:
        """Swap in a new validator set (epoch rotation) in place: no new
        object, no cache loss. Callers mid-``verify_and_tally`` finish
        against the stage they grabbed; the next call sees the new set."""
        pub_keys = [v.pub_key for v in new_val_set]
        powers = new_val_set.powers_array()
        self.val_set = new_val_set
        self._pub_keys = pub_keys
        self._powers = powers
        self._stage = (new_val_set, pub_keys, powers)
        return True

    def verify_and_tally(
        self,
        msgs: list[bytes],
        sigs: list[bytes],
        val_idx: np.ndarray,
        tx_slot: np.ndarray,
        n_slots: int,
        prior_stake: np.ndarray | None = None,
        quorum: int | None = None,
    ) -> TallyResult:
        n = len(msgs)
        val_set, pub_keys, powers = self._stage
        keep = first_occurrence_mask(tx_slot, val_idx)
        valid = np.zeros(n, dtype=bool)
        pending = np.zeros(n, dtype=bool)
        if self.cache is not None:
            keys = [
                VerifyCache.key(msgs[i], sigs[i], pub_keys[int(val_idx[i])])
                if keep[i] and 0 <= val_idx[i] < len(pub_keys)
                else None
                for i in range(n)
            ]
            # claim semantics (VerifyCache.lookup_or_claim_many): misses
            # another engine has in flight come back pending and are
            # DEFERRED (dropped mask), not re-verified — each unique vote
            # costs one host verify process-wide instead of one per engine
            cached, pending = self.cache.lookup_or_claim_many(keys)
            claimed = [
                keys[i]
                for i in range(n)
                if keys[i] is not None and not pending[i] and cached[i] is None
            ]
            stores = []
            try:
                # keepalive: a big miss sweep at ~50 us/verify can outlive
                # the claim TTL; stale claims would hand the same votes to
                # every other engine mid-sweep
                with self.cache.claim_keepalive(claimed):
                    for i in range(n):
                        if keys[i] is None or pending[i]:
                            continue
                        if cached[i] is not None:
                            valid[i] = cached[i]
                        else:
                            valid[i] = host_ed.verify(
                                pub_keys[int(val_idx[i])], msgs[i], sigs[i]
                            )
                            stores.append((keys[i], bool(valid[i])))
            except BaseException:
                # free every claimed-but-unverified key (waiters would
                # otherwise stall until the TTL), then surface the error
                done = {k for k, _ in stores}
                self.cache.release_many(
                    [
                        keys[i]
                        for i in range(n)
                        if keys[i] is not None
                        and not pending[i]
                        and cached[i] is None
                        and keys[i] not in done
                    ]
                )
                self.cache.store_many(stores)
                raise
            if stores:
                self.cache.store_many(stores)
        else:
            for i in range(n):
                vi = int(val_idx[i])
                if keep[i] and 0 <= vi < len(pub_keys):
                    valid[i] = host_ed.verify(pub_keys[vi], msgs[i], sigs[i])
        stake = (
            np.zeros(n_slots, dtype=np.int64)
            if prior_stake is None
            else np.asarray(prior_stake, dtype=np.int64).copy()
        )
        for i in range(n):
            s = int(tx_slot[i])
            if valid[i] and 0 <= s < n_slots:
                stake[s] += int(powers[val_idx[i]])
        q = val_set.quorum_power() if quorum is None else quorum
        return TallyResult(valid, stake, stake >= q, ~keep | pending)

    def submit(
        self,
        msgs,
        sigs,
        val_idx,
        tx_slot,
        n_slots,
        prior_stake=None,
        quorum=None,
    ) -> VerifyTicket:
        """Submit/collect surface on the eager host path: the work runs
        inline (there is no device to overlap with) and the ticket is
        already complete. Subclass overrides of verify_and_tally are
        honored — submit always routes through the instance's own
        verify_and_tally."""
        return ReadyTicket(
            self.verify_and_tally(
                msgs, sigs, val_idx, tx_slot, n_slots,
                prior_stake=prior_stake, quorum=quorum,
            )
        )


def _next_pow2(n: int) -> int:
    return 1 << max(0, n - 1).bit_length()


class _ShapeSet(set):
    """Lock-guarded ``shapes_used``: the engine thread adds shapes from
    the dispatch paths while the BackgroundWarmer thread probes
    membership, discards failed warm dispatches, and snapshots the set —
    a plain set here is a real data race (the old ``_copy_shape_set``
    RuntimeError retry loop papered over concurrent-resize crashes, and
    the race auditor flags the unlocked add/discard pair). Subclassing
    ``set`` keeps reader idiom (``set(dv.shapes_used)``, ``in``) intact;
    mutators and membership go through a leaf lock, and readers that want
    a consistent copy call ``snapshot()``."""

    def __init__(self, name: str):
        super().__init__()
        self._mtx = make_lock(name + "._mtx")
        self._sh = shared_field(name)  # txlint: shared(self._mtx)

    def add(self, shape) -> None:
        with self._mtx:
            self._sh.note_write()
            set.add(self, shape)

    def discard(self, shape) -> None:
        with self._mtx:
            self._sh.note_write()
            set.discard(self, shape)

    def __contains__(self, shape) -> bool:
        with self._mtx:
            self._sh.note_read()
            return set.__contains__(self, shape)

    def snapshot(self) -> set:
        with self._mtx:
            self._sh.note_read()
            return set(self)


class _DeviceStage:
    """One epoch's device constants, bundled so the submit paths read a
    SINGLE attribute and can never mix one epoch's pubkey tables with
    another's powers mid-rotation (``self._stage = ...`` is atomic; a
    batch in flight finishes against the stage it grabbed).

    ``pub_keys``/``val_set`` are the REAL (unpadded) set; ``powers`` /
    ``tables_dev`` / ``powers_dev`` are padded to the verifier's
    validator capacity so every epoch of a run shares the exact compiled
    shapes (restage = two device_puts, zero compiles)."""

    __slots__ = (
        "val_set", "pub_keys", "epoch", "powers", "tables_dev", "powers_dev"
    )

    def __init__(self, val_set, pub_keys, epoch, powers, tables_dev, powers_dev):
        self.val_set = val_set
        self.pub_keys = pub_keys
        self.epoch = epoch
        self.powers = powers
        self.tables_dev = tables_dev
        self.powers_dev = powers_dev


class DeviceVoteVerifier:
    """Batched device verify + tally behind the same interface.

    Per-validator-set-epoch constants (decompressed pubkey window tables,
    voting powers) live on the host as numpy and are gathered per batch;
    the curve math and the segment-sum tally run on device. With a mesh,
    the vote axis is sharded and partial stake tallies are psum-combined
    (parallel.mesh.sharded_verify_and_tally).

    Validator-set churn: the per-epoch constants are padded to
    ``capacity`` (next power of two >= the genesis set size) and bundled
    in one ``_DeviceStage``; ``restage()`` swaps the bundle in place so
    an epoch rotation costs two host->device transfers and NO recompile —
    the bucket ladder is keyed by batch size, never by set identity.
    """

    def __init__(
        self,
        val_set: ValidatorSet,
        mesh=None,
        buckets=DEFAULT_BUCKETS,
        shared_cache: "VerifyCache | bool | None" = None,
        host_prep_workers: int = 0,
        host_prep_backend: str = "thread",
        staging_ring: int = 2,
    ):
        # cross-engine verify-result sharing (VerifyCache docstring):
        # True = own cache; an instance = share with other verifiers
        if shared_cache is True:
            self.cache: VerifyCache | None = VerifyCache()
        else:
            self.cache = shared_cache or None
        self.buckets = buckets
        # the engine must not drain batches beyond the largest bucket:
        # past it, bucket_size degrades to exact-size rounding and every
        # new batch size triggers a fresh (minutes-long on TPU) compile
        self.max_batch = max(buckets)
        # cached-path miss sets get a finer ladder (claims shrink them to
        # ~1/N_engines of a drain, i.e. quarter-drains for the 4-engine
        # LocalNet; light-load steps are far smaller still — a handful of
        # misses padded to a wide program cost the full device step,
        # dominating p50 at 10% offered load, r4 verdict item). Note the
        # actual effect depends on the bucket spacing: for the bench's
        # (bucket, 4*bucket) pair this adds bucket/4 and bucket/16 (e.g.
        # 1024 and 256 at bucket 4096); for the 4x-spaced DEFAULT_BUCKETS
        # it adds nothing (quarters coincide with existing buckets). Every
        # extra shape is a one-time compile banked in the persistent
        # cache — the ladder deliberately stops at /16 rather than going
        # to the 64 floor, trading the last slice of light-load p50
        # against minutes of tunneled first-compile per extra shape.
        self.miss_buckets = tuple(
            sorted(
                {max(64, b // 16) for b in buckets}
                | {max(64, b // 4) for b in buckets}
                | set(buckets)
            )
        )
        self.mesh = mesh
        # every (kind, batch-bucket, slot-bucket) shape this verifier has
        # dispatched — the shape-warm registry (engine.shapes) snapshots it
        # after prewarm and diffs it after a run to detect in-run compiles
        self.shapes_used: set[tuple] = _ShapeSet("verifier.DeviceVoteVerifier.shapes_used")
        # kick the native prep build NOW (cc -O3, seconds when stale): the
        # first lazy build would otherwise land inside the first verify
        # step, stalling the engine right as the node comes under load
        from . import native as _native

        _native.available()

        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            from .parallel.mesh import (
                VOTE_AXIS,
                sharded_compact_step_packed_cached,
            )

            self._n_shards = mesh.size
            self._fn = sharded_compact_step_packed_cached(mesh)
            # per-batch staging shardings: padded vote-axis arrays are
            # device_put split across the mesh, the prior-stake vector
            # replicated — explicit placement so dispatch never falls
            # back to an implicit host->device-0 transfer + reshard, and
            # the compiled programs see one canonical input layout per
            # bucket (zero-recompile across epoch restages, same as the
            # single-device ladder)
            self._vote_sharding = NamedSharding(self.mesh, PartitionSpec(VOTE_AXIS))
            self._rep_sharding = NamedSharding(self.mesh, PartitionSpec())
        else:
            self._n_shards = 1
            self._fn = tally.compact_step_packed_jit()
            self._vote_sharding = None
            self._rep_sharding = None
        # sharded host-prep pool (engine.hostprep): sized by the FIRST
        # sizer — co-located engines sharing this verifier share one pool
        # (ensure_host_pool), so worker count doesn't multiply per node
        self._host_pool = None
        self.host_prep_workers = 0
        self.host_prep_backend = "thread"
        self._stats_mtx = make_lock("verifier.DeviceVoteVerifier._stats_mtx")
        # host-prep stage seconds (prep_stats()): wall time inside
        # prepare_compact on the dispatch paths, and the slice of it spent
        # waiting on pool shards this thread didn't run itself
        self._compact_s = 0.0
        self._compact_pool_wait_s = 0.0
        # double-buffered readback (parallel.staging.StagingRing): packed
        # device results enter the ring at dispatch and a side thread
        # pulls them to host eagerly, so batch N's device_put + dispatch
        # overlaps batch N-1's readback. <=1 = the historical synchronous
        # np.asarray at ticket.result(). Lazily built on first dispatch
        # so verifiers constructed for restage tests cost nothing.
        self.staging_depth = max(0, int(staging_ring))
        self._staging = None
        if host_prep_workers:
            self.ensure_host_pool(host_prep_workers, host_prep_backend)
        # validator capacity: the power-of-two sizes the existing 4/16/64
        # test and bench configs already compile for are their own pow2,
        # so padding is free there and gives odd-sized sets in-place
        # rotation headroom for joins
        self.capacity = _next_pow2(max(val_set.size(), 4))
        self._stage = self._build_stage(val_set)

    # -- per-epoch constants (read the stage ONCE per call; see
    #    _DeviceStage docstring) --

    @property
    def val_set(self) -> ValidatorSet:
        return self._stage.val_set

    @property
    def _pub_keys(self) -> list:
        return self._stage.pub_keys

    @property
    def epoch(self):
        return self._stage.epoch

    @property
    def _powers(self) -> np.ndarray:
        return self._stage.powers

    @property
    def _tables_dev(self):
        return self._stage.tables_dev

    @property
    def _powers_dev(self):
        return self._stage.powers_dev

    def ensure_host_pool(self, workers: int, backend: str = "thread"):
        """Attach (or return) the shared host-prep pool, idempotently.

        First caller with workers > 1 sizes it — backend included; later
        callers — the other engines sharing this verifier — reuse it
        regardless of the count or backend they ask for, so a 4-node
        LocalNet over one shared verifier runs ONE pool, not four.
        ``backend="process"`` degrades to threads if workers can't spawn
        (engine.hostprep.make_host_pool). Returns the pool (None when
        serial)."""
        if workers and workers > 1 and self._host_pool is None:
            with self._stats_mtx:
                if self._host_pool is None:
                    from .engine.hostprep import make_host_pool

                    pool = make_host_pool(
                        workers, backend=backend, name="hostprep-verify"
                    )
                    self.host_prep_workers = pool.workers
                    self.host_prep_backend = pool.backend
                    self._host_pool = pool
        return self._host_pool

    def _prepare(self, msgs, sigs, val_idx, epoch) -> "ed25519_batch.CompactBatch":
        """prepare_compact through the host pool (when attached), with
        stage-seconds accounting for prep_stats()."""
        t0 = monotonic()
        batch = ed25519_batch.prepare_compact(
            msgs, sigs, val_idx, epoch, pool=self._host_pool
        )
        dt = monotonic() - t0
        with self._stats_mtx:
            self._compact_s += dt
            self._compact_pool_wait_s += batch.pool_wait_s
        return batch

    def _stage_readback(self, packed):
        """Enter a just-dispatched device array into the staging ring.

        Lazily builds the ring on first dispatch (under ``_stats_mtx`` —
        one ring per verifier, shared by every engine). Returns the
        handle ``_force_readback`` understands: a ``_RingHandle`` when
        staged, the raw device array when the ring is disabled
        (``staging_ring <= 1``)."""
        if self.staging_depth < 2:
            return packed
        ring = self._staging
        if ring is None:
            with self._stats_mtx:
                ring = self._staging
                if ring is None:
                    from .parallel.staging import StagingRing

                    ring = StagingRing(self.staging_depth, name="verify-staging")
                    self._staging = ring
        return _RingHandle(ring, ring.submit(packed))

    def staging_stats(self) -> dict | None:
        """Staging-ring counters (None until the first staged dispatch)."""
        ring = self._staging
        return None if ring is None else ring.stats()

    def prep_stats(self) -> dict:
        """Host-prep stage seconds across every engine sharing this
        verifier (bench result JSON + profile_host.py host-pool lines)."""
        with self._stats_mtx:
            out = {
                "compact_s": self._compact_s,
                "compact_pool_wait_s": self._compact_pool_wait_s,
                "host_prep_workers": self.host_prep_workers,
                "host_prep_backend": self.host_prep_backend,
            }
        if self._host_pool is not None:
            out["pool"] = self._host_pool.stats()
        return out

    def _build_stage(self, val_set: ValidatorSet) -> _DeviceStage:
        # int32 device tally: with dedup, per-slot batch stake and prior
        # stake are each <= total power, so their sum stays < 2^31 only if
        # total power < 2^30. Larger sets take the scalar (int64) path.
        if val_set.total_voting_power() >= 2**30:
            raise ValueError(
                "total voting power >= 2^30: use ScalarVoteVerifier "
                "(device tally is int32)"
            )
        pub_keys = [v.pub_key for v in val_set]
        pad = self.capacity - len(pub_keys)
        if pad < 0:
            raise ValueError(
                f"validator set of {len(pub_keys)} exceeds staged "
                f"capacity {self.capacity}"
            )
        # pad table rows carry power 0 and an all-zero pubkey (no known
        # private key), and the engine's address->index map never yields a
        # pad index — a vote can neither verify against nor draw stake
        # from the pad range
        epoch = ed25519_batch.EpochTables(pub_keys + [b"\x00" * 32] * pad)
        powers = np.zeros(self.capacity, np.int32)
        powers[: len(pub_keys)] = val_set.powers_array().astype(np.int32)
        import jax

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            # pre-replicate the per-epoch device constants across the mesh
            rep = NamedSharding(self.mesh, PartitionSpec())
            tables_dev = jax.device_put(epoch.tables, rep)
            powers_dev = jax.device_put(powers, rep)
        else:
            tables_dev = epoch.device_tables()
            powers_dev = jax.numpy.asarray(powers)
        return _DeviceStage(val_set, pub_keys, epoch, powers, tables_dev, powers_dev)

    def restage(self, new_val_set: ValidatorSet) -> bool:
        """Swap the per-epoch device constants for a NEW validator set
        without recompiling: same padded shapes, same bucket ladder, same
        VerifyCache, same compiled programs. Returns False when the new
        set exceeds ``capacity`` — the caller must fall back to building
        a fresh verifier. Raises ValueError on the int32 tally cap, like
        construction would. Idempotent for an unchanged set; concurrent
        submitters finish against whichever stage they grabbed."""
        if new_val_set.size() > self.capacity:
            return False
        old = self._stage
        if new_val_set.hash() == old.val_set.hash():
            return True
        stage = self._build_stage(new_val_set)
        # the compile contract this subsystem exists to keep: shapes are
        # a function of capacity + bucket ladder, never of set identity
        assert stage.tables_dev.shape == old.tables_dev.shape, (
            "restage changed the staged table shape"
        )
        assert stage.powers_dev.shape == old.powers_dev.shape, (
            "restage changed the staged powers shape"
        )
        self._stage = stage
        return True

    def warmup(self, n: int = 1, full: bool = False) -> None:
        """Compile the kernel for the bucket shapes of an n-vote batch.

        Call ONCE before concurrent engines share this verifier: N threads
        racing to compile the same uncached shape is at best N redundant
        ~90 s compiles and at worst a remote-compile transport error
        (observed on the tunneled axon backend, r3).

        full=True additionally compiles the shapes loaded runs hit: with
        a shared cache attached, the whole _verify_only miss ladder (the
        fused shapes are unreachable while the cache is on); without one,
        the fused (batch-bucket, slot-bucket) combos — (b, b) and
        (b, smallest) for every bucket b, the combos engine drains
        produce (slots = unique txs <= votes, so slot buckets other than
        the batch's own and the floor are rare). A shape left cold here
        compiles MID-RUN on the first batch that hits it, stalling the
        pipeline for the entire compile (r5 measured: a 169 s throughput
        phase containing ~160 s of one such compile)."""
        self.verify_and_tally(
            [b""] * n, [b""] * n, np.zeros(n, np.int64), np.zeros(n, np.int64), 1
        )
        if self.cache is not None:
            # cached path: every device call is _verify_only over a miss
            # set. Default warmup(n) keeps its documented contract — every
            # shape an n-vote batch can hit must be warm, which on the
            # finer miss ladder means every miss bucket up to n's coarse
            # bucket (a smaller miss set pads to a smaller program).
            # full=True warms the whole ladder.
            limit = self.max_batch if full else bucket_size(n, self.buckets)
            for b in self.miss_buckets:
                if b > limit:
                    break
                self._verify_only(
                    [b"warm-%d" % i for i in range(b)],
                    [b"\x00" * 64] * b,
                    np.zeros(b, np.int64),
                )
            return
        if not full:
            return
        smallest = self.buckets[0]
        for b in self.buckets:
            combos = [(b, b)] if b == smallest else [(b, b), (b, smallest)]
            for nn, n_slots in combos:
                self.verify_and_tally(
                    [b""] * nn, [b""] * nn,
                    np.zeros(nn, np.int64), np.zeros(nn, np.int64),
                    n_slots,
                )

    def verify_and_tally(
        self,
        msgs: list[bytes],
        sigs: list[bytes],
        val_idx: np.ndarray,
        tx_slot: np.ndarray,
        n_slots: int,
        prior_stake: np.ndarray | None = None,
        quorum: int | None = None,
    ) -> TallyResult:
        # the blocking call IS submit + collect: one code path, so the
        # pipelined engine and the serial one take bit-identical decisions
        return self.submit(
            msgs, sigs, val_idx, tx_slot, n_slots,
            prior_stake=prior_stake, quorum=quorum,
        ).result()

    def submit(
        self,
        msgs: list[bytes],
        sigs: list[bytes],
        val_idx: np.ndarray,
        tx_slot: np.ndarray,
        n_slots: int,
        prior_stake: np.ndarray | None = None,
        quorum: int | None = None,
    ) -> VerifyTicket:
        """Dispatch the verify+tally kernel WITHOUT forcing the readback.

        JAX dispatch is async: ``self._fn(...)`` returns as soon as the
        program is enqueued, and only ``np.asarray`` blocks on the device.
        The returned ticket defers that readback to ``result()``, so the
        caller can prep the next batch (or route the previous one) while
        the device computes this one. On the cached path the caller's
        claims are held (with keepalive) by the ticket and settled at
        ``result()``; a dispatch failure here releases them before
        raising."""
        n = len(msgs)
        val_idx = np.asarray(val_idx, dtype=np.int64)
        tx_slot = np.asarray(tx_slot, dtype=np.int32)
        keep = first_occurrence_mask(tx_slot, val_idx)
        st = self._stage  # one read: epoch-consistent tables/powers/quorum
        if self.cache is not None:
            return self._submit_cached(
                msgs, sigs, val_idx, tx_slot, n_slots, prior_stake, quorum,
                keep, st,
            )
        b = bucket_size(n, self.buckets, multiple=self._n_shards)
        # n_slots is a compiled shape too (prior_stake) — bucket it as well,
        # or every step with a new in-flight tx count would recompile the
        # whole kernel; padding slots receive no votes and slice away
        b_slots = bucket_size(n_slots, self.buckets)

        batch = self._prepare(msgs, sigs, val_idx, st.epoch)
        batch.pre_ok &= keep
        # pad to bucket: pre_ok False + slot -1 => contributes nothing
        pad = b - n
        s_nib = _pad(batch.s_nibbles, pad)
        h_nib = _pad(batch.h_nibbles, pad)
        vidx = _pad(batch.val_idx, pad)
        r_y = _pad(batch.r_y, pad)
        r_sign = _pad(batch.r_sign, pad)
        pre_ok = _pad(batch.pre_ok, pad)
        slot = np.full(b, -1, np.int32)
        slot[:n] = tx_slot

        prior = np.zeros(b_slots, np.int32)
        if prior_stake is not None:
            prior[:n_slots] = np.asarray(prior_stake, dtype=np.int32)
        q = np.int32(st.val_set.quorum_power() if quorum is None else quorum)

        self.shapes_used.add(("fused", b, b_slots))
        if self.mesh is not None:
            # explicit placement: vote-axis arrays split across the mesh
            # (b is a multiple of _n_shards by construction), prior
            # replicated — the numpy buffers hand off without an extra
            # host copy and the program never implicitly reshards
            import jax

            s_nib, h_nib, vidx, r_y, r_sign, pre_ok, slot = jax.device_put(
                (s_nib, h_nib, vidx, r_y, r_sign, pre_ok, slot),
                self._vote_sharding,
            )
            prior = jax.device_put(prior, self._rep_sharding)
        packed = self._fn(
            s_nib, h_nib, vidx, r_y, r_sign, pre_ok, slot,
            st.tables_dev, st.powers_dev, prior, q,
        )
        # ONE readback — deferred to ticket.result() and (with a staging
        # ring) already in flight on the ring thread; per-shard layout
        # [valid b/n | stake S | maj S] (tally.compact_step_packed);
        # stake/maj repeat the replicated global per shard — the ticket
        # takes shard 0's copy
        return _FusedDeviceTicket(
            self._stage_readback(packed), n, n_slots, self._n_shards, b,
            b_slots, keep,
        )

    def _submit_cached(
        self, msgs, sigs, val_idx, tx_slot, n_slots, prior_stake, quorum,
        keep, st: _DeviceStage,
    ) -> VerifyTicket:
        """Cache-aware path: device-verify only the cache misses THIS
        caller claims, tally on the host. Decisions are bit-identical to
        the fused kernel — the tally is the same prior + segment-sum over
        valid first-occurrence votes, and validity per vote is a pure
        function the cache merely memoizes. Misses another engine already
        has in flight are NOT verified here: they come back dropped=True
        and the engine re-offers them next step, by which time they are
        hits (claim semantics: VerifyCache.lookup_or_claim_many). With
        co-located engines the steady state is ~1/N_engines of the device
        work each, with no duplicated in-flight verifies — without claims
        the r5 TPU bench measured 580 votes/s (each engine paying a full
        padded device call for a tiny private miss set) vs 12k uncached."""
        n = len(msgs)
        # bound on the REAL set (st.powers is padded to capacity; an index
        # in the pad range must read as unknown-validator, not as a row)
        n_vals = len(st.pub_keys)
        keys: list[bytes | None] = [
            VerifyCache.key(msgs[i], sigs[i], st.pub_keys[int(val_idx[i])])
            if keep[i] and 0 <= val_idx[i] < n_vals
            else None
            for i in range(n)
        ]
        cached, pending = self.cache.lookup_or_claim_many(keys)
        valid = np.zeros(n, dtype=bool)
        miss_idx = []
        for i in range(n):
            if keys[i] is None or pending[i]:
                continue  # unknown validator / in-batch repeat / in flight
            if cached[i] is None:
                miss_idx.append(i)
            else:
                valid[i] = cached[i]
        q = st.val_set.quorum_power() if quorum is None else quorum
        if miss_idx:
            miss_keys = [keys[i] for i in miss_idx]
            # keepalive: the device call can exceed the claim TTL by
            # orders of magnitude (cold-shape compiles run minutes on
            # TPU); without it, expired claims trigger N concurrent
            # compiles of the same shape (VerifyCache.claim_keepalive).
            # Entered HERE, exited by the ticket at result(): the claims
            # stay owned for the whole dispatch->readback window, which
            # the pipelined engine stretches across its next batch prep.
            ka = self.cache.claim_keepalive(miss_keys)
            ka.__enter__()
            try:
                packed, b = self._dispatch_verify_only(
                    [msgs[i] for i in miss_idx],
                    [sigs[i] for i in miss_idx],
                    val_idx[miss_idx],
                    claim_keys=miss_keys,
                    stage=st,
                )
            except BaseException:
                # claims must not outlive a failed dispatch (waiters
                # would stall until the TTL) — hand them to the next asker
                ka.__exit__(None, None, None)
                self.cache.release_many(miss_keys)
                raise
            # pending claims ride the dropped mask (set by the ticket):
            # the engine re-offers them next step exactly like in-batch
            # (slot, validator) repeats
            return _CachedDeviceTicket(
                self.cache, packed, ka, miss_idx, miss_keys, keys,
                valid, tx_slot, n_slots, prior_stake, q, keep, pending,
                st.powers, val_idx, self._n_shards, b,
            )
        # all hits/deferrals: nothing to dispatch — host tally, done now
        stake = (
            np.zeros(n_slots, dtype=np.int64)
            if prior_stake is None
            else np.asarray(prior_stake, dtype=np.int64).copy()
        )
        ok = valid & (tx_slot >= 0) & (tx_slot < n_slots)
        np.add.at(
            stake, tx_slot[ok], st.powers[val_idx[ok]].astype(np.int64)
        )
        return ReadyTicket(
            TallyResult(valid, stake, stake >= q, ~keep | pending)
        )

    def _verify_only(self, msgs, sigs, val_idx) -> np.ndarray:
        """Device signature verification without the tally (slots parked
        at -1, minimal slot bucket): bool[n]. Blocking (warmup uses it);
        the cached submit path dispatches via _dispatch_verify_only and
        defers this readback to the ticket."""
        packed, b = self._dispatch_verify_only(msgs, sigs, val_idx)
        rows = _force_readback(packed).reshape(self._n_shards, -1)
        bs = b // self._n_shards
        return rows[:, :bs].reshape(-1).astype(bool)[: len(msgs)]

    def predicted_shapes(self, n: int, n_slots: int = 1) -> list[tuple]:
        """Every (kind, batch-bucket, slot-bucket) shape an n-vote /
        n_slots-tx batch can dispatch through this verifier — the
        cold-shape gate's input (engine.shapes.ShapeWarmRegistry
        .is_batch_warm). Cached config: the claimed miss subset has any
        size m <= n, so the whole miss ladder up to n's rung is
        reachable. Fused config: exactly one combo."""
        shards = self._n_shards
        if self.cache is not None:
            top = bucket_size(max(n, 1), self.miss_buckets, multiple=shards)
            shapes = []
            for b in self.miss_buckets:
                bb = bucket_size(b, self.miss_buckets, multiple=shards)
                if bb > top:
                    break
                shapes.append(("verify", bb, self.buckets[0]))
            return sorted(set(shapes))
        return [(
            "fused",
            bucket_size(n, self.buckets, multiple=shards),
            bucket_size(n_slots, self.buckets),
        )]

    def _dispatch_verify_only(
        self, msgs, sigs, val_idx, claim_keys=None, stage=None
    ):
        """Enqueue the verify-only program; returns (device_array, b)
        without forcing the readback.

        claim_keys: VerifyCache claims held for this miss set. The
        ``self._fn`` call below is where a cold shape TRACES AND COMPILES
        synchronously — minutes on a tunneled TPU — so the claims are
        re-stamped from THIS thread on both sides of it, belt-and-braces
        with the caller's keepalive thread (ADVICE r5: a stale claim
        mid-compile hands the same keys to every co-located engine and
        piles N concurrent compiles onto one shape)."""
        n = len(msgs)
        st = stage if stage is not None else self._stage
        # fine-grained buckets: cached-path miss sets are far smaller than
        # engine drains (other engines own most votes via claims), and
        # padding a ~100-miss set to a 4096-wide program wastes the whole
        # device step (the r5 580-votes/s pathology's second half)
        b = bucket_size(n, self.miss_buckets, multiple=self._n_shards)
        # slot width stays on the coarse bucket ladder: the already-banked
        # compiled programs use it, and the tally half of the program is
        # insensitive to slot width next to the verify half
        b_slots = self.buckets[0]
        batch = self._prepare(msgs, sigs, val_idx, st.epoch)
        pad = b - n
        self.shapes_used.add(("verify", b, b_slots))
        if claim_keys and self.cache is not None:
            self.cache.heartbeat_many(claim_keys)
        vote_args = (
            _pad(batch.s_nibbles, pad),
            _pad(batch.h_nibbles, pad),
            _pad(batch.val_idx, pad),
            _pad(batch.r_y, pad),
            _pad(batch.r_sign, pad),
            _pad(batch.pre_ok, pad),
            np.full(b, -1, np.int32),
        )
        prior = np.zeros(b_slots, np.int32)
        if self.mesh is not None:
            import jax

            vote_args = jax.device_put(vote_args, self._vote_sharding)
            prior = jax.device_put(prior, self._rep_sharding)
        packed = self._fn(
            *vote_args,
            st.tables_dev,
            st.powers_dev,
            prior,
            np.int32(1),
        )
        if claim_keys and self.cache is not None:
            # the dispatch (and any compile inside it) is behind us: stamp
            # the claims once more so the readback window starts fresh
            self.cache.heartbeat_many(claim_keys)
        return self._stage_readback(packed), b


class ResilientVoteVerifier:
    """Graceful degradation around a device verifier.

    Policy, in order:

    1. bounded retry — a device error is retried up to ``max_attempts``
       times with exponential backoff (base*2^k, capped at backoff_max);
    2. CPU fallback — on exhaustion the verifier DEMOTES: the batch (and
       subsequent batches) are served by ``ScalarVoteVerifier``, the
       golden model, so commits keep flowing at host speed instead of the
       vote path erroring;
    3. recovery probing — while demoted, one caller per ``probe_interval``
       offers its live batch to the device again; success RE-PROMOTES,
       failure re-arms the probe timer and falls back.

    Decisions are unaffected by which path serves a batch: the scalar and
    device verifiers return bit-identical masks and quorum decisions
    (module docstring), so degradation is observable only as latency and
    in the counters here. Used as a ``VerifierMux`` inner (or directly as
    an engine verifier) this keeps a device failure from reaching
    ``_fail_queued`` — the mux's inner call succeeds on the CPU path, so
    queued requests are answered instead of errored.

    The device's shared VerifyCache (when present) is handed to the
    fallback too: verdicts cached by either path serve both, and claims
    released by a failed device call are re-claimable by the fallback.

    ``sleep``/``clock`` are injectable for deterministic tests.
    """

    def __init__(
        self,
        device,
        fallback=None,
        max_attempts: int = 3,
        backoff_base: float = 0.05,
        backoff_max: float = 2.0,
        probe_interval: float = 5.0,
        sleep=time.sleep,
        clock=time.monotonic,
    ):
        self.device = device
        self.val_set = device.val_set
        self.cache = getattr(device, "cache", None)
        if fallback is None:
            fallback = ScalarVoteVerifier(self.val_set, shared_cache=self.cache)
        self.fallback = fallback
        mb = getattr(device, "max_batch", None)
        if mb is not None:
            self.max_batch = mb
        self.max_attempts = max(1, max_attempts)
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.probe_interval = probe_interval
        self._sleep = sleep
        self._clock = clock
        self._lock = make_lock("verifier.ResilientVoteVerifier._lock")
        self._device_ok = True
        self._next_probe = 0.0
        # observability (bench/RPC surface them; tests assert transitions)
        self.device_failures = 0
        self.fallback_calls = 0
        self.demotions = 0
        self.repromotions = 0
        self.last_error: Exception | None = None
        self.on_state_change = lambda healthy: None

    @property
    def device_healthy(self) -> bool:
        return self._device_ok

    def _should_try_device(self) -> bool:
        with self._lock:
            if self._device_ok:
                return True
            now = self._clock()
            if now >= self._next_probe:
                # re-arm BEFORE probing so concurrent callers don't all
                # pay the probe latency; exactly one per interval does
                self._next_probe = now + self.probe_interval
                return True
            return False

    def _mark_device(self, healthy: bool) -> None:
        with self._lock:
            changed = self._device_ok != healthy
            self._device_ok = healthy
            if changed:
                if healthy:
                    self.repromotions += 1
                else:
                    self.demotions += 1
                    self._next_probe = self._clock() + self.probe_interval
        if changed:
            try:
                self.on_state_change(healthy)
            except Exception:
                pass

    def warmup(self, n: int = 1, full: bool = False) -> None:
        try:
            self.device.warmup(n, full=full)
        except Exception as e:
            with self._lock:
                self.device_failures += 1
                self.last_error = e
            self._mark_device(False)

    def restage(self, new_val_set) -> bool:
        """Epoch rotation passthrough: restage the device lane in place
        (keeping its compiled shapes, cache, and the degradation counters
        here) and mirror the set onto the CPU fallback so a demoted node
        rotates identically. False = device can't restage (capacity) —
        the caller rebuilds the whole resilient stack."""
        rs = getattr(self.device, "restage", None)
        if rs is None or not rs(new_val_set):
            return False
        self.val_set = new_val_set
        fb = getattr(self.fallback, "restage", None)
        if fb is not None:
            fb(new_val_set)
        return True

    def verify_and_tally(
        self,
        msgs,
        sigs,
        val_idx,
        tx_slot,
        n_slots,
        prior_stake=None,
        quorum=None,
    ) -> TallyResult:
        if self._should_try_device():
            delay = self.backoff_base
            for attempt in range(self.max_attempts):
                try:
                    result = self.device.verify_and_tally(
                        msgs, sigs, val_idx, tx_slot, n_slots,
                        prior_stake=prior_stake, quorum=quorum,
                    )
                except Exception as e:
                    with self._lock:
                        self.device_failures += 1
                        self.last_error = e
                    if attempt + 1 < self.max_attempts:
                        self._sleep(min(delay, self.backoff_max))
                        delay *= 2
                else:
                    self._mark_device(True)
                    return result
            self._mark_device(False)
        with self._lock:
            self.fallback_calls += 1
        return self.fallback.verify_and_tally(
            msgs, sigs, val_idx, tx_slot, n_slots,
            prior_stake=prior_stake, quorum=quorum,
        )

    def submit(
        self,
        msgs,
        sigs,
        val_idx,
        tx_slot,
        n_slots,
        prior_stake=None,
        quorum=None,
    ) -> VerifyTicket:
        """Async dispatch with the degradation policy at COLLECT time.

        A healthy device gets one async dispatch attempt; a dispatch
        error (or an error surfacing at the ticket's readback) is
        recorded and the batch re-runs through the full blocking
        verify_and_tally policy — bounded retry, backoff, CPU fallback,
        probe re-promotion — so a pipelined engine degrades exactly like
        a serial one, just one ticket later."""
        args = (msgs, sigs, val_idx, tx_slot, n_slots, prior_stake, quorum)
        if self._should_try_device():
            sub = getattr(self.device, "submit", None)
            if sub is not None:
                try:
                    inner = sub(
                        msgs, sigs, val_idx, tx_slot, n_slots,
                        prior_stake=prior_stake, quorum=quorum,
                    )
                except Exception as e:
                    with self._lock:
                        self.device_failures += 1
                        self.last_error = e
                    # fall through: the blocking path owns retry/fallback
                else:
                    return _ResilientTicket(self, inner, args)
        return ReadyTicket(
            self.verify_and_tally(
                msgs, sigs, val_idx, tx_slot, n_slots,
                prior_stake=prior_stake, quorum=quorum,
            )
        )


class _ResilientTicket(VerifyTicket):
    """Device ticket wrapped in the resilience policy: a readback failure
    records the device error and re-serves the batch via the outer
    verifier's blocking policy path (retry/backoff/fallback)."""

    __slots__ = ("_outer", "_inner", "_args", "_done")

    def __init__(self, outer: ResilientVoteVerifier, inner: VerifyTicket, args):
        self._outer = outer
        self._inner = inner
        self._args = args
        self._done: TallyResult | None = None

    def result(self) -> TallyResult:
        if self._done is not None:
            return self._done
        outer = self._outer
        try:
            res = self._inner.result()
        except Exception as e:
            with outer._lock:
                outer.device_failures += 1
                outer.last_error = e
            msgs, sigs, val_idx, tx_slot, n_slots, prior, quorum = self._args
            # cache claims were settled by the failed ticket (release on
            # readback error), so the policy re-run can re-claim them
            res = outer.verify_and_tally(
                msgs, sigs, val_idx, tx_slot, n_slots,
                prior_stake=prior, quorum=quorum,
            )
        else:
            outer._mark_device(True)
        self._done = res
        return res


def _pad(a: np.ndarray, pad: int) -> np.ndarray:
    if pad == 0:
        return a
    return np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])


class VerifierMux:
    """Merge concurrent engines' verify calls into one device invocation.

    N colocated validators (an in-process net, or one host running several
    nodes) each run an engine that calls ``verify_and_tally`` — serially
    that is N device round trips per wave, and the fixed per-call cost
    (dispatch + tunnel round trip + readback) dominates at small batches
    (measured: the raw kernel does 57k votes/s at B=4096 but 85k at 16384;
    end-to-end steps saw ~40 ms of fixed cost per call, r3). The mux
    presents the same blocking ``verify_and_tally`` to each engine and a
    dispatcher thread concatenates concurrent requests — votes appended,
    each request's tx slots shifted into a disjoint slot range — into ONE
    inner call, then splits the results. Decisions are bit-identical to
    separate calls: per-vote verification is independent, the slot shift
    keeps each request's tally rows private, and in-batch (slot, validator)
    dedup cannot cross requests because shifted slot ids never collide.

    Constraints: every caller must share the inner verifier's validator
    set (quorum overrides are not mergeable — reject them), and a
    validator-set rotation means callers should detach to their own
    verifier (engine.update_state does).
    """

    def __init__(
        self,
        inner,
        max_batch_per_caller: int = 4096,
        gather_wait: float = 0.01,
        pipeline_depth: int = 2,
    ):
        import queue as _q
        import threading as _t

        self.inner = inner
        self.val_set = inner.val_set
        # the engine sizes drains off this; the merged batch may hold up to
        # inner.max_batch votes across callers
        self.max_batch = max_batch_per_caller
        self.gather_wait = gather_wait
        # merged device calls kept in flight when the inner verifier has a
        # submit/collect split: the dispatcher launches batch N+1 while the
        # collector still awaits batch N's readback (in submission order).
        # <=1 degrades to the serial serve loop.
        self.pipeline_depth = max(1, pipeline_depth)
        self._q: _q.SimpleQueue = _q.SimpleQueue()
        self._running = False
        self._thread: _t.Thread | None = None
        self._collector: _t.Thread | None = None
        self._lock = make_lock("verifier.VerifierMux._lock")
        # dispatcher generation: a dispatcher that outlives its stop() (a
        # long device batch ran past the join timeout) exits on its own at
        # the next loop turn instead of racing a restarted dispatcher for
        # the queue
        self._gen = 0

    def start(self) -> None:
        import queue as _q
        import threading as _t

        with self._lock:
            if self._running:
                return
            self._running = True
            self._gen += 1
            gen = self._gen
        # a FRESH in-flight queue per generation: a retired dispatcher's
        # exit sentinel must not kill a restarted generation's collector
        pending: _q.Queue = _q.Queue(maxsize=self.pipeline_depth)
        self._collector = _t.Thread(
            target=self._collect_run, args=(pending,),
            name="verifier-mux-collect", daemon=True,
        )
        self._collector.start()
        self._thread = _t.Thread(
            target=self._run, args=(gen, pending), name="verifier-mux",
            daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        with self._lock:
            self._running = False
        self._q.put(None)
        thread = self._thread
        collector = self._collector
        self._thread = None
        self._collector = None
        if thread is not None:
            thread.join(timeout=5)
            if thread.is_alive():
                # dispatcher is mid-batch: the queue is still its to drain
                # (it fails leftovers itself on exit — see _run); draining
                # here would steal the sentinel it needs
                return
        if collector is not None:
            # the dispatcher's exit pushed the collector's sentinel; give
            # in-flight device readbacks time to drain in order
            collector.join(timeout=10)
        # requests still queued (behind the sentinel, or enqueued by a
        # caller that raced the _running check) would otherwise strand
        # their threads in done.wait() forever (r3 advisor low): fail them
        self._fail_queued(RuntimeError("VerifierMux stopped"))

    def _fail_queued(self, err: Exception) -> None:
        import queue as _q

        while True:
            try:
                req = self._q.get_nowait()
            except _q.Empty:
                return
            if req is None:
                continue
            with self._lock:
                if req.claimed:
                    continue
                req.claimed = True
            req.error = err
            req.done.set()

    def warmup(self, n: int = 1, full: bool = False) -> None:
        self.inner.warmup(n, full=full)

    def _make_req(self, msgs, sigs, val_idx, tx_slot, n_slots, prior_stake):
        import threading as _t

        return _MuxReq(
            msgs, sigs,
            np.asarray(val_idx, np.int64),
            np.asarray(tx_slot, np.int64),
            n_slots,
            None if prior_stake is None else np.asarray(prior_stake, np.int64),
            _t.Event(),
        )

    def verify_and_tally(
        self, msgs, sigs, val_idx, tx_slot, n_slots,
        prior_stake=None, quorum=None,
    ) -> TallyResult:
        if quorum is not None and quorum != self.val_set.quorum_power():
            raise ValueError("VerifierMux cannot merge per-call quorum overrides")
        if not self._running:  # not started: passthrough (tests, solo use)
            return self.inner.verify_and_tally(
                msgs, sigs, val_idx, tx_slot, n_slots, prior_stake=prior_stake
            )
        req = self._make_req(msgs, sigs, val_idx, tx_slot, n_slots, prior_stake)
        self._q.put(req)
        return self._await(req)

    def submit(
        self, msgs, sigs, val_idx, tx_slot, n_slots,
        prior_stake=None, quorum=None,
    ) -> VerifyTicket:
        """Enqueue for merging and return immediately: the caller's engine
        preps its next batch while the dispatcher gathers, merges, and
        (asynchronously) runs this one. ticket.result() == the blocking
        verify_and_tally, including the reclaim-on-stop path."""
        if quorum is not None and quorum != self.val_set.quorum_power():
            raise ValueError("VerifierMux cannot merge per-call quorum overrides")
        if not self._running:  # not started: passthrough (tests, solo use)
            sub = getattr(self.inner, "submit", None)
            if sub is not None:
                return sub(
                    msgs, sigs, val_idx, tx_slot, n_slots,
                    prior_stake=prior_stake,
                )
            return ReadyTicket(
                self.inner.verify_and_tally(
                    msgs, sigs, val_idx, tx_slot, n_slots,
                    prior_stake=prior_stake,
                )
            )
        req = self._make_req(msgs, sigs, val_idx, tx_slot, n_slots, prior_stake)
        self._q.put(req)
        return _MuxTicket(self, req)

    def _await(self, req) -> TallyResult:
        # bounded wait + liveness re-check: if the mux stopped after the
        # _running check at enqueue, the dispatcher may never see this
        # request — claim it back and serve it inline on the inner verifier
        while not req.done.wait(timeout=1.0):
            if not self._running:
                with self._lock:
                    orphaned = not req.claimed
                    if orphaned:
                        req.claimed = True
                if orphaned:
                    return self.inner.verify_and_tally(
                        req.msgs, req.sigs, req.val_idx, req.tx_slot,
                        req.n_slots, prior_stake=req.prior,
                    )
                req.done.wait()  # claimed by the dispatcher: finish soon
                break
        if req.error is not None:
            raise req.error
        return req.result

    def _run(self, gen: int, pending) -> None:
        import queue as _q
        import time as _time

        def retired() -> bool:
            # stopped, or superseded by a restart while we ran a long batch
            return not self._running or self._gen != gen

        inner_cap = getattr(self.inner, "max_batch", 1 << 30)
        try:
            while True:
                if retired():
                    # we own the queue until we exit: fail anything left so
                    # no caller strands (stop() skips its drain while we live)
                    if self._gen == gen:
                        self._fail_queued(RuntimeError("VerifierMux stopped"))
                    return
                req = self._q.get()
                if req is None:
                    if retired():
                        if self._gen == gen:
                            self._fail_queued(RuntimeError("VerifierMux stopped"))
                        return
                    continue
                batch = [req]
                total = len(req.msgs)
                deadline = _time.monotonic() + self.gather_wait
                while total < inner_cap:
                    remaining = deadline - _time.monotonic()
                    try:
                        nxt = self._q.get(timeout=max(remaining, 0)) if remaining > 0 else self._q.get_nowait()
                    except _q.Empty:
                        break
                    if nxt is None:
                        if not self._running:
                            self._serve(batch)
                            if self._gen == gen:
                                self._fail_queued(RuntimeError("VerifierMux stopped"))
                            return
                        continue
                    if total + len(nxt.msgs) > inner_cap:
                        self._q.put(nxt)  # next round (order among waiters is free)
                        break
                    batch.append(nxt)
                    total += len(nxt.msgs)
                self._dispatch(batch, pending)
        finally:
            # ALL dispatcher exits release the collector (in-flight tickets
            # drain in submission order first — Queue is FIFO)
            pending.put(None)

    def _claim(self, batch: list) -> list:
        # claim every request first: one already claimed was failed by
        # stop() or reclaimed by its caller — it is no longer ours to serve
        with self._lock:
            batch = [r for r in batch if not r.claimed]
            for r in batch:
                r.claimed = True
        return batch

    @staticmethod
    def _merge(batch: list):
        """Concatenate claimed requests into one call's arguments, each
        request's tx slots shifted into a disjoint slot range."""
        msgs, sigs, vidx, slots, priors = [], [], [], [], []
        off = 0
        for r in batch:
            msgs.extend(r.msgs)
            sigs.extend(r.sigs)
            vidx.append(r.val_idx)
            slots.append(r.tx_slot + off)
            priors.append(
                np.zeros(r.n_slots, np.int64) if r.prior is None else r.prior
            )
            off += r.n_slots
        return (
            msgs, sigs, np.concatenate(vidx), np.concatenate(slots), off,
            np.concatenate(priors),
        )

    @staticmethod
    def _split(batch: list, merged: TallyResult) -> None:
        """Hand each request its slice of the merged result."""
        if len(batch) == 1:
            batch[0].result = merged
            return
        v_off = s_off = 0
        for r in batch:
            nv, ns = len(r.msgs), r.n_slots
            r.result = TallyResult(
                merged.valid[v_off : v_off + nv],
                merged.stake[s_off : s_off + ns],
                merged.maj23[s_off : s_off + ns],
                merged.dropped[v_off : v_off + nv],
            )
            v_off += nv
            s_off += ns

    def _dispatch(self, batch: list, pending) -> None:
        """Claim + merge + async-submit one gathered batch; completion is
        the collector's job. Falls back to synchronous serving when the
        inner verifier has no submit split."""
        sub = getattr(self.inner, "submit", None)
        if sub is None or self.pipeline_depth <= 1:
            self._serve(batch)
            return
        batch = self._claim(batch)
        if not batch:
            return
        try:
            if len(batch) == 1:
                r = batch[0]
                ticket = sub(
                    r.msgs, r.sigs, r.val_idx, r.tx_slot, r.n_slots,
                    prior_stake=r.prior,
                )
            else:
                msgs, sigs, vidx, slots, off, priors = self._merge(batch)
                ticket = sub(
                    msgs, sigs, vidx, slots, off, prior_stake=priors
                )
        except Exception as e:  # dispatch failed: deliver to every waiter
            for r in batch:
                r.error = e
                r.done.set()
            return
        # blocks while pipeline_depth batches are already in flight —
        # backpressure instead of unbounded dispatch queueing
        pending.put((batch, ticket))

    def _collect_run(self, pending) -> None:
        """Resolve in-flight tickets in submission order (FIFO queue) and
        deliver each request its slice."""
        while True:
            item = pending.get()
            if item is None:
                return
            batch, ticket = item
            try:
                merged = ticket.result()
            except Exception as e:  # deliver the failure to every waiter
                for r in batch:
                    r.error = e
                    r.done.set()
                continue
            self._split(batch, merged)
            for r in batch:
                r.done.set()

    def _serve(self, batch: list) -> None:
        batch = self._claim(batch)
        if not batch:
            return
        try:
            if len(batch) == 1:
                r = batch[0]
                r.result = self.inner.verify_and_tally(
                    r.msgs, r.sigs, r.val_idx, r.tx_slot, r.n_slots,
                    prior_stake=r.prior,
                )
            else:
                msgs, sigs, vidx, slots, off, priors = self._merge(batch)
                merged = self.inner.verify_and_tally(
                    msgs, sigs, vidx, slots, off, prior_stake=priors
                )
                self._split(batch, merged)
        except Exception as e:  # deliver the failure to every waiter
            for r in batch:
                r.error = e
        finally:
            for r in batch:
                r.done.set()


class _MuxTicket(VerifyTicket):
    """Caller-side handle to an enqueued mux request. result() runs the
    same await/reclaim protocol as the blocking verify_and_tally."""

    __slots__ = ("_mux", "_req", "_done")

    def __init__(self, mux: VerifierMux, req):
        self._mux = mux
        self._req = req
        self._done: TallyResult | None = None

    def result(self) -> TallyResult:
        if self._done is None:
            self._done = self._mux._await(self._req)
        return self._done


class _MuxReq:
    __slots__ = (
        "msgs", "sigs", "val_idx", "tx_slot", "n_slots", "prior",
        "done", "result", "error", "claimed",
    )

    def __init__(self, msgs, sigs, val_idx, tx_slot, n_slots, prior, done):
        self.msgs = msgs
        self.sigs = sigs
        self.val_idx = val_idx
        self.tx_slot = tx_slot
        self.n_slots = n_slots
        self.prior = prior
        self.done = done
        self.result = None
        self.error = None
        # exactly-once service marker (set under the mux lock): the
        # dispatcher claims requests it serves; a caller that raced stop()
        # claims its own request back and serves it inline — never both
        self.claimed = False
