from .amino import (
    AminoReader,
    TYP3_8BYTE,
    TYP3_BYTELEN,
    TYP3_VARINT,
    encode_time_body,
    field_key,
    read_uvarint,
    uvarint,
    varint,
)

__all__ = [
    "AminoReader",
    "TYP3_8BYTE",
    "TYP3_BYTELEN",
    "TYP3_VARINT",
    "encode_time_body",
    "field_key",
    "read_uvarint",
    "uvarint",
    "varint",
]
