"""Minimal amino binary codec — the subset used by TxVote sign bytes and wire.

go-txflow canonicalizes votes with go-amino v0.14 ``MarshalBinaryLengthPrefixed``
(reference: types/tx_vote.go:83-89, types/codec.go:9-18). Commit decisions hinge
on bit-exact sign bytes, so this module reproduces the relevant wire rules:

- unsigned varints (LEB128);
- signed varints as two's-complement uvarint (proto3 ``int64`` style — the
  reference vectors in types/vote_test.go:62 encode the zero-time seconds
  -62135596800 as a 10-byte uvarint, proving amino does NOT zigzag here);
- field keys ``(field_number << 3) | typ3`` with typ3 Varint=0 / 8Byte=1 /
  ByteLength=2;
- ``binary:"fixed64"`` int64 as 8-byte little-endian (typ3 8Byte);
- ``time.Time`` as an embedded struct {1: seconds varint, 2: nanos varint},
  each elided when zero;
- zero-value field elision: ints == 0, empty strings/slices are skipped;
  fixed-size byte arrays are ALWAYS written (amino's isDefaultValue does not
  treat arrays as default — hence CanonicalTxVote.TxKey serializes as 32 zero
  bytes); struct fields are skipped only when their encoded body is empty
  (the vectors show an empty CanonicalBlockID elided but a zero time written).
"""

from __future__ import annotations

TYP3_VARINT = 0
TYP3_8BYTE = 1
TYP3_BYTELEN = 2

_U64_MASK = (1 << 64) - 1


def uvarint(n: int) -> bytes:
    """LEB128 unsigned varint."""
    if 0 <= n < 0x80:
        return _SMALL[n]  # the overwhelmingly common case on this wire
    if n < 0:
        raise ValueError("uvarint of negative value")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


_SMALL = [bytes((i,)) for i in range(0x80)]


def varint(n: int) -> bytes:
    """Signed varint, two's-complement-as-uint64 (proto3 int64 semantics)."""
    return uvarint(n & _U64_MASK)


def field_key(field_num: int, typ3: int) -> bytes:
    return uvarint((field_num << 3) | typ3)


def fixed64(n: int) -> bytes:
    return (n & _U64_MASK).to_bytes(8, "little")


def length_prefixed(payload: bytes) -> bytes:
    return uvarint(len(payload)) + payload


def encode_time_body(unix_ns: int) -> bytes:
    """Body of an amino-embedded time.Time given integer unix nanoseconds.

    seconds = floor(unix_ns / 1e9) (matches Go Time.Unix() for negative
    times), nanos in [0, 1e9). Each field elided when zero. Runs on the
    per-vote encode/sign-bytes paths, hence the inlined varint loops
    (field keys 0x08/0x10 = (fnum << 3) | TYP3_VARINT).
    """
    seconds, nanos = divmod(unix_ns, 1_000_000_000)
    out = bytearray()
    if seconds != 0:
        out.append(0x08)
        n = seconds & _U64_MASK
        while n > 0x7F:
            out.append((n & 0x7F) | 0x80)
            n >>= 7
        out.append(n)
    if nanos != 0:
        out.append(0x10)
        n = nanos
        while n > 0x7F:
            out.append((n & 0x7F) | 0x80)
            n >>= 7
        out.append(n)
    return bytes(out)


class AminoReader:
    """Cursor over amino binary bytes for decoding."""

    def __init__(self, data: bytes, pos: int = 0, end: int | None = None):
        self.data = data
        self.pos = pos
        self.end = len(data) if end is None else end

    def eof(self) -> bool:
        return self.pos >= self.end

    def read_uvarint(self) -> int:
        # Matches Go binary.Uvarint overflow rules: at most 10 bytes, and the
        # 10th byte may only be 0x01 (values must fit in 64 bits).
        n = 0
        shift = 0
        while True:
            if self.pos >= self.end:
                raise ValueError("truncated uvarint")
            b = self.data[self.pos]
            self.pos += 1
            if shift == 63 and b > 1:
                raise ValueError("uvarint overflows 64 bits")
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                return n
            shift += 7
            if shift > 63:
                raise ValueError("uvarint overflows 64 bits")

    def read_varint(self) -> int:
        n = self.read_uvarint() & _U64_MASK
        if n >= 1 << 63:
            n -= 1 << 64
        return n

    def read_field_key(self) -> tuple[int, int]:
        k = self.read_uvarint()
        return k >> 3, k & 0x07

    def read_fixed64(self) -> int:
        if self.pos + 8 > self.end:
            raise ValueError("truncated fixed64")
        n = int.from_bytes(self.data[self.pos : self.pos + 8], "little")
        self.pos += 8
        if n >= 1 << 63:
            n -= 1 << 64
        return n

    def read_bytes(self) -> bytes:
        ln = self.read_uvarint()
        if self.pos + ln > self.end:
            raise ValueError("truncated byte field")
        out = self.data[self.pos : self.pos + ln]
        self.pos += ln
        return out

    def sub_reader(self) -> "AminoReader":
        ln = self.read_uvarint()
        if self.pos + ln > self.end:
            raise ValueError("truncated embedded struct")
        r = AminoReader(self.data, self.pos, self.pos + ln)
        self.pos += ln
        return r

    def skip_field(self, typ3: int) -> None:
        if typ3 == TYP3_VARINT:
            self.read_uvarint()
        elif typ3 == TYP3_8BYTE:
            self.read_fixed64()
        elif typ3 == TYP3_BYTELEN:
            self.read_bytes()
        else:
            raise ValueError(f"unknown typ3 {typ3}")


def read_uvarint(data: bytes, pos: int = 0) -> tuple[int, int]:
    r = AminoReader(data, pos)
    n = r.read_uvarint()
    return n, r.pos


def decode_time_body(body: bytes) -> int:
    """Inverse of encode_time_body -> unix nanoseconds."""
    r = AminoReader(body)
    seconds = 0
    nanos = 0
    while not r.eof():
        fnum, typ3 = r.read_field_key()
        if fnum == 1 and typ3 == TYP3_VARINT:
            seconds = r.read_varint()
        elif fnum == 2 and typ3 == TYP3_VARINT:
            nanos = r.read_uvarint()
        else:
            r.skip_field(typ3)
    return seconds * 1_000_000_000 + nanos
