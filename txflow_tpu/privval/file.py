"""FilePV: file-backed validator signer with double-sign protection
(reference privval/file.go:21-102 wrapping upstream FilePV).

Two durable artifacts, like the reference:
- the KEY file (address, pubkey, seed) — written once at generation;
- the STATE file (last signed height/round/step + sign bytes + signature)
  — rewritten (atomically, fsync'd) BEFORE every new signature is
  released, so a crash between sign and use can never lead to signing a
  conflicting message for the same (height, round, step) after restart.

Fast-path TxVotes are NOT height/round/step-monotonic (one per tx, all at
the same height) and are signed without last-sign-state, exactly like the
reference's SignTxVote (privval/file.go:58-102); conflicting tx votes are
detected at the protocol layer (types/vote_set.py) instead.
"""

from __future__ import annotations

import json
import os
import tempfile

from ..crypto import ed25519
from ..crypto.hash import address_hash
from ..types.tx_vote import TxVote

# canonical sign-step numbering (upstream privval: Propose=1, Prevote=2,
# Precommit=3)
STEP_PROPOSE = 1
STEP_PREVOTE = 2
STEP_PRECOMMIT = 3

_VOTE_TYPE_TO_STEP = {1: STEP_PREVOTE, 2: STEP_PRECOMMIT}


class ErrDoubleSign(Exception):
    """Refusing to sign: conflicts with the persisted last-sign-state."""


def _atomic_write(path: str, payload: bytes) -> None:
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".pv-")
    try:
        os.write(fd, payload)
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, path)


class FilePV:
    """PrivValidator backed by key + last-sign-state files."""

    def __init__(self, key_path: str, state_path: str, seed: bytes | None = None):
        self.key_path = key_path
        self.state_path = state_path
        if os.path.exists(key_path):
            with open(key_path) as f:
                d = json.load(f)
            self._seed = bytes.fromhex(d["priv_key"])
            self._pub_key = bytes.fromhex(d["pub_key"])
        else:
            self._seed = seed if seed is not None else ed25519.generate_seed()
            self._pub_key = ed25519.public_key_from_seed(self._seed)
            _atomic_write(
                key_path,
                json.dumps(
                    {
                        "address": address_hash(self._pub_key).hex(),
                        "pub_key": self._pub_key.hex(),
                        "priv_key": self._seed.hex(),
                    },
                    indent=1,
                ).encode(),
            )
        # last sign state (height/round/step monotonicity across restarts)
        self.last_height = 0
        self.last_round = 0
        self.last_step = 0
        self.last_sign_bytes: bytes | None = None
        self.last_sign_bytes_no_ts: bytes | None = None
        self.last_timestamp_ns = 0
        self.last_signature: bytes | None = None
        if os.path.exists(state_path):
            with open(state_path) as f:
                d = json.load(f)
            self.last_height = d["height"]
            self.last_round = d["round"]
            self.last_step = d["step"]
            self.last_sign_bytes = (
                bytes.fromhex(d["sign_bytes"]) if d.get("sign_bytes") else None
            )
            self.last_sign_bytes_no_ts = (
                bytes.fromhex(d["sign_bytes_no_ts"])
                if d.get("sign_bytes_no_ts")
                else None
            )
            self.last_timestamp_ns = d.get("timestamp_ns", 0)
            self.last_signature = (
                bytes.fromhex(d["signature"]) if d.get("signature") else None
            )

    @classmethod
    def load_or_generate(cls, directory: str, name: str = "priv_validator") -> "FilePV":
        os.makedirs(directory, exist_ok=True)
        return cls(
            os.path.join(directory, f"{name}_key.json"),
            os.path.join(directory, f"{name}_state.json"),
        )

    # -- identity --

    def get_pub_key(self) -> bytes:
        return self._pub_key

    def get_address(self) -> bytes:
        return address_hash(self._pub_key)

    # -- fast path (no HRS state; see module docstring) --

    def sign_tx_vote(self, chain_id: str, vote: TxVote) -> None:
        vote.signature = ed25519.sign(self._seed, vote.sign_bytes(chain_id))

    # -- block path (HRS-protected) --

    def sign_block_vote(self, chain_id: str, vote) -> None:
        from ..types.block_vote import canonical_block_vote_bytes

        step = _VOTE_TYPE_TO_STEP.get(vote.type)
        if step is None:
            raise ValueError(f"unknown block vote type {vote.type}")
        no_ts = canonical_block_vote_bytes(
            chain_id, vote.height, vote.round, vote.type, vote.block_id, 0
        )
        sig, ts = self._sign_hrs(
            vote.height, vote.round, step, vote.sign_bytes(chain_id),
            no_ts, vote.timestamp_ns,
        )
        if ts != vote.timestamp_ns:
            vote.timestamp_ns = ts  # adopt the previously signed timestamp
        vote.signature = sig

    def sign_proposal(self, chain_id: str, proposal) -> None:
        import dataclasses

        no_ts = dataclasses.replace(proposal, timestamp_ns=0).sign_bytes(chain_id)
        sig, ts = self._sign_hrs(
            proposal.height, proposal.round, STEP_PROPOSE,
            proposal.sign_bytes(chain_id), no_ts, proposal.timestamp_ns,
        )
        if ts != proposal.timestamp_ns:
            proposal.timestamp_ns = ts
        proposal.signature = sig

    def _sign_hrs(
        self,
        height: int,
        round_: int,
        step: int,
        sign_bytes: bytes,
        sign_bytes_no_ts: bytes,
        timestamp_ns: int,
    ) -> tuple[bytes, int]:
        """Returns (signature, timestamp_to_use)."""
        hrs = (height, round_, step)
        last = (self.last_height, self.last_round, self.last_step)
        if hrs < last:
            raise ErrDoubleSign(
                f"height/round/step regression: {hrs} < signed {last}"
            )
        if hrs == last:
            if sign_bytes == self.last_sign_bytes and self.last_signature:
                return self.last_signature, timestamp_ns  # idempotent
            if (
                sign_bytes_no_ts == self.last_sign_bytes_no_ts
                and self.last_signature
            ):
                # same message, only the timestamp differs (e.g. consensus
                # rebuilt the vote after a crash): return the STORED
                # signature + timestamp instead of stalling the validator
                # (upstream checkVotesOnlyDifferByTimestamp)
                return self.last_signature, self.last_timestamp_ns
            raise ErrDoubleSign(
                f"conflicting message at height/round/step {hrs}"
            )
        sig = ed25519.sign(self._seed, sign_bytes)
        # persist BEFORE releasing the signature (crash window safety)
        self.last_height, self.last_round, self.last_step = hrs
        self.last_sign_bytes = sign_bytes
        self.last_sign_bytes_no_ts = sign_bytes_no_ts
        self.last_timestamp_ns = timestamp_ns
        self.last_signature = sig
        self._save_state()
        return sig, timestamp_ns

    def _save_state(self) -> None:
        _atomic_write(
            self.state_path,
            json.dumps(
                {
                    "height": self.last_height,
                    "round": self.last_round,
                    "step": self.last_step,
                    "sign_bytes": (self.last_sign_bytes or b"").hex(),
                    "sign_bytes_no_ts": (self.last_sign_bytes_no_ts or b"").hex(),
                    "timestamp_ns": self.last_timestamp_ns,
                    "signature": (self.last_signature or b"").hex(),
                },
                indent=1,
            ).encode(),
        )

    def __repr__(self) -> str:
        return f"FilePV{{{self.get_address().hex().upper()}}}"
