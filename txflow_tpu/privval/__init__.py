"""Key management: file signer with last-sign-state + remote signer socket
(reference privval/ package)."""

from .file import ErrDoubleSign, FilePV
from .signer import RemoteSignerError, SignerClient, SignerServer

__all__ = [
    "ErrDoubleSign",
    "FilePV",
    "RemoteSignerError",
    "SignerClient",
    "SignerServer",
]
