"""Remote signer: keep validator keys in a separate process (reference
privval/signer_validator_endpoint.go + signer_remote.go + messages.go).

- ``SignerServer`` runs NEXT TO THE KEY: it wraps a local PrivValidator
  (normally a FilePV) and serves sign requests over a socket.
- ``SignerClient`` implements the PrivValidator protocol for the NODE
  side: every sign call round-trips to the server (reference
  SignerValidatorEndpoint :92-97); the pubkey is fetched once.

Wire: length-prefixed JSON frames (u32 big-endian length). Message types
mirror the reference's amino msg set (privval/messages.go:19-26):
pubkey_request/response, sign_tx_vote_request/signed_tx_vote_response,
sign_vote_request/signed_vote_response, sign_proposal_request/
signed_proposal_response; errors travel in the response's "error" field
(e.g. a FilePV double-sign refusal crosses the wire as an error and is
re-raised client-side).
"""

from __future__ import annotations

import json
import socket
import struct
import threading

from ..analysis.lockgraph import make_lock

from ..types.tx_vote import TxVote
from .file import ErrDoubleSign

_LEN = struct.Struct("!I")


def _send_msg(sock: socket.socket, obj: dict) -> None:
    payload = json.dumps(obj).encode()
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_msg(sock: socket.socket) -> dict:
    hdr = _recv_exact(sock, _LEN.size)
    (n,) = _LEN.unpack(hdr)
    if n > 1 << 20:
        raise ValueError("oversized signer frame")
    return json.loads(_recv_exact(sock, n))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("signer connection closed")
        buf += chunk
    return buf


class RemoteSignerError(Exception):
    pass


class SignerServer:
    """Serves a local PrivValidator over TCP (one signer, many requests)."""

    def __init__(self, priv_val, host: str = "127.0.0.1", port: int = 0):
        self.priv_val = priv_val
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(8)
        self.addr = self._srv.getsockname()
        self._running = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._running.set()
        self._thread = threading.Thread(
            target=self._accept_loop, name="signer-server", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._running.clear()
        try:
            self._srv.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while self._running.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            while self._running.is_set():
                try:
                    req = _recv_msg(conn)
                except (ConnectionError, OSError, ValueError):
                    return
                try:
                    resp = self._handle(req)
                except ErrDoubleSign as e:
                    resp = {"type": req.get("type", "") + "_response", "error": f"double sign: {e}"}
                except Exception as e:  # refuse, never crash the key holder
                    resp = {"type": req.get("type", "") + "_response", "error": repr(e)}
                try:
                    _send_msg(conn, resp)
                except OSError:
                    return

    def _handle(self, req: dict) -> dict:
        kind = req.get("type")
        pv = self.priv_val
        if kind == "pubkey_request":
            return {"type": "pubkey_response", "pub_key": pv.get_pub_key().hex()}
        if kind == "sign_tx_vote_request":
            from ..types.tx_vote import decode_tx_vote, encode_tx_vote

            vote = decode_tx_vote(bytes.fromhex(req["vote"]))
            pv.sign_tx_vote(req["chain_id"], vote)
            return {
                "type": "signed_tx_vote_response",
                "vote": encode_tx_vote(vote).hex(),
            }
        if kind == "sign_vote_request":
            from ..types.block_vote import decode_block_vote, encode_block_vote

            vote = decode_block_vote(bytes.fromhex(req["vote"]))
            pv.sign_block_vote(req["chain_id"], vote)
            return {
                "type": "signed_vote_response",
                "vote": encode_block_vote(vote).hex(),
            }
        if kind == "sign_proposal_request":
            from ..consensus.types import Proposal

            d = req["proposal"]
            p = Proposal(
                height=d["height"],
                round=d["round"],
                pol_round=d["pol_round"],
                block_hash=bytes.fromhex(d["block_hash"]),
                timestamp_ns=d["ts"],
            )
            pv.sign_proposal(req["chain_id"], p)
            return {
                "type": "signed_proposal_response",
                "signature": (p.signature or b"").hex(),
            }
        raise ValueError(f"unknown signer request {kind!r}")


class SignerClient:
    """PrivValidator whose key lives behind a SignerServer socket."""

    def __init__(self, host: str, port: int, timeout: float = 5.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.settimeout(timeout)
        self._mtx = make_lock("privval.SignerClient._mtx", allow_blocking=True)
        resp = self._call({"type": "pubkey_request"})
        self._pub_key = bytes.fromhex(resp["pub_key"])

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def _call(self, req: dict) -> dict:
        with self._mtx:  # one in-flight request per connection
            _send_msg(self._sock, req)
            resp = _recv_msg(self._sock)
        if resp.get("error"):
            if resp["error"].startswith("double sign"):
                raise ErrDoubleSign(resp["error"])
            raise RemoteSignerError(resp["error"])
        return resp

    def get_pub_key(self) -> bytes:
        return self._pub_key

    def get_address(self) -> bytes:
        from ..crypto.hash import address_hash

        return address_hash(self._pub_key)

    def sign_tx_vote(self, chain_id: str, vote: TxVote) -> None:
        from ..types.tx_vote import decode_tx_vote, encode_tx_vote

        resp = self._call(
            {
                "type": "sign_tx_vote_request",
                "chain_id": chain_id,
                "vote": encode_tx_vote(vote).hex(),
            }
        )
        signed = decode_tx_vote(bytes.fromhex(resp["vote"]))
        vote.timestamp_ns = signed.timestamp_ns
        vote.signature = signed.signature

    def sign_block_vote(self, chain_id: str, vote) -> None:
        from ..types.block_vote import decode_block_vote, encode_block_vote

        resp = self._call(
            {
                "type": "sign_vote_request",
                "chain_id": chain_id,
                "vote": encode_block_vote(vote).hex(),
            }
        )
        signed = decode_block_vote(bytes.fromhex(resp["vote"]))
        vote.timestamp_ns = signed.timestamp_ns
        vote.signature = signed.signature

    def sign_proposal(self, chain_id: str, proposal) -> None:
        resp = self._call(
            {
                "type": "sign_proposal_request",
                "chain_id": chain_id,
                "proposal": {
                    "height": proposal.height,
                    "round": proposal.round,
                    "pol_round": proposal.pol_round,
                    "block_hash": proposal.block_hash.hex(),
                    "ts": proposal.timestamp_ns,
                },
            }
        )
        proposal.signature = bytes.fromhex(resp["signature"])
