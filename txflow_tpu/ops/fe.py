"""Batched GF(2^255-19) field arithmetic in radix-2^8 int32 limbs.

TPU-first bignum design (replaces nothing in the reference — go-txflow does all
ed25519 math one signature at a time on CPU via Go's crypto/ed25519,
types/tx_vote.go:110-119):

- A field element is an int32 tensor ``[..., 32]`` of little-endian radix-256
  limbs. All ops are elementwise/vectorized over the leading batch dims — no
  data-dependent control flow, so the whole verifier jits into one XLA program
  and shards over a device mesh with ``shard_map``.
- Radix 2^8 keeps every partial product <= 255*255 < 2^16 and every column sum
  of a 32x32 limb convolution <= 32*2^16 < 2^21, far inside int32 — and inside
  float32's 2^24 exact-integer window, so the inner convolution can later be
  lowered to an MXU f32 matmul or a pallas kernel without changing semantics.
- Carry propagation is a few data-parallel passes (no sequential limb scan);
  only the final canonical freeze (needed once per verify, for the
  encode(P) == R byte comparison Go does) uses an exact borrow scan.

Bounds discipline (checked by tests/test_fe.py):
- "normalized": limbs in [0, 512)   — output of fe_carry/fe_mul/fe_sub.
- fe_mul/fe_sq inputs must have limbs in [0, 1311]; sums of two normalized
  values (fe_add output, <= 1024) are therefore legal mul inputs.
- fe_sub(a, b) adds the limbwise constant 8*p before subtracting, so the
  borrow-free requirement is per-limb: b[0] <= 8*0xED = 1896,
  b[1..30] <= 8*0xFF = 2040, b[31] <= 8*0x7F = 1016. All call sites pass
  normalized-or-added values (limbs <= 1024), well inside every bound.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from . import _fe_common as _common

NLIMB = 32
RADIX = 8
MASK = (1 << RADIX) - 1

# p = 2^255 - 19, little-endian radix-256 limbs.
P_INT = 2**255 - 19
P_LIMBS = np.array([0xED] + [0xFF] * 30 + [0x7F], dtype=np.int32)
# Limbwise 8*p: a value ≡ 0 (mod p) that dominates any subtrahend within
# the per-limb bounds documented above, making limbwise subtraction
# borrow-free.
EIGHT_P_LIMBS = 8 * P_LIMBS

# Anti-diagonal gather plan for the 32x32 limb product: column k of the
# product accumulates a[i] * b[k-i]; _IDX/_VALID pre-encode the k-i map.
_K = np.arange(2 * NLIMB - 1)
_I = np.arange(NLIMB)
_IDX = np.clip(_K[None, :] - _I[:, None], 0, NLIMB - 1)  # [32, 63]
_VALID = (_K[None, :] - _I[:, None] >= 0) & (_K[None, :] - _I[:, None] < NLIMB)


def int_to_limbs(x: int) -> np.ndarray:
    """Host helper: python int -> canonical limb vector."""
    return np.array([(x >> (RADIX * i)) & MASK for i in range(NLIMB)], dtype=np.int32)


def limbs_to_int(limbs) -> int:
    """Host helper: limb vector (any bounds) -> python int."""
    out = 0
    for i, v in enumerate(np.asarray(limbs).tolist()):
        out += int(v) << (RADIX * i)
    return out


def bytes_to_limbs(b: bytes) -> np.ndarray:
    assert len(b) == 32
    return np.frombuffer(b, dtype=np.uint8).astype(np.int32)


def fe_carry(x, passes: int = 4):
    """Data-parallel carry with 2^256 ≡ 38 wraparound.

    Each pass moves carries one limb up; the carry out of limb 31 re-enters
    limb 0 scaled by 38. For inputs bounded by 2^29 (worst case out of the
    fe_mul fold) four passes bring every limb under 512.
    """
    for _ in range(passes):
        hi = x >> RADIX
        lo = x & MASK
        wrapped = jnp.concatenate([38 * hi[..., NLIMB - 1 :], hi[..., : NLIMB - 1]], axis=-1)
        x = lo + wrapped
    return x


def fe_add(a, b):
    """Limbwise add; output limbs <= 1024 when inputs are normalized."""
    return a + b


def fe_sub(a, b):
    """a - b mod p, borrow-free via the 8p offset; output normalized."""
    return fe_carry(a + jnp.asarray(EIGHT_P_LIMBS) - b, passes=2)


import os


def fe_mul(a, b):
    """Product mod 2^255-19 (normalized limbs). Inputs: limbs <= 1311.

    32x32 limb convolution (formulation per ``_conv_mode``), then the
    2^256 ≡ 38 fold of the high 31 columns, then carries.
    """
    if _common.conv_mode() == "pad":
        nd = a.ndim
        c = None
        for i in range(NLIMB):
            t = jnp.pad(
                a[..., i : i + 1] * b, [(0, 0)] * (nd - 1) + [(i, NLIMB - 1 - i)]
            )
            c = t if c is None else c + t
    else:
        bsh = jnp.where(jnp.asarray(_VALID), b[..., jnp.asarray(_IDX)], 0)
        c = jnp.einsum("...i,...ik->...k", a, bsh)  # [..., 63]
    hi = jnp.pad(c[..., NLIMB:], [(0, 0)] * (c.ndim - 1) + [(0, 1)])
    # Worst legal input (limbs 1311) folds to < 2^31; five carry passes are
    # needed for the big limb-0 carry to fully settle (it moves up one limb
    # per pass: 0 -> 1 -> 2 -> 3 -> done).
    return fe_carry(c[..., :NLIMB] + 38 * hi, passes=5)


def fe_sq(a):
    return fe_mul(a, a)


def fe_mul_small(a, c: int):
    """Multiply by a small scalar constant (c <= ~2^20); output normalized."""
    return fe_carry(a * c)


def fe_freeze(x):
    """Exact canonical reduction: limbs in [0,256) and value < p.

    Used once per verification for the byte-exact encode(P) == sig[:32]
    comparison (Go compares encodings, never decompressing R). Two borrow
    scans subtract p at most twice: after carrying, the value is < 2^256 =
    2p + 38, so two conditional subtractions always land in [0, p).
    """
    x = fe_carry(x, passes=6)  # limbs <= ~293, value < 2^256
    p = jnp.asarray(P_LIMBS)
    for _ in range(2):
        # Exact x - p with sequential borrow (31 cheap steps, once per verify).
        diff = x - p
        borrows = []
        borrow = jnp.zeros_like(x[..., 0])
        for i in range(NLIMB):
            d = diff[..., i] - borrow
            borrow = (d < 0).astype(x.dtype)
            borrows.append(d + (borrow << RADIX))
        sub = jnp.stack(borrows, axis=-1)
        x = jnp.where((borrow == 0)[..., None], sub, x)
    # Final carry normalization to strict [0, 256) limbs.
    return fe_carry(x, passes=2)


def bytes_to_limbs_device(b):
    """[..., 32] uint8 LE bytes -> [..., NLIMB] int32 limbs (jit-able).
    Radix 2^8: limbs ARE the bytes."""
    return jnp.asarray(b).astype(jnp.int32)


fe_is_equal_frozen = _common.fe_is_equal_frozen
fe_parity_frozen = _common.fe_parity_frozen
fe_inv = _common.make_inv(fe_mul)


# ---------------------------------------------------------------------------
# Radix switch: TXFLOW_FE_RADIX=13 swaps in the 20-limb radix-2^13
# implementation (fe13.py) for the whole process — curve tables, epoch
# tables, and kernels all build on these symbols at import time, so the
# choice must be made before anything imports ops.curve. Default stays
# radix-8 (the TPU-measured configuration) until a live A/B on hardware
# confirms the 20-limb kernel; bench.py exposes the knob.
if os.environ.get("TXFLOW_FE_RADIX") == "13":
    from . import fe13 as _fe13

    NLIMB = _fe13.NLIMB
    RADIX = _fe13.RADIX
    MASK = _fe13.MASK
    P_LIMBS = _fe13.P_LIMBS
    int_to_limbs = _fe13.int_to_limbs
    limbs_to_int = _fe13.limbs_to_int
    bytes_to_limbs = _fe13.bytes_to_limbs
    bytes_to_limbs_device = _fe13.bytes_to_limbs_device
    fe_carry = _fe13.fe_carry
    fe_add = _fe13.fe_add
    fe_sub = _fe13.fe_sub
    fe_mul = _fe13.fe_mul
    fe_sq = _fe13.fe_sq
    fe_mul_small = _fe13.fe_mul_small
    fe_freeze = _fe13.fe_freeze
    fe_is_equal_frozen = _fe13.fe_is_equal_frozen
    fe_parity_frozen = _fe13.fe_parity_frozen
    fe_inv = _fe13.fe_inv
