"""Batched GF(2^255-19) field arithmetic in radix-2^8 int32 limbs.

TPU-first bignum design (replaces nothing in the reference — go-txflow does all
ed25519 math one signature at a time on CPU via Go's crypto/ed25519,
types/tx_vote.go:110-119):

- A field element is an int32 tensor ``[..., 32]`` of little-endian radix-256
  limbs. All ops are elementwise/vectorized over the leading batch dims — no
  data-dependent control flow, so the whole verifier jits into one XLA program
  and shards over a device mesh with ``shard_map``.
- Radix 2^8 keeps every partial product <= 255*255 < 2^16 and every column sum
  of a 32x32 limb convolution <= 32*2^16 < 2^21, far inside int32 — and inside
  float32's 2^24 exact-integer window, so the inner convolution can later be
  lowered to an MXU f32 matmul or a pallas kernel without changing semantics.
- Carry propagation is a few data-parallel passes (no sequential limb scan);
  only the final canonical freeze (needed once per verify, for the
  encode(P) == R byte comparison Go does) uses an exact borrow scan.

Bounds discipline (checked by tests/test_fe.py):
- "normalized": limbs in [0, 512)   — output of fe_carry/fe_mul/fe_sub.
- fe_mul/fe_sq inputs must have limbs in [0, 1311]; sums of two normalized
  values (fe_add output, <= 1024) are therefore legal mul inputs.
- fe_sub(a, b) adds the limbwise constant 8*p before subtracting, so the
  borrow-free requirement is per-limb: b[0] <= 8*0xED = 1896,
  b[1..30] <= 8*0xFF = 2040, b[31] <= 8*0x7F = 1016. All call sites pass
  normalized-or-added values (limbs <= 1024), well inside every bound.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

NLIMB = 32
RADIX = 8
MASK = (1 << RADIX) - 1

# p = 2^255 - 19, little-endian radix-256 limbs.
P_INT = 2**255 - 19
P_LIMBS = np.array([0xED] + [0xFF] * 30 + [0x7F], dtype=np.int32)
# Limbwise 8*p: a value ≡ 0 (mod p) that dominates any subtrahend within
# the per-limb bounds documented above, making limbwise subtraction
# borrow-free.
EIGHT_P_LIMBS = 8 * P_LIMBS

# Anti-diagonal gather plan for the 32x32 limb product: column k of the
# product accumulates a[i] * b[k-i]; _IDX/_VALID pre-encode the k-i map.
_K = np.arange(2 * NLIMB - 1)
_I = np.arange(NLIMB)
_IDX = np.clip(_K[None, :] - _I[:, None], 0, NLIMB - 1)  # [32, 63]
_VALID = (_K[None, :] - _I[:, None] >= 0) & (_K[None, :] - _I[:, None] < NLIMB)


def int_to_limbs(x: int) -> np.ndarray:
    """Host helper: python int -> canonical limb vector."""
    return np.array([(x >> (RADIX * i)) & MASK for i in range(NLIMB)], dtype=np.int32)


def limbs_to_int(limbs) -> int:
    """Host helper: limb vector (any bounds) -> python int."""
    out = 0
    for i, v in enumerate(np.asarray(limbs).tolist()):
        out += int(v) << (RADIX * i)
    return out


def bytes_to_limbs(b: bytes) -> np.ndarray:
    assert len(b) == 32
    return np.frombuffer(b, dtype=np.uint8).astype(np.int32)


def fe_carry(x, passes: int = 4):
    """Data-parallel carry with 2^256 ≡ 38 wraparound.

    Each pass moves carries one limb up; the carry out of limb 31 re-enters
    limb 0 scaled by 38. For inputs bounded by 2^29 (worst case out of the
    fe_mul fold) four passes bring every limb under 512.
    """
    for _ in range(passes):
        hi = x >> RADIX
        lo = x & MASK
        wrapped = jnp.concatenate([38 * hi[..., NLIMB - 1 :], hi[..., : NLIMB - 1]], axis=-1)
        x = lo + wrapped
    return x


def fe_add(a, b):
    """Limbwise add; output limbs <= 1024 when inputs are normalized."""
    return a + b


def fe_sub(a, b):
    """a - b mod p, borrow-free via the 8p offset; output normalized."""
    return fe_carry(a + jnp.asarray(EIGHT_P_LIMBS) - b, passes=2)


import os


def _conv_mode() -> str:
    """Limb-convolution formulation, chosen at trace time per backend.

    'pad'    — 32 shifted multiply-accumulates (elementwise + static pads).
               On TPU this fuses into pure VPU code with NO layout changes;
               the einsum formulation spent 44% of kernel time in reshapes
               XLA inserted around the batched matvec (r3 profile), and
               switching to 'pad' took the verify kernel from 16k to 57k
               votes/s at B=4096 (85k at 16384).
    'gather' — anti-diagonal gather + einsum. Same speed as 'pad' on CPU
               but ~3x faster to compile; kept for CPU/test runs.
    """
    forced = os.environ.get("TXFLOW_FE_CONV")
    if forced:
        return forced
    import jax

    return "pad" if jax.default_backend() == "tpu" else "gather"


def fe_mul(a, b):
    """Product mod 2^255-19 (normalized limbs). Inputs: limbs <= 1311.

    32x32 limb convolution (formulation per ``_conv_mode``), then the
    2^256 ≡ 38 fold of the high 31 columns, then carries.
    """
    if _conv_mode() == "pad":
        nd = a.ndim
        c = None
        for i in range(NLIMB):
            t = jnp.pad(
                a[..., i : i + 1] * b, [(0, 0)] * (nd - 1) + [(i, NLIMB - 1 - i)]
            )
            c = t if c is None else c + t
    else:
        bsh = jnp.where(jnp.asarray(_VALID), b[..., jnp.asarray(_IDX)], 0)
        c = jnp.einsum("...i,...ik->...k", a, bsh)  # [..., 63]
    hi = jnp.pad(c[..., NLIMB:], [(0, 0)] * (c.ndim - 1) + [(0, 1)])
    # Worst legal input (limbs 1311) folds to < 2^31; five carry passes are
    # needed for the big limb-0 carry to fully settle (it moves up one limb
    # per pass: 0 -> 1 -> 2 -> 3 -> done).
    return fe_carry(c[..., :NLIMB] + 38 * hi, passes=5)


def fe_sq(a):
    return fe_mul(a, a)


def fe_mul_small(a, c: int):
    """Multiply by a small scalar constant (c <= ~2^20); output normalized."""
    return fe_carry(a * c)


def fe_freeze(x):
    """Exact canonical reduction: limbs in [0,256) and value < p.

    Used once per verification for the byte-exact encode(P) == sig[:32]
    comparison (Go compares encodings, never decompressing R). Two borrow
    scans subtract p at most twice: after carrying, the value is < 2^256 =
    2p + 38, so two conditional subtractions always land in [0, p).
    """
    x = fe_carry(x, passes=6)  # limbs <= ~293, value < 2^256
    p = jnp.asarray(P_LIMBS)
    for _ in range(2):
        # Exact x - p with sequential borrow (31 cheap steps, once per verify).
        diff = x - p
        borrows = []
        borrow = jnp.zeros_like(x[..., 0])
        for i in range(NLIMB):
            d = diff[..., i] - borrow
            borrow = (d < 0).astype(x.dtype)
            borrows.append(d + (borrow << RADIX))
        sub = jnp.stack(borrows, axis=-1)
        x = jnp.where((borrow == 0)[..., None], sub, x)
    # Final carry normalization to strict [0, 256) limbs.
    return fe_carry(x, passes=2)


def fe_is_equal_frozen(a, b):
    """Bytewise equality of two frozen elements -> bool[...]."""
    return jnp.all(a == b, axis=-1)


def fe_parity_frozen(a):
    """Low bit of a frozen element (the encode() sign source)."""
    return a[..., 0] & 1


def fe_inv(a):
    """a^(p-2) via the standard 25519 addition chain (~254 sq + 11 mul)."""

    def pow2k(x, k):
        for _ in range(k):
            x = fe_sq(x)
        return x

    z2 = fe_sq(a)  # 2
    z9 = fe_mul(pow2k(z2, 2), a)  # 9
    z11 = fe_mul(z9, z2)  # 11
    z2_5_0 = fe_mul(fe_sq(z11), z9)  # 2^5 - 2^0
    z2_10_0 = fe_mul(pow2k(z2_5_0, 5), z2_5_0)
    z2_20_0 = fe_mul(pow2k(z2_10_0, 10), z2_10_0)
    z2_40_0 = fe_mul(pow2k(z2_20_0, 20), z2_20_0)
    z2_50_0 = fe_mul(pow2k(z2_40_0, 10), z2_10_0)
    z2_100_0 = fe_mul(pow2k(z2_50_0, 50), z2_50_0)
    z2_200_0 = fe_mul(pow2k(z2_100_0, 100), z2_100_0)
    z2_250_0 = fe_mul(pow2k(z2_200_0, 50), z2_50_0)
    return fe_mul(pow2k(z2_250_0, 5), z11)  # 2^255 - 21
