"""Stake-weighted quorum tally as a device reduction.

The reference accumulates voting power one vote at a time under a mutex
(types/vote_set.go:143-166: ``sum += power; maj23 = sum >= total*2/3+1``).
Here the tally over a whole batch of verified votes is a segment-sum over
tx slots followed by a threshold compare — one fused XLA reduction, and the
cross-device combine is a single ``psum`` over the vote-sharding mesh axis.

Voting powers are int64 in the reference. The device tally uses int32 —
with per-batch dedup, per-slot batch stake and prior stake are each at
most the total power, so their sum stays below 2^31 whenever total power
is below 2^30. ``DeviceVoteVerifier`` enforces that bound at construction
and raises, directing such sets to ``ScalarVoteVerifier`` (host int64
accumulation); tendermint itself caps total power at 2^63/8, and practical
validator sets are far below 2^30.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import curve


def tally_kernel(valid, tx_slot, power, n_slots: int):
    """Per-slot stake sums for one device shard.

    valid: bool[B] (verified signatures), tx_slot: int32[B] slot id per vote
    (-1 or >= n_slots = no slot / padding), power: int32[B] voting power of
    the vote's validator. Returns int32[n_slots].
    """
    contrib = jnp.where(valid, power, 0)
    slot = jnp.clip(tx_slot, 0, n_slots - 1)
    in_range = (tx_slot >= 0) & (tx_slot < n_slots)
    return jax.ops.segment_sum(
        jnp.where(in_range, contrib, 0), slot, num_segments=n_slots
    )


def verify_and_tally(verify_fn, axis_name: str | None = None):
    """Compose a verify kernel with the quorum tally.

    Returns f(verify_inputs..., tx_slot, power, prior_stake, quorum) ->
    (valid[B], stake[n_slots], maj23[n_slots]).

    prior_stake carries stake already accumulated for each slot in earlier
    batches (the engine's running TxVoteSet sums), so maj23 latches across
    batches exactly like the incremental reference. When ``axis_name`` is
    given the stake partial-sums are psum-combined across the vote-sharded
    mesh axis (ICI collective), giving every shard the global tally.
    """

    def f(verify_inputs, tx_slot, power, prior_stake, quorum):
        valid = verify_fn(*verify_inputs, axis_name=axis_name)
        stake = tally_kernel(valid, tx_slot, power, prior_stake.shape[0])
        if axis_name is not None:
            stake = jax.lax.psum(stake, axis_name)
        total = prior_stake + stake
        return valid, total, total >= quorum

    return f


import functools


@functools.lru_cache(maxsize=None)
def compact_step_jit(axis_name: str | None = None):
    """Process-wide shared jit of ``compact_step``.

    Every ``DeviceVoteVerifier`` in the process (an in-proc validator net
    runs one per node) must share ONE compiled program per input shape —
    epoch tables and powers are arguments, so nothing per-verifier is
    baked in. Constructing a fresh ``jax.jit(compact_step())`` per
    verifier would compile N times (~tens of seconds each on TPU)."""
    return jax.jit(compact_step(axis_name))


def compact_step(axis_name: str | None = None):
    """The fused aggregation step over a compact batch (the hot path).

    f(s_nib, h_nib, val_idx, r_y, r_sign, pre_ok, tx_slot, tables, powers,
      prior_stake, quorum) -> (valid[B], stake[n_slots], maj23[n_slots]).

    Per-epoch constants (``tables`` [V,16,4,32], ``powers`` int32[V]) stay
    device-resident across batches; per-vote inputs are compact uint8/int32
    (~162 B/vote of H2D). Voting power is gathered on device by validator
    index — a vote contributes iff its signature verified.
    """
    from . import ed25519_batch

    def f(s_nib, h_nib, val_idx, r_y, r_sign, pre_ok, tx_slot, tables, powers, prior_stake, quorum):
        valid = ed25519_batch.verify_kernel_gather(
            s_nib, h_nib, val_idx, tables, r_y, r_sign, pre_ok,
            axis_name=axis_name,
        )
        power = jnp.take(powers, val_idx)
        stake = tally_kernel(valid, tx_slot, power, prior_stake.shape[0])
        if axis_name is not None:
            stake = jax.lax.psum(stake, axis_name)
        total = prior_stake + stake
        return valid, total, total >= quorum

    return f


@functools.lru_cache(maxsize=None)
def compact_step_packed_jit(axis_name: str | None = None):
    """Shared jit of the packed-output compact step (see compact_step_packed)."""
    return jax.jit(compact_step_packed(axis_name))


def compact_step_packed(axis_name: str | None = None):
    """compact_step with the three outputs packed into ONE int32 vector.

    Readback layout per shard: [valid (B/n) | stake (S) | maj23 (S)], all
    int32, concatenated. One device->host transfer instead of three — the
    transfer setup cost dominates small reads on tunneled links (~65 ms per
    array measured on the axon TPU path, r3), so packing roughly halves
    end-to-end step latency. With a mesh the stake/maj segments are the
    psum-replicated globals, repeated per shard (the host reads shard 0's).
    """
    inner = compact_step(axis_name)

    def f(*args):
        valid, total, maj = inner(*args)
        total = total.astype(jnp.int32)
        maj = maj.astype(jnp.int32)
        if axis_name is not None:
            # stake/maj are psum-replicated (device-invariant); concatenating
            # them with the device-varying valid segment needs an explicit
            # variance cast for the VMA checker (identity on pre-VMA JAX,
            # see curve._pvary)
            total = curve._pvary(total, axis_name)
            maj = curve._pvary(maj, axis_name)
        return jnp.concatenate([valid.astype(jnp.int32), total, maj])

    return f
