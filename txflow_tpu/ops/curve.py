"""Batched edwards25519 point arithmetic on limb tensors.

Representations (each coordinate an int32 limb tensor ``[..., 32]``):

- **Extended** (X, Y, Z, T): x = X/Z, y = Y/Z, T = XY/Z — the working form.
- **PNiels** (Y+X, Y-X, Z, 2dT): precomputed form making addition cost 8 muls.
  Host-built window tables store affine entries (Z = 1) in this form.

All ops are branch-free and vectorized over leading batch dims, so the
double-scalar multiplication [s]B + [h](-A) — the per-vote work Go does
serially in crypto/ed25519 (reference types/tx_vote.go:110-119) — runs for
thousands of votes in one XLA program.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..crypto import ed25519 as host_ed
from . import fe

# 2*d mod p, as a canonical limb constant.
D2_INT = (2 * host_ed.D) % host_ed.P
D2_LIMBS = fe.int_to_limbs(D2_INT)

TABLE_WINDOW = 4
TABLE_SIZE = 1 << TABLE_WINDOW  # 16
NWINDOWS = 64  # 256 bits / 4


def _pvary(x, axis_name):
    """``lax.pvary`` where this JAX has it, identity where it doesn't.

    The varying-manual-axes cast only exists on JAX builds with the
    shard_map VMA checker; pre-VMA builds (<= 0.4.x) have no variance
    types on the loop carry — there is nothing to cast and no checker to
    satisfy, so the sharded wrappers trace fine without it."""
    fn = getattr(jax.lax, "pvary", None)
    return x if fn is None else fn(x, axis_name)


def ext_identity(batch_shape):
    z = jnp.zeros((*batch_shape, fe.NLIMB), dtype=jnp.int32)
    one = z.at[..., 0].set(1)
    return (z, one, one, z)


def ext_double(p, compute_t: bool = True):
    """Dedicated doubling (RFC 8032 section 5.1.4 'dbl-2008-hwcd')."""
    X1, Y1, Z1, _ = p
    A = fe.fe_sq(X1)
    B = fe.fe_sq(Y1)
    C = fe.fe_mul_small(fe.fe_sq(Z1), 2)
    H = fe.fe_add(A, B)
    # E = H - (X1+Y1)^2  (carry the sum before squaring to respect bounds)
    E = fe.fe_sub(H, fe.fe_sq(fe.fe_carry(fe.fe_add(X1, Y1), passes=2)))
    G = fe.fe_sub(A, B)
    F = fe.fe_add(C, G)
    X3 = fe.fe_mul(E, F)
    Y3 = fe.fe_mul(G, H)
    Z3 = fe.fe_mul(F, G)
    T3 = fe.fe_mul(E, H) if compute_t else X3
    return (X3, Y3, Z3, T3)


def pniels_add(p, n):
    """Extended + PNiels -> Extended ('madd-2008-hwcd-3' generalized to Z2)."""
    X1, Y1, Z1, T1 = p
    YpX2, YmX2, Z2, T2d2 = n
    A = fe.fe_mul(fe.fe_sub(Y1, X1), YmX2)
    B = fe.fe_mul(fe.fe_carry(fe.fe_add(Y1, X1), passes=2), YpX2)
    C = fe.fe_mul(T1, T2d2)
    D = fe.fe_mul_small(fe.fe_mul(Z1, Z2), 2)
    E = fe.fe_sub(B, A)
    F = fe.fe_sub(D, C)
    G = fe.fe_add(D, C)
    H = fe.fe_add(B, A)
    return (
        fe.fe_mul(E, F),
        fe.fe_mul(G, H),
        fe.fe_mul(F, G),
        fe.fe_mul(E, H),
    )


def table_select(table, nibble):
    """Select window entries from a PNiels table by per-item nibble.

    table: [..., 16, 4, 32] (leading dims broadcast against nibble's batch);
    nibble: int32 [...] in [0, 16). Returns PNiels coords, each [..., 32].
    Uses a one-hot contraction (MXU/VPU-friendly; also constant-time, which
    the serial reference path is not).
    """
    onehot = (
        nibble[..., None] == jnp.arange(TABLE_SIZE, dtype=jnp.int32)
    ).astype(jnp.int32)
    if table.ndim == 3:  # shared table [16, 4, 32]
        sel = jnp.einsum("...w,wcl->...cl", onehot, table)
    else:  # per-item table [..., 16, 4, 32]
        sel = jnp.einsum("...w,...wcl->...cl", onehot, table)
    return (sel[..., 0, :], sel[..., 1, :], sel[..., 2, :], sel[..., 3, :])


def table_select_indexed(tables_flat, idx):
    """Select PNiels entries from a SHARED flattened table by scalar index.

    tables_flat: [E, 4*32] (all validators' window entries, row-major);
    idx: int32 [...] in [0, E). Two lowerings, same bit-exact result:

    - E <= 2048: one-hot matmul [..., E] @ [E, 128]. Inputs cast to
      bfloat16 — exact, since one-hot entries are 0/1 and limbs are < 256
      (8 significand bits) — with a float32 accumulator, so the MXU does
      the select instead of the VPU walking a gather. This is the hot
      configuration (validator sets <= 128).
    - E > 2048: plain row gather (the one-hot operand would dwarf the
      table itself).

    Either way the full per-item window table [B, 16, 4, 32] of the naive
    path is never materialized — selection happens inside the scan step,
    one window at a time (the materialized form measured super-linear HBM
    cost past ~16k votes on v5e, r3).
    """
    import math

    E = tables_flat.shape[0]
    batch = math.prod(idx.shape) if idx.shape else 1
    # the one-hot matmul only pays off when the batch actually fills MXU
    # tiles; for tiny batches it also hit a pathological remote-compile
    # path on the tunneled TPU (an 8-vote entry() program compiled for
    # >25 minutes, r3) — small or huge-table cases take the plain gather
    if E <= 2048 and batch >= 256:
        # dtype must represent every table limb EXACTLY: radix-8 limbs
        # (< 256) fit bfloat16's 8 significand bits; radix-13 limbs
        # (< 8192) need float32 (24 bits). One-hot entries are 0/1 and the
        # accumulator is f32 either way, so the select stays bit-exact.
        sel_dtype = jnp.bfloat16 if fe.RADIX == 8 else jnp.float32
        onehot = (
            idx[..., None] == jnp.arange(E, dtype=jnp.int32)
        ).astype(sel_dtype)
        sel = jax.lax.dot_general(
            onehot,
            tables_flat.astype(sel_dtype),
            (((onehot.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            # f32 path (radix-13): default MXU precision truncates f32
            # operands to bf16, which loses the low ~5 bits of 13-bit
            # limbs — HIGHEST keeps the pass bit-exact (r5 review); the
            # bf16 path is exact by construction (limbs < 256)
            precision=(
                None if sel_dtype == jnp.bfloat16 else jax.lax.Precision.HIGHEST
            ),
        ).astype(jnp.int32)
    else:
        sel = jnp.take(tables_flat, idx, axis=0)
    sel = sel.reshape(*idx.shape, 4, fe.NLIMB)
    return (sel[..., 0, :], sel[..., 1, :], sel[..., 2, :], sel[..., 3, :])


def double_scalar_mul_indexed(
    s_nibbles, h_nibbles, base_table, tables, val_idx, axis_name=None
):
    """[s]B + [h]A' with A' looked up per item from shared epoch tables.

    tables: [V, 16, 4, 32] device-resident epoch tables; val_idx: int32 [B].
    Identical results to ``double_scalar_mul`` over gathered per-item
    tables, but the gather collapses to an in-loop indexed select (see
    ``table_select_indexed``), so HBM holds one [V*16, 128] table total
    instead of 8 KiB per vote.
    """
    n_vals = tables.shape[0]
    tables_flat = tables.reshape(n_vals * TABLE_SIZE, 4 * fe.NLIMB)
    base = val_idx * TABLE_SIZE

    def step(w, acc):
        acc = ext_double(acc, compute_t=False)
        acc = ext_double(acc, compute_t=False)
        acc = ext_double(acc, compute_t=False)
        acc = ext_double(acc, compute_t=True)
        s_nib = jax.lax.dynamic_index_in_dim(s_nibbles, w, axis=-1, keepdims=False)
        h_nib = jax.lax.dynamic_index_in_dim(h_nibbles, w, axis=-1, keepdims=False)
        acc = pniels_add(acc, table_select(base_table, s_nib))
        acc = pniels_add(acc, table_select_indexed(tables_flat, base + h_nib))
        return acc

    init = ext_identity(s_nibbles.shape[:-1])
    if axis_name is not None:
        init = tuple(_pvary(t, axis_name) for t in init)
    return jax.lax.fori_loop(0, NWINDOWS, step, init)


def double_scalar_mul(s_nibbles, h_nibbles, base_table, a_tables, axis_name=None):
    """Compute [s]B + [h]A' batched, A' given by per-item PNiels tables.

    s_nibbles, h_nibbles: int32 [B, 64], most-significant nibble first.
    base_table: [16, 4, 32] PNiels multiples of B (host precomputed).
    a_tables:   [B, 16, 4, 32] PNiels multiples of A' (per-validator epoch
                tables gathered per vote; A' = -A for verification).
    Returns an Extended point.

    64 lax.fori_loop window steps of (4 doublings + 2 table additions); a
    uniform body (doubling the identity start is a no-op) keeps the compiled
    program one window-step long instead of 64.

    Under shard_map (``axis_name`` set) the identity start is marked
    device-varying with ``lax.pvary`` so the loop carry has a consistent
    variance type — the per-vote table additions make it varying anyway —
    and the static VMA checker can stay ON.
    """

    def step(w, acc):
        acc = ext_double(acc, compute_t=False)
        acc = ext_double(acc, compute_t=False)
        acc = ext_double(acc, compute_t=False)
        acc = ext_double(acc, compute_t=True)
        s_nib = jax.lax.dynamic_index_in_dim(s_nibbles, w, axis=-1, keepdims=False)
        h_nib = jax.lax.dynamic_index_in_dim(h_nibbles, w, axis=-1, keepdims=False)
        acc = pniels_add(acc, table_select(base_table, s_nib))
        acc = pniels_add(acc, table_select(a_tables, h_nib))
        return acc

    init = ext_identity(s_nibbles.shape[:-1])
    if axis_name is not None:
        # the sharded wrappers run with the VMA checker ON, which needs
        # this variance cast (see _pvary: identity on pre-VMA JAX, where
        # shard_map has no variance types and nothing to cast)
        init = tuple(_pvary(t, axis_name) for t in init)
    return jax.lax.fori_loop(0, NWINDOWS, step, init)


def ext_encode(p):
    """Canonical compressed encoding pieces: (y_frozen [...,32], x_parity [...]).

    encode(P) = y with the parity of x in bit 255 (host_ed.point_compress);
    returning the frozen y limbs + parity lets the caller compare against
    raw signature bytes exactly as Go does.
    """
    X, Y, Z, _ = p
    zinv = fe.fe_inv(Z)
    y = fe.fe_freeze(fe.fe_mul(Y, zinv))
    x = fe.fe_freeze(fe.fe_mul(X, zinv))
    return y, fe.fe_parity_frozen(x)


# ----------------------------------------------------------------------------
# Host-side table construction (numpy/python ints; once per validator epoch).


def _affine_pniels(pt) -> np.ndarray:
    """Host: extended python-int point -> affine PNiels limb block [4, 32]."""
    x, y, z, _ = pt
    zinv = pow(z, host_ed.P - 2, host_ed.P)
    xa, ya = (x * zinv) % host_ed.P, (y * zinv) % host_ed.P
    return np.stack(
        [
            fe.int_to_limbs((ya + xa) % host_ed.P),
            fe.int_to_limbs((ya - xa) % host_ed.P),
            fe.int_to_limbs(1),
            fe.int_to_limbs((2 * host_ed.D * xa * ya) % host_ed.P),
        ]
    )


def build_pniels_table(pt) -> np.ndarray:
    """Host: window table [16, 4, 32] of {0..15} * pt (entry 0 = identity)."""
    rows = [
        np.stack(
            [
                fe.int_to_limbs(1),
                fe.int_to_limbs(1),
                fe.int_to_limbs(1),
                fe.int_to_limbs(0),
            ]
        )
    ]
    acc = host_ed.IDENTITY
    for _ in range(1, TABLE_SIZE):
        acc = host_ed.point_add(acc, pt)
        rows.append(_affine_pniels(acc))
    return np.stack(rows)  # [16, 4, 32]


BASE_TABLE = build_pniels_table(host_ed.BASE)


def scalar_to_nibbles(s: int) -> np.ndarray:
    """Host: 256-bit scalar -> [64] int32 nibbles, most significant first."""
    return np.array(
        [(s >> (4 * (NWINDOWS - 1 - i))) & 0xF for i in range(NWINDOWS)],
        dtype=np.int32,
    )
