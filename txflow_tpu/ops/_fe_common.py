"""Radix-independent pieces shared by the field implementations (fe =
radix-2^8/32-limb, fe13 = radix-2^13/20-limb).

Everything here is expressible purely in terms of a radix's primitive ops
(fe_mul) or operates on frozen canonical limbs where the radix doesn't
matter — kept in ONE place so a fix can never land in one radix and miss
the other (r5 review)."""

from __future__ import annotations

import os

import jax.numpy as jnp


def conv_mode() -> str:
    """Limb-convolution formulation, chosen at trace time per backend.

    'pad'    — shifted multiply-accumulates (elementwise + static pads).
               On TPU this fuses into pure VPU code with NO layout
               changes; the einsum formulation spent 44% of kernel time
               in reshapes XLA inserted around the batched matvec (r3
               profile), and switching to 'pad' took the radix-8 verify
               kernel from 16k to 57k votes/s at B=4096.
    'gather' — anti-diagonal gather + einsum. Same speed as 'pad' on CPU
               but ~3x faster to compile; kept for CPU/test runs.
    """
    forced = os.environ.get("TXFLOW_FE_CONV")
    if forced:
        return forced
    import jax

    return "pad" if jax.default_backend() == "tpu" else "gather"


def fe_is_equal_frozen(a, b):
    """Bytewise equality of two frozen elements -> bool[...]."""
    return jnp.all(a == b, axis=-1)


def fe_parity_frozen(a):
    """Low bit of a frozen element (the encode() sign source)."""
    return a[..., 0] & 1


def make_inv(fe_mul):
    """Build fe_inv = a^(p-2) (standard 25519 addition chain, ~254 sq +
    11 mul) from a radix's fe_mul primitive."""

    def fe_sq(a):
        return fe_mul(a, a)

    def pow2k(x, k):
        for _ in range(k):
            x = fe_sq(x)
        return x

    def fe_inv(a):
        z2 = fe_sq(a)  # 2
        z9 = fe_mul(pow2k(z2, 2), a)  # 9
        z11 = fe_mul(z9, z2)  # 11
        z2_5_0 = fe_mul(fe_sq(z11), z9)  # 2^5 - 2^0
        z2_10_0 = fe_mul(pow2k(z2_5_0, 5), z2_5_0)
        z2_20_0 = fe_mul(pow2k(z2_10_0, 10), z2_10_0)
        z2_40_0 = fe_mul(pow2k(z2_20_0, 20), z2_20_0)
        z2_50_0 = fe_mul(pow2k(z2_40_0, 10), z2_10_0)
        z2_100_0 = fe_mul(pow2k(z2_50_0, 50), z2_50_0)
        z2_200_0 = fe_mul(pow2k(z2_100_0, 100), z2_100_0)
        z2_250_0 = fe_mul(pow2k(z2_200_0, 50), z2_50_0)
        return fe_mul(pow2k(z2_250_0, 5), z11)  # 2^255 - 21

    return fe_inv
