"""Batched GF(2^255-19) arithmetic in radix-2^13 int32 limbs (20 limbs).

The radix upgrade over ``fe`` (radix-2^8, 32 limbs): a field element is
``[..., 20]`` int32 — the limb convolution shrinks from 32x32 to 20x20
partial products (~2.5x fewer multiplies), the main lever for lifting the
verify kernel past the r3 36-39k votes/s plateau. Same API as ``fe``;
``fe`` re-exports this implementation when TXFLOW_FE_RADIX=13.

Bounds discipline (the radix-8 comments generalized; checked by
tests/test_fe13.py):

- 20 * 13 = 260 bits: the top limb carries 8 canonical bits; 2^260 ≡ 608
  (= 2^5 * 19) is the carry wraparound constant.
- "normalized": limbs <= N = 9408 (= 2^13 - 1 + 2*608 + margin). A 20-col
  convolution of two normalized inputs peaks at 20 * N^2 = 1.77e9 < 2^31,
  so the conv stays in int32 — but ONLY for normalized inputs, which is
  why fe_add carries its output here (radix-8 could defer).
- The 2^260 fold must pre-carry the high columns BEFORE multiplying by
  608: high columns reach ~2^30.7, and 608 * 2^30.7 would overflow int32.
  After a 3-pass pre-carry they are < 2^13.2, and 608 * that folds safely.
- fe_sub offsets by 128*p (limbwise): the radix-8 code used 8*p, but p's
  top limb here is 255, and 8 * 255 = 2040 cannot dominate a normalized
  subtrahend limb (<= 9408); 128 * 255 = 32640 can.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from . import _fe_common as _common

NLIMB = 20
RADIX = 13
MASK = (1 << RADIX) - 1

P_INT = 2**255 - 19
WRAP = 608  # 2^260 mod p


def int_to_limbs(x: int) -> np.ndarray:
    """Host helper: python int -> canonical limb vector."""
    return np.array([(x >> (RADIX * i)) & MASK for i in range(NLIMB)], dtype=np.int32)


def limbs_to_int(limbs) -> int:
    """Host helper: limb vector (any bounds) -> python int."""
    out = 0
    for i, v in enumerate(np.asarray(limbs).tolist()):
        out += int(v) << (RADIX * i)
    return out


P_LIMBS = int_to_limbs(P_INT)  # [8173, 8191*18, 255]
OFFSET_P_LIMBS = 128 * P_LIMBS


def bytes_to_limbs(b: bytes) -> np.ndarray:
    assert len(b) == 32
    return int_to_limbs(int.from_bytes(b, "little"))


# 13-bit repack plan: limb j spans bytes (13j)//8 .. +2 at offset (13j)%8.
_J = np.arange(NLIMB)
_BYTE0 = (13 * _J) // 8
_OFF = (13 * _J) % 8


def bytes_to_limbs_device(b):
    """[..., 32] uint8 LE bytes -> [..., 20] int32 limbs (jit-able)."""
    b = jnp.asarray(b).astype(jnp.int32)
    bp = jnp.pad(b, [(0, 0)] * (b.ndim - 1) + [(0, 2)])
    w = (
        bp[..., jnp.asarray(_BYTE0)]
        | (bp[..., jnp.asarray(_BYTE0 + 1)] << 8)
        | (bp[..., jnp.asarray(_BYTE0 + 2)] << 16)
    )
    return (w >> jnp.asarray(_OFF)) & MASK


def fe_carry(x, passes: int = 4):
    """Data-parallel carry with the 2^260 ≡ 608 wraparound."""
    for _ in range(passes):
        hi = x >> RADIX
        lo = x & MASK
        wrapped = jnp.concatenate(
            [WRAP * hi[..., NLIMB - 1 :], hi[..., : NLIMB - 1]], axis=-1
        )
        x = lo + wrapped
    return x


def fe_add(a, b):
    """a + b, CARRIED (unlike radix-8): the sum of two normalized values
    would breach the 20 * limb^2 < 2^31 conv bound if fed to fe_mul raw."""
    return fe_carry(a + b, passes=1)


def fe_sub(a, b):
    """a - b mod p, borrow-free via the 128p offset; output normalized."""
    return fe_carry(a + jnp.asarray(OFFSET_P_LIMBS) - b, passes=2)


# Anti-diagonal gather plan (CPU/compile-fast formulation; the padded
# multiply-accumulate is the TPU formulation — see fe._conv_mode).
_K = np.arange(2 * NLIMB - 1)
_I = np.arange(NLIMB)
_IDX = np.clip(_K[None, :] - _I[:, None], 0, NLIMB - 1)
_VALID = (_K[None, :] - _I[:, None] >= 0) & (_K[None, :] - _I[:, None] < NLIMB)


def fe_mul(a, b):
    """Product mod 2^255-19. Inputs normalized (limbs <= ~9408).

    20x20 limb convolution (formulation per ``_conv_mode``), 3-pass
    pre-carry of the high columns, 2^260 ≡ 608 fold, 4 carry passes.
    """
    if _common.conv_mode() == "pad":
        nd = a.ndim
        c = None
        for i in range(NLIMB):
            t = jnp.pad(
                a[..., i : i + 1] * b, [(0, 0)] * (nd - 1) + [(i, NLIMB - 1 - i)]
            )
            c = t if c is None else c + t
    else:
        bsh = jnp.where(jnp.asarray(_VALID), b[..., jnp.asarray(_IDX)], 0)
        c = jnp.einsum("...i,...ik->...k", a, bsh)  # [..., 39]
    lo = c[..., :NLIMB]
    hi = jnp.pad(c[..., NLIMB:], [(0, 0)] * (c.ndim - 1) + [(0, 1)])
    # pre-carry BEFORE the 608 fold (int32 overflow otherwise — module
    # docstring); the tiny residual above bit 260 wraps via fe_carry's own
    # 608 term, which is exact: hi's value is multiplied by 608 afterwards
    # and 608 * (x mod p) ≡ 608 * x (mod p)
    hi = fe_carry(hi, passes=3)
    return fe_carry(lo + WRAP * hi, passes=4)


def fe_sq(a):
    return fe_mul(a, a)


def fe_mul_small(a, c: int):
    """Multiply by a small constant (c * 9408 must stay < 2^31: c <= ~2^17)."""
    assert c <= (1 << 17)
    return fe_carry(a * c)


def fe_freeze(x):
    """Exact canonical reduction: limbs in [0, 2^13) and value < p.

    After carrying, the value can reach ~2^260.1 (top limb holds 13 bits
    where only 8 are canonical): fold bits >= 255 back via 2^255 ≡ 19,
    twice (the second pass handles the fold's own carry), then at most two
    conditional subtractions of p land in [0, p).
    """
    x = fe_carry(x, passes=5)
    for _ in range(2):
        t = x[..., NLIMB - 1] >> 8  # bits 255.. of the value
        x = x.at[..., NLIMB - 1].set(x[..., NLIMB - 1] & 0xFF)
        x = x.at[..., 0].add(19 * t)
        x = fe_carry(x, passes=2)
    p = jnp.asarray(P_LIMBS)
    for _ in range(2):
        diff = x - p
        borrows = []
        borrow = jnp.zeros_like(x[..., 0])
        for i in range(NLIMB):
            d = diff[..., i] - borrow
            borrow = (d < 0).astype(x.dtype)
            borrows.append(d + (borrow << RADIX))
        sub = jnp.stack(borrows, axis=-1)
        x = jnp.where((borrow == 0)[..., None], sub, x)
    return fe_carry(x, passes=2)


fe_is_equal_frozen = _common.fe_is_equal_frozen
fe_parity_frozen = _common.fe_parity_frozen
fe_inv = _common.make_inv(fe_mul)
