"""Batched ed25519 verification: the TPU replacement for the reference's
one-vote-at-a-time Go verify (types/tx_vote.go:110-119, serialized through
txflow/service.go:123-166).

Work split, designed for the hardware:

- **Host** does all byte-level work: signature parsing, the S < L malleability
  check ("ScMinimal"), SHA-512(R || A || msg) mod L (hashlib; ~1 us per vote,
  never the bottleneck), scalar->nibble decomposition, and — once per
  validator-set epoch — pubkey decompression + 16-entry window tables of -A
  per validator.
- **Device** does all curve math: the batched double-scalar multiplication
  P = [s]B + [h](-A) and the canonical encode(P) == sig[:32] comparison,
  branch-free over the whole batch.

Accept/reject decisions are bit-identical to ``crypto.ed25519.verify_pure``
(the audited golden model of Go's crypto/ed25519) — tested including
adversarial non-canonical encodings.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from ..crypto import ed25519 as host_ed
from . import curve, fe


@dataclass
class PreparedBatch:
    """Host-prepared device inputs for a batch of B signature checks."""

    s_nibbles: np.ndarray  # [B, 64] int32, MSB-first nibbles of S
    h_nibbles: np.ndarray  # [B, 64] int32, MSB-first nibbles of h = H(R|A|m) mod L
    a_tables: np.ndarray  # [B, 16, 4, 32] int32 PNiels tables of -A (gathered)
    r_y: np.ndarray  # [B, 32] int32: low 255 bits of sig[:32] as limbs
    r_sign: np.ndarray  # [B] int32: bit 255 of sig[:32]
    pre_ok: np.ndarray  # [B] bool: host pre-checks passed (S<L, key on curve)

    @property
    def size(self) -> int:
        return self.s_nibbles.shape[0]


def neg_pubkey_table(pub_key: bytes) -> tuple[np.ndarray, bool]:
    """Host: window table of -A for one pubkey; ok=False if off-curve.

    Off-curve keys get an identity-filled table and are force-rejected via
    the pre_ok mask (matching Go, which rejects at decompression).
    """
    A = host_ed.point_decompress(pub_key)
    if A is None:
        return curve.build_pniels_table(host_ed.IDENTITY), False
    return curve.build_pniels_table(host_ed.point_neg(A)), True


class EpochTables:
    """Per-validator-set-epoch device constants: one -A table per validator.

    The reference re-fetches the pubkey and re-verifies per vote
    (types/vote_set.go:117-119); here decompression and windowing are
    amortized across the epoch (validator sets change only at block
    boundaries, state/execution.go:390-414).
    """

    def __init__(self, pub_keys: list[bytes]):
        tables, oks = [], []
        for pk in pub_keys:
            t, ok = neg_pubkey_table(pk)
            tables.append(t)
            oks.append(ok)
        self.pub_keys = list(pub_keys)
        self.tables = np.stack(tables) if tables else np.zeros((0, 16, 4, fe.NLIMB), np.int32)
        self.key_ok = np.array(oks, dtype=bool)
        # [V, 32] uint8 key bytes for the native batch prep's per-vote
        # gather. Malformed key lengths (key_ok already False -> the vote is
        # force-rejected) get a zero row: joining raw would crash or, worse,
        # shift every later validator's row by the length error.
        self.pub_arr = (
            np.frombuffer(
                b"".join(pk if len(pk) == 32 else bytes(32) for pk in pub_keys),
                np.uint8,
            )
            .reshape(-1, 32)
            .copy()
            if pub_keys
            else np.zeros((0, 32), np.uint8)
        )
        self._device_tables = None

    def device_tables(self):
        """Epoch tables as a device array, uploaded once and cached."""
        if self._device_tables is None:
            self._device_tables = jnp.asarray(self.tables)
        return self._device_tables


def prepare_batch(
    msgs: list[bytes],
    sigs: list[bytes],
    val_idx: np.ndarray,
    epoch: EpochTables,
) -> PreparedBatch:
    """Host prep for verify: msgs[i] signed by validator val_idx[i] with sigs[i]."""
    n = len(msgs)
    s_nib = np.zeros((n, curve.NWINDOWS), np.int32)
    h_nib = np.zeros((n, curve.NWINDOWS), np.int32)
    r_y = np.zeros((n, fe.NLIMB), np.int32)
    r_sign = np.zeros(n, np.int32)
    pre_ok = np.zeros(n, bool)
    for i, (msg, sig) in enumerate(zip(msgs, sigs)):
        vi = int(val_idx[i])
        if len(sig) != 64 or not (0 <= vi < len(epoch.pub_keys)):
            continue
        s = int.from_bytes(sig[32:], "little")
        if s >= host_ed.L:  # ScMinimal
            continue
        if not epoch.key_ok[vi]:
            continue
        pub = epoch.pub_keys[vi]
        h = (
            int.from_bytes(hashlib.sha512(sig[:32] + pub + msg).digest(), "little")
            % host_ed.L
        )
        s_nib[i] = curve.scalar_to_nibbles(s)
        h_nib[i] = curve.scalar_to_nibbles(h)
        r_bytes = bytearray(sig[:32])
        r_sign[i] = r_bytes[31] >> 7
        r_bytes[31] &= 0x7F  # low 255 bits only (radix-agnostic: byte level)
        r_y[i] = fe.bytes_to_limbs(bytes(r_bytes))
        pre_ok[i] = True
    a_tables = (
        epoch.tables[np.clip(val_idx, 0, max(len(epoch.pub_keys) - 1, 0))]
        if len(epoch.pub_keys)
        else np.zeros((n, 16, 4, fe.NLIMB), np.int32)
    )
    return PreparedBatch(s_nib, h_nib, a_tables, r_y, r_sign, pre_ok)


def verify_kernel(s_nibbles, h_nibbles, a_tables, r_y, r_sign, pre_ok, axis_name=None):
    """Device kernel: bool[B] of Go-equivalent signature validity.

    Jit/shard_map-able; all inputs are fixed-shape arrays. Computes
    P = [S]B + [h](-A) and accepts iff the canonical encoding of P equals
    the signature's R bytes — exactly Go's comparison, which also rejects
    non-canonical R encodings for free.
    """
    p = curve.double_scalar_mul(
        s_nibbles, h_nibbles, jnp.asarray(curve.BASE_TABLE), a_tables,
        axis_name=axis_name,
    )
    y, x_parity = curve.ext_encode(p)
    enc_match = fe.fe_is_equal_frozen(y, r_y) & (x_parity == r_sign)
    return enc_match & pre_ok


verify_kernel_jit = jax.jit(verify_kernel)


# ----------------------------------------------------------------------------
# Compact path: minimal H2D traffic, device-side epoch-table gather.
#
# The naive path above ships a gathered [B, 16, 4, 32] int32 table block per
# batch (~8 KiB/vote — measured to cap sustained throughput at ~80k votes/s
# on PCIe-class links). Here the per-epoch tables live on device once and
# votes ship as ~162 bytes each (u8 nibbles + R bytes + indices); the
# validator gather happens device-side inside the jit.


@dataclass
class CompactBatch:
    """Host-prepared compact device inputs for a batch of B checks."""

    s_nibbles: np.ndarray  # [B, 64] uint8, MSB-first nibbles of S
    h_nibbles: np.ndarray  # [B, 64] uint8, MSB-first nibbles of h mod L
    val_idx: np.ndarray  # [B] int32 validator index (clipped on device)
    r_y: np.ndarray  # [B, 32] uint8 low 255 bits of sig[:32]
    r_sign: np.ndarray  # [B] uint8 bit 255 of sig[:32]
    pre_ok: np.ndarray  # [B] bool host pre-checks passed
    # seconds the preparing thread spent parked behind host-pool shards
    # it didn't run itself (0.0 on the serial path) — prep accounting
    # only, never part of the batch's identity
    pool_wait_s: float = 0.0

    @property
    def size(self) -> int:
        return self.s_nibbles.shape[0]


def nibbles_from_le_bytes(b: np.ndarray) -> np.ndarray:
    """[B, 32] little-endian uint8 scalars -> [B, 64] MSB-first nibbles."""
    rev = b[:, ::-1]
    out = np.empty((b.shape[0], 64), np.uint8)
    out[:, 0::2] = rev >> 4
    out[:, 1::2] = rev & 15
    return out


# below this many rows a pooled prep loses to its own shard bookkeeping
# (job objects + events cost ~10 us/shard; a 256-row native prep is ~50 us)
_POOL_MIN_ROWS = 256


def prepare_compact(
    msgs: list[bytes],
    sigs: list[bytes],
    val_idx: np.ndarray,
    epoch: EpochTables,
    pool=None,
) -> CompactBatch:
    """Host prep: native C batch (SHA-512 + mod L + ScMinimal) when the
    compiler-built module is available, else the vectorized numpy path
    (``_prepare_compact_np``); ``_prepare_compact_py`` is the per-row
    parity oracle for both (tests/test_native_prep.py, test_mesh_engine).

    ``pool`` (engine.hostprep.HostPrepPool): shard the rows contiguously
    across workers — every row is prepared independently, so the
    concatenated shards are byte-identical to the serial prep. The native
    prep releases the GIL inside ctypes, so sharding is real parallelism;
    the caller reads the queue-wait share back off
    ``CompactBatch.pool_wait_s``."""
    from .. import native

    fn = (
        _prepare_compact_native
        if len(msgs) and native.available()
        else _prepare_compact_np
    )
    n = len(msgs)
    if pool is None or pool.workers <= 1 or n < _POOL_MIN_ROWS:
        return fn(msgs, sigs, val_idx, epoch)
    if getattr(pool, "backend", "thread") == "process":
        # typed shared-memory path: workers run the same row core
        # (prep_proc.prep_rows_cat[_native]) over contiguous shards of
        # the cat-form batch, writing straight into the output segment.
        # Falls back to the thread shards below if the pool has degraded.
        out = pool.prepare_compact_shm(msgs, sigs, np.asarray(val_idx), epoch)
        if out is not None:
            s_nib, h_nib, vidx, r_y, r_sign, pre_ok, wait_s = out
            return CompactBatch(
                s_nib, h_nib, vidx, r_y, r_sign, pre_ok, pool_wait_s=wait_s
            )
    vi = np.asarray(val_idx)

    def _shard(lo: int, hi: int) -> CompactBatch:
        return fn(msgs[lo:hi], sigs[lo:hi], vi[lo:hi], epoch)

    parts, wait_s = pool.map_shards(n, _shard)
    if len(parts) == 1:
        parts[0].pool_wait_s = wait_s
        return parts[0]
    out = CompactBatch(
        np.concatenate([p.s_nibbles for p in parts]),
        np.concatenate([p.h_nibbles for p in parts]),
        np.concatenate([p.val_idx for p in parts]),
        np.concatenate([p.r_y for p in parts]),
        np.concatenate([p.r_sign for p in parts]),
        np.concatenate([p.pre_ok for p in parts]),
        pool_wait_s=wait_s,
    )
    return out


def _prepare_compact_native(
    msgs: list[bytes],
    sigs: list[bytes],
    val_idx: np.ndarray,
    epoch: EpochTables,
) -> CompactBatch:
    from .. import native

    n = len(msgs)
    n_vals = len(epoch.pub_keys)
    vi = np.asarray(val_idx, dtype=np.int64)
    clipped = np.clip(vi, 0, max(n_vals - 1, 0))
    idx_ok = (vi >= 0) & (vi < n_vals)
    sig_ok = np.fromiter((len(s) == 64 for s in sigs), bool, n)
    sig_cat = (
        b"".join(sigs)
        if bool(sig_ok.all())
        else b"".join(s if len(s) == 64 else _ZERO64 for s in sigs)
    )
    sig_arr = np.frombuffer(sig_cat, np.uint8).reshape(n, 64)
    offs = np.zeros(n + 1, np.int64)
    np.cumsum(np.fromiter((len(m) for m in msgs), np.int64, n), out=offs[1:])
    msg_cat = np.frombuffer(b"".join(msgs), np.uint8)
    ok_in = (idx_ok & sig_ok & (epoch.key_ok[clipped] if n_vals else False)).astype(
        np.uint8
    )
    pubs = epoch.pub_arr[clipped] if n_vals else np.zeros((n, 32), np.uint8)
    s_le, h_le, pre_ok = native.prep_batch(msg_cat, offs, sig_arr, pubs, ok_in)
    # match the Python path bit-for-bit: failed rows stay all-zero
    r_y = np.where(pre_ok[:, None], sig_arr[:, :32], 0).astype(np.uint8)
    r_sign = (r_y[:, 31] >> 7).astype(np.uint8)
    r_y[:, 31] &= 0x7F
    return CompactBatch(
        nibbles_from_le_bytes(s_le),
        nibbles_from_le_bytes(h_le),
        clipped.astype(np.int32),
        r_y,
        r_sign,
        pre_ok,
    )


_ZERO64 = bytes(64)


def _prepare_compact_py(
    msgs: list[bytes],
    sigs: list[bytes],
    val_idx: np.ndarray,
    epoch: EpochTables,
) -> CompactBatch:
    n = len(msgs)
    n_vals = len(epoch.pub_keys)
    vi = np.asarray(val_idx, dtype=np.int64)
    idx_ok = (vi >= 0) & (vi < n_vals)
    sig_arr = np.zeros((n, 64), np.uint8)
    s_le = np.zeros((n, 32), np.uint8)
    h_le = np.zeros((n, 32), np.uint8)
    pre_ok = np.zeros(n, bool)
    for i in range(n):
        sig = sigs[i]
        if len(sig) != 64 or not idx_ok[i] or not epoch.key_ok[vi[i]]:
            continue
        s = int.from_bytes(sig[32:], "little")
        if s >= host_ed.L:  # ScMinimal
            continue
        pub = epoch.pub_keys[vi[i]]
        h = (
            int.from_bytes(hashlib.sha512(sig[:32] + pub + msgs[i]).digest(), "little")
            % host_ed.L
        )
        sig_arr[i] = np.frombuffer(sig, np.uint8)
        s_le[i] = np.frombuffer(sig[32:], np.uint8)
        h_le[i] = np.frombuffer(h.to_bytes(32, "little"), np.uint8)
        pre_ok[i] = True
    r_y = sig_arr[:, :32].copy()
    r_sign = (r_y[:, 31] >> 7).astype(np.uint8)
    r_y[:, 31] &= 0x7F
    return CompactBatch(
        nibbles_from_le_bytes(s_le),
        nibbles_from_le_bytes(h_le),
        np.clip(vi, 0, max(n_vals - 1, 0)).astype(np.int32),
        r_y,
        r_sign,
        pre_ok,
    )


def _prepare_compact_np(
    msgs: list[bytes],
    sigs: list[bytes],
    val_idx: np.ndarray,
    epoch: EpochTables,
) -> CompactBatch:
    """Vectorized numpy prep — the serving path when native/_prep.so is
    unavailable (no C compiler in the container).

    Bit-identical to ``_prepare_compact_py`` (pinned by
    tests/test_mesh_engine.py). The row math lives in
    ``prep_proc.prep_rows_cat`` — the SAME function process-pool workers
    run over shared-memory shards — so there is exactly one numpy
    implementation and thread/process/serial assembly parity holds by
    construction, not by duplicated code."""
    from ..prep_proc import cat_msgs, cat_sigs, prep_rows_cat

    msg_cat, offs = cat_msgs(msgs)
    sig_arr, sig_ok = cat_sigs(sigs)
    s_nib, h_nib, vidx, r_y, r_sign, ok = prep_rows_cat(
        msg_cat, offs, sig_arr, sig_ok,
        np.asarray(val_idx, dtype=np.int64), epoch.pub_arr, epoch.key_ok,
    )
    return CompactBatch(s_nib, h_nib, vidx, r_y, r_sign, ok)


def verify_kernel_gather(
    s_nibbles, h_nibbles, val_idx, tables, r_y, r_sign, pre_ok, axis_name=None
):
    """Device kernel with on-device epoch-table gather.

    tables: [V, 16, 4, 32] int32, device-resident per epoch. Per-vote inputs
    are compact uint8; widened to int32 on device. Decisions are identical
    to ``verify_kernel``; the per-item window table is never materialized
    (``curve.double_scalar_mul_indexed`` selects inside the scan step).
    """
    p = curve.double_scalar_mul_indexed(
        s_nibbles.astype(jnp.int32),
        h_nibbles.astype(jnp.int32),
        jnp.asarray(curve.BASE_TABLE),
        tables,
        val_idx,
        axis_name=axis_name,
    )
    y, x_parity = curve.ext_encode(p)
    enc_match = fe.fe_is_equal_frozen(y, fe.bytes_to_limbs_device(r_y)) & (
        x_parity == r_sign.astype(jnp.int32)
    )
    return enc_match & pre_ok


def verify_batch(batch: PreparedBatch) -> np.ndarray:
    """Convenience host API: prepared batch -> bool[B] validity."""
    return np.asarray(
        verify_kernel_jit(
            jnp.asarray(batch.s_nibbles),
            jnp.asarray(batch.h_nibbles),
            jnp.asarray(batch.a_tables),
            jnp.asarray(batch.r_y),
            jnp.asarray(batch.r_sign),
            jnp.asarray(batch.pre_ok),
        )
    )
