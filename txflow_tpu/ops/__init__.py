"""Device kernels: batched field/curve arithmetic, ed25519 verify, stake tally."""

from . import curve, ed25519_batch, fe  # noqa: F401
