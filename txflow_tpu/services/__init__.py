"""Node-attached services (reference node/node.go:211-238 indexer slot)."""

from .indexer import TxIndexer

__all__ = ["TxIndexer"]
