"""Tx indexer service: committed txs queryable by hash, height, and tags
(the slot the reference fills with tendermint's upstream indexer service,
node/node.go:211-238 — an event-bus subscriber writing a KV index).

Subscribes to per-tx commit events from BOTH paths (the fast path's
EventTx fires from TxExecutor, the block path's from BlockExecutor) and
indexes:

- ``tx:<hash>``            -> JSON record (height, code, tags, path)
- ``height:<H>:<hash>``    -> presence row (range scans by height)
- ``tag:<key>=<val>:<hash>`` -> presence row (tag search)

Queries: ``get(hash)``, ``by_height(h)``, ``search(key, value)``.
"""

from __future__ import annotations

import json
import threading

from ..store.db import DB
from ..utils.events import EventBus, EventTx


class TxIndexer:
    def __init__(self, db: DB):
        self.db = db
        self._mtx = threading.Lock()

    # -- write side (event-bus subscriber) --

    def subscribe(self, bus: EventBus) -> None:
        bus.subscribe_callback(EventTx, self._on_tx)

    def _on_tx(self, event) -> None:
        data = event.data
        try:
            self.index(
                tx_hash=data.tx_hash,
                height=data.height,
                code=data.result_code,
                tags=getattr(data, "tags", None) or [],
            )
        except Exception:
            pass  # indexing must never break commit event delivery

    def index(
        self,
        tx_hash: str,
        height: int,
        code: int = 0,
        tags: list[tuple[bytes, bytes]] | None = None,
    ) -> None:
        tags = tags or []
        record = {
            "hash": tx_hash,
            "height": height,
            "code": code,
            "tags": [[k.decode("latin1"), v.decode("latin1")] for k, v in tags],
        }
        with self._mtx:
            self.db.set(b"tx:" + tx_hash.encode(), json.dumps(record).encode())
            self.db.set(b"height:%016d:%s" % (height, tx_hash.encode()), b"1")
            for k, v in tags:
                # tag bytes are arbitrary app data: hex-encode them so a
                # value containing the row delimiters cannot alias other
                # rows or corrupt the parsed-out hash
                self.db.set(_tag_row(k, v) + tx_hash.encode(), b"1")

    # -- read side --

    def get(self, tx_hash: str) -> dict | None:
        raw = self.db.get(b"tx:" + tx_hash.encode())
        return json.loads(raw) if raw is not None else None

    def by_height(self, height: int) -> list[str]:
        prefix = b"height:%016d:" % height
        out = []
        for k, _ in self.db.iterate(prefix, prefix + b"\xff"):
            out.append(k[len(prefix):].decode())
        return out

    def search(self, key: bytes, value: bytes) -> list[str]:
        prefix = _tag_row(key, value)
        out = []
        for k, _ in self.db.iterate(prefix, prefix + b"\xff"):
            out.append(k[len(prefix):].decode())
        return out


def _tag_row(key: bytes, value: bytes) -> bytes:
    return b"tag:" + key.hex().encode() + b"=" + value.hex().encode() + b":"
