"""ProcNet: an N-node validator network in SEPARATE OS processes over
real TCP — the multi-process extension of LocalNet's in-proc testnet.

Each child runs ``python -m txflow_tpu.node.procnode`` (one JSON spec
line in, one JSON info line out); the parent broadcasts the peer address
map and every child's PEX ensure-loop dials the mesh together. All
interaction from then on is an external client's: HTTP RPC and the
Prometheus exposition over real sockets. ``tools/soak.py --overload``
drives its overload/chaos soak through this harness.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import urllib.request

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class ProcNet:
    def __init__(self, n: int = 3, spec: dict | None = None):
        """spec: the procnode spec-line template (see procnode.py); the
        parent fills in ``index``/``n`` per child. Per-child overrides go
        under spec["per_node"][index] and are merged on top."""
        self.n = n
        self.spec = dict(spec or {})
        self.children: list[subprocess.Popen] = []
        self.infos: list[dict] = []
        self._specs: list[dict] = []  # resolved per-child spec (restarts)

    # -- lifecycle --

    def start(self, timeout: float = 60.0) -> None:
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO_ROOT)
        per_node = self.spec.pop("per_node", {}) or {}
        for i in range(self.n):
            child = subprocess.Popen(
                [sys.executable, "-m", "txflow_tpu.node.procnode"],
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                env=env,
            )
            self.children.append(child)
            spec = dict(self.spec, index=i, n=self.n)
            spec.update(per_node.get(i) or per_node.get(str(i)) or {})
            self._specs.append(spec)
            child.stdin.write(json.dumps(spec) + "\n")
            child.stdin.flush()
        deadline = time.monotonic() + timeout
        for i, child in enumerate(self.children):
            line = child.stdout.readline()
            if not line:
                raise RuntimeError(
                    f"procnode {i} died during startup:\n{self._stderr_tail(i)}"
                )
            self.infos.append(json.loads(line))
        peers = {info["node_id"]: info["p2p"] for info in self.infos}
        for child in self.children:
            child.stdin.write(json.dumps({"peers": peers}) + "\n")
            child.stdin.flush()
        # the mesh forms via each child's PEX ensure-loop; wait for full
        # connectivity before handing the net to the caller
        while True:
            try:
                if all(
                    self.rpc_json(i, "/net_info")["result"]["n_peers"] >= self.n - 1
                    for i in range(self.n)
                ):
                    return
            except (OSError, ValueError, KeyError):
                pass
            if time.monotonic() > deadline:
                raise RuntimeError(
                    "procnet mesh did not form: "
                    + ", ".join(
                        str(self.rpc_json(i, "/net_info")["result"]["n_peers"])
                        for i in range(self.n)
                    )
                )
            time.sleep(0.1)

    # -- crash / wipe / rejoin (the soak's wipe-revive-rejoin phase) --

    def kill_node(self, i: int) -> None:
        """SIGKILL child i mid-run (no graceful stop: a crash). Peers see
        the TCP links die; the child's durable state is whatever its
        stores fsynced."""
        child = self.children[i]
        child.kill()
        child.wait(timeout=10)

    def restart_node(self, i: int, wipe: bool = False, timeout: float = 60.0) -> None:
        """Respawn child i with its original spec (same deterministic
        validator identity/node key). ``wipe=True`` first deletes its
        data_dir — the rebuilt node starts empty and must recover the
        committed set from peers via catch-up sync. The new child gets
        the current peer map; the mesh reforms through its outbound PEX
        dials (peers' stale book entries don't matter — inbound links
        count)."""
        spec = dict(self._specs[i])
        if wipe:
            data_dir = spec.get("data_dir")
            if not data_dir:
                raise RuntimeError(f"procnode {i} has no data_dir to wipe")
            import shutil

            shutil.rmtree(data_dir, ignore_errors=True)
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO_ROOT)
        child = subprocess.Popen(
            [sys.executable, "-m", "txflow_tpu.node.procnode"],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=env,
        )
        self.children[i] = child
        child.stdin.write(json.dumps(spec) + "\n")
        child.stdin.flush()
        deadline = time.monotonic() + timeout
        line = child.stdout.readline()
        if not line:
            raise RuntimeError(
                f"procnode {i} died during restart:\n{self._stderr_tail(i)}"
            )
        self.infos[i] = json.loads(line)
        peers = {info["node_id"]: info["p2p"] for info in self.infos}
        child.stdin.write(json.dumps({"peers": peers}) + "\n")
        child.stdin.flush()
        while True:
            try:
                if self.rpc_json(i, "/net_info")["result"]["n_peers"] >= 1:
                    return
            except (OSError, ValueError, KeyError):
                pass
            if time.monotonic() > deadline:
                raise RuntimeError(f"restarted procnode {i} never rejoined the mesh")
            time.sleep(0.1)

    # -- live weather control (netem/) --

    def _command(self, i: int, cmd: dict, ok: str, timeout: float = 10.0) -> dict:
        """Send one control command to child ``i`` and wait for its ack
        line (``{"ok": <ok>, ...}``); returns the ack dict."""
        child = self.children[i]
        child.stdin.write(json.dumps(cmd) + "\n")
        child.stdin.flush()
        return self._wait_ack(i, ok, timeout)

    def set_netem(self, profile: str, links: dict | None = None, timeout: float = 10.0) -> None:
        """Swap every child's link weather live (children must have been
        started with a ``netem`` spec). Writes one control line per child
        and waits for each ack, so on return the whole fleet is on the new
        profile (frames already in flight drain under the old one)."""
        cmd = json.dumps({"cmd": "netem", "profile": profile, "links": links})
        for child in self.children:
            child.stdin.write(cmd + "\n")
            child.stdin.flush()
        for i in range(len(self.children)):
            self._wait_ack(i, "netem", timeout)

    def _wait_ack(self, i: int, ok: str, timeout: float) -> dict:
        deadline = time.monotonic() + timeout
        child = self.children[i]
        while True:
            line = child.stdout.readline()
            if not line:
                raise RuntimeError(
                    f"procnode {i} died during {ok} command:\n{self._stderr_tail(i)}"
                )
            try:
                ack = json.loads(line)
            except ValueError:
                continue  # stray print from the child: skip
            if ack.get("ok") == ok:
                return ack
            if "err" in ack:
                raise RuntimeError(f"procnode {i} {ok} command: {ack['err']}")
            if time.monotonic() > deadline:
                raise RuntimeError(f"procnode {i} {ok} ack timed out")

    def set_adversary(
        self,
        i: int,
        active: bool,
        schedule: dict | None = None,
        timeout: float = 10.0,
    ) -> dict:
        """Arm/disarm child ``i``'s adversary flood (spec field
        ``adversary``, or ``schedule`` to swap in a fresh one while
        disarmed); returns the ack, which carries the drivers' cumulative
        ``emitted`` count (on disarm: the stopped fleet's final total)."""
        cmd: dict = {"cmd": "adversary", "active": bool(active)}
        if schedule is not None:
            cmd["schedule"] = schedule
        return self._command(i, cmd, "adversary", timeout)

    def set_scenario(self, info: dict | None, timeout: float = 10.0) -> None:
        """Publish (``info`` dict) or clear (``None``) the scenario tile
        on EVERY child's /health + txflow_scenario_* surfaces."""
        cmd = json.dumps({"cmd": "scenario", "info": info})
        for child in self.children:
            child.stdin.write(cmd + "\n")
            child.stdin.flush()
        for i in range(len(self.children)):
            self._wait_ack(i, "scenario", timeout)

    def stop(self, timeout: float = 15.0) -> None:
        for child in self.children:
            try:
                child.stdin.close()  # procnode exits on stdin EOF
            except OSError:
                pass
        deadline = time.monotonic() + timeout
        for child in self.children:
            try:
                child.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                child.kill()
        self.children = []
        self.infos = []
        self._specs = []

    def _stderr_tail(self, i: int, n: int = 4000) -> str:
        try:
            return (self.children[i].stderr.read() or "")[-n:]
        except (OSError, ValueError):
            return "<stderr unavailable>"

    # -- client surface (everything over real sockets) --

    def rpc_addr(self, i: int) -> tuple[str, int]:
        host, port = self.infos[i]["rpc"]
        return host, int(port)

    def rpc_json(self, i: int, path: str, timeout: float = 30.0) -> dict:
        host, port = self.rpc_addr(i)
        with urllib.request.urlopen(
            f"http://{host}:{port}{path}", timeout=timeout
        ) as r:
            return json.loads(r.read().decode())

    def metrics_value(self, i: int, name: str) -> float | None:
        """Sum of the samples for one metric name in node i's Prometheus
        exposition; None when the metric is absent."""
        host, port = self.rpc_addr(i)
        with urllib.request.urlopen(
            f"http://{host}:{port}/metrics", timeout=30
        ) as r:
            text = r.read().decode()
        total, seen = 0.0, False
        for line in text.splitlines():
            if line.startswith(name + " "):
                total += float(line.split()[-1])
                seen = True
        return total if seen else None
