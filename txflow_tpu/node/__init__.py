"""Node assembly (reference node/node.go) + in-process validator networks.

``Node`` is the composition root wiring stores, pools, reactors, the
fast-path aggregation engine, and (as later layers land) the block-path
consensus and RPC surface — the analog of ``node.NewNode``
(node/node.go:555-765). ``LocalNet`` builds N fully-connected nodes over
in-memory pipes: the reference's in-process-testnet pattern
(p2p.MakeConnectedSwitches) used by the BASELINE measurement configs.
"""

from .node import Node, NodeConfig
from .localnet import LocalNet
from .procnet import ProcNet

__all__ = ["Node", "NodeConfig", "LocalNet", "ProcNet"]
