"""Node identity + greeting types (reference node/id.go:9-35).

The reference's file is vestigial — ``SignGreeting`` is unimplemented and
returns nil — but the shapes are part of its public surface, so they exist
here too, with the signing actually implemented (a greeting is just a
deterministic byte string under the node key; refusing to leave a stub
costs five lines).
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field

from ..crypto import ed25519


@dataclass
class NodeID:
    name: str
    pub_key: bytes  # ed25519, 32 bytes


@dataclass
class NodeGreeting:
    node_id: NodeID
    version: str
    chain_id: str
    message: str
    time_ns: int = field(default_factory=_time.time_ns)

    def sign_bytes(self) -> bytes:
        # length-prefixed fields: free-form strings must not be able to
        # shift bytes across field boundaries (a '|' join would let
        # version='a|b' collide with chain_id-shifted variants)
        from ..codec import amino

        out = bytearray()
        for f in (
            self.node_id.name.encode(),
            self.node_id.pub_key,
            self.version.encode(),
            self.chain_id.encode(),
            self.message.encode(),
            str(self.time_ns).encode(),
        ):
            out += amino.length_prefixed(f)
        return bytes(out)


@dataclass
class SignedNodeGreeting:
    greeting: NodeGreeting
    signature: bytes

    def verify(self) -> bool:
        return ed25519.verify(
            self.greeting.node_id.pub_key,
            self.greeting.sign_bytes(),
            self.signature,
        )


@dataclass
class PrivNodeID:
    node_id: NodeID
    seed: bytes  # ed25519 seed

    def sign_greeting(
        self, version: str, chain_id: str, message: str = ""
    ) -> SignedNodeGreeting:
        g = NodeGreeting(self.node_id, version, chain_id, message)
        return SignedNodeGreeting(g, ed25519.sign(self.seed, g.sign_bytes()))
