"""Child-process node entrypoint for multi-process real-socket nets.

Run as ``python -m txflow_tpu.node.procnode``: reads ONE JSON spec line
from stdin, assembles validator ``index`` of an ``n``-validator set whose
keys are derived deterministically from ``seed_prefix`` (every child
derives the SAME set with no key exchange), starts the node with real
TCP listen + ephemeral RPC, prints one JSON info line on stdout, then
seeds its PEX address book from the peers line the parent broadcasts —
the PEX ensure-loop dials the mesh together from there.

Spec line fields (all optional except index/n/seed_prefix):

    {"index": 0, "n": 3, "chain_id": "txflow-proc",
     "seed_prefix": "soak1",
     "mempool": {"size": 200},             # MempoolConfig field overrides
     "engine": {"max_batch": 64},          # EngineConfig field overrides
     "trace": {"sample_rate": 16},         # TraceConfig field overrides
     "admission": {"retry_after": 0.5},    # AdmissionConfig kwargs
     "health": {"score_floor": -4.0},      # HealthConfig kwargs
     "fault": {"drop": 0.02, "seed": 7},   # FaultSpec kwargs (chaos on)
     "regossip": 0.25,
     "data_dir": "/tmp/soak/node0",        # durable stores + WALs (wipe drills)
     "sync": {"lag_threshold": 1},         # SyncConfig kwargs (or false = off)
     "blackhole": {"start": 3.0, "duration": 2.0},
     "netem": {"profile": "lossy-edge", "seed": 11},  # WAN weather (netem/)
     "net": true}                          # adaptive transport (p2p/adaptive.py)

``netem`` installs a LinkShaper on the switch (before start, so every
dialed/accepted link is shaped); ``net`` enables the adaptive transport
(defaults ON whenever netem is set). After startup the park loop doubles
as a control channel: each stdin line that parses as JSON is a live
command — ``{"cmd": "netem", "profile": "congested"}`` swaps the weather
and acks ``{"ok": "netem", "profile": ...}`` on stdout (ProcNet.set_netem
drives this to walk one long-lived net through a scenario matrix).

``blackhole`` makes THIS child's chaos router partition itself away for
the window: its outbound gossip black-holes, so its PEERS observe
send-attempts-without-progress, evict it by score, and heal the link
through their address-book re-dial (dial handshakes bypass chaos) —
the real-network self-healing path ISSUE 6's soak asserts.

The child exits when its stdin closes (parent teardown) or on SIGTERM.
"""

from __future__ import annotations

import hashlib
import json
import sys
import threading
import time


def main() -> None:
    spec = json.loads(sys.stdin.readline())
    index = int(spec["index"])
    n = int(spec["n"])
    prefix = spec.get("seed_prefix", "procnet")
    chain_id = spec.get("chain_id", "txflow-proc")

    from ..abci.kvstore import KVStoreApplication
    from ..faults.chaos import ChaosRouter
    from ..faults.plan import FaultSpec
    from ..types.priv_validator import MockPV
    from ..types.validator import Validator, ValidatorSet
    from ..utils.config import test_config
    from .node import Node, NodeConfig

    pvs = [
        MockPV(hashlib.sha256(f"{prefix}-val{i}".encode()).digest())
        for i in range(n)
    ]
    vs = ValidatorSet([Validator.from_pub_key(pv.get_pub_key(), 10) for pv in pvs])
    by_addr = {pv.get_address(): pv for pv in pvs}
    me = by_addr[vs.get_by_index(index).address]

    config = test_config()
    for k, v in (spec.get("mempool") or {}).items():
        setattr(config.mempool, k, v)
    for k, v in (spec.get("engine") or {}).items():
        setattr(config.engine, k, v)
    for k, v in (spec.get("trace") or {}).items():
        setattr(config.trace, k, v)

    # durable stores under data_dir (wipe-revive drills: the parent can
    # kill this child, delete the dir, and restart it — the rebuilt node
    # must recover the committed set from peers via catch-up sync)
    dbs = {}
    data_dir = spec.get("data_dir")
    if data_dir:
        import os

        from ..store.db import FileDB

        os.makedirs(data_dir, exist_ok=True)
        dbs = {
            "tx_store_db": FileDB(f"{data_dir}/txstore.db"),
            "state_db": FileDB(f"{data_dir}/state.db"),
            "block_db": FileDB(f"{data_dir}/blocks.db"),
        }
        config.mempool.wal_dir = data_dir

    admission_config = None
    if spec.get("admission"):
        from ..admission import AdmissionConfig

        admission_config = AdmissionConfig(**spec["admission"])
    health_config = None
    if spec.get("health"):
        from ..health.config import HealthConfig

        health_config = HealthConfig(**spec["health"])
    sync_on = spec.get("sync", True)
    sync_config = None
    if isinstance(sync_on, dict):
        from ..sync import SyncConfig

        sync_config = SyncConfig(**sync_on)
        sync_on = True

    shaper = None
    netem_spec = spec.get("netem")
    if netem_spec:
        from ..netem import LinkShaper

        shaper = LinkShaper(
            netem_spec.get("profile", "lan"),
            seed=int(netem_spec.get("seed", 0)),
            links=netem_spec.get("links"),
        )
    net_on = spec.get("net", shaper is not None)
    net_config = None
    if isinstance(net_on, dict):
        from ..p2p.adaptive import NetTransportConfig

        net_config = NetTransportConfig(**net_on)
        net_on = True

    node = Node(
        node_id=f"proc-{index}",
        chain_id=chain_id,
        val_set=vs,
        app=KVStoreApplication(),
        priv_val=me,
        node_config=NodeConfig(
            config=config,
            use_device_verifier=False,
            enable_consensus=False,
            rpc_port=0,
            node_key_seed=hashlib.sha256(f"{prefix}-key-{index}".encode()).digest(),
            regossip_interval=spec.get("regossip", 0.25),
            admission_config=admission_config,
            health_config=health_config,
            sync=bool(sync_on),
            sync_config=sync_config,
            net=bool(net_on),
            net_config=net_config,
            link_shaper=shaper,
        ),
        **dbs,
    )

    router = None
    if spec.get("fault"):
        # install BEFORE start so every peer (dialed or accepted) gets the
        # interceptor; each child has its OWN router — partitioning this
        # node's id black-holes only its outbound gossip
        router = ChaosRouter(FaultSpec(**spec["fault"]))
        router.install([node.switch])

    node.start()
    host, port = node.switch.listen_tcp("127.0.0.1", 0)
    rhost, rport = node.rpc.addr
    print(
        json.dumps(
            {"node_id": node.switch.node_id, "p2p": [host, port], "rpc": [rhost, rport]}
        ),
        flush=True,
    )

    # peers line: {"peers": {node_id: [host, port], ...}} — seed the book;
    # the PEX ensure-loop does the dialing (and keeps re-dialing)
    peers = json.loads(sys.stdin.readline())["peers"]
    for nid, (phost, pport) in peers.items():
        if nid != node.switch.node_id and node.address_book is not None:
            node.address_book.add(nid, phost, int(pport))

    bh = spec.get("blackhole")
    if bh and router is not None:

        def _blackhole(r=router, me_id=node.switch.node_id):
            time.sleep(float(bh.get("start", 3.0)))
            r.partition([me_id])
            time.sleep(float(bh.get("duration", 2.0)))
            r.heal()

        threading.Thread(target=_blackhole, name="blackhole", daemon=True).start()

    # park until the parent closes our stdin; lines that parse as JSON
    # commands are live controls (weather swaps), everything else ignored
    while True:
        line = sys.stdin.readline()
        if not line:
            break
        line = line.strip()
        if not line:
            continue
        try:
            cmd = json.loads(line)
        except ValueError:
            continue
        if not isinstance(cmd, dict):
            continue
        if cmd.get("cmd") == "netem":
            if shaper is None:
                print(json.dumps({"err": "netem not configured"}), flush=True)
                continue
            shaper.set_profile(cmd.get("profile", "lan"), links=cmd.get("links"))
            print(
                json.dumps({"ok": "netem", "profile": cmd.get("profile", "lan")}),
                flush=True,
            )
    node.stop()


if __name__ == "__main__":
    main()
