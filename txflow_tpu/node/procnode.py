"""Child-process node entrypoint for multi-process real-socket nets.

Run as ``python -m txflow_tpu.node.procnode``: reads ONE JSON spec line
from stdin, assembles validator ``index`` of an ``n``-validator set whose
keys are derived deterministically from ``seed_prefix`` (every child
derives the SAME set with no key exchange), starts the node with real
TCP listen + ephemeral RPC, prints one JSON info line on stdout, then
seeds its PEX address book from the peers line the parent broadcasts —
the PEX ensure-loop dials the mesh together from there.

Spec line fields (all optional except index/n/seed_prefix):

    {"index": 0, "n": 3, "chain_id": "txflow-proc",
     "seed_prefix": "soak1",
     "powers": [40, 10, 10],               # per-validator stake (default 10 each)
     "consensus": true,                    # full block path (default: fast path only)
     "byzantine": {"min_samples": 24},     # ByzantineConfig kwargs (vote-gossip breaker)
     "adversary": {"ghost_txs": ["aa.."],  # scenario-grid flood schedule, armed
                   "drivers": [{...}]},    #   later via the adversary command
     "mempool": {"size": 200},             # MempoolConfig field overrides
     "engine": {"max_batch": 64},          # EngineConfig field overrides
     "trace": {"sample_rate": 16},         # TraceConfig field overrides
     "admission": {"retry_after": 0.5},    # AdmissionConfig kwargs
     "health": {"score_floor": -4.0},      # HealthConfig kwargs
     "fault": {"drop": 0.02, "seed": 7},   # FaultSpec kwargs (chaos on)
     "regossip": 0.25,
     "data_dir": "/tmp/soak/node0",        # durable stores + WALs (wipe drills)
     "sync": {"lag_threshold": 1},         # SyncConfig kwargs (or false = off)
     "blackhole": {"start": 3.0, "duration": 2.0},
     "netem": {"profile": "lossy-edge", "seed": 11},  # WAN weather (netem/)
     "net": true}                          # adaptive transport (p2p/adaptive.py)

``netem`` installs a LinkShaper on the switch (before start, so every
dialed/accepted link is shaped); ``net`` enables the adaptive transport
(defaults ON whenever netem is set). After startup the park loop doubles
as a control channel: each stdin line that parses as JSON is a live
command, acked with one JSON line on stdout —

- ``{"cmd": "netem", "profile": "congested"}`` swaps the weather and
  acks ``{"ok": "netem", "profile": ...}`` (ProcNet.set_netem drives
  this to walk one long-lived net through a scenario matrix);
- ``{"cmd": "adversary", "active": true|false}`` arms/disarms the
  spec's ``adversary`` flood schedule on THIS child (disarms/rearms its
  honest fast-path signer), acking ``{"ok": "adversary", ...}``;
- ``{"cmd": "scenario", "info": {...}}`` publishes the scenario tile
  currently driving this node into /health's "scenario" section and the
  ``txflow_scenario_*`` gauges (``info: null`` clears it).

``blackhole`` makes THIS child's chaos router partition itself away for
the window: its outbound gossip black-holes, so its PEERS observe
send-attempts-without-progress, evict it by score, and heal the link
through their address-book re-dial (dial handshakes bypass chaos) —
the real-network self-healing path ISSUE 6's soak asserts.

The child exits when its stdin closes (parent teardown) or on SIGTERM.
"""

from __future__ import annotations

import hashlib
import json
import sys
import threading
import time


def main() -> None:
    spec = json.loads(sys.stdin.readline())
    index = int(spec["index"])
    n = int(spec["n"])
    prefix = spec.get("seed_prefix", "procnet")
    chain_id = spec.get("chain_id", "txflow-proc")

    from ..abci.kvstore import KVStoreApplication
    from ..faults.chaos import ChaosRouter
    from ..faults.plan import FaultSpec
    from ..types.priv_validator import MockPV
    from ..types.validator import Validator, ValidatorSet
    from ..utils.config import test_config
    from .node import Node, NodeConfig

    pvs = [
        MockPV(hashlib.sha256(f"{prefix}-val{i}".encode()).digest())
        for i in range(n)
    ]
    # per-validator voting powers (scenario grid's stake axis: whale /
    # longtail / churning distributions); default stays uniform 10.
    # Child i IS pvs[i] with powers[i] — the same spec list on every
    # child, so the parent's index arithmetic (who is the whale, who is
    # the adversary) matches the children's without an address sort in
    # between (ValidatorSet orders by address internally regardless).
    powers = spec.get("powers") or [10] * n
    vs = ValidatorSet(
        [
            Validator.from_pub_key(pv.get_pub_key(), int(p))
            for pv, p in zip(pvs, powers)
        ]
    )
    me = pvs[index]

    config = test_config()
    # "consensus": true runs the full block path (the scenario grid's
    # churning-stake tiles commit val: txs through EndBlock -> H+2
    # restage). skip_timeout_commit keeps block cadence test-shaped.
    consensus_on = bool(spec.get("consensus"))
    if consensus_on:
        config.consensus.skip_timeout_commit = True
    for k, v in (spec.get("mempool") or {}).items():
        setattr(config.mempool, k, v)
    for k, v in (spec.get("engine") or {}).items():
        setattr(config.engine, k, v)
    for k, v in (spec.get("trace") or {}).items():
        setattr(config.trace, k, v)

    # durable stores under data_dir (wipe-revive drills: the parent can
    # kill this child, delete the dir, and restart it — the rebuilt node
    # must recover the committed set from peers via catch-up sync)
    dbs = {}
    data_dir = spec.get("data_dir")
    if data_dir:
        import os

        from ..store.db import FileDB

        os.makedirs(data_dir, exist_ok=True)
        dbs = {
            "tx_store_db": FileDB(f"{data_dir}/txstore.db"),
            "state_db": FileDB(f"{data_dir}/state.db"),
            "block_db": FileDB(f"{data_dir}/blocks.db"),
        }
        config.mempool.wal_dir = data_dir

    admission_config = None
    if spec.get("admission"):
        from ..admission import AdmissionConfig

        admission_config = AdmissionConfig(**spec["admission"])
    health_config = None
    if spec.get("health"):
        from ..health.config import HealthConfig

        health_config = HealthConfig(**spec["health"])
    byzantine_config = None
    if spec.get("byzantine"):
        from ..health.byzantine import ByzantineConfig

        byzantine_config = ByzantineConfig(**spec["byzantine"])
    sync_on = spec.get("sync", True)
    sync_config = None
    if isinstance(sync_on, dict):
        from ..sync import SyncConfig

        sync_config = SyncConfig(**sync_on)
        sync_on = True

    shaper = None
    netem_spec = spec.get("netem")
    if netem_spec:
        from ..netem import LinkShaper

        shaper = LinkShaper(
            netem_spec.get("profile", "lan"),
            seed=int(netem_spec.get("seed", 0)),
            links=netem_spec.get("links"),
        )
    net_on = spec.get("net", shaper is not None)
    net_config = None
    if isinstance(net_on, dict):
        from ..p2p.adaptive import NetTransportConfig

        net_config = NetTransportConfig(**net_on)
        net_on = True

    node = Node(
        node_id=f"proc-{index}",
        chain_id=chain_id,
        val_set=vs,
        app=KVStoreApplication(),
        priv_val=me,
        node_config=NodeConfig(
            config=config,
            use_device_verifier=False,
            enable_consensus=consensus_on,
            rpc_port=0,
            byzantine_config=byzantine_config,
            node_key_seed=hashlib.sha256(f"{prefix}-key-{index}".encode()).digest(),
            regossip_interval=spec.get("regossip", 0.25),
            admission_config=admission_config,
            health_config=health_config,
            sync=bool(sync_on),
            sync_config=sync_config,
            net=bool(net_on),
            net_config=net_config,
            link_shaper=shaper,
        ),
        **dbs,
    )

    router = None
    if spec.get("fault"):
        # install BEFORE start so every peer (dialed or accepted) gets the
        # interceptor; each child has its OWN router — partitioning this
        # node's id black-holes only its outbound gossip
        router = ChaosRouter(FaultSpec(**spec["fault"]))
        router.install([node.switch])

    node.start()
    host, port = node.switch.listen_tcp("127.0.0.1", 0)
    rhost, rport = node.rpc.addr
    print(
        json.dumps(
            {"node_id": node.switch.node_id, "p2p": [host, port], "rpc": [rhost, rport]}
        ),
        flush=True,
    )

    # peers line: {"peers": {node_id: [host, port], ...}} — seed the book;
    # the PEX ensure-loop does the dialing (and keeps re-dialing)
    peers = json.loads(sys.stdin.readline())["peers"]
    for nid, (phost, pport) in peers.items():
        if nid != node.switch.node_id and node.address_book is not None:
            node.address_book.add(nid, phost, int(pport))

    bh = spec.get("blackhole")
    if bh and router is not None:

        def _blackhole(r=router, me_id=node.switch.node_id):
            time.sleep(float(bh.get("start", 3.0)))
            r.partition([me_id])
            time.sleep(float(bh.get("duration", 2.0)))
            r.heal()

        threading.Thread(target=_blackhole, name="blackhole", daemon=True).start()

    # scenario-grid adversary (faults/byzantine.py): the spec carries the
    # drawn driver schedule; arming is a live command so one long-lived
    # net can walk adversary and non-adversary tiles. Arming disarms THIS
    # child's honest fast-path signer (its consensus identity stays — the
    # honest remainder must clear quorum without it) and starts the
    # flood; disarming stops the flood and rearms the signer.
    adv_spec = spec.get("adversary") or {}
    adv_drivers: list = []

    def _adversary(active: bool, schedule: dict | None = None) -> dict:
        nonlocal adv_spec, adv_drivers
        if schedule:
            # the command may swap in a fresh schedule (the grid runner
            # walks tiles with different adversary mixes over one net)
            if adv_drivers:
                raise ValueError("disarm before swapping the schedule")
            adv_spec = schedule
        if active and not adv_spec:
            raise ValueError("adversary not configured")
        emitted = sum(d.emitted for d in adv_drivers)
        if active and not adv_drivers:
            from ..faults.byzantine import drivers_from_schedule

            # forgeries target ghost txs (never in any mempool): their
            # vote slots stay open, so garbage signatures are judged on
            # the verify path instead of late-dropping as committed
            ghosts = [bytes.fromhex(h) for h in adv_spec.get("ghost_txs", [])]
            node.txvote_reactor.priv_val = None
            adv_drivers = drivers_from_schedule(
                node.switch,
                me,
                chain_id,
                adv_spec.get("drivers", []),
                targets=lambda: ghosts,
                height_fn=lambda: node.committed_height_view,
                signer_lookup=lambda i: pvs[i % n],
            )
            for d in adv_drivers:
                d.start()
        elif not active and adv_drivers:
            for d in adv_drivers:
                d.stop()
            adv_drivers = []
            node.txvote_reactor.priv_val = me
        return {
            "ok": "adversary",
            "active": bool(adv_drivers),
            # cumulative frames emitted by the fleet (on disarm: the
            # just-stopped drivers' final count — the tile's flood volume)
            "emitted": max(emitted, sum(d.emitted for d in adv_drivers)),
        }

    # park until the parent closes our stdin; lines that parse as JSON
    # commands are live controls (weather swaps, adversary arming,
    # scenario-tile observability), everything else ignored
    while True:
        line = sys.stdin.readline()
        if not line:
            break
        line = line.strip()
        if not line:
            continue
        try:
            cmd = json.loads(line)
        except ValueError:
            continue
        if not isinstance(cmd, dict):
            continue
        if cmd.get("cmd") == "netem":
            if shaper is None:
                print(json.dumps({"err": "netem not configured"}), flush=True)
                continue
            shaper.set_profile(cmd.get("profile", "lan"), links=cmd.get("links"))
            print(
                json.dumps({"ok": "netem", "profile": cmd.get("profile", "lan")}),
                flush=True,
            )
        elif cmd.get("cmd") == "adversary":
            try:
                print(
                    json.dumps(
                        _adversary(bool(cmd.get("active")), cmd.get("schedule"))
                    ),
                    flush=True,
                )
            except Exception as e:  # noqa: BLE001 - parent sees the ack
                print(json.dumps({"err": f"adversary: {e!r}"}), flush=True)
        elif cmd.get("cmd") == "scenario":
            if node.health is None:
                print(json.dumps({"err": "health not enabled"}), flush=True)
                continue
            node.health.registry.set_scenario(cmd.get("info"))
            print(json.dumps({"ok": "scenario"}), flush=True)
    for d in adv_drivers:
        d.stop()
    node.stop()


if __name__ == "__main__":
    main()
