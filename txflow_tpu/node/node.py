"""Node: the composition root (reference node/node.go:555-826).

Assembles, in reference order: DBs/stores -> ABCI proxy connections ->
event bus -> pools (mempool + commitpool + txvotepool) -> TxExecutor +
TxFlow -> p2p switch with the mempool/txvote reactors. The reference's
wiring bug — txvotepool/commitpool reactors created but never
``AddReactor``'d into the switch (node/node.go:488-505 vs :822) — is
fixed here: every reactor registers its channel.

The block-path consensus reactor and the RPC listeners attach to the same
skeleton as those layers land.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass, field

from ..abci.application import Application
from ..abci.proxy import AppConns
from ..consensus.reactor import ConsensusReactor
from ..consensus.replay import Handshaker
from ..consensus.state import ConsensusState
from ..engine.execution import TxExecutor
from ..engine.txflow import TxFlow
from ..p2p import Switch
from ..pool.mempool import Mempool
from ..pool.txvotepool import TxVotePool
from ..reactors import MempoolReactor, StateView, TxVoteReactor
from ..state import BlockExecutor, StateStore, state_from_genesis
from ..store.block_store import BlockStore
from ..store.db import MemDB
from ..store.tx_store import TxStore
from ..types.genesis import GenesisDoc, GenesisValidator
from ..types.priv_validator import PrivValidator
from ..types.validator import ValidatorSet
from ..utils.config import Config, EngineConfig
from ..utils.events import EventBus
from ..utils.metrics import Registry, TxFlowMetrics


@dataclass
class NodeConfig:
    """Assembly knobs beyond the TOML-ish Config sections."""

    config: Config = field(default_factory=Config)
    gossip_batch: int = 4096
    use_device_verifier: bool = True
    # per-reactor broadcast toggles (None = follow config.mempool.broadcast)
    mempool_broadcast: bool | None = None
    vote_broadcast: bool | None = None
    # False disables the signTxRoutine (pregenerated-vote replay benches)
    # WITHOUT removing the node's validator identity from consensus
    sign_votes: bool = True
    # block-path consensus (the BFT ticker fallback); off = fast path only
    enable_consensus: bool = True
    consensus_wal_path: str = ""
    ticker_factory: object = None
    # HTTP RPC + metrics listener (reference startRPC, node/node.go:878-
    # 1007); port 0 = ephemeral (read Node.rpc.addr), None = no listener
    rpc_port: int | None = None
    rpc_host: str = "127.0.0.1"
    # tx indexer (reference TxIndexConfig "kv"/"null", node/node.go:211-238):
    # False = the "null" indexer, no per-commit index rows
    index_txs: bool = True
    # simplified gRPC BroadcastAPI (reference node/node.go:972-986);
    # port 0 = ephemeral (read Node.grpc.port), None = no listener
    grpc_port: int | None = None
    # ed25519 node key seed: enables authenticated secret connections on
    # TCP links (reference p2p.LoadOrGenNodeKey, node/node.go:72)
    node_key_seed: bytes | None = None
    # anti-entropy re-gossip cadence for lossy links (chaos rigs, real
    # networks); None = single-pass cursor walks (reactor docstrings)
    regossip_interval: float | None = None
    # wrap a node-built DeviceVoteVerifier in ResilientVoteVerifier
    # (bounded retry -> CPU fallback -> device re-promotion) so a device
    # failure degrades throughput instead of erroring the vote path
    resilient_verifier: bool = True
    # self-healing liveness layer (health/): quorum-stall watchdog, peer
    # scoring + reconnect backoff, degraded-mode registry behind the RPC
    # /health endpoint. Strictly additive to the data path (re-offers are
    # dedup'd, eviction requires a reconnector); False drops the monitor
    # thread entirely
    health: bool = True
    # HealthConfig override (None = defaults; see health/config.py)
    health_config: object = None
    # accountable vote gossip (health/byzantine.py): ByzantineConfig
    # override for the per-peer strike ledger + invalid-rate circuit
    # breaker. None = defaults; the ledger itself is always assembled
    # (it is a few dicts — the hooks are no-ops without traffic)
    byzantine_config: object = None
    # overload-resilient front door (admission/): edge dedup before any
    # signature work, pool-pressure backpressure to RPC (429) and ingest
    # gossip, fee/priority mempool lanes. False = open door (seed
    # behavior)
    admission: bool = True
    # AdmissionConfig override (None = defaults; see admission/config.py)
    admission_config: object = None
    # tx -> lane callable override (None = the fee-prefix classifier);
    # must be a deterministic function of the tx bytes
    lane_classifier: object = None
    # PEX address-book reactor (p2p/pex.py): learns/persists peer dial
    # addresses and keeps the mesh connected; also feeds the health
    # layer's reconnect hook. None = auto (on exactly when config.p2p.pex
    # and the switch has a node key, i.e. real TCP assemblies)
    pex: bool | None = None
    # address-book persistence path ("" = in-memory only)
    addrbook_path: str = ""
    # dynamic validator sets (epoch/): scheduled rotation + evidence-
    # driven slashing at deterministic epoch boundaries. None or
    # length=0 = static set (seed behavior); see epoch/config.py
    epoch_config: object = None
    # catch-up sync (sync/): every node serves committed ranges on the
    # sync channel; the client half (lag detection + fetch/verify/apply)
    # runs only when this is on. False = serve-only is also off (seed
    # behavior — recovery is the consensus-block path alone)
    sync: bool = True
    # SyncConfig override (None = defaults; see sync/config.py)
    sync_config: object = None
    # adaptive peer transport (p2p/adaptive.py): per-peer RTT/loss
    # estimators + pinger, adaptive send timeouts, bounded send queues
    # with oldest-bulk drop, slow-peer quarantine folded into the health
    # scoreboard. Opt-in (False = exact legacy switch behavior) so seeded
    # chaos drills stay bit-identical; the WAN matrix and netem rigs
    # enable it
    net: bool = False
    # NetTransportConfig override (None = defaults; see p2p/adaptive.py)
    net_config: object = None
    # netem.LinkShaper (or None): wraps every peer connection in WAN
    # weather — install at assembly so links created by PEX/reconnects
    # are shaped too, not just the initial dials
    link_shaper: object = None


class Node:
    def __init__(
        self,
        node_id: str,
        chain_id: str,
        val_set: ValidatorSet,
        app: Application,
        priv_val: PrivValidator | None = None,
        node_config: NodeConfig | None = None,
        tx_store_db=None,
        state_db=None,
        block_db=None,
        verifier=None,
        mesh=None,
        genesis: GenesisDoc | None = None,
    ):
        nc = node_config or NodeConfig()
        self.node_id = node_id
        self.chain_id = chain_id
        self.config = nc.config
        self.priv_val = priv_val

        # -- replicated state (reference state.State; node/node.go:570) --
        if genesis is None:
            genesis = GenesisDoc(
                chain_id=chain_id,
                validators=[
                    GenesisValidator(v.pub_key, v.voting_power) for v in val_set
                ],
            )
        self.genesis = genesis
        self.state_store = StateStore(state_db if state_db is not None else MemDB())
        loaded = self.state_store.load()
        self.chain_state = loaded if loaded is not None else state_from_genesis(genesis)
        self._state_mtx = threading.Lock()
        self._last_block_height = self.chain_state.last_block_height
        self._val_set = self.chain_state.validators

        # -- app + proxy (node/node.go:576). An address string instead of
        # an Application instance crosses the process boundary: the app
        # runs elsewhere behind abci.server.ABCIServer and the node drives
        # it over the socket protocol (abci/wire.py) — the reference's
        # createAndStartProxyAppConns socket mode --
        if isinstance(app, str):
            from ..abci.client import RemoteAppConns

            self.app = None
            self.proxy_app = RemoteAppConns(app)
        else:
            self.app = app
            self.proxy_app = AppConns(app)

        # -- event bus + tx indexer service (node/node.go:585, :211-238).
        # The indexer follows the reference's config gate (index rows are
        # unbounded MemDB growth): on by default like the reference's
        # "kv" indexer, but benches/workers that never serve /tx_search
        # switch it off via NodeConfig.index_txs --
        self.event_bus = EventBus()
        self.tx_indexer = None
        if nc.index_txs:
            from ..services.indexer import TxIndexer

            self.tx_indexer = TxIndexer(MemDB())
            self.tx_indexer.subscribe(self.event_bus)

        # -- pools (node/node.go:627-633); WALs per node under the config's
        # wal_dir (reference InitWAL at OnStart, node/node.go:805-808) --
        wal_dir = self.config.mempool.wal_dir
        self.mempool = Mempool(
            self.config.mempool,
            proxy_app_conn=self.proxy_app.mempool,
            wal_path=f"{wal_dir}/mempool-{node_id}.wal" if wal_dir else "",
        )
        self.commitpool = Mempool(self.config.mempool)  # fast-committed txs for blocks
        self.tx_vote_pool = TxVotePool(
            self.config.mempool,
            wal_path=f"{wal_dir}/txvotes-{node_id}.wal" if wal_dir else "",
        )
        if wal_dir:
            self.mempool.replay_wal()
            self.tx_vote_pool.replay_wal()

        # -- stores + executors (node/node.go:645-668) --
        self.tx_store = TxStore(tx_store_db if tx_store_db is not None else MemDB())
        # per-node registry: N in-proc nodes must not share counters
        self.metrics_registry = Registry()
        self.metrics = TxFlowMetrics(self.metrics_registry)

        # -- per-tx tracing (trace/): ONE tracer per node, attached to
        # every traced hot-path component below (pools, admission,
        # engine, gossip reactors). config.trace.enabled=False swaps in
        # the NullTracer — same surface, zero cost. The commitpool stays
        # untraced: it re-ingests already-committed txs and would
        # double-anchor their e2e spans --
        from ..trace.tracer import make_tracer

        self.tracer = make_tracer(
            self.config.trace, registry=self.metrics_registry, node_id=node_id
        )
        self.mempool.tracer = self.tracer
        self.tx_vote_pool.tracer = self.tracer

        # -- accountable vote gossip (health/byzantine.py): ONE ledger
        # per node, shared by the reactor (pre-check drops + quarantine
        # gate), the engine (invalid-verdict attribution), and the sync
        # client (forged-data strikes). Built before the engine/reactors
        # so their hooks bind at assembly; the scoreboard half is wired
        # after the health layer exists below --
        from ..health.byzantine import ByzantineLedger

        self.byzantine_ledger = ByzantineLedger(
            nc.byzantine_config, metrics_registry=self.metrics_registry
        )

        # -- epoch manager (epoch/): slashing + scheduled rotation folded
        # into EndBlock validator updates at deterministic boundaries.
        # Every node runs the same pure fold over the committed chain, so
        # the derived set is identical everywhere (no gossip, no vote) --
        self.epoch_manager = None
        if nc.epoch_config is not None and getattr(nc.epoch_config, "length", 0) > 0:
            from ..epoch import EpochManager
            from ..utils.metrics import EpochMetrics

            self.epoch_manager = EpochManager(
                nc.epoch_config, metrics=EpochMetrics(self.metrics_registry)
            )

        # -- committee sampling (committee/): per-epoch stake-proportional
        # tx-vote committee, derived deterministically from (chain_id,
        # epoch) on every node. Independent of the epoch_manager gate:
        # length=0 + committee_size>0 is a valid static-committee posture
        # (the bench config). Full-set mode (committee_size=0, default)
        # leaves all of this None — zero behavior change --
        self.committee_schedule = None
        self._committee = None
        if nc.epoch_config is not None and getattr(
            nc.epoch_config, "committee_size", 0
        ) > 0:
            from ..committee import CommitteeSchedule

            self.committee_schedule = CommitteeSchedule(chain_id, nc.epoch_config)
            self._committee = self.committee_schedule.for_vote_height(
                self._last_block_height, self._val_set
            )
            self.byzantine_ledger.committee_rescale(
                self._committee.size() / max(self._val_set.size(), 1)
            )

        # -- admission front door (admission/): sits between the RPC/
        # gossip edges and the mempool; also supplies the pool's lane
        # classifier so every ingress path lands txs in the right lane --
        self.admission = None
        if nc.admission:
            from ..admission import AdmissionController

            self.admission = AdmissionController(
                self.mempool,
                cfg=nc.admission_config,
                registry=self.metrics_registry,
                classifier=nc.lane_classifier,
            )
            # adaptive bulk rate: the bucket fill tracks the engine's
            # live commit rate (EWMA * headroom with hysteresis) instead
            # of the static cfg knob — see controller._sample_commit_rate
            self.admission.commit_rate_source = (
                lambda m=self.metrics: m.committed_txs.value()
            )
            self.admission.tracer = self.tracer
            self.mempool.lane_of = self.admission.lane_of
            # votes inherit their tx's lane (vote.tx_key -> mempool entry),
            # so the verify engine's priority drain covers the whole
            # commit path, not just the mempool walks
            self.tx_vote_pool.lane_of_vote = (
                lambda vote, _pool=self.mempool: _pool.lane_of_key(vote.tx_key)
            )
        self.tx_executor = TxExecutor(
            self.proxy_app.consensus, self.mempool, self.event_bus, self.metrics
        )
        # honor the config's engine section (batching knobs); only the
        # device/scalar choice is a NodeConfig assembly concern
        import dataclasses

        engine_cfg = dataclasses.replace(
            self.config.engine, use_device=nc.use_device_verifier
        )
        # committee mode: the engine's tally set IS the committee — its
        # quorum_power() is the committee quorum, and a constant committee
        # size keeps the device verifier's compile shapes constant across
        # epoch swaps (zero-recompile restage)
        engine_vals = self._committee if self._committee is not None else self._val_set
        if verifier is None and nc.use_device_verifier and mesh is not None:
            from ..verifier import DeviceVoteVerifier, ResilientVoteVerifier

            verifier = DeviceVoteVerifier(
                engine_vals, mesh=mesh,
                host_prep_workers=int(engine_cfg.host_prep_workers or 0),
            )
            if nc.resilient_verifier:
                verifier = ResilientVoteVerifier(verifier)
        self.txflow = TxFlow(
            chain_id,
            self._last_block_height,
            engine_vals,
            self.tx_vote_pool,
            self.mempool,
            self.commitpool,
            self.tx_executor,
            self.tx_store,
            config=engine_cfg,
            verifier=verifier,
            metrics=self.metrics,
        )
        # before txflow.start(): the coalescer built at start() captures
        # the tracer for its linger spans
        self.txflow.tracer = self.tracer
        # every valid=False verdict becomes a ledger strike against the
        # peer whose delivery originated the vote (engine _route_result)
        self.txflow.on_invalid_votes = self.byzantine_ledger.note_invalid_origins

        # -- switch + reactors (node/node.go:688-722; wiring bug fixed) --
        self.switch = Switch(node_id, node_seed=nc.node_key_seed)
        if nc.link_shaper is not None:
            self.switch.set_link_shaper(nc.link_shaper)
        if nc.net:
            self.switch.configure_net(nc.net_config)
        mp_bcast = (
            nc.mempool_broadcast
            if nc.mempool_broadcast is not None
            else self.config.mempool.broadcast
        )
        vote_bcast = (
            nc.vote_broadcast
            if nc.vote_broadcast is not None
            else self.config.mempool.broadcast
        )
        self.mempool_reactor = MempoolReactor(
            self.mempool,
            broadcast=mp_bcast,
            batch_size=nc.gossip_batch,
            regossip_interval=nc.regossip_interval,
            admission=self.admission,
        )
        self.txvote_reactor = TxVoteReactor(
            self.state_view,
            self.mempool,
            self.tx_vote_pool,
            priv_val=priv_val if nc.sign_votes else None,
            broadcast=vote_bcast,
            batch_size=nc.gossip_batch,
            regossip_interval=nc.regossip_interval,
        )
        self.mempool_reactor.tracer = self.tracer
        self.txvote_reactor.tracer = self.tracer
        # quarantine gate + O(1) pre-check drop accounting at vote ingest
        self.txvote_reactor.ledger = self.byzantine_ledger
        self.switch.add_reactor("mempool", self.mempool_reactor)
        self.switch.add_reactor("txvote", self.txvote_reactor)

        # -- PEX address book (p2p/pex.py; reference p2p/pex — channel
        # 0x00): auto-on for keyed TCP assemblies, where dial addresses
        # are learnable and re-dials authenticate; in-memory LocalNet
        # pipes have no dialable addresses, so auto stays off there --
        self.address_book = None
        self.pex = None
        pex_on = (
            self.config.p2p.pex and nc.node_key_seed is not None
            if nc.pex is None
            else nc.pex
        )
        if pex_on:
            from ..p2p.pex import AddressBook, PEXReactor

            self.address_book = AddressBook(nc.addrbook_path)
            self.pex = PEXReactor(self.address_book)
            self.switch.add_reactor("pex", self.pex)

        # -- evidence pool + reactor (node/node.go:354-367; channel 0x38) --
        from ..pool.evidence import EvidencePool
        from ..reactors.evidence_reactor import EvidenceReactor

        # committed-evidence markers share the block store's db (prefix
        # EV:): any node that persists blocks also persists the markers,
        # so the already-committed check survives restarts and fast-sync
        # (r3 advisor: an in-memory set diverges between honest nodes)
        self._block_db = block_db if block_db is not None else MemDB()
        self.evidence_pool = EvidencePool(
            chain_id,
            lambda: self.state_view().validators,
            event_bus=self.event_bus,
            db=self._block_db,
            # epoch-correct admission: verify against the set of the
            # height the offending vote was cast in (per-height snapshots
            # persisted by StateStore.save; None falls back to current)
            val_set_at=lambda h: self.state_store.load_validators(h),
        )
        self.evidence_reactor = EvidenceReactor(self.evidence_pool)
        self.switch.add_reactor("evidence", self.evidence_reactor)

        # -- block path: stores + executor + consensus (node/node.go:636-680) --
        self.block_store = BlockStore(self._block_db)
        self.block_executor = BlockExecutor(
            self.state_store,
            self.proxy_app.consensus,
            self.mempool,
            self.commitpool,
            event_bus=self.event_bus,
            evidence_pool=self.evidence_pool,
            epoch_manager=self.epoch_manager,
        )
        self.consensus: ConsensusState | None = None
        self.consensus_reactor: ConsensusReactor | None = None
        if nc.enable_consensus:
            self.consensus = ConsensusState(
                self.config.consensus,
                self.chain_state,
                self.block_executor,
                self.block_store,
                tx_notifier=self.mempool,
                commitpool=self.commitpool,
                tx_store=self.tx_store,
                priv_val=priv_val,
                event_bus=self.event_bus,
                wal_path=nc.consensus_wal_path,
                ticker_factory=nc.ticker_factory,
                on_commit=self._on_block_commit,
            )
            self.consensus.vtx_claimer = self.txflow.claim_vtx
            self.consensus.on_evidence = lambda ev: self.evidence_pool.add(ev)
            self.block_executor.tx_reserved = self.txflow.is_tx_reserved
            self.consensus_reactor = ConsensusReactor(self.consensus)
            self.switch.add_reactor("consensus", self.consensus_reactor)

        # -- RPC + metrics listener (node/node.go:878-1007) --
        self.rpc = None
        if nc.rpc_port is not None:
            from ..rpc import RPCServer

            self.rpc = RPCServer(self, host=nc.rpc_host, port=nc.rpc_port)
        self.grpc = None
        if nc.grpc_port is not None:
            from ..rpc.grpc_server import GRPCBroadcastServer

            self.grpc = GRPCBroadcastServer(self, host=nc.rpc_host, port=nc.grpc_port)

        # -- self-healing liveness layer (health/monitor.py) --
        self.health = None
        if nc.health:
            from ..health import HealthMonitor

            self.health = HealthMonitor(self, nc.health_config)
            # strikes now reach the same score -> floor -> evict/backoff
            # machinery that drives the rest of peer health
            self.byzantine_ledger.scoreboard = self.health.scoreboard
            if self.address_book is not None:
                # default reconnect hook for TCP assemblies: evicted
                # peers re-dial via the PEX address book (the jittered
                # backoff lives in the scoreboard — health/peers.py)
                from ..p2p.pex import book_reconnector

                self.health.set_reconnector(
                    book_reconnector(self.switch, self.address_book)
                )

        # -- catch-up sync (sync/): server half on every sync-enabled
        # node (read-only range serving), client half on its own thread.
        # Assembled after health so Byzantine strikes reach the same
        # scoreboard that drives eviction + reconnect backoff --
        self.sync_reactor = None
        self.sync_manager = None
        if nc.sync:
            from ..sync import SyncManager, SyncReactor
            from ..utils.metrics import SyncMetrics

            self.sync_reactor = SyncReactor(
                self.tx_store,
                state_store=self.state_store,
                current_vals=lambda: self.state_view().validators,
                config=nc.sync_config,
            )
            self.sync_manager = SyncManager(
                chain_id,
                self.tx_store,
                self.txflow,
                self.switch,
                state_store=self.state_store,
                config=nc.sync_config,
                scoreboard=self.health.scoreboard if self.health else None,
                metrics=SyncMetrics(self.metrics_registry),
                tracer=self.tracer,
                ledger=self.byzantine_ledger,
                committee=self.committee_schedule,
            )
            self.sync_reactor.manager = self.sync_manager
            self.switch.add_reactor("sync", self.sync_reactor)

        # -- durable-path degradation -> admission coupling: a node that
        # can no longer persist (disk full / EIO) sheds ingest load like
        # an overloaded one instead of accepting txs it cannot recover --
        if self.admission is not None:
            self.admission.degraded_source = lambda: (
                self.txflow.storage_degraded
                or self.mempool.wal_degraded
                or self.tx_vote_pool.wal_degraded
            )

        self._started = False

    # -- state view read by reactors (reference reads state.State) --

    def state_view(self) -> StateView:
        with self._state_mtx:
            return StateView(
                self.chain_id,
                self._last_block_height,
                self._val_set,
                committee=self._committee,
            )

    def _engine_val_set(self, height: int, full: ValidatorSet) -> ValidatorSet:
        """The set the engine tallies against at ``height``: the epoch's
        sampled committee in committee mode, the full set otherwise.
        Tracks ``self._committee`` (the reactor pre-check view) and
        restates the breaker thresholds whenever the committee actually
        changes (epoch boundary or slash-rotated full set)."""
        if self.committee_schedule is None:
            return full
        committee = self.committee_schedule.for_vote_height(height, full)
        with self._state_mtx:
            changed = committee is not self._committee
            self._committee = committee
        if changed:
            self.byzantine_ledger.committee_rescale(
                committee.size() / max(full.size(), 1)
            )
        return committee

    def update_state(self, height: int, val_set: ValidatorSet | None = None) -> None:
        """Block boundary: advance height / rotate validators."""
        with self._state_mtx:
            self._last_block_height = height
            if val_set is not None:
                self._val_set = val_set
            full = self._val_set
        self.txflow.update_state(height, self._engine_val_set(height, full))
        self.txvote_reactor.broadcast_height(height)
        self.mempool_reactor.broadcast_height(height)
        self.evidence_pool.prune(height)
        if self.epoch_manager is not None:
            m = self.epoch_manager.metrics
            if m is not None:
                cur = self.state_view().validators
                m.number.set(self.epoch_manager.cfg.epoch_of(height))
                m.length.set(self.epoch_manager.cfg.length)
                m.validators.set(cur.size())
                m.total_power.set(cur.total_voting_power())
                m.quorum_power.set(cur.quorum_power())

    def _on_block_commit(self, new_state, block=None) -> None:
        """Consensus commit hook: sync the fast path to the new height and
        (possibly) rotated validator set (node/node.go's implicit coupling
        via shared state). Vtx double-apply protection lives in the
        claim_vtx wiring, exercised during apply_block itself."""
        self.chain_state = new_state
        if block is not None and block.evidence:
            # committed proofs stop gossiping/pending on EVERY node
            # (reference evpool.Update inside ApplyBlock)
            self.evidence_pool.mark_committed(block.evidence)
        self.update_state(new_state.last_block_height, new_state.validators)

    # -- lifecycle (reference OnStart :768-826 / OnStop :829-874) --

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        # handshake-replay the app against the stores (node/node.go:599);
        # the handshake may advance state past the snapshot loaded in
        # __init__ (crash between block save and state save) — every
        # component keyed on height/validators must adopt the result
        handshaker = Handshaker(
            self.state_store,
            self.chain_state,
            self.block_store,
            genesis=self.genesis,
            tx_store=self.tx_store,
            mempool=self.mempool,
        )
        new_state = handshaker.handshake(self.proxy_app)
        if handshaker.unapplied_commits:
            # certificates whose bytes were unavailable at replay: hand
            # them to the engine's deferral map — a catchup block's vtx
            # (claim_vtx) or late mempool gossip delivers them
            self.txflow.register_unapplied(handshaker.unapplied_commits)
        if new_state.last_block_height != self.chain_state.last_block_height:
            self.chain_state = new_state
            with self._state_mtx:
                self._last_block_height = new_state.last_block_height
                self._val_set = new_state.validators
            self.txflow.update_state(
                new_state.last_block_height,
                self._engine_val_set(
                    new_state.last_block_height, new_state.validators
                ),
            )
            if self.consensus is not None:
                self.consensus.reset_to_state(new_state)
        if self.epoch_manager is not None:
            # refill the pending-offense ledger from committed evidence in
            # the current (partial) epoch, so a crash between an offense
            # landing on-chain and its boundary cannot forgive the slash
            self.epoch_manager.rebuild(
                self.block_store, self.chain_state.last_block_height
            )
        self.switch.start()
        self.txflow.start()
        if self.consensus is not None:
            self.consensus.start()
        if self.rpc is not None:
            self.rpc.start()
        if self.grpc is not None:
            self.grpc.start()
        if self.health is not None:
            self.health.start()
        if self.sync_manager is not None:
            self.sync_manager.start()

    def stop(self) -> None:
        if not self._started:
            return
        self._started = False
        if self.sync_manager is not None:
            self.sync_manager.stop()
        if self.health is not None:
            self.health.stop()
        if self.rpc is not None:
            self.rpc.stop()
        if self.grpc is not None:
            self.grpc.stop()
        if self.consensus is not None:
            self.consensus.stop()
        self.txflow.stop()
        self.switch.stop()
        self.mempool.close_wal()
        self.tx_vote_pool.close_wal()
        if hasattr(self.proxy_app, "close"):  # remote ABCI sockets
            self.proxy_app.close()

    # -- client surface (RPC broadcast_tx analog until the HTTP layer lands) --

    def broadcast_tx(self, tx: bytes) -> None:
        """Client tx ingress: local CheckTx; gossip + votes follow."""
        self.mempool.check_tx(tx)

    def is_committed(self, tx: bytes) -> bool:
        tx_hash = hashlib.sha256(tx).hexdigest().upper()
        return self.txflow.is_tx_committed(tx_hash)

    @property
    def committed_height_view(self) -> int:
        with self._state_mtx:
            return self._last_block_height
