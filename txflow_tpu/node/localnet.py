"""LocalNet: an N-validator in-process network over in-memory pipes.

The rebuild's analog of the reference's in-process testnets
(p2p.MakeConnectedSwitches + real reactors, txvotepool/reactor_test.go:
47-66, consensus/common_test.go:576-656) — and the measurement rig for the
BASELINE configs ("4-validator in-proc net, kvstore app, pregenerated
TxVotes replayed through txvotepool").

Every node runs the full fast path: mempool gossip -> signTxRoutine ->
vote gossip -> batched device verify+tally -> per-tx commit against its
own app instance. All nodes share one process and (on TPU) one chip; the
device kernel is shared-compiled across nodes (ops.tally.compact_step_jit).
"""

from __future__ import annotations

import hashlib
import time

from ..abci.kvstore import KVStoreApplication
from ..p2p import connect_switches
from ..types.priv_validator import MockPV, PrivValidator
from ..types.validator import Validator, ValidatorSet
from ..utils.config import Config, test_config
from .node import Node, NodeConfig


class LocalNet:
    def __init__(
        self,
        n_validators: int = 4,
        chain_id: str = "txflow-localnet",
        app_factory=KVStoreApplication,
        config: Config | None = None,
        use_device_verifier: bool = True,
        voting_power: int = 10,
        priv_vals: list[PrivValidator] | None = None,
        gossip_batch: int = 4096,
        sign: bool = True,
        mempool_broadcast: bool | None = None,
        enable_consensus: bool = False,
        ticker_factory=None,
        wal_dir: str = "",
        verifier=None,
        rpc: bool = False,  # True: each node serves HTTP RPC on an ephemeral port
        index_txs: bool = True,
        n_nodes: int | None = None,
        fault_plan=None,  # FaultSpec/FaultPlan/ChaosRouter: chaos p2p (faults/)
        regossip_interval: float | None = None,
        health: bool = True,
        health_config=None,  # HealthConfig override (health/config.py)
        byzantine_config=None,  # ByzantineConfig override (health/byzantine.py)
        voting_powers: list[int] | None = None,  # per-validator stake override
        epoch_config=None,  # EpochConfig: rotation/slashing (epoch/)
        sync: bool = True,  # catch-up sync channel + client (sync/)
        sync_config=None,  # SyncConfig override (sync/config.py)
        netem=None,  # profile name / NetProfile / LinkShaper: WAN weather (netem/)
        netem_seed: int = 0,  # shaper PRNG seed (ignored for a prebuilt LinkShaper)
        net: bool | None = None,  # adaptive transport; None = on iff netem is set
        net_config=None,  # NetTransportConfig override (p2p/adaptive.py)
    ):
        """n_nodes: host only the first n_nodes validators as full nodes
        (default: one node per validator). A large validator set does not
        imply co-locating every validator in THIS process: the bench's
        16/64-validator configs keep 4 hosted nodes — the other
        validators' votes arrive pregenerated, exactly like votes from
        remote peers — because 64 full-mesh in-proc nodes (~4k threads)
        measures thread thrash, not the protocol (r5: the 64-validator
        CPU bench never completed). Quorum still needs 2/3 of the WHOLE
        set's stake."""
        self.chain_id = chain_id
        if priv_vals is None:
            priv_vals = [
                MockPV(hashlib.sha256(b"localnet-val%d" % i).digest())
                for i in range(n_validators)
            ]
        self.priv_vals = priv_vals
        # non-uniform stake (voting_powers, e.g. faults.stake_distribution)
        # exercises quorum math that uniform powers can never reach: a
        # whale's single vote can be 1/3+ of the total
        if voting_powers is not None and len(voting_powers) != len(priv_vals):
            raise ValueError(
                f"voting_powers must have {len(priv_vals)} entries, "
                f"got {len(voting_powers)}"
            )
        powers = voting_powers or [voting_power] * len(priv_vals)
        self.val_set = ValidatorSet(
            [
                Validator.from_pub_key(pv.get_pub_key(), p)
                for pv, p in zip(priv_vals, powers)
            ]
        )
        cfg = config or test_config()
        self.nodes: list[Node] = []
        if n_nodes is not None and not 1 <= n_nodes <= len(priv_vals):
            raise ValueError(
                f"n_nodes must be in [1, {len(priv_vals)}], got {n_nodes}"
            )
        if enable_consensus and n_nodes is not None and n_nodes < len(priv_vals):
            # mirror the bench.py guard: a hosted subset cannot reach block
            # quorum — the missing validators never prevote, so consensus
            # silently hangs at round 0 instead of failing fast
            raise ValueError(
                f"enable_consensus requires hosting all {len(priv_vals)} "
                f"validators (n_nodes={n_nodes}): a hosted subset cannot "
                "reach block quorum"
            )
        # chaos rig (faults/): accept a FaultSpec, a FaultPlan, or a
        # pre-built ChaosRouter; installed on every switch in start().
        # Lossy links need the reactors' anti-entropy re-walk for
        # liveness — default it on (250 ms) whenever chaos is active.
        self.chaos: "ChaosRouter | None" = None
        if fault_plan is not None:
            from ..faults import ChaosRouter
            from ..faults.chaos import FaultPlan, FaultSpec

            if isinstance(fault_plan, (FaultSpec, FaultPlan)):
                fault_plan = ChaosRouter(fault_plan)
            self.chaos = fault_plan
            if regossip_interval is None:
                regossip_interval = 0.25
        # network weather (netem/): ONE shaper serves the whole net so a
        # live set_profile() walks every link at once; installed on each
        # switch at assembly (node_config) so PEX/reconnect links created
        # later are shaped too. Weather implies frame loss below the
        # reliable lane — default the anti-entropy re-walk on, like chaos.
        self.shaper = None
        if netem is not None:
            from ..netem import LinkShaper

            if isinstance(netem, LinkShaper):
                self.shaper = netem
            else:
                self.shaper = LinkShaper(netem, seed=netem_seed)
            if regossip_interval is None:
                regossip_interval = 0.25
            # in-proc pipes have no PEX ensure-loop: a peer torn down by a
            # weather-corrupted frame must heal through the scoreboard's
            # backoff re-dial instead
            if health_config is None:
                from ..health.config import HealthConfig

                health_config = HealthConfig(redial_lost_peers=True)
        self._net = bool(net) if net is not None else self.shaper is not None
        self._net_config = net_config
        # rebuild inputs, kept so durable members can be crashed and
        # revived over their on-disk artifacts (make_durable/revive_node)
        self._cfg = cfg
        self._app_factory = app_factory
        self._verifier = verifier
        self._gossip_batch = gossip_batch
        self._use_device_verifier = use_device_verifier
        self._mempool_broadcast = mempool_broadcast
        self._enable_consensus = enable_consensus
        self._sign = sign
        self._rpc = rpc
        self._index_txs = index_txs
        self._ticker_factory = ticker_factory
        self._wal_dir = wal_dir
        self._regossip_interval = regossip_interval
        self._health = health
        self._health_config = health_config
        self._byzantine_config = byzantine_config
        self._epoch_config = epoch_config
        self._sync = sync
        self._sync_config = sync_config
        self._durable_roots: dict[int, str] = {}
        self._down: set[int] = set()
        hosted = priv_vals if n_nodes is None else priv_vals[:n_nodes]
        for i, _pv in enumerate(hosted):
            self.nodes.append(self._build_node(i))

    def _build_node(self, i: int) -> Node:
        root = self._durable_roots.get(i)
        dbs = {}
        cfg = self._cfg
        consensus_wal = (
            f"{self._wal_dir}/node{i}-consensus.wal" if self._wal_dir else ""
        )
        if root is not None:
            import copy

            from ..store.db import FileDB

            dbs = {
                "tx_store_db": FileDB(f"{root}/txstore.db"),
                "state_db": FileDB(f"{root}/state.db"),
                "block_db": FileDB(f"{root}/blocks.db"),
            }
            consensus_wal = f"{root}/consensus.wal"
            # pool WALs too (CrashDrill parity): a private config copy so
            # the in-memory members don't start writing WALs as well
            cfg = copy.deepcopy(cfg)
            cfg.mempool.wal_dir = root
        return Node(
            node_id=f"node{i}",
            chain_id=self.chain_id,
            val_set=self.val_set,
            app=self._app_factory(),
            # a shared verifier instance (same val_set for every node)
            # reuses one set of device epoch tables + compiled shapes
            verifier=self._verifier,
            priv_val=self.priv_vals[i],
            node_config=NodeConfig(
                config=cfg,
                gossip_batch=self._gossip_batch,
                use_device_verifier=self._use_device_verifier,
                mempool_broadcast=self._mempool_broadcast,
                enable_consensus=self._enable_consensus,
                # sign=False: fast-path votes are injected externally
                # (pregenerated-vote replay, BASELINE config 1); the
                # node keeps its consensus identity either way
                sign_votes=self._sign,
                rpc_port=0 if self._rpc else None,
                index_txs=self._index_txs,
                ticker_factory=self._ticker_factory,
                consensus_wal_path=consensus_wal,
                regossip_interval=self._regossip_interval,
                health=self._health,
                health_config=self._health_config,
                byzantine_config=self._byzantine_config,
                epoch_config=self._epoch_config,
                sync=self._sync,
                sync_config=self._sync_config,
                net=self._net,
                net_config=self._net_config,
                link_shaper=self.shaper,
            ),
            **dbs,
        )

    def start(self) -> None:
        if self.chaos is not None:
            # before connect: interceptors must cover the peers the full
            # mesh is about to create
            self.chaos.install([n.switch for n in self.nodes])
        for node in self.nodes:
            node.start()
        # full mesh (reference MakeConnectedSwitches connects all pairs)
        for i in range(len(self.nodes)):
            for j in range(i + 1, len(self.nodes)):
                connect_switches(self.nodes[i].switch, self.nodes[j].switch)
        # health monitors can only heal links they can re-dial: give each
        # one a reconnector so peer-score evictions become reconnect
        # cycles instead of permanent degradation
        roster = [n.switch.node_id for n in self.nodes]
        for node in self.nodes:
            if node.health is not None:
                node.health.set_reconnector(self._make_reconnector(node))
                # full-mesh roster: redial_lost_peers rigs heal links torn
                # down before the scoreboard ever observed them
                node.health.set_expected_peers(roster)

    def _make_reconnector(self, node: Node):
        """Closure handed to node's PeerScoreBoard: re-dial a peer by
        switch id over a fresh in-memory pipe (the LocalNet analog of the
        reference's persistent-peer redial loop)."""

        def reconnect(dst_id: str) -> bool:
            target = None
            for other in self.nodes:
                if other is not node and other.switch.node_id == dst_id:
                    target = other
                    break
            if target is None or not target.switch.is_running:
                return False
            if not node.switch.is_running:
                return False
            if node.switch.get_peer(dst_id) is not None:
                return True  # raced with an inbound redial: already healed
            # the evicting side dropped its end; the far side may still
            # hold the dead half of the old pipe — clear it first or
            # add_peer_conn rejects the redial as a duplicate
            stale = target.switch.get_peer(node.switch.node_id)
            if stale is not None:
                target.switch.stop_peer(stale, reason="stale half-link")
            connect_switches(node.switch, target.switch)
            return True

        return reconnect

    # -- durable members: crash/revive drills (faults/crash.py analog) --

    def make_durable(self, i: int, root: str) -> None:
        """Rebuild node i (pre-start) over FileDB stores + consensus WAL
        under ``root`` so it can be crashed and revived in place."""
        if self.nodes[i]._started:
            raise RuntimeError("make_durable must run before start()")
        self._durable_roots[i] = root
        self.nodes[i] = self._build_node(i)

    def crash_node(self, i: int) -> Node:
        """Stop node i in place (peers see the link die); state survives
        only what its stores persisted. Returns the stopped node."""
        node = self.nodes[i]
        node.stop()
        self._down.add(i)
        return node

    def wipe_node(self, i: int) -> None:
        """Delete node i's durable artifacts while it is down — the
        wipe-and-rejoin drill. revive_node then rebuilds it over EMPTY
        stores (a freshly-joined node for all practical purposes) and it
        must recover the committed set from peers via catch-up sync."""
        if i not in self._down:
            raise RuntimeError(f"node {i} must be crashed before wiping")
        root = self._durable_roots.get(i)
        if root is None:
            raise RuntimeError(f"node {i} has no durable root to wipe")
        import os
        import shutil

        shutil.rmtree(root, ignore_errors=True)
        os.makedirs(root, exist_ok=True)

    def revive_node(self, i: int) -> Node:
        """Rebuild node i over its durable artifacts (fresh app instance,
        handshake replay + catchup) and rejoin the mesh."""
        if i not in self._down:
            raise RuntimeError(f"node {i} is not down")
        node = self._build_node(i)
        self.nodes[i] = node
        if self.chaos is not None:
            self.chaos.install([node.switch])
        node.start()
        for j, other in enumerate(self.nodes):
            if j != i and j not in self._down:
                connect_switches(node.switch, other.switch)
        if node.health is not None:
            node.health.set_reconnector(self._make_reconnector(node))
            node.health.set_expected_peers([n.switch.node_id for n in self.nodes])
        self._down.discard(i)
        return node

    def stop(self) -> None:
        for node in self.nodes:
            node.stop()
        if self.chaos is not None:
            self.chaos.uninstall()

    def set_net_profile(self, profile, links=None) -> None:
        """Swap the WAN weather live on every link (netem rigs only)."""
        if self.shaper is None:
            raise RuntimeError("LocalNet was built without netem")
        self.shaper.set_profile(profile, links=links)

    # -- client helpers --

    def broadcast_tx(self, tx: bytes, node_index: int = 0) -> None:
        self.nodes[node_index].broadcast_tx(tx)

    def wait_all_committed(
        self, txs: list[bytes], timeout: float = 30.0, poll: float = 0.01
    ) -> bool:
        """Block until every node has committed every tx (or timeout)."""
        hashes = [hashlib.sha256(tx).hexdigest().upper() for tx in txs]
        deadline = time.monotonic() + timeout
        for node in self.nodes:
            for h in hashes:
                while not node.tx_store.has_tx(h):
                    if time.monotonic() > deadline:
                        return False
                    time.sleep(poll)
        # certificates are decision-time facts; wait for the pipelined
        # committers' ABCI applies to drain too, so callers can compare
        # app state across nodes right after this returns
        for node in self.nodes:
            while not node.txflow.commits_drained():
                if time.monotonic() > deadline:
                    return False
                time.sleep(poll)
        return True

    def committed_votes_total(self) -> int:
        """Sum over nodes of votes in committed certificates."""
        return sum(int(n.metrics.committed_votes.value()) for n in self.nodes)

    # -- tracing (trace/) --

    def trace_dumps(self) -> list[dict]:
        """Per-node span-ring dumps (the /trace RPC payload, in-proc)."""
        return [n.tracer.dump(n.node_id) for n in self.nodes]

    def export_trace(self, path: str) -> int:
        """Merge every node's span ring into one Chrome-trace JSON file
        (open in Perfetto / chrome://tracing). Returns the number of
        span events written."""
        from ..trace.export import write_chrome_trace

        return write_chrome_trace(path, self.trace_dumps())
