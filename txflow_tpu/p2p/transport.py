"""Transports: framed, channel-tagged duplex connections.

Two implementations of one small interface (``send``/``try_send``/``recv``/
``close``):

- ``InMemoryConnection`` — paired bounded queues, the in-process analog of
  the reference's MakeConnectedSwitches wiring (txvotepool/reactor_test.go:
  47-66); used by the BASELINE in-proc validator nets and the gossip tests.
- ``TCPConnection`` — length-prefixed frames over a socket for multi-host
  DCN deployment (the reference's MultiplexTransport slot, node/node.go:
  420-505, minus the station-to-station encryption layer).

Frame format on TCP: ``chan_id u8 | len u32be | payload``.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading

from ..analysis.lockgraph import make_lock, note_blocking

_FRAME_HDR = struct.Struct("!BI")

# Hard cap on one frame; matches the reference's 1 MiB gossip message cap
# (consensus/reactor.go:28) with headroom for batched vote frames.
MAX_FRAME_BYTES = 8 * 1024 * 1024


class ConnectionClosed(Exception):
    pass


class InMemoryConnection:
    """One endpoint of an in-process duplex pipe."""

    def __init__(self, send_q: queue.Queue, recv_q: queue.Queue, label: str = ""):
        self._send_q = send_q
        self._recv_q = recv_q
        self._closed = threading.Event()
        self.label = label

    def send(self, chan_id: int, msg: bytes, timeout: float | None = 10.0) -> bool:
        """Blocking send with backpressure; False if closed/timed out."""
        if self._closed.is_set():
            return False
        try:
            self._send_q.put((chan_id, msg), timeout=timeout)
            return True
        except queue.Full:
            return False

    def try_send(self, chan_id: int, msg: bytes) -> bool:
        if self._closed.is_set():
            return False
        try:
            self._send_q.put_nowait((chan_id, msg))
            return True
        except queue.Full:
            return False

    def recv(self, timeout: float | None = None) -> tuple[int, bytes]:
        """Blocks for the next (chan_id, msg); raises ConnectionClosed."""
        while True:
            if self._closed.is_set() and self._recv_q.empty():
                raise ConnectionClosed()
            try:
                item = self._recv_q.get(timeout=timeout if timeout else 0.2)
            except queue.Empty:
                if timeout is not None:
                    raise TimeoutError()
                continue
            if item is None:  # close sentinel from the other side
                self._closed.set()
                raise ConnectionClosed()
            return item

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self._send_q.put_nowait(None)  # wake the remote recv loop
        except queue.Full:
            pass
        try:
            self._recv_q.put_nowait(None)  # wake our own recv loop
        except queue.Full:
            pass

    @property
    def is_closed(self) -> bool:
        return self._closed.is_set()


def connection_pair(
    capacity: int = 1024, labels: tuple[str, str] = ("a", "b")
) -> tuple[InMemoryConnection, InMemoryConnection]:
    """A duplex in-memory pipe: what a sends, b recvs, and vice versa."""
    ab: queue.Queue = queue.Queue(maxsize=capacity)
    ba: queue.Queue = queue.Queue(maxsize=capacity)
    return (
        InMemoryConnection(ab, ba, labels[0]),
        InMemoryConnection(ba, ab, labels[1]),
    )


class TCPConnection:
    """Framed duplex connection over one TCP socket."""

    def __init__(self, sock: socket.socket, label: str = ""):
        self._sock = sock
        self._rfile = sock.makefile("rb")
        self._wlock = make_lock("p2p.TCPConnection._wlock", allow_blocking=True)
        self._closed = threading.Event()
        self._snd_timeout: float | None = None  # last SO_SNDTIMEO armed
        self.label = label
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def send(self, chan_id: int, msg: bytes, timeout: float | None = 10.0) -> bool:
        """Blocking send; ``timeout`` bounds the whole-frame write. A
        timeout mid-frame leaves the peer's stream desynced, so it closes
        the connection (False) rather than retry — the adaptive transport
        (p2p/adaptive.py) passes per-peer RTT-derived timeouts here."""
        if self._closed.is_set():
            return False
        if len(msg) > MAX_FRAME_BYTES:
            raise ValueError(f"frame too large: {len(msg)}")
        frame = _FRAME_HDR.pack(chan_id, len(msg)) + msg
        # a peer that stops reading can stall sendall for the socket
        # timeout: callers must not hold shared node locks into send()
        note_blocking("p2p.socket-send")
        try:
            with self._wlock:
                if timeout is not None:
                    # SO_SNDTIMEO (NOT settimeout: that would also arm a
                    # timeout on the recv loop's blocked read) bounds each
                    # send syscall; an expiry surfaces as EAGAIN/OSError
                    self._set_send_timeout(timeout)
                self._sock.sendall(frame)  # txlint: allow(lock-blocking) -- _wlock EXISTS to serialize whole-frame writes; interleaved sendall would corrupt the stream
            return True
        except OSError:  # includes a SO_SNDTIMEO expiry (EAGAIN)
            self.close()
            return False

    def _set_send_timeout(self, timeout: float) -> None:
        if timeout == self._snd_timeout:
            return
        sec = int(timeout)
        try:
            self._sock.setsockopt(
                socket.SOL_SOCKET,
                socket.SO_SNDTIMEO,
                struct.pack("ll", sec, int((timeout - sec) * 1e6)),
            )
            self._snd_timeout = timeout
        except (OSError, struct.error):
            pass  # platform without the sockopt: sends stay unbounded

    def try_send(self, chan_id: int, msg: bytes) -> bool:
        """Non-blocking best-effort send (parity with InMemoryConnection):
        False when another sender holds the write lock or the kernel
        buffer can't take the first byte — the stream stays intact either
        way. Once ANY byte of the frame is on the wire the frame must be
        completed (blocking), else the receiver desyncs."""
        if self._closed.is_set():
            return False
        if len(msg) > MAX_FRAME_BYTES:
            raise ValueError(f"frame too large: {len(msg)}")
        frame = _FRAME_HDR.pack(chan_id, len(msg)) + msg
        if not self._wlock.acquire(blocking=False):
            return False
        try:
            try:
                # MSG_DONTWAIT: a per-call non-blocking probe that leaves
                # the socket's timeout state alone (settimeout would also
                # flip the recv loop's blocked read into non-blocking)
                try:
                    sent = self._sock.send(
                        frame, getattr(socket, "MSG_DONTWAIT", 0)
                    )
                except (BlockingIOError, InterruptedError):
                    return False  # kernel buffer full, nothing written
                if sent < len(frame):
                    # committed: finish the frame so the stream stays framed
                    note_blocking("p2p.socket-send")
                    self._sock.sendall(frame[sent:])  # txlint: allow(lock-blocking) -- same frame-integrity contract as send()
                return True
            except OSError:
                self.close()
                return False
        finally:
            self._wlock.release()

    def recv(self, timeout: float | None = None) -> tuple[int, bytes]:
        prev_timeout = self._sock.gettimeout()
        if timeout is not None:
            self._sock.settimeout(timeout)
        try:
            hdr = self._rfile.read(_FRAME_HDR.size)
            if hdr is None or len(hdr) < _FRAME_HDR.size:
                raise ConnectionClosed()
            chan_id, length = _FRAME_HDR.unpack(hdr)
            if length > MAX_FRAME_BYTES:
                raise ConnectionClosed()
            payload = self._rfile.read(length)
            if payload is None or len(payload) < length:
                raise ConnectionClosed()
            return chan_id, payload
        except socket.timeout:
            # a timeout mid-frame leaves the buffered reader desynced
            # (partially consumed frame) — that is a connection error, not
            # a retryable idle timeout; only a clean pre-header timeout
            # (nothing buffered, nothing read) is retryable
            self.close()
            raise ConnectionClosed()
        except (OSError, ValueError):
            raise ConnectionClosed()
        finally:
            if timeout is not None and not self._closed.is_set():
                try:
                    self._sock.settimeout(prev_timeout)
                except OSError:
                    pass

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    @property
    def is_closed(self) -> bool:
        return self._closed.is_set()


def tcp_listen(host: str, port: int) -> socket.socket:
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, port))
    srv.listen(128)
    return srv


def tcp_connect_raw(host: str, port: int, timeout: float = 5.0) -> socket.socket:
    """A connected raw socket (for wrappers like SecretConnection)."""
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.settimeout(None)
    return sock


def tcp_connect(host: str, port: int, timeout: float = 5.0) -> TCPConnection:
    return TCPConnection(tcp_connect_raw(host, port, timeout), label=f"{host}:{port}")
