"""Adaptive peer transport: per-peer RTT/loss/backlog estimation feeding
send timeouts, bounded send queues, and slow-peer quarantine.

Opt-in at the Switch level (``Switch.configure_net``): a bare Switch keeps
the exact legacy PriorityQueue/no-ping behavior, so every seeded chaos
drill that predates this module is bit-identical. When configured:

- a pinger thread sends one PING frame per peer per interval THROUGH the
  normal send path (lowest priority, chaos-interceptable — a black-holed
  link loses its pings too, so the PR 2/6 staleness machinery still sees
  silence as silence);
- ``PeerNetEstimator`` folds PONG RTTs into RFC 6298-style srtt/rttvar,
  ping expiries into a loss EWMA, and samples queue backlog — yielding a
  per-peer adaptive send timeout (clamped) that the send loop passes down
  to ``TCPConnection.send``;
- ``BoundedSendQueue`` replaces the per-peer shared-lane PriorityQueue:
  under backpressure it drops the OLDEST frame from the LEAST-important
  lane not more important than the newcomer (PR 6 semantics: the priority
  lane is preserved; the reliable consensus lane is a separate queue and
  untouched);
- sustained bad weather (loss/RTT over thresholds, with hysteresis) marks
  the peer ``quarantined``; the health scoreboard (health/peers.py) folds
  that into the existing score-floor/eviction/backoff machinery rather
  than inventing a second eviction path.
"""

from __future__ import annotations

import itertools
import queue
import struct
import threading
from collections import deque
from dataclasses import dataclass

from ..utils import clock

_PING_FMT = struct.Struct("!I")


@dataclass(frozen=True)
class NetTransportConfig:
    ping_interval: float = 1.0  # one PING per peer per interval
    ping_timeout: float = 3.0  # outstanding longer than this = lost
    max_outstanding: int = 8  # stop pinging a silent peer past this
    rtt_alpha: float = 0.125  # RFC 6298 SRTT gain
    rtt_beta: float = 0.25  # RFC 6298 RTTVAR gain
    loss_alpha: float = 0.2  # loss EWMA gain per ping outcome
    min_send_timeout: float = 0.5
    max_send_timeout: float = 10.0
    quarantine_loss: float = 0.5  # loss EWMA at/over this is "bad"
    quarantine_rtt: float = 2.0  # seconds of SRTT at/over this is "bad"
    quarantine_after: int = 3  # consecutive bad ticks to enter
    requalify_after: int = 4  # consecutive good ticks to leave
    queue_capacity: int = 4096  # bounded shared-lane depth (frames)


class PeerNetEstimator:
    """One peer's link-quality state. Mutated from the pinger thread and
    the peer's recv loop; a plain lock guards the short update sections
    (no blocking calls inside — chaos.py precedent for unaudited locks)."""

    def __init__(self, cfg: NetTransportConfig):
        self.cfg = cfg
        self._mtx = threading.Lock()
        self.srtt: float | None = None
        self.rttvar = 0.0
        self.loss = 0.0
        self.backlog = 0
        self.quarantined = False
        self.transitions = 0  # quarantine enter/leave count
        self.pings_sent = 0
        self.pongs = 0
        self.ping_timeouts = 0
        self._outstanding: dict[int, float] = {}
        self._seq = itertools.count(1)
        self._bad = 0
        self._good = 0

    def next_ping(self, now: float) -> bytes | None:
        """Payload for the next PING, or None while the peer is so far
        behind that more probes would only inflate the loss estimate."""
        with self._mtx:
            if len(self._outstanding) >= self.cfg.max_outstanding:
                return None
            nonce = next(self._seq) & 0xFFFFFFFF
            self._outstanding[nonce] = now
            self.pings_sent += 1
            return _PING_FMT.pack(nonce)

    def on_pong(self, payload: bytes, now: float) -> None:
        if len(payload) != _PING_FMT.size:
            return
        (nonce,) = _PING_FMT.unpack(payload)
        cfg = self.cfg
        with self._mtx:
            t = self._outstanding.pop(nonce, None)
            if t is None:
                return  # late pong already counted as a loss
            self.pongs += 1
            rtt = max(now - t, 0.0)
            if self.srtt is None:
                self.srtt = rtt
                self.rttvar = rtt / 2.0
            else:
                self.rttvar = (1.0 - cfg.rtt_beta) * self.rttvar + cfg.rtt_beta * abs(
                    self.srtt - rtt
                )
                self.srtt = (1.0 - cfg.rtt_alpha) * self.srtt + cfg.rtt_alpha * rtt
            self.loss = (1.0 - cfg.loss_alpha) * self.loss

    def expire(self, now: float) -> None:
        cfg = self.cfg
        with self._mtx:
            dead = [
                n
                for n, t in self._outstanding.items()
                if now - t > cfg.ping_timeout
            ]
            for n in dead:
                del self._outstanding[n]
                self.ping_timeouts += 1
                self.loss = (1.0 - cfg.loss_alpha) * self.loss + cfg.loss_alpha

    def send_timeout(self) -> float:
        """Adaptive whole-frame send timeout: generous before the first
        RTT sample, then 2*SRTT + 4*RTTVAR (+grace), clamped."""
        cfg = self.cfg
        with self._mtx:
            if self.srtt is None:
                return cfg.max_send_timeout
            raw = 2.0 * self.srtt + 4.0 * self.rttvar + 0.25
        return min(max(raw, cfg.min_send_timeout), cfg.max_send_timeout)

    def note_tick(self, backlog: int) -> None:
        """Once per pinger tick: sample backlog, run quarantine hysteresis."""
        cfg = self.cfg
        with self._mtx:
            self.backlog = backlog
            bad = self.loss >= cfg.quarantine_loss or (
                self.srtt is not None and self.srtt >= cfg.quarantine_rtt
            )
            if bad:
                self._bad += 1
                self._good = 0
            else:
                self._good += 1
                self._bad = 0
            if not self.quarantined and self._bad >= cfg.quarantine_after:
                self.quarantined = True
                self.transitions += 1
            elif self.quarantined and self._good >= cfg.requalify_after:
                self.quarantined = False
                self.transitions += 1

    def snapshot(self) -> dict:
        cfg = self.cfg
        with self._mtx:
            if self.srtt is None:
                timeout = cfg.max_send_timeout
            else:
                timeout = min(
                    max(
                        2.0 * self.srtt + 4.0 * self.rttvar + 0.25,
                        cfg.min_send_timeout,
                    ),
                    cfg.max_send_timeout,
                )
            return {
                "rtt_ms": None if self.srtt is None else self.srtt * 1e3,
                "rttvar_ms": self.rttvar * 1e3,
                "loss": self.loss,
                "backlog": self.backlog,
                "send_timeout_s": timeout,
                "quarantined": self.quarantined,
                "transitions": self.transitions,
                "pings_sent": self.pings_sent,
                "pongs": self.pongs,
                "ping_timeouts": self.ping_timeouts,
                "outstanding": len(self._outstanding),
            }


class BoundedSendQueue:
    """Priority send queue with oldest-bulk drop instead of blocking.

    Drop-in for the per-peer shared-lane PriorityQueue (items are
    ``(prio, seq, chan_id, msg)`` with LOWER prio = MORE important). When
    full, a newcomer evicts the OLDEST frame of the numerically-largest
    (least important) lane — but never a frame more important than
    itself: if everything queued outranks it, the newcomer is rejected
    (queue.Full), which the peer counts as send_fail exactly like the
    legacy queue. ``put`` therefore never blocks; its ``timeout`` arg is
    accepted for interface parity and ignored.
    """

    def __init__(self, capacity: int):
        self._capacity = max(int(capacity), 1)
        self._buckets: dict[int, deque] = {}
        self._size = 0
        self._cond = threading.Condition()
        self.dropped = 0  # evicted-oldest frames (txflow_net_sendq_dropped)

    def put_nowait(self, item) -> None:
        prio = item[0]
        with self._cond:
            if self._size >= self._capacity:
                worst = max(self._buckets)
                if worst < prio:
                    raise queue.Full  # everything queued outranks newcomer
                dq = self._buckets[worst]
                dq.popleft()
                if not dq:
                    del self._buckets[worst]
                self._size -= 1
                self.dropped += 1
            self._buckets.setdefault(prio, deque()).append(item)
            self._size += 1
            self._cond.notify()

    def put(self, item, timeout: float | None = None) -> None:
        self.put_nowait(item)

    def get(self, timeout: float | None = None):
        with self._cond:
            if not self._size:
                self._cond.wait(timeout)
                if not self._size:
                    raise queue.Empty
            best = min(self._buckets)
            dq = self._buckets[best]
            item = dq.popleft()
            if not dq:
                del self._buckets[best]
            self._size -= 1
            return item

    def qsize(self) -> int:
        return self._size


def run_pinger(switch, stop: threading.Event) -> None:
    """Pinger loop body (one thread per configured Switch): every interval,
    expire stale probes, run quarantine ticks, and ping each peer through
    the NORMAL send path (lowest priority; chaos/shaper see it like any
    other frame, so probe loss tracks real frame loss)."""
    from .switch import _PING_CHANNEL  # late: avoid import cycle

    cfg = switch._net_config
    while not stop.wait(cfg.ping_interval):
        for peer in switch.peers():
            net = peer.net
            if net is None:
                continue
            now = clock.monotonic()
            net.expire(now)
            net.note_tick(peer._send_q.qsize())
            payload = net.next_ping(now)
            if payload is not None:
                peer.try_send(_PING_CHANNEL, payload)
