"""P2P gossip layer (reference: tendermint p2p Switch/Peer + reactors).

The reference routes amino-framed messages over prioritized byte-channels
of a TCP MultiplexTransport (node/node.go:420-505); reactors implement
``p2p.Reactor`` and register channel descriptors (e.g. the txvotepool
reactor on channel 0x32, txvotepool/reactor.go:25,142-149).

This package keeps those semantics — reactors, channel ids, priorities,
per-peer send loops with backpressure, sender suppression — over a
transport interface with two implementations: in-memory duplex pipes for
in-process validator networks (the reference's MakeConnectedSwitches test
trick, used here for the BASELINE configs and the gossip tests) and TCP
sockets for multi-host DCN deployment.

Design deviation, deliberate and TPU-first: where the reference gossips
one vote per message (txvotepool/reactor.go:236-251), send loops here
drain *batches* of pool entries into one framed message. The consumer of
those batches is a device kernel that wants thousands of votes at once;
per-vote wire messages would bottleneck the host long before the MXU sees
work.
"""

from .base import (
    ChannelDescriptor,
    Reactor,
    CHANNEL_MEMPOOL,
    CHANNEL_TXVOTE,
    CHANNEL_CONSENSUS_STATE,
    CHANNEL_CONSENSUS_DATA,
    CHANNEL_CONSENSUS_VOTE,
)
from .switch import Peer, Switch, connect_switches, make_connected_switches
from .transport import InMemoryConnection, connection_pair

__all__ = [
    "ChannelDescriptor",
    "Reactor",
    "Peer",
    "Switch",
    "connect_switches",
    "make_connected_switches",
    "InMemoryConnection",
    "connection_pair",
    "CHANNEL_MEMPOOL",
    "CHANNEL_TXVOTE",
    "CHANNEL_CONSENSUS_STATE",
    "CHANNEL_CONSENSUS_DATA",
    "CHANNEL_CONSENSUS_VOTE",
]
