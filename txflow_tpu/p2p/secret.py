"""SecretConnection: authenticated, encrypted peer links (the upstream
tendermint secret-connection slot the reference rides for every p2p
socket — Station-to-Station over X25519 + ed25519 identity signatures,
ChaCha20-Poly1305 frames).

Handshake (both directions symmetric):
1. exchange 32-byte ephemeral X25519 public keys in the clear;
2. shared = X25519(eph_priv, peer_eph_pub); role = lexicographic order
   of the two ephemeral pubkeys (lo/hi, like upstream);
3. HKDF-SHA256(shared, transcript=lo||hi) -> (key_lo->hi, key_hi->lo,
   challenge);
4. each side sends, ENCRYPTED, its ed25519 node pubkey + signature over
   the challenge; the peer verifies the signature before any payload
   flows. The authenticated identity is exposed as ``peer_pub_key`` /
   ``peer_id`` (address hex) — the switch uses it as the node id, so ids
   cannot be spoofed the way the plaintext string handshake allows.

Frames: u32-be length || ChaCha20-Poly1305(ciphertext of
``chan_id u8 || payload``), nonce = 12-byte little-endian per-direction
counter (distinct keys per direction, so counters cannot collide).
"""

from __future__ import annotations

import hashlib
import hmac as hmac_mod
import socket
import struct
import threading

from ..analysis.lockgraph import make_lock

try:  # OpenSSL-backed AEAD when available (the normal case)
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
except ImportError:  # pragma: no cover - environment-dependent
    ChaCha20Poly1305 = None

from ..crypto import ed25519, x25519
from ..crypto.hash import address_hash
from .transport import MAX_FRAME_BYTES, ConnectionClosed

_LEN = struct.Struct("!I")


class _HashlibAEAD:
    """Stdlib-only AEAD with the ChaCha20Poly1305 call surface.

    Used only when the ``cryptography`` package is absent: encrypt-then-MAC
    with an HMAC-SHA256 keystream in counter mode and a 16-byte truncated
    HMAC-SHA256 tag over nonce||aad||ciphertext. Same 16-byte overhead as
    Poly1305, so the frame-length cap math is unchanged. Both endpoints of
    a deployment run the same image, so the two AEADs never need to
    interoperate on the wire.
    """

    _TAG = 16

    def __init__(self, key: bytes):
        self._enc_key = hashlib.sha256(b"txflow-aead-enc" + key).digest()
        self._mac_key = hashlib.sha256(b"txflow-aead-mac" + key).digest()

    def _keystream(self, nonce: bytes, n: int) -> bytes:
        out = bytearray()
        block = 0
        while len(out) < n:
            out += hmac_mod.new(
                self._enc_key, nonce + block.to_bytes(8, "little"), hashlib.sha256
            ).digest()
            block += 1
        return bytes(out[:n])

    @staticmethod
    def _xor(a: bytes, b: bytes) -> bytes:
        return (int.from_bytes(a, "little") ^ int.from_bytes(b, "little")).to_bytes(
            len(a), "little"
        )

    def _tag(self, nonce: bytes, aad: bytes, ct: bytes) -> bytes:
        return hmac_mod.new(
            self._mac_key, nonce + aad + ct, hashlib.sha256
        ).digest()[: self._TAG]

    def encrypt(self, nonce: bytes, data: bytes, aad: bytes) -> bytes:
        ct = self._xor(data, self._keystream(nonce, len(data)))
        return ct + self._tag(nonce, aad or b"", ct)

    def decrypt(self, nonce: bytes, data: bytes, aad: bytes) -> bytes:
        if len(data) < self._TAG:
            raise ValueError("aead: frame shorter than tag")
        ct, tag = data[: -self._TAG], data[-self._TAG :]
        if not hmac_mod.compare_digest(tag, self._tag(nonce, aad or b"", ct)):
            raise ValueError("aead: tag mismatch")
        return self._xor(ct, self._keystream(nonce, len(ct)))


_AEAD = ChaCha20Poly1305 if ChaCha20Poly1305 is not None else _HashlibAEAD


def _hkdf_sha256(ikm: bytes, info: bytes, n: int) -> bytes:
    """HKDF (RFC 5869) with a fixed zero salt."""
    prk = hmac_mod.new(b"\x00" * 32, ikm, hashlib.sha256).digest()
    out, t, i = b"", b"", 1
    while len(out) < n:
        t = hmac_mod.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
        out += t
        i += 1
    return out[:n]


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionClosed()
        buf += chunk
    return buf


class SecretConnection:
    """Same send/recv surface as transport.TCPConnection, authenticated."""

    HANDSHAKE_TIMEOUT = 10.0

    def __init__(self, sock: socket.socket, node_seed: bytes, label: str = ""):
        self._sock = sock
        self._wlock = make_lock("p2p.SecretConnection._wlock", allow_blocking=True)
        self._closed = threading.Event()
        self.label = label
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # the WHOLE handshake is time-bounded: a silent client must not
        # wedge the caller (the plaintext path bounds its handshake recv;
        # an unbounded one here is a zero-byte DoS on the accept path)
        prev_timeout = sock.gettimeout()
        sock.settimeout(self.HANDSHAKE_TIMEOUT)
        try:
            self._handshake(sock, node_seed)
        except (socket.timeout, TimeoutError):
            self.close()
            raise ValueError("secret connection: handshake timeout")
        except Exception:
            self.close()
            raise
        finally:
            if not self._closed.is_set():
                try:
                    sock.settimeout(prev_timeout)
                except OSError:
                    pass

    def _handshake(self, sock: socket.socket, node_seed: bytes) -> None:
        # 1-2: ephemeral exchange + shared secret
        eph_priv = x25519.generate_private()
        eph_pub = x25519.public_key(eph_priv)
        sock.sendall(eph_pub)
        peer_eph = _recv_exact(sock, 32)
        shared = x25519.shared_secret(eph_priv, peer_eph)
        lo, hi = sorted((eph_pub, peer_eph))
        we_are_lo = eph_pub == lo

        # 3: key schedule + challenge
        material = _hkdf_sha256(shared, b"txflow-secret-conn" + lo + hi, 96)
        key_lo_to_hi, key_hi_to_lo = material[:32], material[32:64]
        challenge = material[64:]
        self._send_aead = _AEAD(key_lo_to_hi if we_are_lo else key_hi_to_lo)
        self._recv_aead = _AEAD(key_hi_to_lo if we_are_lo else key_lo_to_hi)
        self._send_ctr = 0
        self._recv_ctr = 0

        # 4: authenticate identities over the encrypted channel. The signed
        # material binds the signer's ROLE via its own ephemeral key: both
        # directions share `challenge`, so a bare signature over it could be
        # reflected back by a keyless man-in-the-middle (decrypt our auth
        # frame, re-encrypt under its own send key) to authenticate as us.
        # Signing challenge||own-ephemeral makes the two directions sign
        # different messages (echoing our ephemeral back would leave the
        # attacker without the DH shared secret, so it cannot re-frame).
        node_pub = ed25519.public_key_from_seed(node_seed)
        sig = ed25519.sign(node_seed, challenge + eph_pub)
        self._send_frame(0xFF, node_pub + sig)
        chan, auth = self._recv_frame()
        if chan != 0xFF or len(auth) != 96:
            raise ValueError("secret connection: bad auth frame")
        peer_pub, peer_sig = auth[:32], auth[32:]
        if peer_pub == node_pub:
            raise ValueError("secret connection: peer claims our own identity")
        if not ed25519.verify(peer_pub, challenge + peer_eph, peer_sig):
            raise ValueError("secret connection: peer identity signature invalid")
        self.peer_pub_key = peer_pub
        self.peer_id = address_hash(peer_pub).hex().upper()

    # -- framing (TCPConnection-compatible surface) --

    def _nonce(self, ctr: int) -> bytes:
        return ctr.to_bytes(12, "little")

    def _send_frame(self, chan_id: int, msg: bytes) -> None:
        with self._wlock:
            ct = self._send_aead.encrypt(
                self._nonce(self._send_ctr), bytes([chan_id]) + msg, b""
            )
            self._send_ctr += 1
            self._sock.sendall(_LEN.pack(len(ct)) + ct)  # txlint: allow(lock-blocking) -- _wlock EXISTS to serialize frame writes; nonce counter and wire bytes must advance together

    def _recv_frame(self, timeout: float | None = None) -> tuple[int, bytes]:
        prev = self._sock.gettimeout()
        if timeout is not None:
            self._sock.settimeout(timeout)
        try:
            hdr = _recv_exact(self._sock, _LEN.size)
            (n,) = _LEN.unpack(hdr)
            if n > MAX_FRAME_BYTES + 17:
                raise ConnectionClosed()
            ct = _recv_exact(self._sock, n)
            try:
                pt = self._recv_aead.decrypt(self._nonce(self._recv_ctr), ct, b"")
            except Exception:
                # tampered/replayed frame: the link is gone, not retryable
                raise ConnectionClosed()
            self._recv_ctr += 1
            return pt[0], pt[1:]
        except socket.timeout:
            self.close()
            raise ConnectionClosed()
        except OSError:
            raise ConnectionClosed()
        finally:
            if timeout is not None and not self._closed.is_set():
                try:
                    self._sock.settimeout(prev)
                except OSError:
                    pass

    def send(self, chan_id: int, msg: bytes, timeout: float | None = 10.0) -> bool:
        if self._closed.is_set():
            return False
        if len(msg) > MAX_FRAME_BYTES:
            raise ValueError(f"frame too large: {len(msg)}")
        try:
            self._send_frame(chan_id, msg)
            return True
        except OSError:
            self.close()
            return False

    def try_send(self, chan_id: int, msg: bytes) -> bool:
        """Best-effort send: skips (False) when another sender holds the
        write lock. Unlike TCPConnection.try_send it cannot probe the
        kernel buffer first — AEAD nonces must advance in lockstep with
        wire bytes, so a frame once encrypted is always written in full."""
        if self._closed.is_set():
            return False
        if len(msg) > MAX_FRAME_BYTES:
            raise ValueError(f"frame too large: {len(msg)}")
        if not self._wlock.acquire(blocking=False):
            return False
        try:
            ct = self._send_aead.encrypt(
                self._nonce(self._send_ctr), bytes([chan_id]) + msg, b""
            )
            self._send_ctr += 1
            self._sock.sendall(_LEN.pack(len(ct)) + ct)  # txlint: allow(lock-blocking) -- same nonce/wire lockstep contract as _send_frame
            return True
        except OSError:
            self.close()
            return False
        finally:
            self._wlock.release()

    def recv(self, timeout: float | None = None) -> tuple[int, bytes]:
        if self._closed.is_set():
            raise ConnectionClosed()
        return self._recv_frame(timeout)

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    @property
    def is_closed(self) -> bool:
        return self._closed.is_set()
