"""Switch + Peer: reactor registry and per-peer message loops.

Reference semantics kept (p2p.Switch, node/node.go:488-505):
- reactors register channel descriptors; one reactor owns each channel id;
- every peer gets a prioritized outbound queue drained by one send thread
  (the reference's per-peer MConnection send routine) and one recv thread
  dispatching inbound frames to the owning reactor's ``receive``;
- a reactor error on receive stops the peer (txvotepool/reactor.go:174);
- ``make_connected_switches`` wires N switches fully connected over
  in-memory pipes — the reference's in-process-testnet trick
  (p2p.MakeConnectedSwitches, txvotepool/reactor_test.go:47-66).
"""

from __future__ import annotations

import itertools
import queue
import threading

from ..analysis.lockgraph import make_rlock
import time

from .base import ChannelDescriptor, Reactor
from .transport import (
    ConnectionClosed,
    TCPConnection,
    connection_pair,
    tcp_connect,
)

_HANDSHAKE_CHANNEL = 0xFF
_WAKE_CHANNEL = 0xFE  # internal sentinel: wakes a send loop, never sent
# link-quality probes (p2p/adaptive.py): sent only by switches with
# configure_net(); every switch answers PING so mixed fleets interoperate
_PING_CHANNEL = 0xFD
_PONG_CHANNEL = 0xFC


class PeerStats:
    """Per-peer liveness counters read by the health layer's peer scorer
    (health/peers.py). Plain int bumps under the GIL — the send/recv loops
    must not pay a lock for observability; the scorer reads deltas between
    ticks, so a torn read only smears one tick."""

    __slots__ = (
        "send_attempts",
        "send_ok",
        "send_fail",
        "recv_count",
        "duplicates",
        "last_recv",
        "connected_at",
    )

    def __init__(self):
        now = time.monotonic()
        # frames handed to send/try_send, counted BEFORE the fault-
        # injection hook: a black-holed link (chaos partition) reports
        # send success and never reaches the transport loop, so attempt
        # count is the only signal that we kept talking to a silent peer
        # (health/peers.py staleness gate)
        self.send_attempts = 0
        self.send_ok = 0  # frames handed to the transport successfully
        self.send_fail = 0  # transport failures + queue-full backpressure
        self.recv_count = 0  # frames received from the peer
        self.duplicates = 0  # frames the owning reactor flagged as dups
        self.last_recv = now
        self.connected_at = now


class Peer:
    """A connected remote switch endpoint."""

    _id_counter = itertools.count(1)

    def __init__(
        self,
        conn,
        node_id: str,
        outbound: bool,
        channels: dict[int, ChannelDescriptor],
        net_config=None,
    ):
        self.conn = conn
        self.node_id = node_id
        self.outbound = outbound
        self.kv: dict[str, object] = {}  # peer state (reference peer.Set/Get)
        self._channels = channels
        if net_config is not None:
            # adaptive transport (p2p/adaptive.py): bounded shared lane
            # with oldest-bulk drop + per-peer link estimator. Opt-in —
            # the legacy blocking PriorityQueue below stays bit-identical
            # for unconfigured switches.
            from .adaptive import BoundedSendQueue, PeerNetEstimator

            self._send_q = BoundedSendQueue(net_config.queue_capacity)
            self.net = PeerNetEstimator(net_config)
        else:
            self._send_q = queue.PriorityQueue(maxsize=4096)
            self.net = None
        # lane for reliable channels (consensus): never dropped under BULK
        # pressure (its pressure is its own), drained ahead of the shared
        # queue. Bounded all the same — a stalled peer must not grow memory
        # without limit; at this depth the peer is effectively dead and
        # will resync via block catchup when it returns
        self._reliable_q: queue.Queue = queue.Queue(maxsize=1024)
        self._seq = itertools.count()
        self._running = threading.Event()
        self._send_thread: threading.Thread | None = None
        self._recv_thread: threading.Thread | None = None
        # fault-injection hook (faults.chaos.ChaosRouter): consulted by
        # send/try_send with (peer, chan_id, msg); returns None to pass the
        # message through, or a bool send-result when it handled (dropped,
        # deferred, duplicated) it. Installed via Switch.set_fault_injector;
        # None (the default) costs one attribute read on the send path.
        self.intercept = None
        self.stats = PeerStats()

    def set(self, key: str, value) -> None:
        self.kv[key] = value

    def get(self, key: str, default=None):
        return self.kv.get(key, default)

    def _is_reliable(self, chan_id: int) -> bool:
        ch = self._channels.get(chan_id)
        return ch is not None and ch.reliable

    def _put_reliable(self, chan_id: int, msg: bytes) -> bool:
        try:
            self._reliable_q.put_nowait((chan_id, msg))
        except queue.Full:
            self.stats.send_fail += 1
            return False  # peer stalled beyond any live-round backlog
        # wake the send loop if it is blocked on the shared queue
        try:
            self._send_q.put_nowait((-(1 << 30), next(self._seq), _WAKE_CHANNEL, b""))
        except queue.Full:
            pass  # loop is busy draining anyway
        return True

    def send(self, chan_id: int, msg: bytes, timeout: float | None = 10.0) -> bool:
        """Queue a message; blocks under backpressure. False if peer down."""
        if not self._running.is_set():
            return False
        self.stats.send_attempts += 1
        ic = self.intercept
        if ic is not None:
            handled = ic(self, chan_id, msg)
            if handled is not None:
                return handled
        return self.send_direct(chan_id, msg, timeout)

    def send_direct(self, chan_id: int, msg: bytes, timeout: float | None = 10.0) -> bool:
        """send() minus the fault-injection hook (chaos late deliveries)."""
        if not self._running.is_set():
            return False
        if self._is_reliable(chan_id):
            return self._put_reliable(chan_id, msg)
        prio = -self._channels[chan_id].priority if chan_id in self._channels else 0
        try:
            self._send_q.put((prio, next(self._seq), chan_id, msg), timeout=timeout)
            return True
        except queue.Full:
            self.stats.send_fail += 1
            return False

    def try_send(self, chan_id: int, msg: bytes) -> bool:
        if not self._running.is_set():
            return False
        self.stats.send_attempts += 1
        ic = self.intercept
        if ic is not None:
            handled = ic(self, chan_id, msg)
            if handled is not None:
                return handled
        return self.try_send_direct(chan_id, msg)

    def try_send_direct(self, chan_id: int, msg: bytes) -> bool:
        if not self._running.is_set():
            return False
        if self._is_reliable(chan_id):
            return self._put_reliable(chan_id, msg)
        prio = -self._channels[chan_id].priority if chan_id in self._channels else 0
        try:
            self._send_q.put_nowait((prio, next(self._seq), chan_id, msg))
            return True
        except queue.Full:
            self.stats.send_fail += 1
            return False

    def is_running(self) -> bool:
        return self._running.is_set()

    def __repr__(self) -> str:
        return f"Peer({self.node_id}{' out' if self.outbound else ' in'})"


class SwitchError(Exception):
    pass


class Switch:
    def __init__(self, node_id: str, node_seed: bytes | None = None):
        # ed25519 node key: when set, TCP links use the authenticated
        # SecretConnection and peer ids are derived from VERIFIED pubkeys
        # (upstream rides secret connections for every socket,
        # node/node.go:420-505); None = plaintext string handshake
        # (in-proc pipes, legacy tests).
        #
        # With a key, our OWN advertised id must be the same verified-key
        # address our peers will register us under — otherwise PEX compares
        # book ids against verified ids, never sees a match, and redials
        # every known peer forever (r3 review finding).
        if node_seed is not None:
            from ..crypto import ed25519 as _ed
            from ..crypto.hash import address_hash as _ah

            self.node_id = _ah(_ed.public_key_from_seed(node_seed)).hex().upper()
        else:
            self.node_id = node_id
        self._node_seed = node_seed
        self.reactors: dict[str, Reactor] = {}
        self._chan_to_reactor: dict[int, Reactor] = {}
        self._channels: dict[int, ChannelDescriptor] = {}
        self._peers: dict[str, Peer] = {}
        self._mtx = make_rlock("p2p.Switch._mtx")
        self._running = False
        self._fault_injector = None
        self._link_shaper = None  # netem.LinkShaper, wraps future conns
        self._net_config = None  # adaptive.NetTransportConfig (opt-in)
        self._net_stop: threading.Event | None = None

    # -- reactor registry (reference Switch.AddReactor) --

    def add_reactor(self, name: str, reactor: Reactor) -> Reactor:
        with self._mtx:
            for ch in reactor.get_channels():
                if ch.id in self._chan_to_reactor:
                    raise SwitchError(f"channel {ch.id:#x} already registered")
                self._chan_to_reactor[ch.id] = reactor
                self._channels[ch.id] = ch
            self.reactors[name] = reactor
        reactor.set_switch(self)
        return reactor

    def reactor(self, name: str) -> Reactor | None:
        return self.reactors.get(name)

    # -- lifecycle --

    def start(self) -> None:
        with self._mtx:
            if self._running:
                return
            self._running = True
            reactors = list(self.reactors.values())
        for r in reactors:
            r.on_start()

    def stop(self) -> None:
        with self._mtx:
            if not self._running:
                return
            self._running = False
            peers = list(self._peers.values())
        if self._net_stop is not None:
            self._net_stop.set()
        self.close_listener()
        for p in peers:
            self.stop_peer(p, reason="switch stopping")
        for r in list(self.reactors.values()):
            r.on_stop()

    @property
    def is_running(self) -> bool:
        return self._running

    # -- peers --

    def peers(self) -> list[Peer]:
        with self._mtx:
            return list(self._peers.values())

    def n_peers(self) -> int:
        with self._mtx:
            return len(self._peers)

    def get_peer(self, node_id: str) -> Peer | None:
        with self._mtx:
            return self._peers.get(node_id)

    def set_fault_injector(self, injector) -> None:
        """Install (or clear, with None) a fault injector on this switch:
        every current and future peer's send path consults
        ``injector.make_interceptor(self.node_id, peer.node_id)``
        (faults.chaos.ChaosRouter). Test/chaos-rig plumbing — never set in
        production assembly."""
        with self._mtx:
            self._fault_injector = injector
            peers = list(self._peers.values())
        for p in peers:
            p.intercept = (
                None
                if injector is None
                else injector.make_interceptor(self.node_id, p.node_id)
            )

    def set_link_shaper(self, shaper) -> None:
        """Install a netem.LinkShaper: every FUTURE peer connection is
        wrapped in its directed-link weather (install before connecting —
        existing links keep their raw transport). Clear with None."""
        with self._mtx:
            self._link_shaper = shaper

    def configure_net(self, config=None) -> None:
        """Enable the adaptive peer transport (p2p/adaptive.py): bounded
        per-peer send queues, RTT/loss/backlog estimators fed by a pinger
        thread, adaptive send timeouts, and quarantine flags the health
        scoreboard folds into score-floor eviction. Opt-in: a bare Switch
        keeps exact legacy queue/no-ping behavior."""
        from .adaptive import NetTransportConfig, run_pinger

        with self._mtx:
            if self._net_config is not None:
                self._net_config = config or NetTransportConfig()
                return
            self._net_config = config or NetTransportConfig()
            self._net_stop = threading.Event()
        threading.Thread(
            target=run_pinger,
            args=(self, self._net_stop),
            name=f"p2p-ping-{self.node_id}",
            daemon=True,
        ).start()

    def net_snapshot(self) -> dict:
        """Per-peer link-quality + shaping counters (health /metrics/bench)."""
        out: dict = {
            "configured": self._net_config is not None,
            "peers": {},
            "quarantined": 0,
            "sendq_dropped": 0,
        }
        for p in self.peers():
            dropped = getattr(p._send_q, "dropped", 0)
            out["sendq_dropped"] += dropped
            net = p.net
            if net is None:
                continue
            snap = net.snapshot()
            snap["sendq_dropped"] = dropped
            snap["backlog"] = p._send_q.qsize()
            out["peers"][p.node_id] = snap
            if snap["quarantined"]:
                out["quarantined"] += 1
        shaper = self._link_shaper
        if shaper is not None:
            out["shaper"] = shaper.snapshot()
        return out

    def add_peer_conn(self, conn, node_id: str, outbound: bool) -> Peer:
        """Attach a live connection as a peer and start its loops."""
        if self._link_shaper is not None:
            conn = self._link_shaper.wrap(conn, self.node_id, node_id)
        peer = Peer(
            conn, node_id, outbound, dict(self._channels), net_config=self._net_config
        )
        if self._fault_injector is not None:
            peer.intercept = self._fault_injector.make_interceptor(
                self.node_id, node_id
            )
        with self._mtx:
            if not self._running:
                # a handshake completing during/after stop() must not
                # register threads and sockets nothing will ever stop
                conn.close()
                raise SwitchError("switch is stopped")
            if node_id in self._peers:
                conn.close()
                raise SwitchError(f"duplicate peer {node_id}")
            if node_id == self.node_id:
                conn.close()
                raise SwitchError("cannot connect to self")
            self._peers[node_id] = peer
        peer._running.set()
        peer._send_thread = threading.Thread(
            target=self._send_loop, args=(peer,), name=f"p2p-send-{node_id}", daemon=True
        )
        peer._recv_thread = threading.Thread(
            target=self._recv_loop, args=(peer,), name=f"p2p-recv-{node_id}", daemon=True
        )
        peer._send_thread.start()
        peer._recv_thread.start()
        for r in list(self.reactors.values()):
            r.add_peer(peer)
        return peer

    def dial_tcp(self, host: str, port: int) -> Peer:
        """Outbound TCP connect. With a node key: authenticated secret
        connection, peer id = verified pubkey address. Without: legacy
        plaintext node-id string handshake."""
        if self._node_seed is not None:
            from .secret import SecretConnection
            from .transport import tcp_connect_raw

            conn = SecretConnection(
                tcp_connect_raw(host, port), self._node_seed, label=f"{host}:{port}"
            )
            return self.add_peer_conn(conn, conn.peer_id, outbound=True)
        conn = tcp_connect(host, port)
        conn.send(_HANDSHAKE_CHANNEL, self.node_id.encode())
        chan_id, payload = conn.recv(timeout=5.0)
        if chan_id != _HANDSHAKE_CHANNEL:
            conn.close()
            raise SwitchError("handshake expected")
        return self.add_peer_conn(conn, payload.decode(), outbound=True)

    def accept_tcp(self, sock) -> Peer:
        """Inbound accept (call with an accepted socket); secret-connection
        authenticated when this switch has a node key."""
        if self._node_seed is not None:
            from .secret import SecretConnection

            conn = SecretConnection(sock, self._node_seed)
            return self.add_peer_conn(conn, conn.peer_id, outbound=False)
        conn = TCPConnection(sock)
        chan_id, payload = conn.recv(timeout=5.0)
        if chan_id != _HANDSHAKE_CHANNEL:
            conn.close()
            raise SwitchError("handshake expected")
        conn.send(_HANDSHAKE_CHANNEL, self.node_id.encode())
        return self.add_peer_conn(conn, payload.decode(), outbound=False)

    def listen_tcp(self, host: str = "127.0.0.1", port: int = 0) -> tuple[str, int]:
        """Start accepting inbound TCP peers (reference transport.Listen,
        node/node.go:795-800). Returns the bound (host, port)."""
        from .transport import tcp_listen

        srv = tcp_listen(host, port)
        self._listener = srv
        self.listen_addr = srv.getsockname()

        def _handshake_one(sock):
            try:
                self.accept_tcp(sock)
            except Exception:
                try:
                    sock.close()
                except OSError:
                    pass

        def _accept_loop():
            while True:
                try:
                    sock, _ = srv.accept()
                except OSError:
                    return  # listener closed
                # handshake off the accept thread: one slow/silent client
                # must not block further accepts (handshakes are also
                # individually time-bounded in SecretConnection)
                threading.Thread(
                    target=_handshake_one, args=(sock,), daemon=True
                ).start()

        threading.Thread(
            target=_accept_loop, name=f"p2p-accept-{self.node_id}", daemon=True
        ).start()
        return self.listen_addr

    def close_listener(self) -> None:
        srv = getattr(self, "_listener", None)
        if srv is not None:
            try:
                srv.close()
            except OSError:
                pass

    def stop_peer(self, peer: Peer, reason: object = None) -> None:
        with self._mtx:
            existing = self._peers.pop(peer.node_id, None)
        if existing is None:
            return
        peer._running.clear()
        peer.conn.close()
        for r in list(self.reactors.values()):
            r.remove_peer(peer, reason)

    def stop_peer_for_error(self, peer: Peer, err: object) -> None:
        """Reference StopPeerForError: tear down a misbehaving peer."""
        import os
        import sys

        if os.environ.get("TXFLOW_P2P_QUIET") != "1":
            # reference logs every peer stop (p2p/switch.go); a silent stop
            # here buried real consensus bugs in r3 debugging
            print(
                f"p2p[{self.node_id}]: stopping peer {peer.node_id}: {err!r}",
                file=sys.stderr,
            )
        self.stop_peer(peer, reason=err)

    # -- message plumbing --

    def broadcast(self, chan_id: int, msg: bytes) -> None:
        for p in self.peers():
            p.try_send(chan_id, msg)

    def _send_loop(self, peer: Peer) -> None:
        while peer._running.is_set():
            # reliable lane first: consensus messages must not wait behind
            # (or be dropped by) bulk txvote/mempool batches
            try:
                chan_id, msg = peer._reliable_q.get_nowait()
            except queue.Empty:
                try:
                    _, _, chan_id, msg = peer._send_q.get(timeout=0.2)
                except queue.Empty:
                    continue
                if chan_id == _WAKE_CHANNEL:
                    continue
            net = peer.net
            timeout = 10.0 if net is None else net.send_timeout()
            if not peer.conn.send(chan_id, msg, timeout):
                peer.stats.send_fail += 1
                self.stop_peer(peer, reason="send failed")
                return
            peer.stats.send_ok += 1

    def _recv_loop(self, peer: Peer) -> None:
        while peer._running.is_set():
            try:
                chan_id, msg = peer.conn.recv()
            except ConnectionClosed:
                self.stop_peer(peer, reason="connection closed")
                return
            except TimeoutError:
                continue
            st = peer.stats
            st.recv_count += 1
            st.last_recv = time.monotonic()
            if chan_id == _PING_CHANNEL:
                # answer through the full send path (interceptor included):
                # the pong rides OUR outbound direction, so a cut or shaped
                # reverse link must cost pongs — that asymmetry is exactly
                # what the prober's loss estimate should see
                peer.try_send(_PONG_CHANNEL, msg)
                continue
            if chan_id == _PONG_CHANNEL:
                net = peer.net
                if net is not None:
                    net.on_pong(msg, time.monotonic())
                continue
            reactor = self._chan_to_reactor.get(chan_id)
            if reactor is None:
                continue  # unknown channel: ignore (switch filters by NodeInfo upstream)
            try:
                reactor.receive(chan_id, peer, msg)
            except Exception as e:  # reference: undecodable msg stops the peer
                self.stop_peer_for_error(peer, e)
                return


def connect_switches(a: Switch, b: Switch, capacity: int = 1024) -> tuple[Peer, Peer]:
    """Wire two switches with an in-memory duplex pipe (reference
    p2p.Connect2Switches)."""
    ca, cb = connection_pair(capacity, labels=(f"{a.node_id}->{b.node_id}", f"{b.node_id}->{a.node_id}"))
    pa = a.add_peer_conn(ca, b.node_id, outbound=True)
    pb = b.add_peer_conn(cb, a.node_id, outbound=False)
    return pa, pb


def make_connected_switches(n: int, init_switch, start: bool = True) -> list[Switch]:
    """N switches, fully connected (reference p2p.MakeConnectedSwitches).

    ``init_switch(i, switch)`` registers reactors on switch i and returns
    the switch (mirroring the initSwitch callback upstream).
    """
    switches = [init_switch(i, Switch(f"node{i}")) for i in range(n)]
    if start:
        for sw in switches:
            sw.start()
    for i in range(n):
        for j in range(i + 1, n):
            connect_switches(switches[i], switches[j])
    return switches
