"""Peer exchange + address book (the reference's PEX reactor + addrbook
slot, node/node.go:507-552).

``AddressBook``: known peer listen addresses, optionally persisted as
JSON (the addrbook.json analog). ``PEXReactor`` (channel 0x00): on every
new connection it advertises its own listen address and known peers and
requests the peer's; an ensure-peers loop dials known-but-unconnected
addresses until ``max_peers`` — so a node seeded with ONE address
discovers and joins the whole network.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time

from .base import ChannelDescriptor, Reactor

CHANNEL_PEX = 0x00  # reference PexChannel
_ENSURE_INTERVAL = 0.5

MSG_REQUEST = 1
MSG_ADDRS = 2


class AddressBook:
    def __init__(self, path: str = ""):
        self.path = path
        self._mtx = threading.Lock()
        self._addrs: dict[str, tuple[str, int]] = {}  # node_id -> (host, port)
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    self._addrs = {
                        k: (v[0], int(v[1])) for k, v in json.load(f).items()
                    }
            except (ValueError, OSError):
                pass

    def add(self, node_id: str, host: str, port: int) -> bool:
        with self._mtx:
            known = self._addrs.get(node_id)
            if known == (host, port):
                return False
            self._addrs[node_id] = (host, port)
        self._save()
        return True

    def get(self, node_id: str) -> tuple[str, int] | None:
        with self._mtx:
            return self._addrs.get(node_id)

    def entries(self) -> dict[str, tuple[str, int]]:
        with self._mtx:
            return dict(self._addrs)

    def size(self) -> int:
        with self._mtx:
            return len(self._addrs)

    def _save(self) -> None:
        if not self.path:
            return
        with self._mtx:
            payload = json.dumps(
                {k: [h, p] for k, (h, p) in self._addrs.items()}, indent=1
            ).encode()
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".addrbook-")
        try:
            os.write(fd, payload)
        finally:
            os.close(fd)
        os.replace(tmp, self.path)


def book_reconnector(switch, book: AddressBook):
    """Default PeerScoreBoard reconnect hook for TCP assemblies: re-dial
    an evicted peer at its address-book entry. Node auto-wires this into
    the health monitor whenever the switch has a node key and a PEX book
    (node/node.py); the jittered retry backoff lives in the scoreboard
    (health/peers.py) — this hook is one dial attempt."""

    def reconnect(node_id: str) -> bool:
        addr = book.get(node_id)
        if addr is None:
            return False
        try:
            peer = switch.dial_tcp(addr[0], addr[1])
        except Exception:
            return False
        # the secret-connection handshake verifies who answered: a stale
        # book entry that now serves a DIFFERENT node is a failure
        return peer is not None and peer.node_id == node_id

    return reconnect


class PEXReactor(Reactor):
    def __init__(self, book: AddressBook, max_peers: int = 50):
        super().__init__("pex")
        self.book = book
        self.max_peers = max_peers
        self._stop = threading.Event()

    def get_channels(self) -> list[ChannelDescriptor]:
        return [ChannelDescriptor(id=CHANNEL_PEX, priority=1)]

    def on_start(self) -> None:
        self._stop.clear()
        threading.Thread(
            target=self._ensure_peers_loop, name="pex-ensure", daemon=True
        ).start()

    def on_stop(self) -> None:
        self._stop.set()

    # -- gossip --

    def _my_addr_entry(self):
        sw = self.switch
        addr = getattr(sw, "listen_addr", None)
        if addr is None:
            return None
        return [sw.node_id, addr[0], addr[1]]

    def add_peer(self, peer) -> None:
        # advertise ourself + what we know, and ask for theirs
        self._send_addrs(peer)
        peer.try_send(CHANNEL_PEX, bytes([MSG_REQUEST]))

    def _send_addrs(self, peer) -> None:
        addrs = [[nid, h, p] for nid, (h, p) in self.book.entries().items()]
        me = self._my_addr_entry()
        if me is not None:
            addrs.append(me)
        if addrs:
            peer.try_send(
                CHANNEL_PEX, bytes([MSG_ADDRS]) + json.dumps(addrs).encode()
            )

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        kind, body = msg[0], msg[1:]
        if kind == MSG_REQUEST:
            self._send_addrs(peer)
        elif kind == MSG_ADDRS:
            for nid, host, port in json.loads(body):
                if nid == self.switch.node_id:
                    continue
                self.book.add(str(nid), str(host), int(port))
        else:
            raise ValueError(f"unknown pex msg {kind}")

    # -- dialing --

    def _ensure_peers_loop(self) -> None:
        while not self._stop.wait(_ENSURE_INTERVAL):
            sw = self.switch
            if sw is None or not sw.is_running:
                continue
            if sw.n_peers() >= self.max_peers:
                continue
            connected = {p.node_id for p in sw.peers()}
            for nid, (host, port) in self.book.entries().items():
                if nid == sw.node_id or nid in connected:
                    continue
                if sw.n_peers() >= self.max_peers:
                    break
                try:
                    sw.dial_tcp(host, port)
                except Exception:
                    continue  # unreachable for now; retried next tick
