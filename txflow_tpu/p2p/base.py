"""Reactor interface + channel descriptors (reference p2p/base_reactor.go).

Channel ids match the reference byte values so the wire layout is
recognizable: consensus 0x20-0x22 (consensus/reactor.go:22-27), mempool
0x30 (mempool/reactor.go:21), txvotes 0x32 (txvotepool/reactor.go:25).
"""

from __future__ import annotations

from dataclasses import dataclass

CHANNEL_CONSENSUS_STATE = 0x20
CHANNEL_CONSENSUS_DATA = 0x21
CHANNEL_CONSENSUS_VOTE = 0x22
CHANNEL_MEMPOOL = 0x30
CHANNEL_TXVOTE = 0x32
# catch-up sync (sync/reactor.py). 0x38 is already the evidence channel,
# so the sync channel takes the next free slot in the 0x3x range.
CHANNEL_SYNC = 0x3A


@dataclass(frozen=True)
class ChannelDescriptor:
    """One prioritized byte-channel (reference p2p/conn ChannelDescriptor)."""

    id: int
    priority: int = 1
    send_queue_capacity: int = 64
    recv_message_capacity: int = 1024 * 1024  # 1 MiB (consensus/reactor.go:28)
    # reliable channels are never dropped on queue pressure and are drained
    # ahead of the shared priority queue: consensus proposals/votes are
    # push-once (no retransmit), so one drop stalls the whole round until
    # timeout (ADVICE r2) — unlike txvote/mempool batches, which re-gossip
    reliable: bool = False


class Reactor:
    """Base reactor (reference p2p.BaseReactor). Override the hooks.

    Lifecycle: the switch calls ``set_switch`` at registration,
    ``on_start``/``on_stop`` with its own start/stop, ``add_peer`` after a
    peer's connection is live, ``remove_peer`` after it is torn down, and
    ``receive`` from the peer's recv loop for every inbound message on one
    of this reactor's channels.
    """

    def __init__(self, name: str):
        self.name = name
        self.switch = None

    def get_channels(self) -> list[ChannelDescriptor]:
        return []

    def set_switch(self, switch) -> None:
        self.switch = switch

    def on_start(self) -> None:
        pass

    def on_stop(self) -> None:
        pass

    def add_peer(self, peer) -> None:
        pass

    def remove_peer(self, peer, reason: object = None) -> None:
        pass

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        pass
