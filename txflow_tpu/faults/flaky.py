"""FlakyVerifier: scripted device-verifier failures.

A transparent proxy around any verifier that raises
``InjectedDeviceError`` on a deterministic schedule — the first N calls,
an explicit call-index set, or whenever ``failing`` is toggled on (for
"device dies mid-run, later recovers" scenarios). Wrapped in
``verifier.ResilientVoteVerifier`` it exercises the full degradation
policy: retry/backoff, CPU fallback, and device re-promotion.
"""

from __future__ import annotations


class InjectedDeviceError(RuntimeError):
    """A deliberately injected device-verifier failure."""


class FlakyVerifier:
    def __init__(
        self,
        inner,
        fail_first: int = 0,
        fail_calls=(),
        error_factory=None,
        fail_at: str = "result",
    ):
        # fail_at governs WHERE a scheduled failure surfaces on the async
        # submit path: "result" (default) models a readback/transport
        # failure — submit succeeds, ticket.result() raises — which is
        # where a real device loss usually lands once dispatch is async;
        # "submit" models a dispatch failure (enqueue itself errors).
        # The blocking verify_and_tally path always raises inline.
        if fail_at not in ("result", "submit"):
            raise ValueError("fail_at must be 'result' or 'submit'")
        self.inner = inner
        self.val_set = inner.val_set
        self.cache = getattr(inner, "cache", None)
        mb = getattr(inner, "max_batch", None)
        if mb is not None:
            self.max_batch = mb
        self.fail_at = fail_at
        self.fail_first = fail_first
        self.fail_calls = set(fail_calls)
        self.failing = False  # toggle: fail every call while True
        self.calls = 0
        self.failures = 0
        self._make_error = error_factory or (
            lambda i: InjectedDeviceError(f"injected device failure (call {i})")
        )

    def warmup(self, n: int = 1, full: bool = False) -> None:
        self.inner.warmup(n, full=full)

    def _due(self) -> int | None:
        """Advance the call counter; return the call index if this call
        is scheduled to fail, else None."""
        i = self.calls
        self.calls += 1
        if self.failing or i < self.fail_first or i in self.fail_calls:
            self.failures += 1
            return i
        return None

    def verify_and_tally(self, *args, **kwargs):
        i = self._due()
        if i is not None:
            raise self._make_error(i)
        return self.inner.verify_and_tally(*args, **kwargs)

    def submit(self, *args, **kwargs):
        from ..verifier import ReadyTicket

        i = self._due()
        if i is not None:
            if self.fail_at == "submit":
                raise self._make_error(i)
            return _FailAtResultTicket(self._make_error(i))
        sub = getattr(self.inner, "submit", None)
        if sub is not None:
            return sub(*args, **kwargs)
        return ReadyTicket(self.inner.verify_and_tally(*args, **kwargs))


class _FailAtResultTicket:
    """Ticket whose dispatch 'succeeded' but whose readback fails —
    exercises collect-time degradation (ResilientVoteVerifier's
    _ResilientTicket policy re-run)."""

    __slots__ = ("_err",)

    def __init__(self, err: Exception):
        self._err = err

    def result(self):
        raise self._err
