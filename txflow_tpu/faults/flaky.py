"""FlakyVerifier: scripted device-verifier failures.

A transparent proxy around any verifier that raises
``InjectedDeviceError`` on a deterministic schedule — the first N calls,
an explicit call-index set, or whenever ``failing`` is toggled on (for
"device dies mid-run, later recovers" scenarios). Wrapped in
``verifier.ResilientVoteVerifier`` it exercises the full degradation
policy: retry/backoff, CPU fallback, and device re-promotion.
"""

from __future__ import annotations


class InjectedDeviceError(RuntimeError):
    """A deliberately injected device-verifier failure."""


class FlakyVerifier:
    def __init__(
        self,
        inner,
        fail_first: int = 0,
        fail_calls=(),
        error_factory=None,
    ):
        self.inner = inner
        self.val_set = inner.val_set
        self.cache = getattr(inner, "cache", None)
        mb = getattr(inner, "max_batch", None)
        if mb is not None:
            self.max_batch = mb
        self.fail_first = fail_first
        self.fail_calls = set(fail_calls)
        self.failing = False  # toggle: fail every call while True
        self.calls = 0
        self.failures = 0
        self._make_error = error_factory or (
            lambda i: InjectedDeviceError(f"injected device failure (call {i})")
        )

    def warmup(self, n: int = 1, full: bool = False) -> None:
        self.inner.warmup(n, full=full)

    def verify_and_tally(self, *args, **kwargs):
        i = self.calls
        self.calls += 1
        if self.failing or i < self.fail_first or i in self.fail_calls:
            self.failures += 1
            raise self._make_error(i)
        return self.inner.verify_and_tally(*args, **kwargs)
