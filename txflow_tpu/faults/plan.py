"""FaultPlan: deterministic, seed-driven link-fault decisions.

Each directed link (src node -> dst node) gets its own PRNG stream seeded
from sha256(seed, src, dst), and decisions are drawn in per-link message
order. The decision SEQUENCE per link is therefore a pure function of
(seed, src, dst, message index) — rerunning a net with the same seed
replays the same fault pattern per link, regardless of how the OS
interleaves threads across links. (Wall-clock interleaving between links
is inherently nondeterministic; the per-link trace is what "same seed =>
same fault trace" means, and what test_chaos asserts.)

Decisions never consume randomness for out-of-scope channels, so adding
consensus traffic to a net does not shift the gossip-channel stream.
"""

from __future__ import annotations

import hashlib
import random
import threading
from dataclasses import dataclass

from ..p2p.base import CHANNEL_MEMPOOL, CHANNEL_SYNC, CHANNEL_TXVOTE
from ..utils.domains import FAULTPLAN_LINK

# default chaos scope: the at-least-once gossip channels. Consensus
# channels (0x20-0x22) are push-once state-machine traffic; faulting them
# exercises the BFT view-change path, not the fast path, and needs its
# own liveness budget — opt in via FaultSpec.channels.
# The catch-up channel (0x3A) is deliberately NOT in the default scope:
# adding it would consume extra PRNG draws per link and shift the
# deterministic fault trace of every existing seeded test. Opt in with
# ``channels=GOSSIP_CHANNELS | SYNC_CHANNELS`` or FaultSpec.sync_only().
GOSSIP_CHANNELS = frozenset((CHANNEL_MEMPOOL, CHANNEL_TXVOTE))
SYNC_CHANNELS = frozenset((CHANNEL_SYNC,))

# decision kinds (first element of a trace entry / decide() result)
DELIVER = "deliver"
DROP = "drop"
DELAY = "delay"
DUPLICATE = "duplicate"


@dataclass(frozen=True)
class FaultSpec:
    """Per-message fault probabilities and delay bounds.

    Probabilities are evaluated in order drop -> duplicate -> delay on one
    uniform draw, so they must sum to <= 1; the remainder delivers clean.
    A duplicate delivers the original immediately AND schedules a delayed
    copy; a delay defers the original — both produce reordering relative
    to messages sent after them on the same link.

    ``liveness_budget`` is the spec's own timeout allowance: when chaos
    covers the consensus channels (channels=None or any of 0x20-0x22),
    dropped push-once state-machine messages are only recovered by BFT
    round timeouts, so "the net still commits" is a claim about THIS many
    seconds, not the gossip-path defaults. Harnesses (and tests) should
    bound their waits with it instead of inventing per-test deadlines.
    """

    seed: int = 0
    drop: float = 0.0
    duplicate: float = 0.0
    delay: float = 0.0
    delay_min: float = 0.005
    delay_max: float = 0.05
    channels: frozenset = GOSSIP_CHANNELS  # None = every channel
    liveness_budget: float = 30.0

    def __post_init__(self):
        total = self.drop + self.duplicate + self.delay
        if not 0 <= total <= 1:
            raise ValueError(f"fault probabilities sum to {total}, need [0, 1]")
        if self.delay_min < 0 or self.delay_max < self.delay_min:
            raise ValueError("need 0 <= delay_min <= delay_max")
        if self.liveness_budget <= 0:
            raise ValueError("liveness_budget must be positive")

    def sync_only(self) -> "FaultSpec":
        """This spec rescoped to the catch-up channel alone (0x3A):
        drills that want a healthy fast path but a hostile recovery
        plane — sync requests time out, rotate, back off — without
        touching the gossip-channel fault traces."""
        return FaultSpec(
            seed=self.seed,
            drop=self.drop,
            duplicate=self.duplicate,
            delay=self.delay,
            delay_min=self.delay_min,
            delay_max=self.delay_max,
            channels=SYNC_CHANNELS,
            liveness_budget=self.liveness_budget,
        )


class FaultPlan:
    """Seeded decision source consulted once per intercepted message.

    ``decide(src, dst, chan_id)`` returns ``(kind, delay_seconds)`` where
    kind is one of DELIVER/DROP/DELAY/DUPLICATE and delay_seconds is 0.0
    unless the message (or its duplicate copy) is deferred. Every non-
    DELIVER decision is appended to ``trace`` as
    ``(src, dst, msg_index, kind, delay)``.
    """

    def __init__(self, spec: FaultSpec):
        self.spec = spec
        self._mtx = threading.Lock()
        self._links: dict[tuple[str, str], random.Random] = {}
        self._counts: dict[tuple[str, str], int] = {}
        self.trace: list[tuple[str, str, int, str, float]] = []

    def _link_rng(self, src: str, dst: str) -> random.Random:
        key = (src, dst)
        rng = self._links.get(key)
        if rng is None:
            digest = hashlib.sha256(
                FAULTPLAN_LINK
                + b"|%d|%s|%s" % (self.spec.seed, src.encode(), dst.encode())
            ).digest()
            rng = random.Random(int.from_bytes(digest[:8], "little"))
            self._links[key] = rng
            self._counts[key] = 0
        return rng

    def in_scope(self, chan_id: int) -> bool:
        return self.spec.channels is None or chan_id in self.spec.channels

    def decide(self, src: str, dst: str, chan_id: int) -> tuple[str, float]:
        if not self.in_scope(chan_id):
            return DELIVER, 0.0
        s = self.spec
        with self._mtx:
            rng = self._link_rng(src, dst)
            n = self._counts[(src, dst)]
            self._counts[(src, dst)] = n + 1
            r = rng.random()
            if r < s.drop:
                kind, delay = DROP, 0.0
            elif r < s.drop + s.duplicate:
                kind = DUPLICATE
                delay = rng.uniform(s.delay_min, s.delay_max)
            elif r < s.drop + s.duplicate + s.delay:
                kind = DELAY
                delay = rng.uniform(s.delay_min, s.delay_max)
            else:
                return DELIVER, 0.0
            self.trace.append((src, dst, n, kind, delay))
            return kind, delay

    def link_trace(self, src: str, dst: str) -> list[tuple[int, str, float]]:
        """The (msg_index, kind, delay) sequence recorded for one link."""
        with self._mtx:
            return [
                (n, kind, delay)
                for (s, d, n, kind, delay) in self.trace
                if s == src and d == dst
            ]
