"""Fault injection: chaos p2p, Byzantine vote generators, crash drills.

The paper's claim — per-tx quorum certification stays live and safe under
a purely asynchronous vote flood — is only meaningful if it survives the
conditions that define "asynchronous": lost/reordered/duplicated gossip,
partitions, equivocating validators, nodes crashing mid-commit, and the
verify accelerator itself failing. This package makes each of those a
first-class, seed-reproducible test input:

- ``FaultPlan`` / ``FaultSpec``  — deterministic per-link drop/delay/
  duplicate decisions (plan.py);
- ``ChaosRouter``        — installs a plan on live switches via the
  ``Peer`` interceptor hook, schedules delayed deliveries, and cuts/heals
  partitions (chaos.py);
- ``byzantine``          — equivocating / garbage-signature / stale /
  wrong-chain TxVote generators, block-vote equivocation evidence, and
  the live adversary fleet (sig-garbage flooder, identical-vote
  replayer, stale spammer, txvote equivocator, selective withholder)
  that drives the accountable-gossip drills (byzantine.py);
- ``CrashDrill``         — build a durable node, kill it mid-run (optionally
  at a failpoint), restart from WAL + stores, and compare replayed state
  (crash.py);
- ``FlakyVerifier``      — scripted device-verifier failures for exercising
  ``ResilientVoteVerifier`` degradation (flaky.py);
- ``stake``              — seed-deterministic voting-power distributions
  (uniform / whale / long-tail / churning) + Gini, so weighted-quorum
  scenarios and bench runs share one generator (stake.py).
"""

from .plan import FaultPlan, FaultSpec
from .chaos import ChaosRouter
from .crash import CrashDrill
from .flaky import FlakyVerifier, InjectedDeviceError
from .stake import churn_schedule, gini, stake_distribution
from . import byzantine
from .byzantine import (
    ByzantineVoteGen,
    IdenticalVoteReplayer,
    SelectiveWithholder,
    SigGarbageFlooder,
    StaleVoteSpammer,
    TxVoteEquivocator,
)

__all__ = [
    "ByzantineVoteGen",
    "SigGarbageFlooder",
    "IdenticalVoteReplayer",
    "StaleVoteSpammer",
    "TxVoteEquivocator",
    "SelectiveWithholder",
    "FaultPlan",
    "FaultSpec",
    "ChaosRouter",
    "CrashDrill",
    "FlakyVerifier",
    "InjectedDeviceError",
    "byzantine",
    "stake_distribution",
    "churn_schedule",
    "gini",
]
