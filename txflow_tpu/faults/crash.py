"""CrashDrill: kill a durable node mid-run, restart it, compare state.

Generalizes the crash-consistency test rig (tests/test_crash_recovery.py)
into a reusable drill: one validator over durable artifacts (FileDB
stores + pool WALs + consensus WAL) that can be crashed — optionally at
an armed failpoint inside a commit path — and rebuilt from disk with a
FRESH app. The restart model matches the reference's handshake replay:
stores survive, the app restarts empty and is reconstructed by block
replay + fast-path commit redelivery in persisted commit order, so
"replay convergence" is checkable as exactly-once delivery plus a
committed-order prefix match.
"""

from __future__ import annotations

import hashlib
import time

from ..abci.kvstore import KVStoreApplication
from ..node.node import Node, NodeConfig
from ..store.db import FileDB
from ..types.priv_validator import MockPV
from ..types.tx_vote import TxVote
from ..types.validator import Validator, ValidatorSet
from ..utils import failpoints
from ..utils.config import test_config


class CrashDrill:
    def __init__(
        self,
        root_dir,
        chain_id: str = "txflow-crash-drill",
        enable_consensus: bool = False,
        app_factory=KVStoreApplication,
        use_device_verifier: bool = False,
        seed: bytes = b"crash-drill-val",
    ):
        self.root = str(root_dir)
        self.chain_id = chain_id
        self.enable_consensus = enable_consensus
        self.app_factory = app_factory
        self.use_device_verifier = use_device_verifier
        self.pv = MockPV(hashlib.sha256(seed).digest())
        self.val_set = ValidatorSet(
            [Validator.from_pub_key(self.pv.get_pub_key(), 10)]
        )
        self.node: Node | None = None
        self.app = None
        self.restarts = 0

    # -- lifecycle --

    def _build(self, app=None) -> Node:
        cfg = test_config()
        cfg.consensus.skip_timeout_commit = True
        cfg.mempool.wal_dir = self.root
        self.app = app if app is not None else self.app_factory()
        return Node(
            node_id="crash-drill",
            chain_id=self.chain_id,
            val_set=self.val_set,
            app=self.app,
            priv_val=self.pv,
            node_config=NodeConfig(
                config=cfg,
                use_device_verifier=self.use_device_verifier,
                enable_consensus=self.enable_consensus,
                consensus_wal_path=f"{self.root}/consensus.wal",
            ),
            tx_store_db=FileDB(f"{self.root}/txstore.db"),
            state_db=FileDB(f"{self.root}/state.db"),
            block_db=FileDB(f"{self.root}/blocks.db"),
        )

    def start(self, app=None) -> Node:
        assert self.node is None, "drill node already running"
        self.node = self._build(app)
        self.node.start()
        return self.node

    def crash(self, failpoint: str | None = None, timeout: float = 20.0) -> None:
        """Stop the node. With ``failpoint``, arm it first and wait for a
        commit path to hit it, so the on-disk state is the partial state
        the failpoint models (utils.failpoints)."""
        assert self.node is not None, "drill node not running"
        if failpoint is not None:
            if not failpoints.fired(failpoint):
                failpoints.arm(failpoint)
                deadline = time.monotonic() + timeout
                while not failpoints.fired(failpoint):
                    if time.monotonic() > deadline:
                        failpoints.disarm()
                        raise TimeoutError(f"failpoint {failpoint} never fired")
                    time.sleep(0.01)
        self.node.stop()
        failpoints.disarm()
        self.node = None

    def restart(self, app=None) -> Node:
        """Rebuild over the same durable artifacts with a fresh app and
        start (handshake replay runs inside Node.start)."""
        self.restarts += 1
        return self.start(app)

    def stop(self) -> None:
        if self.node is not None:
            self.node.stop()
            self.node = None
        failpoints.disarm()

    # -- traffic + assertions --

    def submit(self, tx: bytes) -> None:
        """Client ingress + the validator's own vote (signed inline so the
        drill does not race the signTxRoutine's walk)."""
        assert self.node is not None
        self.node.broadcast_tx(tx)
        key = hashlib.sha256(tx).digest()
        v = TxVote(
            height=0,
            tx_hash=key.hex().upper(),
            tx_key=key,
            validator_address=self.pv.get_address(),
        )
        self.pv.sign_tx_vote(self.chain_id, v)
        self.node.tx_vote_pool.check_tx(v)

    def wait_committed(self, txs, timeout: float = 20.0, poll: float = 0.01) -> bool:
        assert self.node is not None
        deadline = time.monotonic() + timeout
        while not all(self.node.is_committed(t) for t in txs):
            if time.monotonic() > deadline:
                return False
            time.sleep(poll)
        return True

    def committed_order(self) -> list[str]:
        assert self.node is not None
        return self.node.tx_store.committed_hashes_in_order()
