"""ChaosRouter: applies a FaultPlan to live switches.

Installs itself as each switch's fault injector (``Switch.
set_fault_injector``); every ``Peer.send``/``try_send`` then consults the
router before enqueueing. Delivered-late and duplicated messages go
through one scheduler thread and re-enter the peer's queue via
``try_send_direct`` (bypassing the interceptor so a delayed message is
not re-faulted — one decision per offered message).

Partitions are orthogonal to the probabilistic plan: ``partition()``
black-holes ALL traffic crossing group boundaries (every channel, even
ones outside the plan's scope — a partition is a physical cut) without
consuming the per-link PRNG streams, so ``heal()`` resumes the seeded
sequence exactly where it left off.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import Counter

from .plan import DELAY, DELIVER, DROP, DUPLICATE, FaultPlan, FaultSpec


class ChaosRouter:
    def __init__(self, plan: FaultPlan | FaultSpec):
        if isinstance(plan, FaultSpec):
            plan = FaultPlan(plan)
        self.plan = plan
        self.stats: Counter = Counter()
        self._heap: list = []  # (due, seq, peer, chan_id, msg)
        self._seq = itertools.count()
        self._cv = threading.Condition()
        self._running = False
        self._thread: threading.Thread | None = None
        # groups of node ids; nodes in no group form one implicit group
        self._partition: tuple[frozenset, ...] | None = None
        self._switches: list = []

    # -- wiring --

    def install(self, switches) -> None:
        """Register as fault injector on every switch (existing AND
        future peers) and start the delayed-delivery scheduler."""
        self.start()
        for sw in switches:
            sw.set_fault_injector(self)
            self._switches.append(sw)

    def uninstall(self) -> None:
        for sw in self._switches:
            sw.set_fault_injector(None)
        self._switches = []
        self.stop()

    def make_interceptor(self, src: str, dst: str):
        """Per-link hook handed to each Peer by Switch.set_fault_injector."""

        def intercept(peer, chan_id: int, msg: bytes):
            return self._route(peer, src, dst, chan_id, msg)

        return intercept

    # -- partitions --

    def partition(self, *groups) -> None:
        """Cut all traffic between the given node-id groups (and
        between any listed group and unlisted nodes)."""
        self._partition = tuple(frozenset(g) for g in groups)

    def heal(self) -> None:
        self._partition = None

    def _crosses_partition(self, src: str, dst: str) -> bool:
        groups = self._partition
        if groups is None:
            return False

        def group_of(node: str) -> int:
            for i, g in enumerate(groups):
                if node in g:
                    return i
            return -1  # unlisted nodes share one implicit group

        return group_of(src) != group_of(dst)

    # -- the per-message decision --

    def _route(self, peer, src: str, dst: str, chan_id: int, msg: bytes):
        # partition first, without consuming link randomness: heal()
        # resumes the seeded fault sequence where it paused. Partitions
        # cut EVERY channel regardless of plan scope — they model a
        # physical link cut, and a scoped side channel (e.g. catch-up
        # sync STATUS heartbeats) crossing the cut would feed the peer
        # scorer false liveness during partition drills
        if self._partition is not None and self._crosses_partition(src, dst):
            self.stats["partitioned"] += 1
            return True  # swallowed: sender sees success (black hole)
        kind, delay = self.plan.decide(src, dst, chan_id)
        if kind == DELIVER:
            return None  # pass through untouched
        self.stats[kind] += 1
        if kind == DROP:
            return True
        self._schedule(delay, peer, chan_id, msg)
        # DELAY defers the original; DUPLICATE also delivers it now
        return True if kind == DELAY else None

    # -- delayed delivery --

    def _schedule(self, delay: float, peer, chan_id: int, msg: bytes) -> None:
        with self._cv:
            if not self._running:
                return  # router stopped mid-run: late copy is just dropped
            heapq.heappush(
                self._heap,
                (time.monotonic() + delay, next(self._seq), peer, chan_id, msg),
            )
            self._cv.notify()

    def start(self) -> None:
        with self._cv:
            if self._running:
                return
            self._running = True
        self._thread = threading.Thread(
            target=self._run, name="chaos-scheduler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        with self._cv:
            self._running = False
            self._heap.clear()
            self._cv.notify()
        t = self._thread
        self._thread = None
        if t is not None:
            t.join(timeout=2)

    def _run(self) -> None:
        while True:
            with self._cv:
                if not self._running:
                    return
                if not self._heap:
                    self._cv.wait(timeout=0.2)
                    continue
                due = self._heap[0][0] - time.monotonic()
                if due > 0:
                    self._cv.wait(timeout=due)
                    continue
                _, _, peer, chan_id, msg = heapq.heappop(self._heap)
            # deliver outside the lock; bypass the interceptor so the
            # late copy is not faulted again
            if peer.is_running():
                peer.try_send_direct(chan_id, msg)
                self.stats["late_delivered"] += 1
