"""Byzantine validator behaviors, generated against types/tx_vote.py.

Fast-path misbehavior is constructed here exactly as a hostile validator
would emit it; what the honest net must do with each class:

- equivocating re-signs — two valid signatures from one validator for one
  tx (distinct signing timestamps => distinct sign bytes). NOT evidence by
  design (types/evidence.py docstring: a yes-only vote has no conflicting
  choice); the pool admits both as distinct entries and the engine's
  authoritative TxVoteSet counts the validator's stake once,
  first-signature-wins.
- garbage / wrong-chain / forged-address signatures — fail device+scalar
  verification identically; never enter a certificate; counted in
  metrics.invalid_votes.
- stale votes — heights far behind the net; valid signatures, but the
  per-peer lag throttle stops gossiping them and certificates bind the
  tx, not the height.
- withheld votes — a validator that simply never signs (run a LocalNet
  node with ``sign=False``); safety is unaffected, liveness holds while
  honest stake > 2/3.

Block-path equivocation (the slashable kind) is generated as conflicting
``BlockVote`` pairs and detected through the types/evidence.py path
(``DuplicateBlockVoteEvidence`` -> ``EvidencePool.add``).
"""

from __future__ import annotations

import hashlib
import random

from ..types.block_vote import PREVOTE, BlockVote
from ..types.evidence import DuplicateBlockVoteEvidence
from ..types.tx_vote import MAX_SIGNATURE_SIZE, TxVote


def _tx_key(tx: bytes) -> bytes:
    return hashlib.sha256(tx).digest()


class ByzantineVoteGen:
    """Deterministic generator of hostile TxVotes for one validator key.

    ``priv_val`` is the byzantine validator's signer (a MockPV in tests);
    ``seed`` fixes the garbage-signature bytes so a chaos run replays
    identically.
    """

    def __init__(self, priv_val, chain_id: str, seed: int = 0):
        self.pv = priv_val
        self.chain_id = chain_id
        self._rng = random.Random(seed)

    def _vote(self, tx: bytes, height: int, timestamp_ns: int | None = None) -> TxVote:
        key = _tx_key(tx)
        v = TxVote(
            height=height,
            tx_hash=key.hex().upper(),
            tx_key=key,
            validator_address=self.pv.get_address(),
        )
        if timestamp_ns is not None:
            v.timestamp_ns = timestamp_ns
        return v

    def honest_vote(self, tx: bytes, height: int = 0) -> TxVote:
        v = self._vote(tx, height)
        self.pv.sign_tx_vote(self.chain_id, v)
        return v

    def equivocating_pair(self, tx: bytes, height: int = 0) -> tuple[TxVote, TxVote]:
        """Two VALID signatures for one (tx, validator): signing timestamps
        differ, so sign bytes and signatures differ. The pool keys entries
        by sha256(signature) and admits both; only one may contribute
        stake to the certificate (first-signature-wins)."""
        a = self._vote(tx, height, timestamp_ns=1_700_000_000_000_000_000)
        b = self._vote(tx, height, timestamp_ns=1_700_000_000_000_000_001)
        self.pv.sign_tx_vote(self.chain_id, a)
        self.pv.sign_tx_vote(self.chain_id, b)
        return a, b

    def garbage_signature_vote(self, tx: bytes, height: int = 0) -> TxVote:
        """Well-formed vote carrying seeded random bytes as a signature."""
        v = self._vote(tx, height)
        v.signature = bytes(
            self._rng.getrandbits(8) for _ in range(MAX_SIGNATURE_SIZE)
        )
        return v

    def wrong_chain_vote(self, tx: bytes, height: int = 0) -> TxVote:
        """Validly signed — for a different chain id (replayed cross-chain
        vote); verification against OUR chain id must fail."""
        v = self._vote(tx, height)
        self.pv.sign_tx_vote("byzantine-other-chain", v)
        return v

    def forged_address_vote(
        self, tx: bytes, victim_address: bytes, height: int = 0
    ) -> TxVote:
        """Claims a victim validator's address over our own signature:
        fails the pubkey/address binding check in TxVote.verify."""
        key = _tx_key(tx)
        v = TxVote(
            height=height,
            tx_hash=key.hex().upper(),
            tx_key=key,
            validator_address=victim_address,
        )
        v.signature = self.pv.sign_bytes_raw(v.sign_bytes(self.chain_id))
        return v

    def stale_vote(self, tx: bytes, height: int = 0, lag: int = 1000) -> TxVote:
        """Validly signed at a height far behind the net (withheld, then
        released long after)."""
        v = self._vote(tx, max(0, height - lag))
        self.pv.sign_tx_vote(self.chain_id, v)
        return v


def equivocating_block_votes(
    priv_val,
    chain_id: str,
    height: int,
    round_: int = 0,
    vote_type: int = PREVOTE,
) -> DuplicateBlockVoteEvidence:
    """Slashable block-path equivocation: one validator, one
    height/round/type, two different block ids — both validly signed.
    ``EvidencePool.add`` must verify and admit the pair."""
    votes = []
    for block_id in (b"\xaa" * 32, b"\xbb" * 32):
        v = BlockVote(
            height=height,
            round=round_,
            type=vote_type,
            block_id=block_id,
            timestamp_ns=1_700_000_000_000_000_000,
            validator_address=priv_val.get_address(),
        )
        priv_val.sign_block_vote(chain_id, v)
        votes.append(v)
    return DuplicateBlockVoteEvidence(votes[0], votes[1])


def forged_block_vote_evidence(
    priv_val, chain_id: str, height: int
) -> DuplicateBlockVoteEvidence:
    """An evidence pair whose second signature is garbage: the evidence
    path must REJECT it (a forged accusation), not admit it."""
    ev = equivocating_block_votes(priv_val, chain_id, height)
    ev.vote_b.signature = b"\x01" * 64
    return ev
