"""Byzantine validator behaviors, generated against types/tx_vote.py.

Fast-path misbehavior is constructed here exactly as a hostile validator
would emit it; what the honest net must do with each class:

- equivocating re-signs — two valid signatures from one validator for one
  tx (distinct signing timestamps => distinct sign bytes). NOT evidence by
  design (types/evidence.py docstring: a yes-only vote has no conflicting
  choice); the pool admits both as distinct entries and the engine's
  authoritative TxVoteSet counts the validator's stake once,
  first-signature-wins.
- garbage / wrong-chain / forged-address signatures — fail device+scalar
  verification identically; never enter a certificate; counted in
  metrics.invalid_votes.
- stale votes — heights far behind the net; valid signatures, but the
  per-peer lag throttle stops gossiping them and certificates bind the
  tx, not the height.
- withheld votes — a validator that simply never signs (run a LocalNet
  node with ``sign=False``); safety is unaffected, liveness holds while
  honest stake > 2/3.

Block-path equivocation (the slashable kind) is generated as conflicting
``BlockVote`` pairs and detected through the types/evidence.py path
(``DuplicateBlockVoteEvidence`` -> ``EvidencePool.add``).
"""

from __future__ import annotations

import hashlib
import random
import threading

from ..p2p.base import CHANNEL_TXVOTE
from ..types.block_vote import PREVOTE, BlockVote
from ..types.evidence import DuplicateBlockVoteEvidence
from ..types.tx_vote import MAX_SIGNATURE_SIZE, TxVote


def _tx_key(tx: bytes) -> bytes:
    return hashlib.sha256(tx).digest()


class ByzantineVoteGen:
    """Deterministic generator of hostile TxVotes for one validator key.

    ``priv_val`` is the byzantine validator's signer (a MockPV in tests);
    ``seed`` fixes the garbage-signature bytes so a chaos run replays
    identically.
    """

    def __init__(self, priv_val, chain_id: str, seed: int = 0):
        self.pv = priv_val
        self.chain_id = chain_id
        self._rng = random.Random(seed)

    def _vote(self, tx: bytes, height: int, timestamp_ns: int | None = None) -> TxVote:
        key = _tx_key(tx)
        v = TxVote(
            height=height,
            tx_hash=key.hex().upper(),
            tx_key=key,
            validator_address=self.pv.get_address(),
        )
        if timestamp_ns is not None:
            v.timestamp_ns = timestamp_ns
        return v

    def honest_vote(self, tx: bytes, height: int = 0) -> TxVote:
        v = self._vote(tx, height)
        self.pv.sign_tx_vote(self.chain_id, v)
        return v

    def equivocating_pair(self, tx: bytes, height: int = 0) -> tuple[TxVote, TxVote]:
        """Two VALID signatures for one (tx, validator): signing timestamps
        differ, so sign bytes and signatures differ. The pool keys entries
        by sha256(signature) and admits both; only one may contribute
        stake to the certificate (first-signature-wins)."""
        a = self._vote(tx, height, timestamp_ns=1_700_000_000_000_000_000)
        b = self._vote(tx, height, timestamp_ns=1_700_000_000_000_000_001)
        self.pv.sign_tx_vote(self.chain_id, a)
        self.pv.sign_tx_vote(self.chain_id, b)
        return a, b

    def garbage_signature_vote(self, tx: bytes, height: int = 0) -> TxVote:
        """Well-formed vote carrying seeded random bytes as a signature."""
        v = self._vote(tx, height)
        v.signature = bytes(
            self._rng.getrandbits(8) for _ in range(MAX_SIGNATURE_SIZE)
        )
        return v

    def wrong_chain_vote(self, tx: bytes, height: int = 0) -> TxVote:
        """Validly signed — for a different chain id (replayed cross-chain
        vote); verification against OUR chain id must fail."""
        v = self._vote(tx, height)
        self.pv.sign_tx_vote("byzantine-other-chain", v)
        return v

    def forged_address_vote(
        self, tx: bytes, victim_address: bytes, height: int = 0
    ) -> TxVote:
        """Claims a victim validator's address over our own signature:
        fails the pubkey/address binding check in TxVote.verify."""
        key = _tx_key(tx)
        v = TxVote(
            height=height,
            tx_hash=key.hex().upper(),
            tx_key=key,
            validator_address=victim_address,
        )
        v.signature = self.pv.sign_bytes_raw(v.sign_bytes(self.chain_id))
        return v

    def stale_vote(self, tx: bytes, height: int = 0, lag: int = 1000) -> TxVote:
        """Validly signed at a height far behind the net (withheld, then
        released long after)."""
        v = self._vote(tx, max(0, height - lag))
        self.pv.sign_tx_vote(self.chain_id, v)
        return v

    def wrong_chain_equivocating_pair(
        self, tx: bytes, height: int = 0
    ) -> tuple[TxVote, TxVote]:
        """The other-chain signer, extended to vote-level equivocation:
        TWO distinct signatures from one validator for one tx, both made
        for a foreign chain id. Against OUR chain both fail verification
        (two strikes for the origin peer), and the signer's key is now on
        record double-signing — the block-path evidence bridge below
        turns the same key's conduct into the slashable kind."""
        a = self._vote(tx, height, timestamp_ns=1_700_000_000_000_000_000)
        b = self._vote(tx, height, timestamp_ns=1_700_000_000_000_000_001)
        self.pv.sign_tx_vote("byzantine-other-chain", a)
        self.pv.sign_tx_vote("byzantine-other-chain", b)
        return a, b


def equivocating_block_votes(
    priv_val,
    chain_id: str,
    height: int,
    round_: int = 0,
    vote_type: int = PREVOTE,
) -> DuplicateBlockVoteEvidence:
    """Slashable block-path equivocation: one validator, one
    height/round/type, two different block ids — both validly signed.
    ``EvidencePool.add`` must verify and admit the pair."""
    votes = []
    for block_id in (b"\xaa" * 32, b"\xbb" * 32):
        v = BlockVote(
            height=height,
            round=round_,
            type=vote_type,
            block_id=block_id,
            timestamp_ns=1_700_000_000_000_000_000,
            validator_address=priv_val.get_address(),
        )
        priv_val.sign_block_vote(chain_id, v)
        votes.append(v)
    return DuplicateBlockVoteEvidence(votes[0], votes[1])


def forged_block_vote_evidence(
    priv_val, chain_id: str, height: int
) -> DuplicateBlockVoteEvidence:
    """An evidence pair whose second signature is garbage: the evidence
    path must REJECT it (a forged accusation), not admit it."""
    ev = equivocating_block_votes(priv_val, chain_id, height)
    ev.vote_b.signature = b"\x01" * 64
    return ev


# -- adversary fleet (ISSUE 14): live flood drivers ------------------------
#
# Each driver is a thread that crafts hostile vote frames and broadcasts
# them on the TXVOTE channel THROUGH A SWITCH — exactly the byte stream a
# compromised process would emit, entering honest nodes via the normal
# reactor receive path (wire cache, pre-checks, pool, device verify).
# Crucially the frames bypass the hostile node's OWN pool/engine: a real
# adversary does not politely verify its garbage before sending, and
# injecting into the local pool would let the local engine judge + remove
# the votes before gossip picks them up.
#
# Drivers count what they emit (``frames``, ``emitted``) so drills can
# assert against ground truth instead of inferring the attack volume.


def _encode_vote_frame(votes: list[TxVote]) -> bytes:
    # local twin of reactors.txvote_reactor.encode_vote_batch, kept here
    # so faults/ does not import reactors/ (health/watchdog.py already
    # imports the reactor module — keeping this layer leaf-ward avoids
    # ever closing that cycle)
    from ..codec import amino
    from ..types import encode_tx_vote

    body = bytearray([1])  # MSG_VOTES
    for v in votes:
        body += amino.length_prefixed(encode_tx_vote(v))
    return bytes(body)


class _FloodDriver:
    """Base: a paced emit loop over a switch. Subclasses build one frame
    per tick via ``_tick_votes()``; empty = skip the tick."""

    name = "adversary"

    def __init__(self, switch, interval: float = 0.02):
        self.switch = switch
        self.interval = interval
        self.frames = 0
        self.emitted = 0  # total votes across all frames
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _tick_votes(self) -> list[TxVote]:
        raise NotImplementedError

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"byz-{self.name}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.is_set():
            votes = self._tick_votes()
            if votes:
                self.switch.broadcast(CHANNEL_TXVOTE, _encode_vote_frame(votes))
                self.frames += 1
                self.emitted += len(votes)
            self._stop.wait(self.interval)


class SigGarbageFlooder(_FloodDriver):
    """Floods forged signatures for real txs: a rotation of garbage
    bytes, other-chain re-signs, and forged-address claims — every one
    costs the honest net a verify slot until the breaker quarantines the
    sender. ``txs`` is a callable returning the current target tx bytes
    (drills point it at the live honest workload)."""

    name = "sig-garbage"

    def __init__(
        self, switch, gen: ByzantineVoteGen, txs, height_fn,
        victim_address: bytes | None = None,
        batch: int = 32, interval: float = 0.02,
    ):
        super().__init__(switch, interval)
        self.gen = gen
        self.txs = txs
        self.height_fn = height_fn
        self.victim_address = victim_address
        self.batch = batch
        self._n = 0

    def _tick_votes(self) -> list[TxVote]:
        txs = self.txs()
        if not txs:
            return []
        h = self.height_fn()
        out = []
        for _ in range(self.batch):
            tx = txs[self._n % len(txs)]
            kind = self._n % 3
            self._n += 1
            if kind == 0:
                out.append(self.gen.garbage_signature_vote(tx, h))
            elif kind == 1 or self.victim_address is None:
                out.append(self.gen.wrong_chain_vote(tx, h))
            else:
                out.append(
                    self.gen.forged_address_vote(tx, self.victim_address, h)
                )
        return out


class IdenticalVoteReplayer(_FloodDriver):
    """Replays ONE frame of validly-signed votes forever: the cheapest
    possible flood (no signing cost per tick). Honest defense in depth:
    the pool's signature dedup absorbs it, the verdict cache guarantees
    zero repeat device dispatches, and the ledger's replay counters make
    the sender visible (quarantinable where ``quarantine_replays`` is
    on). The frame is frozen at start — call ``reload`` to re-arm with
    fresh votes."""

    name = "replayer"

    def __init__(self, switch, votes: list[TxVote], interval: float = 0.005):
        super().__init__(switch, interval)
        self._votes = list(votes)
        self._frame = _encode_vote_frame(self._votes)

    def reload(self, votes: list[TxVote]) -> None:
        self._votes = list(votes)
        self._frame = _encode_vote_frame(self._votes)

    def _run(self) -> None:  # frame prebuilt: skip per-tick encode
        while not self._stop.is_set():
            if self._votes:
                self.switch.broadcast(CHANNEL_TXVOTE, self._frame)
                self.frames += 1
                self.emitted += len(self._votes)
            self._stop.wait(self.interval)

    def _tick_votes(self) -> list[TxVote]:  # pragma: no cover - unused
        return self._votes


class StaleVoteSpammer(_FloodDriver):
    """Floods validly-signed votes for heights far behind the net (the
    withhold-then-release pattern). Timestamps advance per tick so every
    frame is new signatures — pure dedup cannot absorb it; the
    stale-height pre-check must."""

    name = "stale"

    def __init__(
        self, switch, gen: ByzantineVoteGen, txs, height_fn,
        lag: int = 1000, batch: int = 16, interval: float = 0.02,
    ):
        super().__init__(switch, interval)
        self.gen = gen
        self.txs = txs
        self.height_fn = height_fn
        self.lag = lag
        self.batch = batch
        self._ts = 1_600_000_000_000_000_000

    def _tick_votes(self) -> list[TxVote]:
        txs = self.txs()
        if not txs:
            return []
        h = self.height_fn()
        out = []
        for i in range(self.batch):
            v = self.gen._vote(
                txs[i % len(txs)], max(0, h - self.lag), timestamp_ns=self._ts
            )
            self._ts += 1
            self.gen.pv.sign_tx_vote(self.gen.chain_id, v)
            out.append(v)
        return out


class TxVoteEquivocator(_FloodDriver):
    """Emits vote-level equivocation: pairs of distinct valid signatures
    per (tx, validator) on the fast path (stake counted once, first-
    signature-wins — NOT evidence by design), plus other-chain
    equivocating pairs (two invalid strikes each). ``block_evidence``
    bridges the same signer's conduct into the slashable block-path
    kind for the PR 7 evidence -> slash drill."""

    name = "equivocator"

    def __init__(
        self, switch, gen: ByzantineVoteGen, txs, height_fn,
        wrong_chain: bool = False, interval: float = 0.05,
    ):
        super().__init__(switch, interval)
        self.gen = gen
        self.txs = txs
        self.height_fn = height_fn
        self.wrong_chain = wrong_chain
        self._n = 0

    def _tick_votes(self) -> list[TxVote]:
        txs = self.txs()
        if not txs:
            return []
        tx = txs[self._n % len(txs)]
        self._n += 1
        h = self.height_fn()
        if self.wrong_chain:
            a, b = self.gen.wrong_chain_equivocating_pair(tx, h)
        else:
            a, b = self.gen.equivocating_pair(tx, h)
        return [a, b]

    def block_evidence(self, height: int) -> DuplicateBlockVoteEvidence:
        """The same signer equivocating on the BLOCK path — the kind the
        evidence pool admits and the epoch manager slashes."""
        return equivocating_block_votes(self.gen.pv, self.gen.chain_id, height)


class SelectiveWithholder:
    """A validator that signs only the txs it favors. Not a flood — a
    LIVENESS adversary: install on a node (replacing its sign routine)
    and it signs txs matching ``predicate`` while silently withholding
    the rest. Safety is unaffected; withheld txs still commit iff the
    remaining honest stake clears 2n/3 without this key."""

    name = "withholder"

    def __init__(self, node, predicate, interval: float = 0.01, batch: int = 256):
        self.node = node
        self.predicate = predicate
        self.interval = interval
        self.batch = batch
        self.signed = 0
        self.withheld = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def install(self) -> None:
        """Disarm the node's honest sign routine (keep its validator
        identity) and start the selective one. Call BEFORE node.start()."""
        self.node.txvote_reactor.priv_val = None
        self.start()

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="byz-withholder", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _run(self) -> None:
        node = self.node
        pv = node.priv_val
        cursor = 0
        while not self._stop.is_set():
            items, cursor = node.mempool.entries_from(cursor, limit=self.batch)
            if not items:
                self._stop.wait(self.interval)
                continue
            st = node.state_view()
            for tx_key, tx, _h, fast_path, _lane in items:
                if not fast_path:
                    continue
                if not self.predicate(tx):
                    self.withheld += 1
                    continue
                vote = TxVote(
                    height=st.last_block_height,
                    tx_hash=tx_key.hex().upper(),
                    tx_key=tx_key,
                    validator_address=pv.get_address(),
                )
                pv.sign_tx_vote(st.chain_id, vote)
                try:
                    node.tx_vote_pool.check_tx(vote)
                    self.signed += 1
                except Exception:
                    continue


def drivers_from_schedule(
    switch, priv_val, chain_id: str, driver_specs, targets, height_fn,
    signer_lookup=None,
):
    """Assemble a flood-driver fleet from a scenario-grid adversary
    schedule (scenario/spec.py draws the knobs from the adversary PRNG
    domain; this owns turning each drawn dict into a live driver, so the
    schedule format and the drivers evolve together in faults/).

    ``driver_specs``: list of dicts, each with a ``kind`` plus that
    kind's drawn knobs. ``targets``/``height_fn`` are the usual flood
    callables. ``signer_lookup(index) -> priv validator`` is required by
    ``replayer`` specs: replayed votes are validly signed by ANOTHER
    validator's key — the replay breaker judges the SENDER's repeats,
    not the signature.
    """
    drivers = []
    for d in driver_specs:
        kind = d.get("kind")
        if kind == "sig-garbage":
            gen = ByzantineVoteGen(priv_val, chain_id, seed=int(d.get("seed", 0)))
            drivers.append(
                SigGarbageFlooder(
                    switch, gen, targets, height_fn,
                    batch=int(d.get("batch", 8)),
                    interval=float(d.get("interval", 0.03)),
                )
            )
        elif kind == "stale":
            gen = ByzantineVoteGen(priv_val, chain_id, seed=int(d.get("seed", 0)))
            drivers.append(
                StaleVoteSpammer(
                    switch, gen, targets, height_fn,
                    lag=int(d.get("lag", 1000)),
                    batch=int(d.get("batch", 4)),
                    interval=float(d.get("interval", 0.05)),
                )
            )
        elif kind == "unknown-signer":
            # the rogue non-validator flood: garbage-signed votes whose
            # signer is not in the validator set at all, so honest nodes
            # judge them at the pre-check (unknown validator) instead of
            # the device verify path
            from ..types.priv_validator import MockPV

            rogue = MockPV(
                hashlib.sha256(
                    b"rogue-signer-%d" % int(d.get("seed", 0))
                ).digest()
            )
            gen = ByzantineVoteGen(rogue, chain_id, seed=int(d.get("seed", 0)))
            drivers.append(
                SigGarbageFlooder(
                    switch, gen, targets, height_fn,
                    batch=int(d.get("batch", 12)),
                    interval=float(d.get("interval", 0.02)),
                )
            )
        elif kind == "replayer":
            if signer_lookup is None:
                raise ValueError("replayer spec needs a signer_lookup")
            gen = ByzantineVoteGen(
                signer_lookup(int(d.get("signer_index", 1))), chain_id
            )
            txs = list(targets())[: int(d.get("n_votes", 3))]
            drivers.append(
                IdenticalVoteReplayer(
                    switch,
                    [gen.honest_vote(tx, 0) for tx in txs],
                    interval=float(d.get("interval", 0.01)),
                )
            )
        else:
            raise ValueError(f"unknown adversary driver kind {kind!r}")
    return drivers
