"""Stake-distribution generator: weighted voting-power shapes for drills
and bench.

"Weighted Voting on the Blockchain" (arxiv 1903.04213): uniform-stake
test sets hide the failure modes that matter, because where the 2n/3
quorum boundary *sits* depends on the shape of the distribution — a
whale can be a single point of quorum failure, a long tail means many
small validators are individually irrelevant but collectively decisive.
Every generator here is seed-deterministic (drills replay exactly) and
emits small ints (device tallies are int32; keep totals far below 2^30).

Kinds:

- ``uniform``  — every validator holds ``base`` power (the legacy test
  shape);
- ``whale``    — one validator holds ~half the total stake, the rest
  split the remainder evenly: quorum is unreachable without the whale;
- ``longtail`` — zipf-like power_i ∝ 1/(i+1), deterministically
  shuffled: a few heavies plus a long tail of minnows;
- ``churning`` — a longtail base re-drawn per seed, for scenarios that
  re-weight every epoch (pair with ``churn_schedule``).
"""

from __future__ import annotations

import random

KINDS = ("uniform", "whale", "longtail", "churning")


def stake_distribution(
    kind: str, n: int, seed: int = 0, base: int = 10
) -> list[int]:
    """``n`` voting powers (each >= 1) for the named distribution.
    Deterministic in (kind, n, seed, base)."""
    if n <= 0:
        raise ValueError("n must be positive")
    if kind == "uniform":
        return [base] * n
    rng = random.Random(("stake", kind, n, seed, base).__repr__())
    if kind == "whale":
        # one validator at ~half the total: total = 2*base*n, whale takes
        # base*n, the other n-1 split base*n (min 1 each)
        if n == 1:
            return [2 * base]
        rest = max(1, (base * n) // (n - 1))
        powers = [rest] * n
        powers[rng.randrange(n)] = base * n
        return powers
    if kind in ("longtail", "churning"):
        # zipf-ish: power_i ∝ 1/(i+1), scaled so the heaviest holds
        # ~base*n/2; churning re-draws the shuffle AND jitters weights
        # per seed so successive epochs genuinely re-weight
        top = max(2, (base * n) // 2)
        powers = [max(1, top // (i + 1)) for i in range(n)]
        if kind == "churning":
            powers = [max(1, p + rng.randrange(-p // 2 or 1, p // 2 + 1)) for p in powers]
        rng.shuffle(powers)
        return powers
    raise ValueError(f"unknown stake distribution {kind!r} (want {KINDS})")


def churn_schedule(
    pub_keys: list[bytes], n_epochs: int, seed: int = 0, base: int = 10
) -> dict[int, list[tuple[bytes, int]]]:
    """An ``EpochConfig.schedule`` that re-weights every validator at
    every epoch boundary with a fresh ``churning`` draw — the adversarial
    steady state where no two epochs share a stake table. Deterministic
    in (pub_keys, n_epochs, seed, base); never removes anyone (powers
    stay >= 1), so quorum topology questions stay with the drill."""
    sched: dict[int, list[tuple[bytes, int]]] = {}
    for e in range(n_epochs):
        powers = stake_distribution("churning", len(pub_keys), seed=seed + e, base=base)
        sched[e] = list(zip(pub_keys, powers))
    return sched


def gini(powers) -> float:
    """Gini coefficient of a power vector (0 = perfectly uniform,
    → 1 = one validator holds everything). Standard mean-absolute-
    difference form; O(n^2) is fine at validator-set sizes."""
    vals = [int(p) for p in powers]
    n = len(vals)
    total = sum(vals)
    if n == 0 or total == 0:
        return 0.0
    diff_sum = sum(abs(a - b) for a in vals for b in vals)
    return diff_sum / (2.0 * n * total)
