"""Version + protocol identifiers (reference version/version.go:17-46).

The semver tracks THIS framework; the protocol numbers are what cross-host
handshakes and block headers key compatibility on (the reference pins
P2PProtocol=7 / BlockProtocol=10 inherited from tendermint v0.31; this
framework's wire formats are its own, so its protocol numbers start at 1).
"""

# framework release version
SEMVER = "0.3.0"

# ABCI-compatible app interface revision (reference ABCISemVer "0.16.0")
ABCI_SEMVER = "0.16.0"

# p2p wire protocol: frame format + channel ids + handshake
P2P_PROTOCOL = 1

# block protocol: header/encode format + chain app-hash rule
BLOCK_PROTOCOL = 1
