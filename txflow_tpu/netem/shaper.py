"""LinkShaper: deterministic WAN weather on the transport send path.

Sits BELOW the per-peer priority queues and ABOVE the raw connection:
``Switch.add_peer_conn`` wraps each accepted/dialed connection (TCP,
SecretConnection, or in-memory pipe) in a ``ShapedConnection``, so every
frame a send loop hands to the transport passes through one directed
link's weather — latency + jitter, token-bucket byte pacing with a
bounded backlog (tail-drop), probabilistic loss / duplication /
corruption, and deterministic flap windows. Each endpoint shapes its own
outbound direction, so a duplex link is two independent directed streams.

Determinism contract (mirrors faults/plan.py): every directed link owns a
PRNG seeded from ``sha256(b"netem|<seed>|<src>|<dst>")``, drawn once per
frame in send order, and the stream SURVIVES reconnects (the rng lives on
the LinkShaper, not the connection). The domain prefix is disjoint from
FaultPlan's ``b"faultplan|..."`` so composing a shaper with a ChaosRouter
never perturbs existing seeded chaos behavior (tests/test_netem.py
stream-stability test).

Two deliberate asymmetries with ChaosRouter:

- loss here returns True from ``send`` (the frame vanishes on the wire;
  a TCP sender can't see an IP drop either) — returning False would make
  the switch stop the peer;
- flapping consumes NO randomness: down-windows are a schedule computed
  from the link clock, like partitions in ChaosRouter.partition().

Corruption flips one payload byte AFTER any chaos interception and (on
keyed TCP) BEFORE SecretConnection encryption, so the flipped byte
arrives authenticated-but-wrong — exactly the case verify-before-apply
must catch and never commit.
"""

from __future__ import annotations

import hashlib
import heapq
import itertools
import random
import threading
import weakref

from ..analysis.lockgraph import make_lock
from ..utils import clock
from ..utils.domains import NETEM_LINK
from .profiles import NetProfile, get_profile

_STAT_KEYS = (
    "frames",
    "delivered",
    "dropped",
    "flap_dropped",
    "queue_dropped",
    "duplicated",
    "corrupted",
    "reordered",
    "send_fail",
)


class ShapedConnection:
    """One directed link's weather applied to a wrapped connection.

    ``send``/``try_send`` are O(1): draw the link decision, push onto a
    due-time heap, notify the delivery worker. The worker thread delivers
    frames to the inner connection in due order (jitter larger than the
    inter-frame gap therefore reorders, on top of the explicit reorder
    hold-back). ``recv``/``close`` delegate to the inner connection.
    """

    def __init__(self, inner, shaper: "LinkShaper", src: str, dst: str):
        self.inner = inner
        self.label = getattr(inner, "label", "")
        self._shaper = shaper
        self._src = src
        self._dst = dst
        self._rng = shaper._link_rng(src, dst)
        self.stats = {k: 0 for k in _STAT_KEYS}
        self._heap: list = []  # (due, seq, chan_id, msg)
        self._seq = itertools.count()
        self._epoch = clock.monotonic()  # flap-schedule origin
        self._next_free = 0.0  # token-bucket virtual clock
        self._closed = False
        self._mtx = make_lock(f"netem.ShapedConnection[{src}->{dst}]")
        self._cond = threading.Condition(self._mtx)
        self._worker = threading.Thread(
            target=self._deliver_loop, name=f"netem-{src}->{dst}", daemon=True
        )
        self._worker.start()

    # -- send path (called from the peer send loop) --

    def send(self, chan_id: int, msg: bytes, timeout: float | None = 10.0) -> bool:
        prof = self._shaper.profile_for(self._src, self._dst)
        with self._cond:
            if self._closed:
                return False
            st = self.stats
            st["frames"] += 1
            now = clock.monotonic()
            # flap: scheduled down-windows, no randomness consumed
            if prof.flap_period_s > 0.0:
                phase = ((now - self._epoch) % prof.flap_period_s) / prof.flap_period_s
                if phase < prof.flap_down_frac:
                    st["flap_dropped"] += 1
                    return True
            rng = self._rng
            u_loss = rng.random()
            u_dup = rng.random()
            u_corrupt = rng.random()
            u_reorder = rng.random()
            u_jitter = rng.random()
            if u_loss < prof.loss:
                st["dropped"] += 1
                return True
            delay = prof.latency_ms / 1e3 + u_jitter * (prof.jitter_ms / 1e3)
            rate = prof.bytes_per_s
            if rate > 0.0:
                if self._next_free < now:
                    self._next_free = now
                backlog = (self._next_free - now) * rate
                if prof.queue_kib > 0 and backlog > prof.queue_kib * 1024:
                    st["queue_dropped"] += 1
                    return True  # tail-drop: pacing queue is full
                send_at = self._next_free
                self._next_free = send_at + len(msg) / rate
                delay += send_at - now
            if u_reorder < prof.reorder:
                st["reordered"] += 1
                delay += prof.reorder_extra_ms / 1e3
            if u_corrupt < prof.corrupt and len(msg) > 0:
                st["corrupted"] += 1
                pos = rng.randrange(len(msg))
                corrupted = bytearray(msg)
                corrupted[pos] ^= 0xFF
                msg = bytes(corrupted)
            due = now + delay
            heapq.heappush(self._heap, (due, next(self._seq), chan_id, msg))
            if u_dup < prof.duplicate:
                st["duplicated"] += 1
                heapq.heappush(
                    self._heap, (due + 1e-3, next(self._seq), chan_id, msg)
                )
            self._cond.notify()
        return True

    def try_send(self, chan_id: int, msg: bytes) -> bool:
        return self.send(chan_id, msg)

    # -- delivery worker --

    def _deliver_loop(self) -> None:
        while True:
            with self._cond:
                while not self._closed and (
                    not self._heap or self._heap[0][0] > clock.monotonic()
                ):
                    if self._heap:
                        wait = self._heap[0][0] - clock.monotonic()
                        self._cond.wait(min(max(wait, 0.0), 0.2))
                    else:
                        self._cond.wait(0.2)
                if self._closed:
                    return
                _, _, chan_id, msg = heapq.heappop(self._heap)
            # inner.send outside the lock: a stalled socket must not block
            # concurrent enqueues (they would inherit its stall as drops)
            if not self.inner.send(chan_id, msg):
                with self._cond:
                    self.stats["send_fail"] += 1
                self.close()
                return
            with self._cond:
                self.stats["delivered"] += 1

    # -- passthrough --

    def recv(self, timeout: float | None = None):
        return self.inner.recv(timeout)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._heap.clear()
            self._cond.notify_all()
        self.inner.close()

    @property
    def is_closed(self) -> bool:
        return self._closed or self.inner.is_closed

    def link(self) -> tuple[str, str]:
        return (self._src, self._dst)


class LinkShaper:
    """Factory + live registry of shaped directed links.

    One shaper serves a whole process (or a whole LocalNet): install with
    ``Switch.set_link_shaper`` before peers connect; every subsequent
    ``add_peer_conn`` wraps its connection. ``set_profile`` swaps the
    weather LIVE — existing links read the current profile per frame, so
    one long-lived net can walk the whole scenario matrix.

    Per-link overrides (``links={"A->B": profile_or_name}``) express
    asymmetric topologies (e.g. the stake-heavy validator behind the worst
    link, arxiv 1903.04213's motivating case).
    """

    def __init__(
        self,
        profile: NetProfile | str = "lan",
        seed: int = 0,
        links: dict[str, NetProfile | str] | None = None,
    ):
        self.seed = int(seed)
        self._mtx = make_lock("netem.LinkShaper._mtx")
        self._profile = get_profile(profile)
        self._links = {k: get_profile(v) for k, v in (links or {}).items()}
        self._rngs: dict[tuple[str, str], random.Random] = {}
        self._conns: list = []  # weakrefs to ShapedConnections

    def _link_rng(self, src: str, dst: str) -> random.Random:
        """One PRNG stream per directed link, surviving reconnects.

        Domain-separated from FaultPlan._link_rng (``faultplan|...``) so a
        shaper never consumes or perturbs chaos streams.
        """
        with self._mtx:
            key = (src, dst)
            rng = self._rngs.get(key)
            if rng is None:
                digest = hashlib.sha256(
                    NETEM_LINK
                    + b"|%d|%s|%s"
                    % (self.seed, src.encode(), dst.encode())
                ).digest()
                rng = random.Random(int.from_bytes(digest[:8], "big"))
                self._rngs[key] = rng
            return rng

    def profile_for(self, src: str, dst: str) -> NetProfile:
        with self._mtx:
            return self._links.get(f"{src}->{dst}", self._profile)

    def set_profile(
        self,
        profile: NetProfile | str,
        links: dict[str, NetProfile | str] | None = None,
    ) -> None:
        """Swap the weather on every current and future link."""
        with self._mtx:
            self._profile = get_profile(profile)
            self._links = {k: get_profile(v) for k, v in (links or {}).items()}

    @property
    def profile(self) -> NetProfile:
        with self._mtx:
            return self._profile

    def wrap(self, conn, src: str, dst: str) -> ShapedConnection:
        shaped = ShapedConnection(conn, self, src, dst)
        with self._mtx:
            self._conns = [r for r in self._conns if r() is not None]
            self._conns.append(weakref.ref(shaped))
        return shaped

    def snapshot(self) -> dict:
        """Aggregate + per-link shaping counters (health/metrics/bench)."""
        with self._mtx:
            conns = [r() for r in self._conns]
            profile = self._profile.name
        total = {k: 0 for k in _STAT_KEYS}
        links = {}
        for c in conns:
            if c is None:
                continue
            src, dst = c.link()
            per = links.setdefault(f"{src}->{dst}", {k: 0 for k in _STAT_KEYS})
            for k in _STAT_KEYS:
                v = c.stats[k]
                per[k] += v
                total[k] += v
        return {"profile": profile, "seed": self.seed, "total": total, "links": links}
