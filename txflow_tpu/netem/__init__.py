"""Network weather: deterministic WAN link conditioning on real sockets.

``LinkShaper`` wraps the transport layer (below the per-peer priority
queues, above TCP/SecretConnection/in-memory pipes) with seed-driven
latency+jitter, token-bucket byte pacing, loss, duplication, corruption,
reordering, and scheduled flap windows — composable with the message-level
``faults.ChaosRouter`` and selectable as named profiles declared as data
(``netem.PROFILES``: lan / intercontinental / lossy-edge / congested /
flapping). The adaptive half lives in ``p2p.adaptive`` (per-peer RTT/loss
estimators, bounded send queues, slow-peer quarantine); the proof lives in
``tools/soak.py --wan-matrix``.
"""

from .profiles import PROFILES, NetProfile, get_profile, profile_names
from .shaper import LinkShaper, ShapedConnection

__all__ = [
    "PROFILES",
    "NetProfile",
    "get_profile",
    "profile_names",
    "LinkShaper",
    "ShapedConnection",
]
