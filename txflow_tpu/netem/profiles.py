"""Named network-weather profiles, declared as data.

Each profile models one directed link's weather: one-way propagation
latency + uniform jitter, a serialization-rate cap (token-bucket byte
pacing with a bounded backlog queue), and per-frame loss / duplication /
corruption / reordering probabilities. ``flap_*`` describes deterministic
up/down windows (no randomness — like a partition, flapping is a schedule,
not a coin flip).

The ``p50_budget_ms``/``p99_budget_ms`` fields are the per-scenario commit
budgets the soak matrix (tools/soak.py --wan-matrix) gates on. They are
deliberately loose regression nets for a 1-core CI box, not SLOs — scale
them with ``SOAK_WAN_BUDGET_SCALE`` or floor p50 with ``SOAK_P50_BUDGET_MS``
(documented in README "Network weather").
"""

from __future__ import annotations

from dataclasses import dataclass, replace


@dataclass(frozen=True)
class NetProfile:
    name: str
    latency_ms: float = 0.0  # one-way propagation delay
    jitter_ms: float = 0.0  # uniform extra delay in [0, jitter_ms)
    bandwidth_mbps: float = 0.0  # serialization rate cap; 0 = unlimited
    queue_kib: int = 0  # pacing backlog cap (tail-drop); 0 = unlimited
    loss: float = 0.0  # P(frame silently lost)
    duplicate: float = 0.0  # P(frame delivered twice)
    corrupt: float = 0.0  # P(one payload byte flipped)
    reorder: float = 0.0  # P(frame held back an extra reorder_extra_ms)
    reorder_extra_ms: float = 0.0
    flap_period_s: float = 0.0  # 0 = link never flaps
    flap_down_frac: float = 0.0  # fraction of each period spent down
    p50_budget_ms: float = 4000.0
    p99_budget_ms: float = 10000.0

    @property
    def bytes_per_s(self) -> float:
        return self.bandwidth_mbps * 1e6 / 8.0

    def scaled_budgets(self, scale: float) -> "NetProfile":
        return replace(
            self,
            p50_budget_ms=self.p50_budget_ms * scale,
            p99_budget_ms=self.p99_budget_ms * scale,
        )


PROFILES: dict[str, NetProfile] = {
    p.name: p
    for p in (
        # co-located racks: the control — budgets here anchor the matrix
        NetProfile(
            "lan",
            latency_ms=0.2,
            jitter_ms=0.1,
            p50_budget_ms=3000.0,
            p99_budget_ms=8000.0,
        ),
        # geo-distributed committee (arxiv 2302.00418 runs WAN evaluations
        # for exactly this shape): ~90ms one-way, mild jitter, rare loss
        NetProfile(
            "intercontinental",
            latency_ms=90.0,
            jitter_ms=10.0,
            bandwidth_mbps=50.0,
            queue_kib=2048,
            loss=0.001,
            p50_budget_ms=5000.0,
            p99_budget_ms=12000.0,
        ),
        # last-mile/wireless edge: loss, reordering, and the occasional
        # flipped byte (which verify-before-apply must catch, never commit)
        NetProfile(
            "lossy-edge",
            latency_ms=30.0,
            jitter_ms=15.0,
            bandwidth_mbps=10.0,
            queue_kib=512,
            loss=0.05,
            duplicate=0.01,
            corrupt=0.003,
            reorder=0.05,
            reorder_extra_ms=40.0,
            p50_budget_ms=7000.0,
            p99_budget_ms=16000.0,
        ),
        # oversubscribed uplink: tight rate cap + shallow queue, so pacing
        # and tail-drop (not the random-loss coin) dominate
        NetProfile(
            "congested",
            latency_ms=20.0,
            jitter_ms=5.0,
            bandwidth_mbps=2.0,
            queue_kib=64,
            loss=0.01,
            p50_budget_ms=7000.0,
            p99_budget_ms=16000.0,
        ),
        # link that dies and returns on a schedule: exercises the jittered-
        # backoff reconnector + address-book re-dial without dial storms
        NetProfile(
            "flapping",
            latency_ms=10.0,
            jitter_ms=3.0,
            flap_period_s=4.0,
            flap_down_frac=0.3,
            p50_budget_ms=9000.0,
            p99_budget_ms=20000.0,
        ),
    )
}


def get_profile(name_or_profile) -> NetProfile:
    """Resolve a profile by name (or pass a NetProfile through)."""
    if isinstance(name_or_profile, NetProfile):
        return name_or_profile
    try:
        return PROFILES[name_or_profile]
    except KeyError:
        raise KeyError(
            f"unknown net profile {name_or_profile!r}; "
            f"known: {sorted(PROFILES)}"
        ) from None


def profile_names() -> tuple[str, ...]:
    """Profile names in canonical (declaration) order — "lan" first. The
    scenario grid's weather axis levels ARE this tuple, so its baseline
    level and tile ordering track the profile table automatically."""
    return tuple(PROFILES)
