"""Operator tools (reference consensus/replay_file.go and friends)."""
