"""Consensus-WAL inspection and replay (reference consensus/replay_file.go).

The reference ships an interactive console that re-feeds a consensus WAL
into a fresh state machine for post-mortem debugging (replay_file.go:35-325).
Same capability here, shaped for scripts first and a console second:

- ``read_wal(path)``       -> decoded frames (dicts) in log order
- ``summarize(path)``      -> per-height counts: proposals/votes/timeouts
- ``python -m txflow_tpu.tools.wal_replay <wal> [--summary|--limit N]``
  prints frames or the summary.

The interactive loop of the reference (next/back/locate) falls out of
``--limit N`` plus re-running; deliberately no cursor state to corrupt.
Actually re-feeding frames into a live state machine is the node's crash
catchup (consensus/state.py catchup + consensus/replay.py Handshaker) —
what a restarting node replays is exactly the frames this tool prints.
"""

from __future__ import annotations

import json
import sys

from ..consensus.wal import decode_wal_message
from ..utils.wal import WAL


def read_wal(path: str) -> list[dict]:
    """Decoded WAL frames, oldest first. Torn tails are dropped by the
    underlying CRC WAL exactly as on node restart."""
    wal = WAL(path)
    out = []
    try:
        for raw in wal.replay():
            kind, payload = decode_wal_message(raw)
            if kind == "proposal":
                p, block = payload
                out.append(
                    {
                        "t": "proposal",
                        "height": p.height,
                        "round": p.round,
                        "pol_round": p.pol_round,
                        "block_hash": p.block_hash.hex()[:16],
                        "has_block": block is not None,
                    }
                )
            elif kind == "vote":
                v = payload
                out.append(
                    {
                        "t": "vote",
                        "height": v.height,
                        "round": v.round,
                        "type": v.type,
                        "validator": v.validator_address.hex()[:12],
                    }
                )
            elif kind == "timeout":
                ti = payload
                out.append(
                    {
                        "t": "timeout",
                        "height": ti.height,
                        "round": ti.round,
                        "step": ti.step,
                        "duration": ti.duration,
                    }
                )
            elif kind == "end_height":
                out.append({"t": "end_height", "height": payload})
            else:  # pragma: no cover - decode_wal_message is total today
                out.append({"t": kind})
    finally:
        wal.close()
    return out


def summarize(path: str) -> dict:
    """{height: {"proposals": n, "votes": n, "timeouts": n, "ended": bool}}"""
    by_height: dict[int, dict] = {}

    def row(h: int) -> dict:
        return by_height.setdefault(
            h, {"proposals": 0, "votes": 0, "timeouts": 0, "ended": False}
        )

    for fr in read_wal(path):
        t = fr["t"]
        if t == "proposal":
            row(fr["height"])["proposals"] += 1
        elif t == "vote":
            row(fr["height"])["votes"] += 1
        elif t == "timeout":
            row(fr["height"])["timeouts"] += 1
        elif t == "end_height":
            row(fr["height"])["ended"] = True
    return by_height


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: wal_replay <consensus.wal> [--summary | --limit N]")
        return 2
    path = argv[0]
    if "--summary" in argv:
        for h, row in sorted(summarize(path).items()):
            print(json.dumps({"height": h, **row}))
        return 0
    frames = read_wal(path)
    limit = None
    if "--limit" in argv:
        limit = int(argv[argv.index("--limit") + 1])
    for i, fr in enumerate(frames if limit is None else frames[:limit]):
        print(json.dumps({"i": i, **fr}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
