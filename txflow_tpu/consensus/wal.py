"""Consensus WAL: typed message log (reference consensus/wal.go:9-21).

Every message the receive routine processes — proposals (with the full
block), votes, timeouts — is WAL'd BEFORE it mutates consensus state
(reference consensus/state.go:620-638), and an EndHeight marker is
written, fsync'd, after every commit (:1306). Catchup replay re-feeds
messages after the last EndHeight into the state machine
(consensus/replay.go:103-171).

Frames ride the shared CRC WAL (utils.wal) with a JSON envelope.
"""

from __future__ import annotations

import json

from ..types.block import Block, decode_block, encode_block
from ..types.block_vote import BlockVote, decode_block_vote, encode_block_vote
from ..utils.wal import WAL
from .ticker import TimeoutInfo
from .types import Proposal


def encode_wal_proposal(p: Proposal, block: Block | None) -> bytes:
    return json.dumps(
        {
            "t": "proposal",
            "height": p.height,
            "round": p.round,
            "pol_round": p.pol_round,
            "block_hash": p.block_hash.hex(),
            "ts": p.timestamp_ns,
            "sig": (p.signature or b"").hex(),
            "block": encode_block(block).hex() if block is not None else "",
        }
    ).encode()


def encode_wal_vote(v: BlockVote) -> bytes:
    return json.dumps({"t": "vote", "v": encode_block_vote(v).hex()}).encode()


def encode_wal_timeout(ti: TimeoutInfo) -> bytes:
    return json.dumps(
        {
            "t": "timeout",
            "duration": ti.duration,
            "height": ti.height,
            "round": ti.round,
            "step": ti.step,
        }
    ).encode()


def encode_wal_end_height(height: int) -> bytes:
    return json.dumps({"t": "end_height", "height": height}).encode()


def decode_wal_message(raw: bytes):
    """Returns (kind, payload): ('proposal', (Proposal, Block|None)) |
    ('vote', BlockVote) | ('timeout', TimeoutInfo) | ('end_height', int).

    Raises ValueError on any malformed frame (the CRC layer makes those
    near-impossible from our own disk, but replay must be total)."""
    try:
        d = json.loads(raw)
    except json.JSONDecodeError as e:
        raise ValueError(f"bad WAL frame: {e}") from None
    if not isinstance(d, dict) or "t" not in d:
        raise ValueError("malformed WAL frame")
    try:
        return _decode_wal_fields(d)
    except (KeyError, TypeError) as e:
        raise ValueError(f"malformed WAL frame: {e!r}") from None


def _decode_wal_fields(d: dict):
    kind = d["t"]
    if kind == "proposal":
        p = Proposal(
            height=d["height"],
            round=d["round"],
            pol_round=d["pol_round"],
            block_hash=bytes.fromhex(d["block_hash"]),
            timestamp_ns=d["ts"],
            signature=bytes.fromhex(d["sig"]) or None,
        )
        block = decode_block(bytes.fromhex(d["block"])) if d["block"] else None
        return "proposal", (p, block)
    if kind == "vote":
        return "vote", decode_block_vote(bytes.fromhex(d["v"]))
    if kind == "timeout":
        return "timeout", TimeoutInfo(
            d["duration"], d["height"], d["round"], d["step"]
        )
    if kind == "end_height":
        return "end_height", d["height"]
    raise ValueError(f"unknown WAL message kind {kind!r}")


class ConsensusWAL:
    """Typed wrapper over the CRC-framed WAL file."""

    def __init__(self, path: str):
        self.wal = WAL(path)

    def write_proposal(self, p: Proposal, block: Block | None) -> None:
        self.wal.write(encode_wal_proposal(p, block))

    def write_vote(self, v: BlockVote) -> None:
        self.wal.write(encode_wal_vote(v))

    def write_timeout(self, ti: TimeoutInfo) -> None:
        self.wal.write(encode_wal_timeout(ti))

    def write_end_height(self, height: int) -> None:
        # fsync'd: the commit marker is the recovery anchor (:1306)
        self.wal.write_sync(encode_wal_end_height(height))

    def flush_and_sync(self) -> None:
        self.wal.flush_and_sync()

    def close(self) -> None:
        self.wal.close()

    def messages_after_end_height(self, height: int) -> list:
        """Decoded messages after the LAST 'end_height' marker for
        ``height`` (or all messages if no such marker) — the catchup
        replay input (consensus/replay.go:103-171)."""
        msgs: list = []
        for raw in self.wal.replay():
            try:
                kind, payload = decode_wal_message(raw)
            except Exception:
                continue
            if kind == "end_height":
                if payload >= height:
                    msgs = []  # everything before this marker is committed
                continue
            msgs.append((kind, payload))
        return msgs
