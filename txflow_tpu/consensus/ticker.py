"""TimeoutTicker (reference consensus/ticker.go:17-24).

One pending timeout at a time, keyed (duration, height, round, step); a
newer schedule replaces the pending one (timeoutRoutine :94-134 semantics:
stale timeouts for earlier height/round/step are skipped on fire). Fires
into the consensus state's message queue via a callback.

A ``ManualTicker`` replaces it in tests for deterministic stepping (the
reference's mockTicker, consensus/common_test.go:698-741).
"""

from __future__ import annotations

import threading

from ..analysis.lockgraph import make_lock
from dataclasses import dataclass
from typing import Callable


@dataclass(frozen=True)
class TimeoutInfo:
    duration: float  # seconds
    height: int
    round: int
    step: int


class TimeoutTicker:
    def __init__(self, fire: Callable[[TimeoutInfo], None]):
        self._fire = fire
        self._mtx = make_lock("consensus.Ticker._mtx")
        self._timer: threading.Timer | None = None
        self._pending: TimeoutInfo | None = None
        self._running = False

    def start(self) -> None:
        with self._mtx:
            self._running = True

    def stop(self) -> None:
        with self._mtx:
            self._running = False
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            self._pending = None

    def schedule(self, ti: TimeoutInfo) -> None:
        """Replace any pending timeout with ti."""
        with self._mtx:
            if not self._running:
                return
            if self._timer is not None:
                self._timer.cancel()
            self._pending = ti
            self._timer = threading.Timer(ti.duration, self._on_fire, args=(ti,))
            self._timer.daemon = True
            self._timer.start()

    def _on_fire(self, ti: TimeoutInfo) -> None:
        with self._mtx:
            if not self._running or self._pending is not ti:
                return  # replaced or stopped: stale
            self._pending = None
            self._timer = None
        self._fire(ti)


class ManualTicker:
    """Test ticker: timeouts fire only when the test calls ``fire_next``."""

    def __init__(self, fire: Callable[[TimeoutInfo], None]):
        self._fire = fire
        self._mtx = make_lock("consensus.Ticker._mtx")
        self._pending: TimeoutInfo | None = None

    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass

    def schedule(self, ti: TimeoutInfo) -> None:
        with self._mtx:
            self._pending = ti

    def pending(self) -> TimeoutInfo | None:
        with self._mtx:
            return self._pending

    def fire_next(self) -> bool:
        with self._mtx:
            ti, self._pending = self._pending, None
        if ti is None:
            return False
        self._fire(ti)
        return True
