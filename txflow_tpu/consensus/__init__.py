"""Block-path BFT consensus (reference consensus/ package).

The Tendermint-style round state machine — the "block ticker" fallback
that orders fast-path commits into replayable blocks (SURVEY §1 layer 6):
Propose -> Prevote -> Precommit -> Commit with POL locking
(consensus/state.go:577-1344), a height/round/step-keyed TimeoutTicker
(consensus/ticker.go:17-24), a consensus WAL with catchup replay
(consensus/replay.go:48-171), an ABCI Handshaker (replay.go:201-472) and
a gossip reactor (consensus/reactor.go).
"""

from .types import RoundState, RoundStep
from .ticker import TimeoutInfo, TimeoutTicker
from .state import ConsensusState
from .reactor import ConsensusReactor
from .replay import Handshaker

__all__ = [
    "RoundState",
    "RoundStep",
    "TimeoutInfo",
    "TimeoutTicker",
    "ConsensusState",
    "ConsensusReactor",
    "Handshaker",
]
