"""Handshaker: sync the ABCI app with the stores on boot (reference
consensus/replay.go:201-472).

On restart the app may be behind the block store (crash between block
save and app commit) or empty (in-memory app). The handshake: query app
Info for (height, hash); if behind, re-deliver missed blocks.

Fast-path awareness (beyond the reference, whose recovery story for
per-tx commits is incomplete — SURVEY §0): during replay both ``Txs``
AND ``Vtxs`` are delivered, because Vtxs' effects entered the app via
per-tx fast-path commits that a fresh app has not seen; afterwards the
fast-path commits SINCE the last block are re-applied from the TxStore's
commit-order log. Normal (non-replay) block application still never
re-delivers Vtxs.
"""

from __future__ import annotations

from ..abci.proxy import AppConns
from ..abci.types import RequestBeginBlock, RequestEndBlock
from ..state import State, StateStore
from ..store.block_store import BlockStore
from ..store.tx_store import TxStore
from ..types.genesis import GenesisDoc


class AppHashMismatch(Exception):
    pass


class Handshaker:
    def __init__(
        self,
        state_store: StateStore,
        state: State,
        block_store: BlockStore,
        genesis: GenesisDoc | None = None,
        tx_store: TxStore | None = None,
        mempool=None,
    ):
        self.state_store = state_store
        self.initial_state = state
        self.block_store = block_store
        self.genesis = genesis
        self.tx_store = tx_store
        self.mempool = mempool
        self.n_blocks_replayed = 0

    def handshake(self, proxy_app: AppConns) -> State:
        """Returns the (possibly unchanged) state after syncing the app."""
        info = proxy_app.query.info_sync()
        app_height = info.last_block_height
        state = self.initial_state
        store_height = self.block_store.height()

        if app_height == 0 and self.genesis is not None:
            from ..abci.types import ValidatorUpdate

            proxy_app.consensus.init_chain_sync(
                [
                    ValidatorUpdate(gv.pub_key, gv.power)
                    for gv in self.genesis.validators
                ]
            )

        # replay store blocks the app has not seen (replay.go:409-498)
        app_hash = info.last_block_app_hash
        for h in range(app_height + 1, store_height + 1):
            block = self.block_store.load_block(h)
            if block is None:
                raise ValueError(f"missing block {h} during handshake replay")
            app_hash = self._exec_replay_block(proxy_app, block)
            self.n_blocks_replayed += 1

        # re-apply fast-path commits made after the last block's Vtxs were
        # drained (their effects are in no block yet)
        if self.tx_store is not None and self.mempool is not None:
            replayed_from_blocks: set[bytes] = set()
            for h in range(1, store_height + 1):
                b = self.block_store.load_block(h)
                if b is not None:
                    for tx in list(b.txs) + list(b.vtxs):
                        import hashlib

                        replayed_from_blocks.add(hashlib.sha256(tx).digest())
            for tx_hash in self.tx_store.committed_hashes_in_order():
                key = bytes.fromhex(tx_hash)
                if key in replayed_from_blocks:
                    continue
                tx = self.mempool.get_tx(key)
                if tx is None:
                    continue  # tx bytes unavailable (not in mempool WAL)
                proxy_app.consensus.deliver_tx_async(tx)
                proxy_app.consensus.flush()
                res = proxy_app.consensus.commit_sync()
                app_hash = res.data

        # verify agreement when the app claims a hash (replay.go:258-266)
        if (
            app_height == state.last_block_height
            and info.last_block_app_hash
            and state.app_hash
            and info.last_block_app_hash != state.app_hash
        ):
            raise AppHashMismatch(
                f"app hash {info.last_block_app_hash.hex()} != "
                f"state {state.app_hash.hex()} at height {app_height}"
            )
        return state

    def _exec_replay_block(self, proxy_app: AppConns, block) -> bytes:
        """Deliver one stored block to the app, INCLUDING Vtxs (replay-only
        behavior — see module docstring), then commit."""
        conn = proxy_app.consensus
        conn.begin_block_sync(
            RequestBeginBlock(
                hash=block.hash(),
                height=block.height,
                proposer_address=block.header.proposer_address,
            )
        )
        for tx in list(block.vtxs) + list(block.txs):
            conn.deliver_tx_async(tx)
        conn.flush()
        conn.end_block_sync(RequestEndBlock(height=block.height))
        res = conn.commit_sync()
        return res.data
