"""Handshaker: sync the ABCI app with the stores on boot (reference
consensus/replay.go:201-472).

On restart the app may be behind the block store (crash between block
save and app commit) or empty (in-memory app). The handshake: query app
Info for (height, hash); if behind, re-deliver missed blocks.

Fast-path awareness (beyond the reference, whose recovery story for
per-tx commits is incomplete — SURVEY §0): during replay both ``Txs``
AND ``Vtxs`` are delivered, because Vtxs' effects entered the app via
per-tx fast-path commits that a fresh app has not seen; afterwards the
fast-path commits SINCE the last block are re-applied from the TxStore's
commit-order log. Normal (non-replay) block application still never
re-delivers Vtxs.
"""

from __future__ import annotations

from ..abci.proxy import AppConns
from ..abci.types import RequestBeginBlock, RequestEndBlock
from ..state import State, StateStore
from ..store.block_store import BlockStore
from ..store.tx_store import TxStore
from ..types.genesis import GenesisDoc


class AppHashMismatch(Exception):
    pass


class Handshaker:
    # how far back the restart dedup walk looks: a tx only recurs across
    # blocks within the commitpool race window (a couple of heights), and
    # fast commits re-enter a block within a couple of heights; 256 is
    # orders of magnitude of headroom while keeping restarts O(1) in
    # chain length
    DEDUP_WINDOW = 256

    def __init__(
        self,
        state_store: StateStore,
        state: State,
        block_store: BlockStore,
        genesis: GenesisDoc | None = None,
        tx_store: TxStore | None = None,
        mempool=None,
    ):
        self.state_store = state_store
        self.initial_state = state
        self.block_store = block_store
        self.genesis = genesis
        # certificates whose APPLY could not be replayed (bytes in no
        # replayed block and not in the mempool WAL): the node hands these
        # to the engine's deferred-apply map so a catchup block carrying
        # the tx (claim_vtx) or late mempool arrival delivers it — the
        # restart analog of the quorum-before-tx deferral (r5: a rebuilt
        # app silently missed such txs and claim_vtx refused the block
        # delivery because the certificate existed)
        self.unapplied_commits: list[tuple[str, bytes]] = []
        self.tx_store = tx_store
        self.mempool = mempool
        self.n_blocks_replayed = 0

    def handshake(self, proxy_app: AppConns) -> State:
        """Returns the state after syncing the app AND the state store.

        Crash windows handled (reference replay.go:294-464):
        - app behind store (crash before app commit): replay the missing
          blocks into the app, recording each post-commit app hash;
        - state behind store (crash between block save / app commit and
          the state save — 'consensus-after-*' / 'block-after-commit'
          failpoints): advance state through the extra block(s) from the
          saved ABCI responses, WITHOUT re-delivering txs the app already
          committed, so state, store, and app agree before consensus
          starts and block H is never executed twice.

        Durable-app contract: for an app that persists its own state
        (app_height > 0 at handshake), a fast-path certificate at crash
        time is ambiguous — the apply may or may not have reached the app
        (store-then-apply order). The replay resolves the ambiguity the
        reference's way ("at most once"): entries with bytes available
        are redelivered only when no replayed block carried them; entries
        without bytes are skipped, never deferred (deferring would
        double-apply when a catchup block re-carries the tx). Apps
        needing exactly-once across crashes should restart EMPTY and be
        rebuilt by this replay — the framework's documented fast-path
        crash model (tests/test_crash_recovery.py).
        """
        info = proxy_app.query.info_sync()
        app_height = info.last_block_height
        state = self.initial_state
        store_height = self.block_store.height()

        if app_height == 0 and self.genesis is not None:
            from ..abci.types import ValidatorUpdate

            proxy_app.consensus.init_chain_sync(
                [
                    ValidatorUpdate(gv.pub_key, gv.power)
                    for gv in self.genesis.validators
                ]
            )

        # replay store blocks the app has not seen (replay.go:409-498).
        # A tx can legitimately appear twice across blocks (reaped into
        # block.Txs, then fast-path-committed and re-carried as a later
        # block's Vtx — the live nodes deduped the second delivery via
        # the engine claim); the replay applies the same exactly-once rule
        # with a delivered-set spanning the whole replay.
        app_hash = info.last_block_app_hash
        replay_hashes: dict[int, bytes] = {}  # height -> post-commit app hash
        replay_responses: dict[int, object] = {}  # height -> ABCIResponses
        import hashlib as _hl

        # ONE bounded chain walk seeds both dedup sets: full-chain scans
        # per restart are O(history) for nothing — a tx only recurs across
        # blocks within the short commitpool race window, and the fast-path
        # redelivery exclusion likewise only concerns recent blocks (every
        # fast commit re-enters a block within a couple of heights).
        walk_base = max(1, store_height - self.DEDUP_WINDOW)
        block_txs: set[bytes] = set()
        for h in range(walk_base, store_height + 1):
            b = self.block_store.load_block(h)
            if b is not None:
                for tx in list(b.txs) + list(b.vtxs):
                    block_txs.add(_hl.sha256(tx).digest())
        # "already delivered" = txs of blocks the app has seen
        delivered: set[bytes] = set()
        for h in range(walk_base, app_height + 1):
            b = self.block_store.load_block(h)
            if b is not None:
                for tx in list(b.txs) + list(b.vtxs):
                    delivered.add(_hl.sha256(tx).digest())
        for h in range(app_height + 1, store_height + 1):
            block = self.block_store.load_block(h)
            if block is None:
                raise ValueError(f"missing block {h} during handshake replay")
            app_hash, responses = self._exec_replay_block(
                proxy_app, block, delivered
            )
            replay_hashes[h] = app_hash
            replay_responses[h] = responses
            self.n_blocks_replayed += 1

        # advance the state store through blocks it missed (storeHeight >
        # stateHeight window, replay.go:294-340): reconstruct each state
        # transition from the saved ABCI responses (written before the app
        # commit) or, if those are gone too, from a live replay response.
        if store_height > state.last_block_height:
            from ..state.execution import parse_responses, update_state
            from ..state.state import ABCIResponses

            for h in range(state.last_block_height + 1, store_height + 1):
                block = self.block_store.load_block(h)
                if block is None:
                    raise ValueError(f"missing block {h} during state catchup")
                # response source, best first: persisted at exec time; the
                # live responses this handshake's own replay just computed
                # (crash at 'block-after-exec': block saved, responses not);
                # empty only if neither exists
                raw = self.state_store.load_abci_responses(h)
                if raw is not None:
                    responses = parse_responses(raw)
                elif h in replay_responses:
                    responses = replay_responses[h]
                else:
                    responses = ABCIResponses()
                val_updates = (
                    [
                        (u.pub_key, u.power)
                        for u in responses.end_block.validator_updates
                    ]
                    if responses.end_block is not None
                    else []
                )
                # state.app_hash is the deterministic chain digest computed
                # inside update_state (state.execution.chain_app_hash) —
                # nothing to reconstruct from the app
                new_state = update_state(
                    state, block.hash(), block, responses, val_updates
                )
                self.state_store.save(new_state)
                state = new_state

        # re-apply fast-path commits made after the last block's Vtxs were
        # drained (their effects are in no block yet)
        if self.tx_store is not None and self.mempool is not None:
            for tx_hash in self.tx_store.committed_hashes_in_order():
                key = bytes.fromhex(tx_hash)
                # dedup against BOTH the window set and every block this
                # handshake replayed/credited (r5 review: on chains older
                # than DEDUP_WINDOW the windowed set alone let historical
                # entries be re-delivered — or worse, spuriously deferred)
                if key in block_txs or key in delivered:
                    continue  # already delivered via block replay
                tx = self.mempool.get_tx(key)
                if tx is None:
                    if app_height == 0:
                        # rebuilt-empty app (the framework's fast-path
                        # crash model): the apply is genuinely owed —
                        # DEFER it (see unapplied_commits in __init__)
                        self.unapplied_commits.append((tx_hash, key))
                    # durable app (app_height > 0): every certificate at
                    # or below its height was applied synchronously
                    # before the crash — deferring would double-apply
                    # when a catchup block re-carries the tx (r5 review)
                    continue
                proxy_app.consensus.deliver_tx_async(tx)
                proxy_app.consensus.flush()
                res = proxy_app.consensus.commit_sync()
                app_hash = res.data

        # the mempool WAL is append-only: its replay re-ingested txs the
        # chain already carries (fast-committed OR block-committed); left
        # in, they would be re-proposed into new blocks and double-applied
        # network-wide. Purge everything already committed by either path.
        if self.mempool is not None:
            committed_now = [
                tx
                for _, tx in self.mempool.entries()
                if _hl.sha256(tx).digest() in block_txs
                or (
                    self.tx_store is not None
                    and self.tx_store.has_tx(
                        _hl.sha256(tx).hexdigest().upper()
                    )
                )
            ]
            if committed_now:
                self.mempool.update(state.last_block_height, committed_now)

        # NOTE: the reference's app-hash equality check (replay.go:258-266)
        # is deliberately absent: state.app_hash is the deterministic chain
        # digest (state.execution.chain_app_hash), not the live app's hash;
        # the two are incomparable under realtime per-tx commits. Replay
        # agreement is enforced structurally by the deliver sequence above.
        return state

    def _exec_replay_block(self, proxy_app: AppConns, block, delivered: set):
        """Deliver one stored block to the app, INCLUDING Vtxs (replay-only
        behavior — see module docstring), then commit. ``delivered`` dedups
        across the replay: repeats get a synthesized OK response, exactly
        like the live path's skipped claims, so the reconstructed results
        match the original execution. Returns (app_hash, ABCIResponses);
        responses cover block.txs only (Vtxs are never in the results
        hash)."""
        import hashlib as _hl

        from ..abci.types import ResponseDeliverTx
        from ..state.state import ABCIResponses

        conn = proxy_app.consensus
        conn.begin_block_sync(
            RequestBeginBlock(
                hash=block.hash(),
                height=block.height,
                proposer_address=block.header.proposer_address,
            )
        )
        results = []
        for tx in list(block.vtxs) + list(block.txs):
            key = _hl.sha256(tx).digest()
            if key in delivered:
                results.append(ResponseDeliverTx())
                continue
            delivered.add(key)
            results.append(conn.deliver_tx_async(tx).value)
        conn.flush()
        end = conn.end_block_sync(RequestEndBlock(height=block.height))
        res = conn.commit_sync()
        return res.data, ABCIResponses(
            deliver_tx=results[len(block.vtxs):], end_block=end
        )
