"""RoundState: the consensus-internal state (reference
consensus/types/round_state.go:20-100) + the 8-step round enum.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from ..types.block import Block
from ..types.block_vote import BlockCommit, HeightVoteSet
from ..types.validator import ValidatorSet


class RoundStep(enum.IntEnum):
    NEW_HEIGHT = 1
    NEW_ROUND = 2
    PROPOSE = 3
    PREVOTE = 4
    PREVOTE_WAIT = 5
    PRECOMMIT = 6
    PRECOMMIT_WAIT = 7
    COMMIT = 8


@dataclass
class Proposal:
    """A signed block proposal (upstream types.Proposal; the block itself
    travels in the same message — no part-sets, see p2p package doc)."""

    height: int
    round: int
    pol_round: int  # -1 if no proposal-of-lock round
    block_hash: bytes
    timestamp_ns: int = 0
    signature: bytes | None = None

    def sign_bytes(self, chain_id: str) -> bytes:
        from ..codec import amino

        body = bytearray()
        body += amino.field_key(1, amino.TYP3_8BYTE)
        body += amino.fixed64(self.height)
        body += amino.field_key(2, amino.TYP3_8BYTE)
        body += amino.fixed64(self.round)
        body += amino.field_key(3, amino.TYP3_VARINT)
        body += amino.varint(self.pol_round)
        if self.block_hash:
            body += amino.field_key(4, amino.TYP3_BYTELEN)
            body += amino.length_prefixed(self.block_hash)
        ts = amino.encode_time_body(self.timestamp_ns)
        if ts:
            body += amino.field_key(5, amino.TYP3_BYTELEN)
            body += amino.length_prefixed(ts)
        if chain_id:
            body += amino.field_key(6, amino.TYP3_BYTELEN)
            body += amino.length_prefixed(chain_id.encode())
        return amino.length_prefixed(bytes(body))


@dataclass
class PeerRoundState:
    """What a peer has told us about its round position and vote
    knowledge (reference consensus/types/peer_round_state.go +
    the PeerState bitarrays of consensus/reactor.go:904-1340).

    ``vote_masks`` maps (round, vote_type) -> validator-index bitmask of
    votes the peer is known to hold — from its periodic announces (exact),
    from votes it sent us (it has what it sends), and from votes we sent
    it over the reliable consensus lane (it will have them). The re-offer
    path sends a peer only the deltas, replacing the full round-data dump
    per gossip tick."""

    height: int = 0
    round: int = -1
    step: int = -1
    committed: int = 0
    has_proposal: bool = False
    vote_masks: dict = field(default_factory=dict)

    def mark_vote(self, round_: int, vote_type: int, val_idx: int) -> None:
        if val_idx >= 0:
            key = (round_, vote_type)
            self.vote_masks[key] = self.vote_masks.get(key, 0) | (1 << val_idx)

    def has_vote(self, round_: int, vote_type: int, val_idx: int) -> bool:
        return bool(self.vote_masks.get((round_, vote_type), 0) >> val_idx & 1)


@dataclass
class RoundState:
    height: int = 1
    round: int = 0
    step: RoundStep = RoundStep.NEW_HEIGHT
    start_time_ns: int = 0
    commit_time_ns: int = 0
    validators: ValidatorSet | None = None
    proposal: Proposal | None = None
    proposal_block: Block | None = None
    locked_round: int = -1
    locked_block: Block | None = None
    # last known polka (valid_*): most recent +2/3 prevotes for a block
    valid_round: int = -1
    valid_block: Block | None = None
    votes: HeightVoteSet | None = None
    commit_round: int = -1
    last_commit: BlockCommit | None = None
    last_validators: ValidatorSet | None = None

    def round_step_key(self) -> tuple[int, int, int]:
        return (self.height, self.round, int(self.step))
