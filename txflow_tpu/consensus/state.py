"""ConsensusState: the Tendermint-style round state machine (reference
consensus/state.go).

Structure preserved from the reference:

- ONE receive routine serializes every input — peer messages, internal
  (own) messages, timeouts (receiveRoutine :577-647); everything is WAL'd
  before it mutates state (:620-638);
- round flow: NewHeight -(timeout_commit)-> NewRound -> Propose (proposer
  creates the block, reaping mempool txs AND the commitpool's fast-path
  commits as Vtxs, :945-962) -> Prevote -> PrevoteWait -> Precommit (POL
  lock/unlock, :1051-1144) -> PrecommitWait -> Commit -> finalize
  (:1251-1344: save block, WAL EndHeight, ApplyBlock, advance);
- POL rules (v0.31): prevote the locked block if locked, else the valid
  proposal; on +2/3 prevotes for a block in this round, lock and
  precommit it; on +2/3 prevotes for nil, unlock and precommit nil; else
  precommit nil without unlocking. A newer polka (valid_round) unlocks
  via the proposal's pol_round path (:968-1020).

Deviations (documented): no part-sets (whole blocks in proposal
messages), push-style gossip via the reactor instead of per-peer
walk-routines, single consensus channel. Byzantine-fault handling,
timeout scheduling, lock rules, and WAL-before-process are semantically
per the reference.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable

from ..state import BlockExecutor, State
from ..analysis.lockgraph import make_rlock
from ..utils.clock import now_ns
from ..store.block_store import BlockStore
from ..types.block import Block
from ..types.block_vote import (
    PRECOMMIT,
    PREVOTE,
    BlockCommit,
    BlockVote,
    HeightVoteSet,
)
from ..types.priv_validator import PrivValidator
from ..utils import failpoints
from ..utils.config import ConsensusConfig
from ..utils.events import EventBus, EventNewRoundStep
from .ticker import TimeoutInfo, TimeoutTicker
from .types import Proposal, RoundState, RoundStep
from .wal import ConsensusWAL


class ConsensusState:
    def __init__(
        self,
        config: ConsensusConfig,
        state: State,
        block_executor: BlockExecutor,
        block_store: BlockStore,
        tx_notifier=None,  # object with txs_available() -> Event (mempool)
        commitpool=None,  # fast-path commits also make blocks non-empty
        tx_store=None,  # fast-path commit store: which vtxs we applied
        priv_val: PrivValidator | None = None,
        event_bus: EventBus | None = None,
        wal_path: str = "",
        ticker_factory=None,
        on_commit: "Callable | None" = None  # (new_state, block) -> None,
    ):
        self.config = config
        self.block_exec = block_executor
        self.block_store = block_store
        self.tx_notifier = tx_notifier
        self.commitpool = commitpool
        self.tx_store = tx_store
        # atomic "has the fast path applied this vtx" claim (see
        # _vtx_filter); the composition root wires the engine's claim_vtx
        self.vtx_claimer = None
        # equivocation capture (node wires the evidence pool)
        self.on_evidence = None
        self.priv_val = priv_val
        self.event_bus = event_bus
        self.on_commit = on_commit
        # outbound hooks, set by the reactor: broadcast own proposal/votes
        self.broadcast_proposal: Callable[[Proposal, Block], None] = lambda p, b: None
        self.broadcast_vote: Callable[[BlockVote], None] = lambda v: None
        self.broadcast_step: Callable[[RoundState], None] = lambda rs: None

        self.state = state  # last committed chain state
        self.rs = RoundState()
        self._mtx = make_rlock("consensus.ConsensusState._mtx", allow_blocking=True)
        self._queue: queue.Queue = queue.Queue(maxsize=10000)
        self._running = False
        self._thread: threading.Thread | None = None
        make_ticker = ticker_factory or TimeoutTicker
        self.ticker = make_ticker(self._fire_timeout)
        self.wal = ConsensusWAL(wal_path) if wal_path else None
        self._decided_once = threading.Event()  # any block committed
        self.height_committed = threading.Condition()
        # votes for height+1 that arrived while we finalize this height:
        # without buffering, push-once gossip loses them permanently and
        # slow nodes fall onto block catchup every height (ADVICE r2).
        # Keyed by (validator, type, round) first-wins so a byzantine peer
        # cannot evict honest votes with duplicates; validator membership
        # is checked against next_validators at buffering time.
        self._future_votes: dict[tuple, tuple[BlockVote, str]] = {}
        # votes to re-feed after the current message finishes (drained by
        # the receive routine — a blocking _queue.put here would deadlock:
        # this thread is the queue's only consumer)
        self._reinject: list[tuple[BlockVote, str]] = []

        self._update_to_state(state)

    # ---------------------------------------------------------------- API

    def start(self) -> None:
        with self._mtx:
            if self._running:
                return
            self._running = True
        self.ticker.start()
        if not self.config.create_empty_blocks:
            # watcher: wake enterPropose when work shows up in either pool
            # (reference txNotifier.TxsAvailable into receiveRoutine :590)
            t = threading.Thread(
                target=self._txs_watcher, name="consensus-txs", daemon=True
            )
            t.start()
        # catchup replay of the current height's WAL messages (:296-321),
        # processed SYNCHRONOUSLY like the reference's catchupReplay
        # (consensus/replay.go:48-101 re-feeds into the handler before
        # the receive routine consumes anything live). Queueing them
        # instead deadlocked start(): the bounded queue has no consumer
        # yet, and one height's WAL backlog can exceed its capacity
        # (r5 soak: a 300 s churn run wedged node revival exactly here).
        if self.wal is not None:
            for kind, payload in self.wal.messages_after_end_height(
                self.state.last_block_height
            ):
                self._process("replay_" + kind, payload, replay=True)
        self._thread = threading.Thread(
            target=self._receive_routine, name="consensus", daemon=True
        )
        self._thread.start()
        self._schedule_round0()

    def stop(self) -> None:
        with self._mtx:
            if not self._running:
                return
            self._running = False
        self.ticker.stop()
        self._queue.put(("quit", None))
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self.wal is not None:
            self.wal.close()

    def add_proposal(self, proposal: Proposal, block: Block, peer_id: str = "") -> None:
        """Peer/own proposal into the serialized queue."""
        self._queue.put(("proposal", (proposal, block, peer_id)))

    def add_vote(self, vote: BlockVote, peer_id: str = "") -> None:
        self._queue.put(("vote", (vote, peer_id)))

    def round_state(self) -> RoundState:
        with self._mtx:
            return self.rs

    def round_summary(self) -> dict:
        """Position + vote-knowledge announce payload: height/round/step/
        committed plus current-round prevote/precommit bitmasks and a
        has-proposal flag. Receivers keep these in PeerRoundState so the
        re-offer path sends only what a peer lacks (the reference ships
        the same facts as NewRoundStep + per-vote HasVote messages,
        consensus/reactor.go:904-1340)."""
        with self._mtx:
            rs = self.rs
            d = {
                "height": rs.height,
                "round": rs.round,
                "step": int(rs.step),
                "committed": self.state.last_block_height,
                "has_proposal": rs.proposal is not None,
            }
            if rs.votes is not None:
                d["prevotes"] = "%x" % rs.votes.prevotes(rs.round).bitmask()
                d["precommits"] = "%x" % rs.votes.precommits(rs.round).bitmask()
            return d

    def current_round_data(self):
        """Snapshot for retransmission gossip: (proposal, block, votes).
        Push-once gossip loses messages sent before peers connect; the
        reactor re-offers this data to same-height peers — the framework's
        equivalent of the reference's per-peer gossipDataRoutine/
        gossipVotesRoutine walks (consensus/reactor.go:465-729).

        Bounded to the last 3 rounds plus any POL round: re-sending EVERY
        round's votes grows linearly with round churn and can flood the
        peer's reliable lane into dropping fresh proposals (r3 stall
        postmortem #2) — the exact loss it exists to repair."""
        with self._mtx:
            rs = self.rs
            votes: list[BlockVote] = []
            if rs.votes is not None:
                rounds = set(range(max(0, rs.round - 2), rs.round + 1))
                pol_round, _ = rs.votes.pol_info()
                if pol_round >= 0:
                    rounds.add(pol_round)  # old polka: peers need it to unlock
                for r in sorted(rounds):
                    votes.extend(rs.votes.prevotes(r).vote_list())
                    votes.extend(rs.votes.precommits(r).vote_list())
            return rs.proposal, rs.proposal_block, votes

    def is_proposer(self) -> bool:
        with self._mtx:
            return (
                self.priv_val is not None
                and self.rs.validators is not None
                and self.rs.validators.get_proposer().address
                == self.priv_val.get_address()
            )

    def reset_to_state(self, state: State) -> None:
        """Adopt a handshake-advanced state BEFORE start() (node boot found
        the state store behind the block store and caught it up)."""
        with self._mtx:
            assert not self._running, "reset_to_state after start"
            self._update_to_state(state)

    def wait_for_height(self, height: int, timeout: float = 30.0) -> bool:
        deadline = time.monotonic() + timeout
        with self.height_committed:
            while self.state.last_block_height < height:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self.height_committed.wait(remaining)
        return True

    # ------------------------------------------------------- receive loop

    def _fire_timeout(self, ti: TimeoutInfo) -> None:
        self._queue.put(("timeout", ti))

    def _receive_routine(self) -> None:
        while True:
            with self._mtx:
                if not self._running:
                    return
            try:
                kind, payload = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if kind == "quit":
                return
            self._process(kind, payload)

    def _process(self, kind: str, payload, replay: bool = False) -> None:
        """Handle ONE message plus the reinject drain it may release —
        the shared body of the receive routine and of start()'s
        synchronous WAL catchup replay (reference catchupReplay,
        consensus/replay.go:48-101). A bad message must not kill
        consensus (or boot: a torn WAL tail replays as garbage).

        replay=True drains reinjected votes with replay semantics: those
        votes were just read FROM the WAL, and the live "vote" branch
        would append each back — one duplicate per restart, a WAL that
        grows with restart count (r5 review)."""
        try:
            self._handle(kind, payload)
        except Exception:
            import traceback

            traceback.print_exc()
        # buffered future votes released by a height change: processed
        # here at top level, exactly like fresh arrivals
        while self._reinject:
            vote, peer = self._reinject.pop(0)
            try:
                if replay:  # replay_vote takes the bare vote, no WAL write
                    self._handle("replay_vote", vote)
                else:
                    self._handle("vote", (vote, peer))
            except Exception:
                import traceback

                traceback.print_exc()

    def _handle(self, kind: str, payload) -> None:
        with self._mtx:
            if kind == "proposal":
                proposal, block, _peer = payload
                if self.wal is not None:
                    self.wal.write_proposal(proposal, block)
                self._set_proposal(proposal, block)
            elif kind == "replay_proposal":
                proposal, block = payload
                self._set_proposal(proposal, block)
            elif kind == "vote":
                vote, peer = payload
                if self.wal is not None:
                    self.wal.write_vote(vote)
                self._try_add_vote(vote, peer)
            elif kind == "replay_vote":
                self._try_add_vote(payload)
            elif kind == "timeout":
                ti: TimeoutInfo = payload
                rs = self.rs
                if (
                    ti.height != rs.height
                    or ti.round < rs.round
                    or (ti.round == rs.round and ti.step < int(rs.step))
                ):
                    return  # stale (reference handleTimeout :710-717)
                if self.wal is not None:
                    self.wal.write_timeout(ti)
                self._handle_timeout(ti)
            elif kind == "replay_timeout":
                ti = payload
                rs = self.rs
                if ti.height == rs.height and ti.round >= rs.round:
                    self._handle_timeout(ti)
            elif kind == "txs_available":
                rs = self.rs
                if rs.step == RoundStep.NEW_ROUND:
                    self._enter_propose(rs.height, rs.round)

    # -------------------------------------------------------- transitions

    def _update_to_state(self, state: State) -> None:
        """Reset round state for the next height (reference updateToState
        :1332-1338 -> :466-560)."""
        self.state = state
        height = state.last_block_height + 1
        # last precommits: the seen commit that finalized the previous block
        last_commit = None
        if state.last_block_height > 0:
            last_commit = self.block_store.load_seen_commit(state.last_block_height)
        self.rs = RoundState(
            height=height,
            round=0,
            step=RoundStep.NEW_HEIGHT,
            validators=state.validators.copy(),
            votes=HeightVoteSet(state.chain_id, height, state.validators),
            last_commit=last_commit,
            last_validators=state.last_validators.copy(),
            start_time_ns=now_ns(),
        )
        self.rs.votes.set_round(0)
        # re-feed buffered votes that were early for the previous height and
        # are now current; handed to the receive routine via _reinject (NOT
        # _queue.put: this runs on the receive thread itself, and blocking
        # on the full queue it alone drains would deadlock consensus)
        if self._future_votes:
            self._reinject.extend(
                vp for vp in self._future_votes.values() if vp[0].height == height
            )
            self._future_votes = {
                k: vp
                for k, vp in self._future_votes.items()
                if vp[0].height > height
            }

    def _schedule_round0(self) -> None:
        # NewHeight -> NewRound after timeout_commit (reference :560-576)
        self.ticker.schedule(
            TimeoutInfo(
                0.0 if self.state.last_block_height == 0 or self.config.skip_timeout_commit
                else self.config.timeout_commit,
                self.rs.height,
                0,
                int(RoundStep.NEW_HEIGHT),
            )
        )

    def _handle_timeout(self, ti: TimeoutInfo) -> None:
        step = RoundStep(ti.step)
        if step == RoundStep.NEW_HEIGHT:
            self._enter_new_round(ti.height, 0)
        elif step == RoundStep.NEW_ROUND:
            self._enter_propose(ti.height, 0)
        elif step == RoundStep.PROPOSE:
            self._enter_prevote(ti.height, ti.round)
        elif step == RoundStep.PREVOTE_WAIT:
            self._enter_precommit(ti.height, ti.round)
        elif step == RoundStep.PRECOMMIT_WAIT:
            self._enter_precommit(ti.height, ti.round)
            self._enter_new_round(ti.height, ti.round + 1)

    def _enter_new_round(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step != RoundStep.NEW_HEIGHT
        ):
            return
        if round_ > rs.round:
            # proposer rotates per round (reference enterNewRound :780-784)
            rs.validators = rs.validators.increment_proposer_priority(
                round_ - rs.round
            )
        rs.round = round_
        rs.step = RoundStep.NEW_ROUND
        if round_ > 0:
            # new round: drop the stale proposal (reference :793-799)
            rs.proposal = None
            rs.proposal_block = None
        rs.votes.set_round(round_)
        self._new_step()
        # wait for txs before proposing? (create_empty_blocks handling,
        # reference :809-826)
        if (
            not self.config.create_empty_blocks
            and round_ == 0
            and self._no_work_pending()
        ):
            return  # enterPropose fires on txs_available via _on_txs_available
        self._enter_propose(height, round_)

    def _no_work_pending(self) -> bool:
        mempool_empty = (
            self.block_exec.mempool.size() == 0 if self.block_exec.mempool else True
        )
        commitpool_empty = self.commitpool.size() == 0 if self.commitpool else True
        return mempool_empty and commitpool_empty

    def notify_txs_available(self) -> None:
        """Mempool/commitpool tx arrival while waiting to propose."""
        self._queue.put(("txs_available", None))

    def _txs_watcher(self) -> None:
        last = (-1, -1)
        while True:
            with self._mtx:
                if not self._running:
                    return
            cur = (
                self.tx_notifier.seq() if self.tx_notifier is not None else 0,
                self.commitpool.seq() if self.commitpool is not None else 0,
            )
            if cur != last:
                last = cur
                self.notify_txs_available()
            if self.tx_notifier is not None:
                self.tx_notifier.wait_for_new(cur[0], timeout=0.05)
            else:
                time.sleep(0.05)

    def _enter_propose(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and int(rs.step) >= int(RoundStep.PROPOSE)
        ):
            return
        rs.step = RoundStep.PROPOSE
        self._new_step()
        # propose-timeout -> prevote whatever we have (reference :858-861)
        self.ticker.schedule(
            TimeoutInfo(
                self.config.propose_timeout(round_), height, round_,
                int(RoundStep.PROPOSE),
            )
        )
        if self.is_proposer():
            self._decide_proposal(height, round_)
        # if we already have a complete proposal (e.g. replay), advance
        if rs.proposal_block is not None:
            self._enter_prevote(height, round_)

    def _decide_proposal(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.locked_block is not None:  # propose the locked block (:889-893)
            block = rs.locked_block
            pol_round = rs.locked_round
        elif rs.valid_block is not None:  # else the last-known polka block
            block = rs.valid_block
            pol_round = rs.valid_round
        else:
            block = self.block_exec.create_proposal_block(
                height, self.state, rs.last_commit,
                self.priv_val.get_address(),
            )
            pol_round = -1
        proposal = Proposal(
            height=height, round=round_, pol_round=pol_round,
            block_hash=block.hash(), timestamp_ns=now_ns(),
        )
        try:
            self.priv_val.sign_proposal(self.state.chain_id, proposal)
        except Exception:
            return  # signer refused (reference logs and returns)
        # internal message: same serialized path as peer proposals (:912-921)
        self.add_proposal(proposal, block)
        self.broadcast_proposal(proposal, block)

    def verify_proposal_signature(self, proposal: Proposal) -> bool:
        """True iff the proposal is for the CURRENT (height, round) and
        carries the current proposer's valid signature — the gate for
        accepting a chunked-proposal parts header before any block bytes
        are buffered (the reference's parts ride under an already-
        verified Proposal the same way, consensus/state.go:688-692)."""
        with self._mtx:
            rs = self.rs
            if proposal.height != rs.height or proposal.round != rs.round:
                return False
            proposer = rs.validators.get_proposer()
            chain_id = self.state.chain_id
        from ..crypto import ed25519

        return bool(proposal.signature) and ed25519.verify(
            proposer.pub_key,
            proposal.sign_bytes(chain_id),
            proposal.signature,
        )

    def _set_proposal(self, proposal: Proposal, block: Block | None) -> None:
        rs = self.rs
        if rs.proposal is not None:
            return  # already have one for this round
        if proposal.height != rs.height or proposal.round != rs.round:
            return
        proposer = rs.validators.get_proposer()
        from ..crypto import ed25519

        if not proposal.signature or not ed25519.verify(
            proposer.pub_key,
            proposal.sign_bytes(self.state.chain_id),
            proposal.signature,
        ):
            return  # invalid proposal signature (reference :688-692)
        if block is None or block.hash() != proposal.block_hash:
            return
        rs.proposal = proposal
        rs.proposal_block = block
        if int(rs.step) <= int(RoundStep.PROPOSE):
            self._enter_prevote(rs.height, rs.round)
        else:
            self._try_finalize_commit(rs.height)

    def _enter_prevote(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and int(rs.step) >= int(RoundStep.PREVOTE)
        ):
            return
        rs.step = RoundStep.PREVOTE
        self._new_step()
        self._do_prevote(height, round_)

    def _do_prevote(self, height: int, round_: int) -> None:
        """defaultDoPrevote (:968-1020)."""
        rs = self.rs
        if rs.locked_block is not None:
            self._sign_add_vote(PREVOTE, rs.locked_block.hash())
            return
        if rs.proposal_block is None:
            self._sign_add_vote(PREVOTE, b"")  # nil
            return
        err = self.block_exec.validate_block(self.state, rs.proposal_block)
        self._sign_add_vote(PREVOTE, b"" if err else rs.proposal_block.hash())

    def _enter_prevote_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and int(rs.step) >= int(RoundStep.PREVOTE_WAIT)
        ):
            return
        rs.step = RoundStep.PREVOTE_WAIT
        self._new_step()
        self.ticker.schedule(
            TimeoutInfo(
                self.config.prevote_timeout(round_), height, round_,
                int(RoundStep.PREVOTE_WAIT),
            )
        )

    def _enter_precommit(self, height: int, round_: int) -> None:
        """POL lock logic (:1051-1144)."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and int(rs.step) >= int(RoundStep.PRECOMMIT)
        ):
            return
        rs.step = RoundStep.PRECOMMIT
        self._new_step()
        maj = rs.votes.prevotes(round_).two_thirds_majority()
        if maj is None:
            # no polka: precommit nil, keep any lock (:1072-1086)
            self._sign_add_vote(PRECOMMIT, b"")
            return
        if maj == b"":
            # polka for nil: unlock (:1096-1105)
            rs.locked_round = -1
            rs.locked_block = None
            self._sign_add_vote(PRECOMMIT, b"")
            return
        # polka for a block
        if rs.locked_block is not None and rs.locked_block.hash() == maj:
            rs.locked_round = round_  # re-lock at this round (:1110-1116)
            self._sign_add_vote(PRECOMMIT, maj)
            return
        if rs.proposal_block is not None and rs.proposal_block.hash() == maj:
            err = self.block_exec.validate_block(self.state, rs.proposal_block)
            if err is None:
                rs.locked_round = round_
                rs.locked_block = rs.proposal_block
                self._sign_add_vote(PRECOMMIT, maj)
                return
        # polka for a block we don't have: unlock, precommit nil (:1132-1142)
        rs.locked_round = -1
        rs.locked_block = None
        self._sign_add_vote(PRECOMMIT, b"")

    def _enter_precommit_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and int(rs.step) >= int(RoundStep.PRECOMMIT_WAIT)
        ):
            return
        rs.step = RoundStep.PRECOMMIT_WAIT
        self._new_step()
        self.ticker.schedule(
            TimeoutInfo(
                self.config.precommit_timeout(round_), height, round_,
                int(RoundStep.PRECOMMIT_WAIT),
            )
        )

    def _enter_commit(self, height: int, commit_round: int) -> None:
        rs = self.rs
        if rs.height != height or int(rs.step) >= int(RoundStep.COMMIT):
            return
        rs.step = RoundStep.COMMIT
        rs.commit_round = commit_round
        rs.commit_time_ns = now_ns()
        self._new_step()
        maj = rs.votes.precommits(commit_round).two_thirds_majority()
        assert maj, "enter_commit without precommit majority"
        # if the committed block is the locked block, it is the proposal
        if rs.locked_block is not None and rs.locked_block.hash() == maj:
            rs.proposal_block = rs.locked_block
        self._try_finalize_commit(height)

    def _try_finalize_commit(self, height: int) -> None:
        rs = self.rs
        if rs.height != height or rs.step != RoundStep.COMMIT:
            return
        maj = rs.votes.precommits(rs.commit_round).two_thirds_majority()
        if not maj:
            return
        if rs.proposal_block is None or rs.proposal_block.hash() != maj:
            return  # don't have the block yet: wait for gossip/catchup
        self._finalize_commit(height)

    def _finalize_commit(self, height: int) -> None:
        """(:1251-1344): save block -> WAL EndHeight -> ApplyBlock -> next."""
        rs = self.rs
        block = rs.proposal_block
        precommits = rs.votes.precommits(rs.commit_round)
        seen_commit = precommits.make_commit(block.hash())

        if self.block_store.height() < height:
            self.block_store.save_block(block, seen_commit)

        failpoints.fail("consensus-after-save-block")

        if self.wal is not None:
            self.wal.write_end_height(height)

        failpoints.fail("consensus-after-end-height")

        new_state = self.block_exec.apply_block(
            self.state, block, vtx_filter=self._vtx_filter()
        )

        self._update_to_state(new_state)
        self._decided_once.set()
        if self.on_commit is not None:
            try:
                self.on_commit(new_state, block)
            except Exception:
                pass
        with self.height_committed:
            self.height_committed.notify_all()
        self._schedule_round0()

    def apply_catchup_block(self, block: Block, commit: BlockCommit) -> None:
        """Apply a block received via catchup (the fast-sync analog): the
        commit must carry +2/3 of the block height's validator set."""
        from ..state.execution import verify_commit

        with self._mtx:
            state = self.state
            if block.height != state.last_block_height + 1:
                return
            err = verify_commit(
                state.chain_id, state.validators, block.hash(), block.height,
                commit,
            )
            if err:
                return
            if self.block_store.height() < block.height:
                self.block_store.save_block(block, commit)
            if self.wal is not None:
                self.wal.write_end_height(block.height)
            new_state = self.block_exec.apply_block(
                state, block, vtx_filter=self._vtx_filter()
            )
            self._update_to_state(new_state)
            self._decided_once.set()
            if self.on_commit is not None:
                try:
                    self.on_commit(new_state, block)
                except Exception:
                    pass
        with self.height_committed:
            self.height_committed.notify_all()
        self._schedule_round0()

    # ------------------------------------------------------------- votes

    def _try_add_vote(self, vote: BlockVote, peer_id: str = "") -> None:
        rs = self.rs
        if vote.height != rs.height:
            if vote.height == rs.height + 1 and len(self._future_votes) < 4096:
                # buffer next-height votes arriving while we finalize this
                # height; released by _update_to_state. Signature-verified
                # BEFORE buffering — with unverified first-wins keying, one
                # forged message per (validator, type, round) would evict
                # the honest validator's real vote (r3 review)
                nv = self.state.next_validators
                if nv is not None:
                    _, val = nv.get_by_address(vote.validator_address)
                    if val is not None and vote.verify(
                        self.state.chain_id, val.pub_key
                    ):
                        key = (vote.validator_address, vote.type, vote.round)
                        self._future_votes.setdefault(key, (vote, peer_id))
            elif vote.height == rs.height - 1 and vote.type == PRECOMMIT:
                self._extend_last_commit(vote)
            return
        added, err = rs.votes.add_vote(vote, peer_id)
        if not added:
            from ..types.block_vote import ErrConflictingBlockVote

            if isinstance(err, ErrConflictingBlockVote):
                # equivocation: same validator, same (h, r, type),
                # different block — capture instead of just dropping.
                # The NEW vote's signature is verified FIRST: the conflict
                # check fires before signature verification, so without
                # this gate a peer could spam forged conflicts and make
                # every one cost the evidence pool two ed25519 verifies
                vset = (
                    rs.votes.prevotes(vote.round)
                    if vote.type == PREVOTE
                    else rs.votes.precommits(vote.round)
                )
                existing = vset.get_by_address(vote.validator_address)
                if existing is not None and self.on_evidence is not None:
                    _, val = rs.validators.get_by_address(vote.validator_address)
                    if val is not None and vote.verify(
                        self.state.chain_id, val.pub_key
                    ):
                        from ..types.evidence import DuplicateBlockVoteEvidence

                        try:
                            self.on_evidence(
                                DuplicateBlockVoteEvidence(
                                    existing.copy(), vote.copy()
                                )
                            )
                        except Exception:
                            pass
            return
        if vote.type == PREVOTE:
            prevotes = rs.votes.prevotes(vote.round)
            maj = prevotes.two_thirds_majority()
            if maj is not None and maj != b"":
                # polka for a block: update valid_* (reference :1522-1534)
                if rs.valid_round < vote.round and rs.proposal_block is not None \
                        and rs.proposal_block.hash() == maj:
                    rs.valid_round = vote.round
                    rs.valid_block = rs.proposal_block
                # unlock if locked on something else and a newer polka forms
                if (
                    rs.locked_block is not None
                    and rs.locked_round < vote.round
                    and rs.locked_block.hash() != maj
                ):
                    rs.locked_round = -1
                    rs.locked_block = None
            if vote.round == rs.round:
                if maj is not None:
                    self._enter_precommit(rs.height, vote.round)
                elif prevotes.has_two_thirds_any() and int(rs.step) >= int(
                    RoundStep.PREVOTE
                ):
                    self._enter_prevote_wait(rs.height, vote.round)
            elif vote.round > rs.round and prevotes.has_two_thirds_any():
                self._enter_new_round(rs.height, vote.round)  # catchup
        else:  # PRECOMMIT
            precommits = rs.votes.precommits(vote.round)
            maj = precommits.two_thirds_majority()
            if maj is not None and maj != b"":
                self._enter_commit(rs.height, vote.round)
            elif maj == b"":
                # +2/3 precommit nil: straight to next round (:1602-1606)
                self._enter_new_round(rs.height, vote.round + 1)
            elif vote.round == rs.round and precommits.has_two_thirds_any():
                self._enter_precommit_wait(rs.height, vote.round)
            elif vote.round > rs.round and precommits.has_two_thirds_any():
                self._enter_new_round(rs.height, vote.round)

    def _vtx_filter(self):
        """Predicate selecting vtxs the LOCAL fast path has not applied:
        those must be delivered with the block or this app's hash diverges
        from nodes that fast-path-committed them (BlockExecutor.apply_block
        docstring).

        When a fast-path engine is attached (``vtx_claimer``, wired by the
        node), the claim is an atomic check-and-mark against the engine —
        a plain tx-store lookup would race the engine's pipelined commit
        queue and double-apply. Without a fast path every vtx is missing
        by definition."""
        if self.vtx_claimer is not None:
            return self.vtx_claimer
        if self.tx_store is None:
            return lambda tx: True
        import hashlib

        return lambda tx: not self.tx_store.has_tx(
            hashlib.sha256(tx).hexdigest().upper()
        )

    def _extend_last_commit(self, vote: BlockVote) -> None:
        """Fold a late precommit for the committed previous height into the
        stored seen-commit (commit-gossip liveness: the reference extends
        cs.LastCommit so lagging peers can still assemble +2/3)."""
        rs = self.rs
        commit = rs.last_commit
        if commit is None or vote.block_id != commit.block_id:
            return
        if any(
            v.validator_address == vote.validator_address for v in commit.precommits
        ):
            return
        _, val = rs.last_validators.get_by_address(vote.validator_address)
        if val is None or not vote.verify(self.state.chain_id, val.pub_key):
            return
        commit.precommits.append(vote)
        self.block_store.save_seen_commit(vote.height, commit)

    def _sign_add_vote(self, vote_type: int, block_id: bytes) -> None:
        rs = self.rs
        if self.priv_val is None or not rs.validators.has_address(
            self.priv_val.get_address()
        ):
            return
        vote = BlockVote(
            height=rs.height,
            round=rs.round,
            type=vote_type,
            block_id=block_id,
            validator_address=self.priv_val.get_address(),
        )
        try:
            self.priv_val.sign_block_vote(self.state.chain_id, vote)
        except Exception:
            return
        self.add_vote(vote)  # own vote through the same serialized path
        self.broadcast_vote(vote)

    # ------------------------------------------------------------- misc

    def _new_step(self) -> None:
        if self.event_bus is not None:
            self.event_bus.publish(EventNewRoundStep, self.rs)
        self.broadcast_step(self.rs)
