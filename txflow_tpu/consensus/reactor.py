"""ConsensusReactor: block-path gossip (reference consensus/reactor.go).

Message kinds on the consensus channel (0x20): round-step announcements,
signed proposals (carrying the full block — no part-sets), block votes,
and a block-catchup request/response pair that replaces the reference's
separate blockchain fast-sync reactor v1 for lagging peers.

Deviation (documented): the reference runs per-peer gossip routines that
walk PeerState bitarrays (reactor.go:465-729); here nodes PUSH their own
proposals/votes to all peers as they are produced, which is equivalent
under the full-mesh topologies the framework deploys (validators
interconnect over DCN; LocalNet mirrors that); catchup for late joiners
rides the block request/response path.
"""

from __future__ import annotations

import json
import threading

from ..p2p.base import CHANNEL_CONSENSUS_STATE, ChannelDescriptor, Reactor
from ..types.block import Block, decode_block, encode_block
from ..types.block_vote import decode_block_vote, encode_block_vote
from ..types.block_vote import BlockVote
from .state import ConsensusState
from .types import Proposal, RoundState

MSG_ROUND_STEP = 1
MSG_PROPOSAL = 2
MSG_VOTE = 3
MSG_BLOCK_REQUEST = 4
MSG_BLOCK_RESPONSE = 5

PEER_HEIGHT_KEY = "consensus_height"


def _encode_proposal_msg(p: Proposal, block: Block) -> bytes:
    return bytes([MSG_PROPOSAL]) + json.dumps(
        {
            "height": p.height,
            "round": p.round,
            "pol_round": p.pol_round,
            "block_hash": p.block_hash.hex(),
            "ts": p.timestamp_ns,
            "sig": (p.signature or b"").hex(),
            "block": encode_block(block).hex(),
        }
    ).encode()


def _decode_proposal_msg(body: bytes) -> tuple[Proposal, Block]:
    d = json.loads(body)
    p = Proposal(
        height=d["height"],
        round=d["round"],
        pol_round=d["pol_round"],
        block_hash=bytes.fromhex(d["block_hash"]),
        timestamp_ns=d["ts"],
        signature=bytes.fromhex(d["sig"]) or None,
    )
    return p, decode_block(bytes.fromhex(d["block"]))


class ConsensusReactor(Reactor):
    def __init__(self, consensus: ConsensusState):
        super().__init__("consensus")
        self.consensus = consensus
        consensus.broadcast_proposal = self._broadcast_proposal
        consensus.broadcast_vote = self._broadcast_vote
        consensus.broadcast_step = self._broadcast_step
        self._gossip_stop = threading.Event()
        # encoded-proposal cache: gossip re-offers the SAME proposal to
        # same-height peers every tick, and each encode walks the whole
        # block's tx lists (r4 config-5 profile: block re-encoding was
        # the single largest fast-path/block-path interference cost)
        self._prop_cache_key: tuple | None = None
        self._prop_cache_msg: bytes = b""

    def get_channels(self) -> list[ChannelDescriptor]:
        # priority 6 (above the bulk txvote/mempool channels) and reliable:
        # proposals/votes are push-once, so a queue-pressure drop would
        # stall the round until timeout (reference gives consensus its own
        # high-priority channels + per-peer retransmit walks, reactor.go:
        # 354-377; this framework's equivalent is the lossless lane)
        return [
            ChannelDescriptor(id=CHANNEL_CONSENSUS_STATE, priority=6, reliable=True)
        ]

    def on_start(self) -> None:
        # periodic position announce: push-once gossip can lose messages
        # (e.g. sent before a peer connected); a lagging peer's reply to
        # the announce carries the missing proposal/votes (retransmission —
        # the liveness role of the reference's per-peer gossip routines)
        self._gossip_stop.clear()
        threading.Thread(
            target=self._gossip_loop, name="consensus-gossip", daemon=True
        ).start()

    def on_stop(self) -> None:
        self._gossip_stop.set()

    def _gossip_loop(self) -> None:
        sleep = getattr(self.consensus.config, "peer_gossip_sleep", 0.1)
        while not self._gossip_stop.wait(sleep):
            if self.switch is not None and self.switch.peers():
                self._broadcast_step(self.consensus.round_state())

    # -- outbound (hooks called by ConsensusState) --

    def _encoded_proposal(self, p: Proposal, block: Block) -> bytes:
        key = (p.height, p.round, p.block_hash)
        if self._prop_cache_key == key:
            return self._prop_cache_msg
        msg = _encode_proposal_msg(p, block)
        self._prop_cache_key = key
        self._prop_cache_msg = msg
        return msg

    def _broadcast_proposal(self, p: Proposal, block: Block) -> None:
        if self.switch is not None:
            self.switch.broadcast(
                CHANNEL_CONSENSUS_STATE, self._encoded_proposal(p, block)
            )

    def _broadcast_vote(self, vote: BlockVote) -> None:
        if self.switch is not None:
            self.switch.broadcast(
                CHANNEL_CONSENSUS_STATE,
                bytes([MSG_VOTE]) + encode_block_vote(vote),
            )

    def _broadcast_step(self, rs: RoundState) -> None:
        if self.switch is not None:
            self.switch.broadcast(CHANNEL_CONSENSUS_STATE, self._step_msg(rs))

    def _step_msg(self, rs: RoundState) -> bytes:
        return bytes([MSG_ROUND_STEP]) + json.dumps(
            {
                "height": rs.height,
                "round": rs.round,
                "step": int(rs.step),
                "committed": self.consensus.state.last_block_height,
            }
        ).encode()

    def add_peer(self, peer) -> None:
        # announce our position so lagging peers can request catchup
        peer.try_send(CHANNEL_CONSENSUS_STATE, self._step_msg(self.consensus.round_state()))

    # -- inbound --

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        if not msg:
            raise ValueError("empty consensus message")
        kind, body = msg[0], msg[1:]
        if kind == MSG_ROUND_STEP:
            d = json.loads(body)
            peer.set(PEER_HEIGHT_KEY, d["committed"])
            my_committed = self.consensus.state.last_block_height
            if d["committed"] < my_committed:
                # peer is behind: ship the next block it needs
                self._send_catchup(peer, d["committed"] + 1)
            elif d["committed"] > my_committed:
                # we are behind: ask for our next block
                peer.try_send(
                    CHANNEL_CONSENSUS_STATE,
                    bytes([MSG_BLOCK_REQUEST])
                    + json.dumps({"height": my_committed + 1}).encode(),
                )
            else:
                # same committed height: re-offer round data — this plus
                # the periodic announce is what makes push-once gossip
                # eventually deliver (liveness, r3 stall postmortem).
                # Receivers dedup everything. Volume is bounded by need:
                # a peer at a DIFFERENT (round, step) gets the full dump;
                # a peer at the SAME position (which can still differ in
                # vote knowledge) gets current-round votes, plus the block
                # only while it could actually be missing it (<= PREVOTE:
                # nil-prevoters without the proposal sit exactly there).
                rs = self.consensus.round_state()
                if d["height"] == rs.height:
                    same_pos = d.get("round", -1) == rs.round and d.get(
                        "step", -1
                    ) == int(rs.step)
                    self._send_round_data(
                        peer,
                        current_round_only=same_pos,
                        with_block=(not same_pos)
                        or d.get("step", 99) <= 4,  # RoundStep.PREVOTE
                    )
        elif kind == MSG_PROPOSAL:
            p, block = _decode_proposal_msg(body)  # decode error stops peer
            self.consensus.add_proposal(p, block, peer_id=peer.node_id)
        elif kind == MSG_VOTE:
            vote = decode_block_vote(body)
            self.consensus.add_vote(vote, peer_id=peer.node_id)
        elif kind == MSG_BLOCK_REQUEST:
            d = json.loads(body)
            self._send_catchup(peer, d["height"])
        elif kind == MSG_BLOCK_RESPONSE:
            d = json.loads(body)
            block = decode_block(bytes.fromhex(d["block"]))
            from ..types.block_vote import decode_block_commit

            commit = decode_block_commit(bytes.fromhex(d["commit"]))
            self.consensus.apply_catchup_block(block, commit)
            # keep pulling until caught up
            peer.try_send(
                CHANNEL_CONSENSUS_STATE,
                bytes([MSG_BLOCK_REQUEST])
                + json.dumps(
                    {"height": self.consensus.state.last_block_height + 1}
                ).encode(),
            )
        else:
            raise ValueError(f"unknown consensus msg type {kind}")

    def _send_round_data(
        self, peer, current_round_only: bool = False, with_block: bool = True
    ) -> None:
        # rate limit per peer: announces arrive on every step change AND
        # every gossip tick; responding to each with a full round-data
        # dump floods the reliable lane (drops!) exactly when rounds churn
        import time as _time

        now = _time.monotonic()
        last = peer.get("consensus_rd_last", 0.0)
        if now - last < getattr(self.consensus.config, "peer_gossip_sleep", 0.1):
            return
        peer.set("consensus_rd_last", now)
        proposal, block, votes = self.consensus.current_round_data()
        if current_round_only:
            rs = self.consensus.round_state()
            votes = [v for v in votes if v.round == rs.round]
        if with_block and proposal is not None and block is not None:
            peer.try_send(
                CHANNEL_CONSENSUS_STATE, self._encoded_proposal(proposal, block)
            )
        for v in votes:
            peer.try_send(
                CHANNEL_CONSENSUS_STATE, bytes([MSG_VOTE]) + encode_block_vote(v)
            )

    def _send_catchup(self, peer, height: int) -> None:
        store = self.consensus.block_store
        if height > store.height():
            return
        block = store.load_block(height)
        commit = store.load_seen_commit(height) or store.load_block_commit(height)
        if block is None or commit is None:
            return
        from ..types.block_vote import encode_block_commit

        peer.try_send(
            CHANNEL_CONSENSUS_STATE,
            bytes([MSG_BLOCK_RESPONSE])
            + json.dumps(
                {
                    "block": encode_block(block).hex(),
                    "commit": encode_block_commit(commit).hex(),
                }
            ).encode(),
        )
