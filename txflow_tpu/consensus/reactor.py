"""ConsensusReactor: block-path gossip (reference consensus/reactor.go).

Message kinds on the consensus channel (0x20): round-step announcements,
signed proposals (carrying the full block — no part-sets), block votes,
and a block-catchup request/response pair that replaces the reference's
separate blockchain fast-sync reactor v1 for lagging peers.

Deviation (documented): the reference runs per-peer gossip routines that
walk PeerState bitarrays (reactor.go:465-729); here nodes PUSH their own
proposals/votes to all peers as they are produced, and periodic position
announces carry current-round prevote/precommit BITMASKS + a
has-proposal flag, kept per peer in PeerRoundState — the re-offer path
then ships only deltas. This subsumes the reference's separate
queryMaj23Routine/VoteSetBits exchange (reactor.go:729-780): those
messages exist to learn which votes a peer lacks, which the announce
bitmasks state directly. Catchup for late joiners rides the parallel
block request/response pool.
"""

from __future__ import annotations

import json
import threading

from ..p2p.base import CHANNEL_CONSENSUS_STATE, ChannelDescriptor, Reactor
from ..types.block import Block, decode_block, encode_block
from ..types.block_vote import PRECOMMIT, PREVOTE, decode_block_vote, encode_block_vote
from ..types.block_vote import BlockVote
from ..types.part_set import PART_SIZE, PartSetBuffer, PartSetHeader, make_part_set
from .state import ConsensusState
from .types import PeerRoundState, Proposal, RoundState

MSG_ROUND_STEP = 1
MSG_PROPOSAL = 2
MSG_VOTE = 3
MSG_BLOCK_REQUEST = 4
MSG_BLOCK_RESPONSE = 5
MSG_BLOCK_PART = 6

# parallel fast-sync: how many block requests ride in flight at once
# (reference wires bcv1's multi-peer request pool, node/node.go:369-385)
SYNC_WINDOW = 16
SYNC_RETRY_S = 3.0

PEER_HEIGHT_KEY = "consensus_height"
PEER_STATE_KEY = "consensus_peer_state"


def _proposal_fields(p: Proposal) -> dict:
    return {
        "height": p.height,
        "round": p.round,
        "pol_round": p.pol_round,
        "block_hash": p.block_hash.hex(),
        "ts": p.timestamp_ns,
        "sig": (p.signature or b"").hex(),
    }


def _encode_proposal_msg(p: Proposal, block: Block) -> bytes:
    """Whole-block proposal (blocks that fit one p2p message)."""
    d = _proposal_fields(p)
    d["block"] = encode_block(block).hex()
    return bytes([MSG_PROPOSAL]) + json.dumps(d).encode()


def _encode_proposal_header_msg(p: Proposal, header: PartSetHeader) -> bytes:
    """Chunked proposal: parts header only; block bytes follow as
    MSG_BLOCK_PART messages (reference part-set gossip,
    consensus/reactor.go:465-530)."""
    d = _proposal_fields(p)
    d["parts"] = header.to_wire()
    return bytes([MSG_PROPOSAL]) + json.dumps(d).encode()


def _decode_proposal_fields(d: dict) -> Proposal:
    return Proposal(
        height=d["height"],
        round=d["round"],
        pol_round=d["pol_round"],
        block_hash=bytes.fromhex(d["block_hash"]),
        timestamp_ns=d["ts"],
        signature=bytes.fromhex(d["sig"]) or None,
    )


class ConsensusReactor(Reactor):
    def __init__(self, consensus: ConsensusState):
        super().__init__("consensus")
        self.consensus = consensus
        consensus.broadcast_proposal = self._broadcast_proposal
        consensus.broadcast_vote = self._broadcast_vote
        consensus.broadcast_step = self._broadcast_step
        self._gossip_stop = threading.Event()
        # encoded-proposal cache: gossip re-offers the SAME proposal to
        # same-height peers every tick, and each encode walks the whole
        # block's tx lists (r4 config-5 profile: block re-encoding was
        # the single largest fast-path/block-path interference cost).
        # For an over-size block the cache holds (header_msg, part_msgs).
        self._prop_cache_key: tuple | None = None
        self._prop_cache_msg: bytes = b""
        self._prop_cache_parts: list[bytes] = []
        # part assembly buffers: (height, round, block_hash) -> buffer
        self._part_bufs: dict[tuple, tuple[Proposal, PartSetBuffer]] = {}
        self._part_mtx = threading.Lock()
        # parallel fast-sync request pool: height -> (peer_id, asked_at)
        self._sync_mtx = threading.Lock()
        self._sync_inflight: dict[int, tuple[str, float]] = {}
        self._sync_blocks: dict[int, tuple[Block, object]] = {}

    def get_channels(self) -> list[ChannelDescriptor]:
        # priority 6 (above the bulk txvote/mempool channels) and reliable:
        # proposals/votes are push-once, so a queue-pressure drop would
        # stall the round until timeout (reference gives consensus its own
        # high-priority channels + per-peer retransmit walks, reactor.go:
        # 354-377; this framework's equivalent is the lossless lane)
        return [
            ChannelDescriptor(id=CHANNEL_CONSENSUS_STATE, priority=6, reliable=True)
        ]

    def on_start(self) -> None:
        # periodic position announce: push-once gossip can lose messages
        # (e.g. sent before a peer connected); a lagging peer's reply to
        # the announce carries the missing proposal/votes (retransmission —
        # the liveness role of the reference's per-peer gossip routines)
        self._gossip_stop.clear()
        threading.Thread(
            target=self._gossip_loop, name="consensus-gossip", daemon=True
        ).start()

    def on_stop(self) -> None:
        self._gossip_stop.set()

    def _gossip_loop(self) -> None:
        sleep = getattr(self.consensus.config, "peer_gossip_sleep", 0.1)
        while not self._gossip_stop.wait(sleep):
            if self.switch is not None and self.switch.peers():
                self._broadcast_step(self.consensus.round_state())
                self._sync_pump()  # re-request timed-out catchup blocks

    # -- outbound (hooks called by ConsensusState) --

    def _encoded_proposal(self, p: Proposal, block: Block) -> tuple[bytes, list[bytes]]:
        """(header-or-whole-block msg, part msgs). Small blocks ship whole
        in one message ([] parts); blocks whose encoding exceeds one part
        ship as a parts header + MSG_BLOCK_PART chunks, so block size is
        no longer capped by the p2p max message (reference MakePartSet,
        consensus/state.go:945-962)."""
        key = (p.height, p.round, p.block_hash)
        if self._prop_cache_key == key:
            return self._prop_cache_msg, self._prop_cache_parts
        enc = encode_block(block)
        if len(enc) <= PART_SIZE:
            msg, part_msgs = _encode_proposal_msg(p, block), []
        else:
            header, parts = make_part_set(enc)
            msg = _encode_proposal_header_msg(p, header)
            meta = {"height": p.height, "round": p.round,
                    "block_hash": p.block_hash.hex()}
            part_msgs = [
                bytes([MSG_BLOCK_PART])
                + json.dumps({**meta, "index": i, "part": part.hex()}).encode()
                for i, part in enumerate(parts)
            ]
        self._prop_cache_key = key
        self._prop_cache_msg = msg
        self._prop_cache_parts = part_msgs
        return msg, part_msgs

    def _broadcast_proposal(self, p: Proposal, block: Block) -> None:
        if self.switch is not None:
            msg, part_msgs = self._encoded_proposal(p, block)
            self.switch.broadcast(CHANNEL_CONSENSUS_STATE, msg)
            for pm in part_msgs:
                self.switch.broadcast(CHANNEL_CONSENSUS_STATE, pm)

    def _broadcast_vote(self, vote: BlockVote) -> None:
        if self.switch is not None:
            msg = bytes([MSG_VOTE]) + encode_block_vote(vote)
            idx, _ = self.consensus.state.validators.get_by_address(
                vote.validator_address
            )
            # per-peer send so the delta-gossip mark reflects REALITY: a
            # peer whose reliable queue dropped the send (try_send False)
            # must stay unmarked or the re-offer path would never repair
            # it (r5 review — the exact gap re-offer gossip exists for)
            for peer in self.switch.peers():
                if peer.try_send(CHANNEL_CONSENSUS_STATE, msg):
                    ps = self._peer_state(peer)
                    if ps.height == vote.height:
                        ps.mark_vote(vote.round, vote.type, idx)

    def _broadcast_step(self, rs: RoundState) -> None:
        if self.switch is not None:
            self.switch.broadcast(CHANNEL_CONSENSUS_STATE, self._step_msg(rs))

    def _step_msg(self, rs: RoundState) -> bytes:
        return bytes([MSG_ROUND_STEP]) + json.dumps(
            self.consensus.round_summary()
        ).encode()

    def _peer_state(self, peer) -> PeerRoundState:
        ps = peer.get(PEER_STATE_KEY)
        if ps is None:
            ps = PeerRoundState()
            peer.set(PEER_STATE_KEY, ps)
        return ps

    def add_peer(self, peer) -> None:
        # announce our position so lagging peers can request catchup
        peer.try_send(CHANNEL_CONSENSUS_STATE, self._step_msg(self.consensus.round_state()))

    # -- inbound --

    def receive(self, chan_id: int, peer, msg: bytes) -> None:
        if not msg:
            raise ValueError("empty consensus message")
        kind, body = msg[0], msg[1:]
        if kind == MSG_ROUND_STEP:
            d = json.loads(body)
            # type-validate before ANY field reaches peer state: a str
            # height would sit poisoned in PeerRoundState (json carries
            # no schema; undecodable -> the switch stops the peer)
            if not isinstance(d.get("height"), int) or not isinstance(
                d.get("committed"), int
            ):
                raise ValueError("malformed announce: height/committed")
            if not isinstance(d.get("round", -1), int) or not isinstance(
                d.get("step", -1), int
            ):
                raise ValueError("malformed announce: round/step")
            peer.set(PEER_HEIGHT_KEY, d["committed"])
            ps = self._peer_state(peer)
            if ps.height != d["height"]:
                # masks describe ONE height's rounds: a height change
                # invalidates them (same round numbers recur every height)
                ps.vote_masks.clear()
            ps.height = d["height"]
            ps.round = d.get("round", -1)
            ps.step = d.get("step", -1)
            ps.committed = d["committed"]
            ps.has_proposal = bool(d.get("has_proposal", False))
            # the peer's announce is the AUTHORITATIVE current-round mask
            # (a superset of anything we optimistically recorded).
            # Bounded parse: a hostile multi-megabyte hex string would
            # otherwise become a million-bit int consulted per vote
            for f in ("prevotes", "precommits"):
                if len(str(d.get(f, ""))) > 2048:  # 8192 validators
                    raise ValueError("oversized vote mask in announce")
            if "prevotes" in d:
                ps.vote_masks[(ps.round, PREVOTE)] = (
                    ps.vote_masks.get((ps.round, PREVOTE), 0)
                    | int(d["prevotes"], 16)
                )
            if "precommits" in d:
                ps.vote_masks[(ps.round, PRECOMMIT)] = (
                    ps.vote_masks.get((ps.round, PRECOMMIT), 0)
                    | int(d["precommits"], 16)
                )
            my_committed = self.consensus.state.last_block_height
            if d["committed"] < my_committed:
                # peer is behind: ship the next block it needs
                self._send_catchup(peer, d["committed"] + 1)
            elif d["committed"] > my_committed:
                # we are behind: fill the parallel request window
                self._sync_pump()
            else:
                # same committed height: re-offer round data — this plus
                # the periodic announce is what makes push-once gossip
                # eventually deliver (liveness, r3 stall postmortem).
                # Receivers dedup everything. Volume is bounded by need:
                # a peer at a DIFFERENT (round, step) gets the full dump;
                # a peer at the SAME position (which can still differ in
                # vote knowledge) gets current-round votes, plus the block
                # only while it could actually be missing it (<= PREVOTE:
                # nil-prevoters without the proposal sit exactly there).
                rs = self.consensus.round_state()
                if d["height"] == rs.height:
                    same_pos = d.get("round", -1) == rs.round and d.get(
                        "step", -1
                    ) == int(rs.step)
                    self._send_round_data(
                        peer,
                        current_round_only=same_pos,
                        with_block=(not same_pos)
                        or d.get("step", 99) <= 4,  # RoundStep.PREVOTE
                    )
        elif kind == MSG_PROPOSAL:
            d = json.loads(body)  # decode error stops peer
            p = _decode_proposal_fields(d)
            ps = self._peer_state(peer)
            if p.height == ps.height and p.round == ps.round:
                ps.has_proposal = True  # the sender has what it sends
            if "block" in d:
                block = decode_block(bytes.fromhex(d["block"]))
                self.consensus.add_proposal(p, block, peer_id=peer.node_id)
            else:
                header = PartSetHeader.from_wire(d["parts"])
                if header.validate_basic() is not None:
                    raise ValueError("invalid part-set header")
                if header.total > 4096:
                    raise ValueError("part-set too large")
                # Authenticate the header BEFORE buffering any bytes: only
                # the current round's proposer can open an assembly buffer
                # (r5 review: an unauthenticated first-header-wins buffer
                # let anyone block assembly of the real proposal, and
                # unbounded keys let a byzantine peer OOM the node).
                if not self.consensus.verify_proposal_signature(p):
                    return
                key = (p.height, p.round, p.block_hash)
                with self._part_mtx:
                    if key not in self._part_bufs:
                        # signed headers are current-round only, so live
                        # buffers are bounded by proposer equivocation;
                        # cap defensively and drop stale rounds
                        rs = self.consensus.round_state()
                        for k in [
                            k
                            for k in self._part_bufs
                            if (k[0], k[1]) != (rs.height, rs.round)
                        ]:
                            del self._part_bufs[k]
                        if len(self._part_bufs) >= 4:
                            return
                        self._part_bufs[key] = (p, PartSetBuffer(header))
        elif kind == MSG_BLOCK_PART:
            d = json.loads(body)
            key = (d["height"], d["round"], bytes.fromhex(d["block_hash"]))
            with self._part_mtx:
                entry = self._part_bufs.get(key)
                if entry is None:
                    return  # header not seen (or already assembled)
                p, buf = entry
                buf.add_part(int(d["index"]), bytes.fromhex(d["part"]))
                done = buf.is_complete()
                if done:
                    del self._part_bufs[key]
            if done:
                block = decode_block(buf.assemble())
                # _set_proposal re-checks block.hash() == p.block_hash, so
                # a forged header/parts can never install a wrong block
                self.consensus.add_proposal(p, block, peer_id=peer.node_id)
        elif kind == MSG_VOTE:
            vote = decode_block_vote(body)
            ps = self._peer_state(peer)
            if ps.height == vote.height:
                idx, _ = self.consensus.state.validators.get_by_address(
                    vote.validator_address
                )
                ps.mark_vote(vote.round, vote.type, idx)
            self.consensus.add_vote(vote, peer_id=peer.node_id)
        elif kind == MSG_BLOCK_REQUEST:
            d = json.loads(body)
            self._send_catchup(peer, d["height"])
        elif kind == MSG_BLOCK_RESPONSE:
            d = json.loads(body)
            block = decode_block(bytes.fromhex(d["block"]))
            from ..types.block_vote import decode_block_commit

            commit = decode_block_commit(bytes.fromhex(d["commit"]))
            # parallel fast-sync: stash out-of-order arrivals, apply the
            # contiguous prefix, then refill the request window — blocks
            # stream from several peers concurrently instead of one block
            # per round trip (reference bcv1 request pool,
            # node/node.go:369-385)
            with self._sync_mtx:
                self._sync_inflight.pop(block.height, None)
                self._sync_blocks[block.height] = (block, commit)
            self._sync_apply_ready()
            self._sync_pump()
        else:
            raise ValueError(f"unknown consensus msg type {kind}")

    def _send_round_data(
        self, peer, current_round_only: bool = False, with_block: bool = True
    ) -> None:
        # rate limit per peer: announces arrive on every step change AND
        # every gossip tick; responding to each with a full round-data
        # dump floods the reliable lane (drops!) exactly when rounds churn
        import time as _time

        now = _time.monotonic()
        last = peer.get("consensus_rd_last", 0.0)
        if now - last < getattr(self.consensus.config, "peer_gossip_sleep", 0.1):
            return
        peer.set("consensus_rd_last", now)
        proposal, block, votes = self.consensus.current_round_data()
        rs = self.consensus.round_state()
        ps = self._peer_state(peer)
        if current_round_only:
            votes = [v for v in votes if v.round == rs.round]
        # per-peer delta gossip (reference PeerState bitarrays,
        # consensus/reactor.go:904-1340): send only the votes the peer is
        # not known to hold and the proposal only if it lacks one — the
        # previous full re-dump per tick was O(peers x votes) redundant
        # bandwidth (r4 verdict missing-item 1). Peer knowledge comes from
        # its announces (authoritative), what it sent us, and what we
        # already pushed down the reliable lane (marked below).
        val_set = self.consensus.state.validators
        if (
            with_block
            and proposal is not None
            and block is not None
            and not (
                ps.has_proposal
                and ps.height == proposal.height
                and ps.round == proposal.round
            )
        ):
            msg, part_msgs = self._encoded_proposal(proposal, block)
            sent_all = peer.try_send(CHANNEL_CONSENSUS_STATE, msg)
            for pm in part_msgs:
                sent_all = (
                    peer.try_send(CHANNEL_CONSENSUS_STATE, pm) and sent_all
                )
            # mark only a FULLY delivered proposal (r5 review: a dropped
            # part with has_proposal set left the peer unable to assemble
            # and the re-offer path suppressed forever)
            if (
                sent_all
                and ps.height == proposal.height
                and ps.round == proposal.round
            ):
                ps.has_proposal = True
        same_height = ps.height == rs.height
        for v in votes:
            idx, _ = val_set.get_by_address(v.validator_address)
            if same_height and idx >= 0 and ps.has_vote(v.round, v.type, idx):
                continue
            if (
                peer.try_send(
                    CHANNEL_CONSENSUS_STATE,
                    bytes([MSG_VOTE]) + encode_block_vote(v),
                )
                and same_height
            ):
                ps.mark_vote(v.round, v.type, idx)

    # -- parallel fast-sync (requester side) --

    def _sync_pump(self) -> None:
        """Fill the in-flight request window across all peers that have
        the heights we lack, round-robin; re-request timed-out heights
        from a different peer. Called on announces, responses, and gossip
        ticks."""
        if self.switch is None:
            return
        import time as _time

        my_h = self.consensus.state.last_block_height
        peers = [
            (p, p.get(PEER_HEIGHT_KEY, 0)) for p in self.switch.peers()
        ]
        peers = [(p, h) for p, h in peers if h > my_h]
        if not peers:
            return
        target = max(h for _, h in peers)
        now = _time.monotonic()
        with self._sync_mtx:
            # drop stale state at/below our height
            for h in [h for h in self._sync_inflight if h <= my_h]:
                del self._sync_inflight[h]
            for h in [h for h in self._sync_blocks if h <= my_h]:
                del self._sync_blocks[h]
            wanted = [
                h
                for h in range(my_h + 1, min(my_h + SYNC_WINDOW, target) + 1)
                if h not in self._sync_blocks
                and (
                    h not in self._sync_inflight
                    or now - self._sync_inflight[h][1] > SYNC_RETRY_S
                )
            ]
            asks: list[tuple[object, int]] = []
            for i, h in enumerate(wanted):
                # round-robin across capable peers; on retry prefer a
                # DIFFERENT peer than the one that timed out
                capable = [(p, ph) for p, ph in peers if ph >= h]
                if not capable:
                    continue
                prev = self._sync_inflight.get(h)
                if prev is not None and len(capable) > 1:
                    capable = [
                        (p, ph) for p, ph in capable if p.node_id != prev[0]
                    ] or capable
                p, _ph = capable[i % len(capable)]
                self._sync_inflight[h] = (p.node_id, now)
                asks.append((p, h))
        for p, h in asks:
            p.try_send(
                CHANNEL_CONSENSUS_STATE,
                bytes([MSG_BLOCK_REQUEST]) + json.dumps({"height": h}).encode(),
            )

    def _sync_apply_ready(self) -> None:
        """Apply the contiguous buffered prefix in height order."""
        while True:
            next_h = self.consensus.state.last_block_height + 1
            with self._sync_mtx:
                entry = self._sync_blocks.pop(next_h, None)
            if entry is None:
                return
            block, commit = entry
            try:
                self.consensus.apply_catchup_block(block, commit)
            except Exception:
                # invalid catchup data: drop it and re-request elsewhere
                with self._sync_mtx:
                    self._sync_inflight.pop(block.height, None)
                return

    def _send_catchup(self, peer, height: int) -> None:
        store = self.consensus.block_store
        if height > store.height():
            return
        block = store.load_block(height)
        commit = store.load_seen_commit(height) or store.load_block_commit(height)
        if block is None or commit is None:
            return
        from ..types.block_vote import encode_block_commit

        peer.try_send(
            CHANNEL_CONSENSUS_STATE,
            bytes([MSG_BLOCK_RESPONSE])
            + json.dumps(
                {
                    "block": encode_block(block).hex(),
                    "commit": encode_block_commit(commit).hex(),
                }
            ).encode(),
        )
