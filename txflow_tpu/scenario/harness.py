"""Shared soak/grid harness: real-TCP bring-up, the zero-loss /
set-equality / prefix-stability / quarantine assertion core, and the
machine-readable run report every mode emits.

Before this module, ``tools/soak.py --overload``, ``--wan-matrix`` and
``--byzantine`` each carried a private copy of "start a ProcNet, probe it
over RPC, judge admitted-tx loss and cross-node agreement, print a
banner and exit 1" — and the scenario grid would have been the fourth.
Every mode (and every grid tile) now judges through one set of helpers,
raising :class:`Breach` with a typed breach class; the CLI edge turns
that into one final ``RESULT {...}`` JSON line plus a distinct exit code
per class, so callers stop grepping log text for ``SOAK STALL`` markers.

Exit-code contract (stable; scripts may match on it):

==================  ====  ==============================================
breach class        exit  meaning
==================  ====  ==============================================
(ok)                   0  every assertion held
``infra``              1  harness/environment failure (legacy catch-all)
``loss``              10  an admitted tx never committed somewhere
``divergence``        11  cross-node committed sets unequal, or a node
                          rewrote its committed prefix
``slo``               12  a latency budget breached
``adversary``         13  an adversary was not struck/quarantined, or
                          post-quarantine waste exceeded its bound
``liveness``          14  the net never reached/settled a required state
                          (mesh, height, sync, drain)
==================  ====  ==============================================
"""

from __future__ import annotations

import hashlib
import json
import statistics
import sys
import time
import urllib.request
from contextlib import contextmanager

BREACH_CLASSES = ("infra", "loss", "divergence", "slo", "adversary", "liveness")

EXIT_OK = 0
EXIT_CODES: dict[str, int] = {
    "infra": 1,
    "loss": 10,
    "divergence": 11,
    "slo": 12,
    "adversary": 13,
    "liveness": 14,
}

# severity order for aggregating many tile verdicts into one exit code:
# losing admitted txs outranks disagreeing, which outranks being slow
BREACH_SEVERITY = ("loss", "divergence", "adversary", "liveness", "slo", "infra")


class Breach(Exception):
    """One failed soak/grid assertion, carrying its breach class."""

    def __init__(self, kind: str, msg: str):
        if kind not in BREACH_CLASSES:
            raise ValueError(f"unknown breach class {kind!r}")
        super().__init__(msg)
        self.kind = kind
        self.msg = msg


def worst_breach(kinds) -> str | None:
    """The most severe class among ``kinds`` (None when empty)."""
    present = [k for k in BREACH_SEVERITY if k in set(kinds)]
    return present[0] if present else None


def emit_result(mode: str, ok: bool, breach: str | None = None,
                detail: str = "", **summary) -> int:
    """Print the one machine-readable final line every soak/grid mode
    ends with, and return the exit code for it. The ``RESULT `` prefix
    is the contract: exactly one such line per run, always last."""
    code = EXIT_OK if ok else EXIT_CODES.get(breach or "infra", 1)
    payload = {
        "mode": mode,
        "ok": ok,
        "exit_code": code,
        "breach": None if ok else (breach or "infra"),
        "detail": detail,
        **summary,
    }
    print("RESULT " + json.dumps(payload, sort_keys=True), flush=True)
    return code


def run_mode(mode: str, fn) -> None:
    """CLI edge wrapper: run ``fn`` (which returns a summary dict on
    success and raises Breach on a failed assertion), emit the banner +
    RESULT line, and exit with the class's code. Unexpected exceptions
    are an ``infra`` breach — the environment broke, not an SLO."""
    try:
        summary = fn() or {}
    except Breach as b:
        print(f"SOAK STALL: {b.msg}", flush=True)
        sys.exit(emit_result(mode, False, b.kind, b.msg))
    except KeyboardInterrupt:
        raise
    except Exception as e:  # noqa: BLE001 - the report IS the handler
        print(f"SOAK STALL: harness failure: {e!r}", flush=True)
        sys.exit(emit_result(mode, False, "infra", repr(e)))
    sys.exit(emit_result(mode, True, **summary))


# -- shared bring-up / teardown --------------------------------------------


@contextmanager
def live_net(n: int, spec: dict, timeout: float = 90.0):
    """The real-TCP bring-up/teardown every soak mode shares: a started
    ProcNet that is always stopped, however the mode exits. (The grid
    runner manages net lifetime itself — one net outlives many tiles —
    but judges through the same assertion core below.)"""
    from ..node.procnet import ProcNet

    net = ProcNet(n, spec=spec)
    net.start(timeout=timeout)
    try:
        yield net
    finally:
        net.stop()


# -- RPC probe helpers (everything over real sockets) ----------------------


def commit_latency(net, i: int, tx: str, timeout: float = 10.0):
    """Submit via ``broadcast_tx_commit``; ``(seconds-to-commit or None,
    tx hash)``. None means slow, not necessarily lost: callers re-check
    the hash at quiescence before calling it loss."""
    host, port = net.rpc_addr(i)
    t0 = time.monotonic()
    with urllib.request.urlopen(
        f'http://{host}:{port}/broadcast_tx_commit?tx="{tx}"'
        f"&timeout={timeout}",
        timeout=timeout + 5,
    ) as r:
        res = json.loads(r.read().decode())["result"]
    lat = time.monotonic() - t0 if res.get("committed") else None
    return lat, res["hash"]


def broadcast(net, i: int, tx: str, timeout: float = 10.0) -> str:
    """Fire-and-forget ``broadcast_tx``; returns the admitted hash."""
    host, port = net.rpc_addr(i)
    with urllib.request.urlopen(
        f'http://{host}:{port}/broadcast_tx?tx="{tx}"', timeout=timeout
    ) as r:
        return json.loads(r.read().decode())["result"]["hash"]


def percentiles(lats: list[float]) -> tuple[float, float]:
    """(p50, p99) in ms; p99 is the max at soak-sized sample counts."""
    return statistics.median(lats) * 1e3, max(lats) * 1e3


# -- the assertion core ----------------------------------------------------


def assert_all_committed(
    net, hashes, nodes, deadline_s: float, what: str = "admitted txs",
    kind: str = "loss",
) -> None:
    """Zero admitted-tx loss: every hash commits on EVERY listed node
    before the deadline. Polls /tx; raises ``Breach(kind)`` naming the
    nodes still missing txs."""
    remaining = {i: set(hashes) for i in nodes}
    deadline = time.monotonic() + deadline_s
    while any(remaining.values()) and time.monotonic() < deadline:
        for i in nodes:
            remaining[i] = {
                h
                for h in remaining[i]
                if not net.rpc_json(i, f"/tx?hash={h}")["result"]["committed"]
            }
        if any(remaining.values()):
            time.sleep(0.4)
    missing = {i: len(r) for i, r in remaining.items() if r}
    if missing:
        raise Breach(
            kind,
            f"{what}: {missing} never committed within {deadline_s:.0f}s "
            f"(node -> missing count)",
        )


def commit_log_heads(net, nodes) -> dict[int, dict]:
    """Per-node commit-log head digests (cheap ``count=0`` probes) for a
    later prefix-stability check."""
    return {i: net.rpc_json(i, "/commit_log?count=0")["result"] for i in nodes}


def assert_prefix_stable(net, pre: dict[int, dict], label: str = "") -> None:
    """No node may rewrite committed history: the log each node had when
    ``pre`` was captured must be an exact prefix of its log now."""
    tag = f"[{label}] " if label else ""
    for i, head in pre.items():
        res = net.rpc_json(i, f"/commit_log?start=0&count={head['total']}")[
            "result"
        ]
        digest = hashlib.sha256()
        for h in res["hashes"]:
            digest.update(h.encode())
        if digest.hexdigest() != head["digest"]:
            raise Breach(
                "divergence", f"{tag}node {i} rewrote its committed prefix"
            )


def assert_committed_sets_equal(
    net, nodes, deadline_s: float, label: str = ""
) -> list[dict]:
    """Cross-node committed-SET equality (there is no global total order
    across fast-path nodes — each node's log is its own decision order).
    Returns the final per-node commit logs on success."""
    deadline = time.monotonic() + deadline_s
    logs: list[dict] = []
    while time.monotonic() < deadline:
        logs = [net.rpc_json(i, "/commit_log")["result"] for i in nodes]
        sets = [frozenset(lg["hashes"]) for lg in logs]
        if all(s == sets[0] for s in sets):
            return logs
        time.sleep(0.4)
    tag = f"[{label}] " if label else ""
    raise Breach(
        "divergence",
        f"{tag}committed sets diverged: totals "
        f"{[lg['total'] for lg in logs]}",
    )


def assert_slo(p50_ms: float, p99_ms: float, p50_budget_ms: float,
               p99_budget_ms: float, label: str = "") -> None:
    tag = f"[{label}] " if label else ""
    if p50_ms > p50_budget_ms:
        raise Breach(
            "slo",
            f"{tag}commit p50 {p50_ms:.0f}ms breached the "
            f"{p50_budget_ms:.0f}ms budget",
        )
    if p99_ms > p99_budget_ms:
        raise Breach(
            "slo",
            f"{tag}commit p99 {p99_ms:.0f}ms breached the "
            f"{p99_budget_ms:.0f}ms budget",
        )


# -- adversary judging (health/byzantine.py over RPC) ----------------------


def byzantine_peer_state(net, i: int, peer_id: str) -> dict:
    """One honest node's ledger record for ``peer_id`` (via /health)."""
    byz = net.rpc_json(i, "/health")["result"].get("byzantine") or {}
    return (byz.get("peers") or {}).get(peer_id) or {}


def adversary_activity_marks(net, nodes, peer_id: str) -> dict[int, tuple]:
    """Per-honest-node (strikes, quarantined-frame drops) counters for
    the adversary — captured before a tile so judging can require real
    DELTAS, not totals left over from an earlier tile on the same net."""
    marks = {}
    for i in nodes:
        st = byzantine_peer_state(net, i, peer_id)
        marks[i] = (
            st.get("strikes", 0),
            (st.get("drops") or {}).get("quarantined", 0),
        )
    return marks


def wait_quarantined(net, nodes, peer_id: str, deadline_s: float,
                     label: str = "") -> None:
    """Block until every listed honest node quarantines ``peer_id``.
    Used right after arming, BEFORE offered load starts: a busy net has
    the adversary relaying honest votes, and those valid relays race its
    bad fraction away from the breaker line — armed-and-quiet, the
    garbage dominates and the latch trips in a round-trip or two."""
    tag = f"[{label}] " if label else ""
    deadline = time.monotonic() + deadline_s
    while True:
        states = {i: byzantine_peer_state(net, i, peer_id) for i in nodes}
        if all(s.get("quarantined") for s in states.values()):
            return
        if time.monotonic() > deadline:
            lagging = [i for i, s in states.items() if not s.get("quarantined")]
            raise Breach(
                "adversary",
                f"{tag}{peer_id} never quarantined on nodes {lagging}",
            )
        time.sleep(0.2)


def assert_adversary_quarantined(
    net, nodes, peer_id: str, marks: dict[int, tuple],
    deadline_s: float, label: str = "",
) -> dict:
    """Every honest node must (a) currently quarantine ``peer_id`` and
    (b) show fresh evidence the flood was live this tile: a strike delta
    (garbage reached verdicts) or quarantined-frame-drop delta (the
    front-door gate absorbed it pre-decode). Returns a summary dict."""
    tag = f"[{label}] " if label else ""
    deadline = time.monotonic() + deadline_s
    states: dict[int, dict] = {}
    while True:
        states = {i: byzantine_peer_state(net, i, peer_id) for i in nodes}
        if all(s.get("quarantined") for s in states.values()):
            break
        if time.monotonic() > deadline:
            lagging = [i for i, s in states.items() if not s.get("quarantined")]
            raise Breach(
                "adversary",
                f"{tag}{peer_id} never quarantined on nodes {lagging}",
            )
        time.sleep(0.3)
    while True:
        states = {i: byzantine_peer_state(net, i, peer_id) for i in nodes}
        deltas = {
            i: (
                s.get("strikes", 0) - marks[i][0],
                (s.get("drops") or {}).get("quarantined", 0) - marks[i][1],
            )
            for i, s in states.items()
        }
        if all(ds > 0 or dq > 0 for ds, dq in deltas.values()):
            break
        if time.monotonic() > deadline:
            idle = [i for i, d in deltas.items() if max(d) <= 0]
            raise Breach(
                "adversary",
                f"{tag}{peer_id} quarantined but nodes {idle} saw no fresh "
                f"strikes or gated drops — was the flood live?",
            )
        time.sleep(0.3)
    return {
        "peer": peer_id,
        "strike_deltas": {i: d[0] for i, d in deltas.items()},
        "gated_drop_deltas": {i: d[1] for i, d in deltas.items()},
    }


# -- liveness helpers ------------------------------------------------------


def wait_height(net, nodes, height: int, deadline_s: float,
                field: str = "fast_path_height", label: str = "") -> None:
    """Wait for every listed node's /health progress ``field`` to reach
    ``height``; liveness breach past the deadline."""
    deadline = time.monotonic() + deadline_s
    heights: dict[int, int] = {}
    while time.monotonic() < deadline:
        heights = {
            i: (net.rpc_json(i, "/health")["result"].get("progress") or {}).get(
                field
            )
            or 0
            for i in nodes
        }
        if all(h >= height for h in heights.values()):
            return
        time.sleep(0.2)
    tag = f"[{label}] " if label else ""
    raise Breach(
        "liveness",
        f"{tag}{field} never reached {height} everywhere: {heights}",
    )


def wait_mesh(net, nodes, min_peers: int, deadline_s: float,
              label: str = "") -> None:
    deadline = time.monotonic() + deadline_s
    n_peers: list[int] = []
    while time.monotonic() < deadline:
        n_peers = [
            net.rpc_json(i, "/net_info")["result"]["n_peers"] for i in nodes
        ]
        if all(p >= min_peers for p in n_peers):
            return
        time.sleep(0.4)
    tag = f"[{label}] " if label else ""
    raise Breach("liveness", f"{tag}mesh never (re)formed: peers {n_peers}")
