"""Declarative scenario-grid spec: axes × levels, seed-deterministic.

The ROADMAP's "scenario grid" frontier: the repo owns four independent
stress axes — adversary fleet (faults/byzantine.py), WAN weather
(netem/), overload flood + admission (admission/), stake distributions +
churn (faults/stake.py, "Weighted Voting on the Blockchain" arxiv
1903.04213) — and production meets them simultaneously. A ``TileSpec``
names one level per axis; ``GridSpec`` enumerates a configured
cross-product (or the smoke diagonal) and ``materialize`` turns a tile
into the concrete, seed-deterministic schedules each axis contributes.

PRNG-domain discipline (the FaultPlan/LinkShaper rule, composed): every
axis draws its schedule from its OWN stream seeded by
``sha256("scenario|<seed>|<axis>|<level>")``. No draw ever crosses axes,
so toggling one axis's level leaves every other axis's drawn schedule
byte-identical — the composition property tests/test_scenario_grid.py
pins. Never share one Random across axes when extending this module.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import random
from dataclasses import dataclass, field

from ..faults.stake import stake_distribution
from ..netem.profiles import PROFILES, profile_names
from ..utils.domains import SCENARIO_AXIS

# Axis order is part of the spec: tile ids, cross-product walk order and
# the smoke diagonal all derive from it. The FIRST level of each axis is
# its baseline (the unstressed control level).
ADVERSARY_LEVELS = ("none", "flooder", "fleet")
WEATHER_LEVELS = profile_names()  # lan first: the baseline profile
OVERLOAD_LEVELS = ("none", "surge", "flood")
STAKE_LEVELS = ("uniform", "whale", "longtail", "churning")

AXES: dict[str, tuple[str, ...]] = {
    "adversary": ADVERSARY_LEVELS,
    "weather": WEATHER_LEVELS,
    "overload": OVERLOAD_LEVELS,
    "stake": STAKE_LEVELS,
}

# overload offered-load shape per level: flood thread count and the
# worst-case SLO relief the extra contention buys (budget multiplier).
# The pacing interval itself is DRAWN from the overload domain so the
# schedule is a real per-level PRNG artifact, not just a constant table.
_OVERLOAD_SHAPE = {
    "none": dict(threads=0, budget_scale=1.0),
    "surge": dict(threads=2, budget_scale=2.0),
    "flood": dict(threads=4, budget_scale=3.0),
}

# adversary driver mixes per level (faults/byzantine.py fleet). Batch /
# interval bounds are drawn per driver from the adversary domain. The
# fleet deliberately does NOT include the "stale" spammer: its lag-1000
# votes clamp to height 0 on a fresh fast-path net, so honest pre-checks
# judge them VALID — they only pad the breaker window with good events
# and dilute the fleet's own bad fraction below the trip line. The
# unknown-signer flood is the undiluted replacement: dropped at the
# pre-check (unknown validator), one bad window event per vote.
_ADVERSARY_MIX = {
    "none": (),
    "flooder": ("sig-garbage",),
    "fleet": ("sig-garbage", "unknown-signer", "replayer"),
}

# per-stake-level SLO relief: churning runs block consensus + live
# rotations alongside the fast path, which costs real latency on a
# contended box
_STAKE_BUDGET_SCALE = {"churning": 1.5}


def axis_seed(seed: int, axis: str, level: str) -> int:
    """The disjoint PRNG domain for one (grid seed, axis, level): no two
    axes — and no two levels of one axis — ever share a stream."""
    digest = hashlib.sha256(
        SCENARIO_AXIS + b"|%d|%s|%s" % (seed, axis.encode(), level.encode())
    ).digest()
    return int.from_bytes(digest[:8], "little")


def axis_rng(seed: int, axis: str, level: str) -> random.Random:
    return random.Random(axis_seed(seed, axis, level))


@dataclass(frozen=True)
class TileSpec:
    """One grid tile: a level per axis plus the grid seed."""

    adversary: str = "none"
    weather: str = "lan"
    overload: str = "none"
    stake: str = "uniform"
    seed: int = 0

    def __post_init__(self):
        for axis, levels in AXES.items():
            level = getattr(self, axis)
            if level not in levels:
                raise ValueError(
                    f"unknown {axis} level {level!r} (want one of {levels})"
                )

    @property
    def tile_id(self) -> str:
        return (
            f"adv={self.adversary}|wan={self.weather}"
            f"|load={self.overload}|stake={self.stake}"
        )

    def level(self, axis: str) -> str:
        return getattr(self, axis)

    @property
    def composed(self) -> bool:
        """Every axis off its baseline: the production-weather shape no
        single-axis soak ever exercised."""
        return all(
            self.level(axis) != levels[0] for axis, levels in AXES.items()
        )

    def axes_dict(self) -> dict[str, str]:
        return {axis: self.level(axis) for axis in AXES}


@dataclass(frozen=True)
class GridSpec:
    """A configured grid: which levels of which axes, over how many
    validators, under which seed. ``axes`` may restrict levels (a spec
    file naming two weather profiles walks a 2-wide weather axis) but
    never invent new ones."""

    seed: int = 0
    n_validators: int = 4
    axes: dict[str, tuple[str, ...]] = field(
        default_factory=lambda: {a: tuple(ls) for a, ls in AXES.items()}
    )

    def __post_init__(self):
        if self.n_validators < 4:
            # an adversary tile disarms one validator's signer; the
            # remaining honest stake must still clear 2n/3 on its own
            raise ValueError("scenario grids need >= 4 validators")
        for axis, levels in self.axes.items():
            if axis not in AXES:
                raise ValueError(f"unknown axis {axis!r} (want {tuple(AXES)})")
            bad = [lv for lv in levels if lv not in AXES[axis]]
            if bad:
                raise ValueError(f"unknown {axis} levels {bad}")
            if not levels:
                raise ValueError(f"axis {axis!r} has no levels")
        for axis in AXES:
            if axis not in self.axes:
                raise ValueError(f"spec is missing axis {axis!r}")

    @classmethod
    def from_dict(cls, d: dict) -> "GridSpec":
        axes = {a: tuple(ls) for a, ls in AXES.items()}
        axes.update({a: tuple(ls) for a, ls in (d.get("axes") or {}).items()})
        return cls(
            seed=int(d.get("seed", 0)),
            n_validators=int(d.get("n_validators", 4)),
            axes=axes,
        )

    @classmethod
    def from_json_file(cls, path: str) -> "GridSpec":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def _tile(self, levels: dict[str, str]) -> TileSpec:
        return TileSpec(seed=self.seed, **levels)

    def full_tiles(self) -> list[TileSpec]:
        """The configured cross-product, walked in axis order (adversary
        outermost). This is the offline soak; CI runs the diagonal."""
        names = list(AXES)
        return [
            self._tile(dict(zip(names, combo)))
            for combo in itertools.product(*(self.axes[a] for a in names))
        ]

    def smoke_diagonal(self) -> list[TileSpec]:
        """One bounded walk covering every level of every axis at least
        once: tile k takes level ``k mod len(levels)`` on each axis, for
        k in [0, max axis width). With the default axes, tile 1 composes
        all four axes off-baseline — the acceptance tile."""
        width = max(len(levels) for levels in self.axes.values())
        return [
            self._tile(
                {a: self.axes[a][k % len(self.axes[a])] for a in AXES}
            )
            for k in range(width)
        ]

    # -- materialization: tile -> per-axis concrete schedules --

    def materialize(self, tile: TileSpec) -> "TilePlan":
        """Draw the tile's concrete schedules, one disjoint PRNG domain
        per axis. Everything returned is JSON-serializable: the
        byte-stability contract is over ``json.dumps`` of each schedule."""
        return TilePlan(
            tile=tile,
            adversary=_adversary_schedule(tile, self.n_validators),
            weather=_weather_schedule(tile),
            overload=_overload_schedule(tile),
            stake=_stake_schedule(tile, self.n_validators),
        )


@dataclass(frozen=True)
class TilePlan:
    """A materialized tile: the four drawn schedules plus derived run
    facts (net signature, budgets) the runner consumes."""

    tile: TileSpec
    adversary: dict
    weather: dict
    overload: dict
    stake: dict

    def schedules(self) -> dict[str, dict]:
        return {
            "adversary": self.adversary,
            "weather": self.weather,
            "overload": self.overload,
            "stake": self.stake,
        }

    @property
    def net_signature(self) -> tuple:
        """Tiles with equal signatures can share one live ProcNet: the
        stake table (and whether consensus must run for churn) is fixed
        at bring-up; weather, adversary activity and offered load all
        swap live."""
        return ("stake", self.tile.stake)

    @property
    def consensus(self) -> bool:
        """Churn re-weights validators through committed blocks (kvstore
        ``val:`` txs -> EndBlock -> H+2 rule), so churning tiles run the
        block path alongside the fast path."""
        return bool(self.stake.get("churn"))

    @property
    def budget_scale(self) -> float:
        return float(self.overload["budget_scale"]) * float(
            self.stake.get("budget_scale", 1.0)
        )

    @property
    def adversary_index(self) -> int | None:
        """The validator index that turns adversarial for this tile, or
        None for adversary-free tiles. Drawn from the STAKE schedule
        (smallest stake) so quorum reachability stays a property of the
        stake table, not of which adversary level happens to be active."""
        if self.adversary["level"] == "none":
            return None
        return int(self.stake["adversary_index"])


def _adversary_schedule(tile: TileSpec, n_validators: int) -> dict:
    level = tile.adversary
    if level == "none":
        return {"level": "none", "drivers": []}
    rng = axis_rng(tile.seed, "adversary", level)
    # forgeries target ghost txs (never in any mempool) so garbage
    # signatures reach live verify verdicts instead of late-dropping
    # against committed txs — the byzantine soak's trick, drawn here
    ghosts = [
        b"scn-ghost-%d-%d" % (i, rng.randrange(1 << 30)) for i in range(6)
    ]
    drivers = []
    for kind in _ADVERSARY_MIX[level]:
        if kind == "sig-garbage":
            drivers.append(
                {
                    "kind": kind,
                    "seed": rng.randrange(1 << 30),
                    "batch": rng.randrange(6, 12),
                    "interval": round(rng.uniform(0.02, 0.05), 4),
                }
            )
        elif kind == "unknown-signer":
            drivers.append(
                {
                    "kind": kind,
                    "seed": rng.randrange(1 << 30),
                    "batch": rng.randrange(8, 14),
                    "interval": round(rng.uniform(0.02, 0.05), 4),
                }
            )
        elif kind == "replayer":
            drivers.append(
                {
                    "kind": kind,
                    # replays are honest-signed ghost votes: the signer is
                    # drawn from the honest validators (never the
                    # adversary's own disarmed key)
                    "signer_index": rng.randrange(1, n_validators),
                    "n_votes": rng.randrange(2, 5),
                    # paced BELOW the garbage/stale floods: replays are
                    # counted (win_events) but not judged bad unless the
                    # replay breaker is armed, so a replay firehose would
                    # dilute the fleet's bad-rate under the breaker line
                    # and the composed adversary would hide behind its
                    # own noise
                    "interval": round(rng.uniform(0.05, 0.1), 4),
                }
            )
    return {
        "level": level,
        "ghost_txs": [g.hex() for g in ghosts],
        "drivers": drivers,
    }


def _weather_schedule(tile: TileSpec) -> dict:
    # the LinkShaper owns per-link domain separation below this seed
    # (sha256 over seed|src|dst inside netem/shaper.py) — the axis only
    # has to hand it a level-scoped root
    prof = PROFILES[tile.weather]
    return {
        "profile": tile.weather,
        "shaper_seed": axis_seed(tile.seed, "weather", tile.weather),
        "p50_budget_ms": prof.p50_budget_ms,
        "p99_budget_ms": prof.p99_budget_ms,
    }


def _overload_schedule(tile: TileSpec) -> dict:
    shape = _OVERLOAD_SHAPE[tile.overload]
    sched: dict = {"level": tile.overload, **shape}
    if shape["threads"]:
        rng = axis_rng(tile.seed, "overload", tile.overload)
        sched["intervals"] = [
            round(rng.uniform(0.01, 0.05), 4) for _ in range(shape["threads"])
        ]
        sched["tag"] = rng.randrange(1 << 20)
    return sched


def _stake_schedule(tile: TileSpec, n_validators: int) -> dict:
    level = tile.stake
    rng = axis_rng(tile.seed, "stake", level)
    kind = "churning" if level == "churning" else level
    powers = stake_distribution(
        kind, n_validators, seed=rng.randrange(1 << 30), base=10
    )
    sched: dict = {"level": level, "kind": kind, "powers": powers}
    # the adversary must never be quorum-critical: it takes the smallest
    # stake, so disarming + quarantining it still leaves honest stake
    # clear of 2n/3 (whale tiles put the whale on the honest side)
    sched["adversary_index"] = powers.index(min(powers))
    if level == "churning":
        sched["budget_scale"] = _STAKE_BUDGET_SCALE["churning"]
        # live churn: seed-deterministic ``val:`` re-weights (kvstore ->
        # EndBlock -> H+2 engine restage), strictly-unique powers so the
        # mempool dedup cache can never silently no-op an event
        # never re-weight the (potential) adversary slot: a churn event
        # boosting a disarmed validator could make it quorum-critical
        # mid-tile, turning a stake statement into a liveness failure
        candidates = [
            i for i in range(n_validators) if i != sched["adversary_index"]
        ]
        events = []
        for k in range(3):
            events.append(
                {
                    "at_frac": round((k + 1) / 4 + rng.uniform(-0.05, 0.05), 3),
                    "validator": rng.choice(candidates),
                    "power": 20 + 3 * k + rng.randrange(3),
                }
            )
        sched["churn"] = events
    return sched
