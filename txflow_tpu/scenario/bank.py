"""Results-matrix banking for the scenario grid, under bench.py's
clean-supersede contract.

The banked artifact (``bench_artifacts/scenario_grid_latest.json``) is
the regression reference: "handles every scenario" as a matrix of tile
verdicts a later run can be diffed against. Clean means the RUN was
sound — the walk completed without a harness error and every tile got a
real judgment. A clean run ALWAYS overwrites (red tiles are data, not
dirt: a regression must be allowed to update the reference it will be
blamed against); a dirty run (harness crash, infra-breach tiles) never
displaces a clean banked matrix — an artifact that mostly measured a
broken environment is worse than a stale clean one.

``verdict_fingerprint`` is the seed-reproducibility handle: a sha256
over the ordered (tile id, pass, breach) triples, so "same seed, same
verdicts" is one string compare instead of a tree diff.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
ARTIFACT_DIR = os.path.join(REPO_ROOT, "bench_artifacts")
GRID_LATEST = os.path.join(ARTIFACT_DIR, "scenario_grid_latest.json")


def verdict_fingerprint(verdicts: list[dict]) -> str:
    """sha256 over the ordered (tile, pass, breach) triples — the
    matrix's identity for same-seed reproducibility checks."""
    digest = hashlib.sha256()
    for v in verdicts:
        digest.update(
            json.dumps(
                [v.get("tile"), bool(v.get("pass")), v.get("breach")]
            ).encode()
        )
    return digest.hexdigest()


def matrix_clean(matrix: dict) -> bool:
    """A matrix is clean when the walk itself was sound: no harness
    error and no tile judged ``infra`` (a tile failing a REAL gate —
    loss/divergence/slo/adversary/liveness — is clean data)."""
    if matrix.get("error"):
        return False
    tiles = matrix.get("tiles") or []
    if not tiles:
        return False
    return all(t.get("breach") != "infra" for t in tiles)


def build_matrix(grid, tiles_kind: str, verdicts: list[dict],
                 error: str | None = None) -> dict:
    """Assemble the artifact payload from a walk's verdicts."""
    return {
        "kind": "scenario_grid",
        "tiles_kind": tiles_kind,  # "smoke-diagonal" | "full" | "filtered"
        "seed": grid.seed,
        "n_validators": grid.n_validators,
        "axes": {a: list(ls) for a, ls in grid.axes.items()},
        "tiles": verdicts,
        "passed": sum(1 for v in verdicts if v.get("pass")),
        "failed": sum(1 for v in verdicts if not v.get("pass")),
        "verdict_fingerprint": verdict_fingerprint(verdicts),
        "error": error,
    }


def bank_matrix(matrix: dict, path: str = GRID_LATEST) -> bool:
    """Bank under the clean-supersede contract; returns True when the
    artifact was written (False: dirty run held back by a clean bank)."""
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        matrix = dict(
            matrix,
            measured_at_unix=round(time.time(), 1),
            clean=matrix_clean(matrix),
        )
        existing = load_banked(path)
        if (
            existing is not None
            and not matrix["clean"]
            and existing.get("clean", matrix_clean(existing))
        ):
            return False
        with open(path, "w") as f:
            f.write(json.dumps(matrix, indent=1))
        return True
    except OSError:
        return False


def load_banked(path: str = GRID_LATEST) -> dict | None:
    try:
        with open(path) as f:
            return json.loads(f.read())
    except (OSError, ValueError):
        return None
