"""Scenario grid: composed Byzantine × WAN × overload × stake weather
over real-TCP ProcNets, with a banked results matrix.

- ``spec``: declarative axes × levels, seed-deterministic per-axis
  schedules (disjoint PRNG domains — the composition property);
- ``harness``: the shared soak/grid assertion core (zero admitted-tx
  loss, committed-set equality, SLO, quarantine) + the ``RESULT`` line /
  typed-exit-code contract every soak mode reports through;
- ``runner``: walks tiles over shared live nets and judges each;
- ``bank``: the results-matrix artifact under the clean-supersede
  contract (``bench_artifacts/scenario_grid_latest.json``).

``tools/scenario_grid.py`` is the CLI (``--list``/``--dry-run``/
``--smoke``/``--full``); tools/soak.py's three modes are single-axis
ancestors rebuilt on the same harness.
"""

from .harness import BREACH_CLASSES, EXIT_CODES, Breach, emit_result, worst_breach
from .spec import AXES, GridSpec, TilePlan, TileSpec, axis_seed
from .runner import GridRunner
from .bank import GRID_LATEST, bank_matrix, build_matrix, load_banked, verdict_fingerprint

__all__ = [
    "AXES",
    "BREACH_CLASSES",
    "Breach",
    "EXIT_CODES",
    "GRID_LATEST",
    "GridRunner",
    "GridSpec",
    "TilePlan",
    "TileSpec",
    "axis_seed",
    "bank_matrix",
    "build_matrix",
    "emit_result",
    "load_banked",
    "verdict_fingerprint",
    "worst_breach",
]
