"""Scenario-grid runner: walk tiles over live real-TCP ProcNets and
judge each one pass/fail.

One net serves every tile that shares a ``net_signature`` (the stake
table is fixed at bring-up; weather swaps live via ``set_netem``, the
adversary arms/disarms via ``set_adversary``, offered load is the
parent's flood threads, and churn rides committed ``val:`` txs) — so a
12-tile walk costs a handful of bring-ups, not twelve. Tiles are judged
INDEPENDENTLY: a failed tile records its breach and the walk continues,
so one bad tile yields a matrix with one red cell instead of a dead run.

Per-tile judgment (the four acceptance gates, all over real sockets):

- zero admitted-tx loss: every hash the net admitted (priority probes
  AND bulk riders) commits on every node before the drain deadline;
- cross-node committed-set equality, plus no node rewriting the
  committed prefix it entered the tile with;
- per-lane SLO: priority-probe p50/p99 against the tile's weather-
  profile budgets scaled by its overload/stake relief (and the
  ``SOAK_P50_BUDGET_MS`` / ``SOAK_BUDGET_SCALE`` relief valves for
  heavily-shared boxes);
- adversary quarantine: every honest node quarantines the adversary AND
  shows fresh strike/gated-drop deltas from THIS tile's flood.
"""

from __future__ import annotations

import os
import threading
import time

from ..admission.config import soak_spec_overrides
from . import harness as H
from .spec import GridSpec, TileSpec

# the byzantine soak's production-shaped breaker posture (tools/soak.py
# --byzantine): armed from t=0, strike_penalty 0 so the scoreboard floor
# never tears down links mid-tile — link churn is the overload axis's
# subject, not the adversary axis's.
#
# quarantine_replays stays OFF here, unlike the byzantine soak: grid
# nets regossip aggressively over shaped WAN links, and while a flood
# backlog drains every re-walk re-sends vote signatures peers already
# hold. With replays counted as breaker-bad, HONEST peers cross the
# 0.5-bad-rate line within one overload tile and the whole mesh
# quarantines itself (observed live: 4/4 nodes mutually quarantined,
# zero commits for 600 s). The adversary axis does not need the replay
# breaker to be convicted — sig-garbage and stale-vote traffic trips
# the bad gate, and forged signatures draw engine invalid-verdict
# strikes.
GRID_BYZANTINE_POSTURE = {
    "min_samples": 24,
    "max_bad_rate": 0.5,
    "stale_height_slack": 8,
    "quarantine_replays": False,
    "quarantine_secs": 600.0,
    "strike_penalty": 0.0,
    "quarantine_penalty": 0.5,
}


class _Flood:
    """Parent-side bulk offered load: ``threads`` loops hammering the
    honest nodes' /broadcast_tx at the tile's drawn pacing. Admitted
    hashes are collected (they join the zero-loss set); 429 sheds are
    counted but shed traffic owes nothing."""

    def __init__(self, net, nodes, schedule: dict, tile_id: str):
        self.net = net
        self.nodes = list(nodes)
        self.schedule = schedule
        self.tag = schedule.get("tag", 0)
        self.tile_id = tile_id
        self.admitted: list[str] = []
        self.shed = 0
        self._mtx = threading.Lock()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def start(self) -> None:
        for t, interval in enumerate(self.schedule.get("intervals", [])):
            th = threading.Thread(
                target=self._run,
                args=(t, float(interval)),
                name=f"grid-flood-{t}",
                daemon=True,
            )
            self._threads.append(th)
            th.start()

    def _run(self, t: int, interval: float) -> None:
        seq = 0
        while not self._stop.is_set():
            node = self.nodes[(t + seq) % len(self.nodes)]
            tx = "grid-bulk-%d-%d-%d=v" % (self.tag, t, seq)
            seq += 1
            try:
                h = H.broadcast(self.net, node, tx, timeout=5.0)
                with self._mtx:
                    self.admitted.append(h)
            except Exception:
                # 429 shed (or a transient socket error): not admitted,
                # so it owes no commit
                with self._mtx:
                    self.shed += 1
            self._stop.wait(interval)

    def stop(self) -> tuple[list[str], int]:
        self._stop.set()
        for th in self._threads:
            th.join(timeout=10.0)
        with self._mtx:
            return list(self.admitted), self.shed


class _Churner:
    """Stake-churn driver: injects the tile's drawn ``val:`` re-weights
    (kvstore -> EndBlock -> H+2 restage) at their scheduled fractions of
    the tile window. Retries 429 sheds — churn is control-plane traffic
    and must land even mid-flood."""

    def __init__(self, net, nodes, events, pub_hexes, duration: float):
        self.net = net
        self.nodes = list(nodes)
        self.events = sorted(events, key=lambda e: e["at_frac"])
        self.pub_hexes = pub_hexes
        self.duration = duration
        self.landed: list[str] = []  # admitted val: tx hashes
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="grid-churn", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        t0 = time.monotonic()
        for k, ev in enumerate(self.events):
            at = max(0.0, float(ev["at_frac"])) * self.duration
            while time.monotonic() - t0 < at:
                if self._stop.wait(0.1):
                    return
            tx = "val:%s!%d" % (self.pub_hexes[int(ev["validator"])], int(ev["power"]))
            while not self._stop.is_set():
                try:
                    h = H.broadcast(
                        self.net, self.nodes[k % len(self.nodes)], tx, timeout=5.0
                    )
                    self.landed.append(h)
                    break
                except Exception:
                    if self._stop.wait(0.3):
                        return

    def stop(self) -> list[str]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        return list(self.landed)


class GridRunner:
    """Walks a tile list over shared ProcNets and returns one verdict
    record per tile (see ``run``)."""

    def __init__(
        self,
        grid: GridSpec,
        smoke: bool = True,
        log=print,
        data_root: str | None = None,
    ):
        self.grid = grid
        self.smoke = smoke
        self.log = log
        self.data_root = data_root
        # knobs (seconds); smoke keeps CI inside the tier-1 budget, the
        # full posture is the offline soak's
        self.tile_duration = 4.0 if smoke else 20.0
        self.commit_wait = float(
            os.environ.get("SOAK_COMMIT_WAIT", "25" if smoke else "120")
        )
        self.quarantine_wait = 20.0 if smoke else 60.0
        self.probe_interval = 0.25 if smoke else 0.5
        # per-probe server-side wait: a slow probe is counted (and its
        # hash re-checked at quiescence), never allowed to wedge the tile
        self.probe_timeout = 8.0 if smoke else 20.0
        # relief valves shared with the soaks: heavily-loaded boxes scale
        # budgets up rather than turning contention into red tiles
        self.budget_scale_env = float(os.environ.get("SOAK_BUDGET_SCALE", "1"))
        self.p50_floor_ms = float(os.environ.get("SOAK_P50_BUDGET_MS", "0"))

    # -- net lifecycle (one net per net_signature group) --

    def _spec_for(self, plan) -> dict:
        n = self.grid.n_validators
        spec: dict = {
            "chain_id": "txflow-grid",
            "seed_prefix": f"grid-{self.grid.seed}-{plan.tile.stake}",
            "powers": list(plan.stake["powers"]),
            # the block path runs only where churn needs it (churning
            # stake tiles: val: txs -> EndBlock -> H+2 restage). On a
            # consensus net /commit_log stops being the complete commit
            # record — a tx block-committed before its fast-path quorum
            # lands never gets an S: row, and which path wins races
            # differently per node — so the fast-path equality gates are
            # judged on the fast-path-only groups and consensus nets are
            # judged on owed-set coverage + block liveness instead.
            "consensus": plan.consensus,
            "byzantine": dict(GRID_BYZANTINE_POSTURE),
            "admission": soak_spec_overrides(),
            "mempool": {"size": 300, "cache_size": 20000},
            # scalar host verify: small batches keep head-of-line blocking
            # out of the priority drain (the overload soak's sizing)
            "engine": {"max_batch": 8, "min_batch": 1},
            "netem": {
                "profile": plan.weather["profile"],
                "seed": plan.weather["shaper_seed"],
            },
            "regossip": 0.25,
        }
        if self.data_root:
            spec["per_node"] = {
                i: {"data_dir": f"{self.data_root}/{plan.tile.stake}/node{i}"}
                for i in range(n)
            }
        return spec

    def _bring_up(self, plan):
        from ..node.procnet import ProcNet

        net = ProcNet(self.grid.n_validators, spec=self._spec_for(plan))
        net.start(timeout=90.0)
        return net

    def _pub_hexes(self, plan) -> list[str]:
        import hashlib as _h

        from ..types.priv_validator import MockPV

        prefix = f"grid-{self.grid.seed}-{plan.tile.stake}"
        return [
            MockPV(_h.sha256(f"{prefix}-val{i}".encode()).digest())
            .get_pub_key()
            .hex()
            for i in range(self.grid.n_validators)
        ]

    # -- the walk --

    def run(self, tiles: list[TileSpec]) -> list[dict]:
        """Run ``tiles`` (grouped by net signature, walk order otherwise
        preserved) and return one verdict dict per tile, in the original
        tile order."""
        plans = [self.grid.materialize(t) for t in tiles]
        groups: dict[tuple, list[int]] = {}
        for idx, plan in enumerate(plans):
            groups.setdefault(plan.net_signature, []).append(idx)
        verdicts: dict[int, dict] = {}
        total = len(tiles)
        for sig, idxs in groups.items():
            net = None
            try:
                self.log(
                    f"grid: bringing up {self.grid.n_validators}-process net "
                    f"for {sig[0]}={sig[1]} ({len(idxs)} tiles)"
                )
                net = self._bring_up(plans[idxs[0]])
                for idx in idxs:
                    verdicts[idx] = self._run_tile(
                        net, plans[idx], idx, total
                    )
            except Exception as e:  # bring-up/teardown infra failure:
                # every unjudged tile in the group records it
                for idx in idxs:
                    if idx not in verdicts:
                        verdicts[idx] = self._verdict(
                            plans[idx], False, "infra", f"net: {e!r}"
                        )
            finally:
                if net is not None:
                    net.stop()
        return [verdicts[i] for i in range(total)]

    def _verdict(self, plan, ok: bool, breach: str | None, detail: str, **extra) -> dict:
        return {
            "tile": plan.tile.tile_id,
            "axes": plan.tile.axes_dict(),
            "composed": plan.tile.composed,
            "pass": ok,
            "breach": breach,
            "detail": detail,
            **extra,
        }

    def _run_tile(self, net, plan, idx: int, total: int) -> dict:
        tile = plan.tile
        nodes = list(range(self.grid.n_validators))
        adv_idx = plan.adversary_index
        honest = [i for i in nodes if i != adv_idx]
        self.log(f"grid: tile {idx + 1}/{total} {tile.tile_id}")
        t0 = time.monotonic()
        flood = None
        churner = None
        armed = False
        try:
            net.set_netem(plan.weather["profile"])
            net.set_scenario(
                {
                    "active": True,
                    "tile": tile.tile_id,
                    "tile_index": idx,
                    "tiles_total": total,
                    "started_unix": time.time(),
                    "axes": tile.axes_dict(),
                }
            )
            pre_heads = H.commit_log_heads(net, nodes)
            marks = (
                H.adversary_activity_marks(
                    net, honest, net.infos[adv_idx]["node_id"]
                )
                if adv_idx is not None
                else {}
            )
            if adv_idx is not None:
                net.set_adversary(adv_idx, True, schedule=plan.adversary)
                armed = True
                # conviction must land while the net is still quiet: once
                # offered load starts, the (disarmed-signer) adversary
                # RELAYS honest votes, and those valid frames race its
                # judged-bad fraction back under the breaker line. Armed-
                # and-quiet the garbage dominates within a round-trip or
                # two, and once the latch trips, relays are gated at the
                # front door and stop counting as good events — the
                # verdict is then stable for the whole tile.
                H.wait_quarantined(
                    net, honest, net.infos[adv_idx]["node_id"],
                    self.quarantine_wait, label=tile.tile_id,
                )
            flood = _Flood(net, honest, plan.overload, tile.tile_id)
            flood.start()
            if plan.stake.get("churn"):
                churner = _Churner(
                    net,
                    honest,
                    plan.stake["churn"],
                    self._pub_hexes(plan),
                    self.tile_duration,
                )
                churner.start()

            # priority probes: the tile's latency sample AND its zero-loss
            # sentinels; fee=1 rides the priority lane past any shed
            lats: list[float] = []
            slow_probes = 0
            probe_hashes: list[str] = []
            seq = 0
            t_load = time.monotonic()  # the latch wait is not tile time
            while time.monotonic() - t_load < self.tile_duration:
                node = honest[seq % len(honest)]
                tx = f"fee=1;grid-probe-{idx}-{seq}=v"
                seq += 1
                lat, h = H.commit_latency(
                    net, node, tx, timeout=self.probe_timeout
                )
                probe_hashes.append(h)
                if lat is None:
                    slow_probes += 1
                else:
                    lats.append(lat)
                time.sleep(self.probe_interval)

            # quiesce offered load, then judge
            riders, shed = flood.stop()
            flood = None
            churn_hashes = churner.stop() if churner is not None else []
            churner = None
            adv_summary = {}
            if adv_idx is not None:
                adv_summary = H.assert_adversary_quarantined(
                    net,
                    honest,
                    net.infos[adv_idx]["node_id"],
                    marks,
                    self.quarantine_wait,
                    label=tile.tile_id,
                )
                ack = net.set_adversary(adv_idx, False)
                armed = False
                adv_summary["emitted"] = ack.get("emitted", 0)

            owed = probe_hashes + riders + churn_hashes
            H.assert_all_committed(
                net, owed, nodes, self.commit_wait,
                what=f"[{tile.tile_id}] admitted txs",
            )
            H.assert_prefix_stable(net, pre_heads, label=tile.tile_id)
            if not plan.consensus:
                # fast-path-only net: /commit_log IS the complete commit
                # record, so cross-node committed-SET equality holds
                H.assert_committed_sets_equal(
                    net, nodes, self.commit_wait, label=tile.tile_id
                )
            else:
                # consensus net: agreement is the block path's total
                # order; judge that it stayed LIVE through the churn
                # (owed-set coverage above already pins zero loss)
                base = min(
                    (
                        net.rpc_json(i, "/health")["result"].get("progress")
                        or {}
                    ).get("consensus_height")
                    or 0
                    for i in nodes
                )
                H.wait_height(
                    net, nodes, base + 2, self.commit_wait,
                    field="consensus_height", label=tile.tile_id,
                )

            if not lats:
                raise H.Breach(
                    "liveness",
                    f"[{tile.tile_id}] no probe committed inside its window",
                )
            p50, p99 = H.percentiles(lats)
            scale = plan.budget_scale * self.budget_scale_env
            p50_budget = max(
                plan.weather["p50_budget_ms"] * scale, self.p50_floor_ms
            )
            p99_budget = max(
                plan.weather["p99_budget_ms"] * scale, 2 * self.p50_floor_ms
            )
            H.assert_slo(p50, p99, p50_budget, p99_budget, label=tile.tile_id)

            return self._verdict(
                plan,
                True,
                None,
                "",
                probes=len(probe_hashes),
                slow_probes=slow_probes,
                riders=len(riders),
                shed=shed,
                churn_events=len(churn_hashes),
                p50_ms=round(p50, 1),
                p99_ms=round(p99, 1),
                p50_budget_ms=round(p50_budget, 1),
                p99_budget_ms=round(p99_budget, 1),
                adversary=adv_summary,
                duration_s=round(time.monotonic() - t0, 1),
            )
        except H.Breach as b:
            self.log(f"grid: tile {tile.tile_id} FAILED [{b.kind}]: {b.msg}")
            return self._verdict(
                plan, False, b.kind, b.msg,
                duration_s=round(time.monotonic() - t0, 1),
            )
        except Exception as e:  # noqa: BLE001 - tile-scoped infra failure
            self.log(f"grid: tile {tile.tile_id} infra failure: {e!r}")
            return self._verdict(
                plan, False, "infra", repr(e),
                duration_s=round(time.monotonic() - t0, 1),
            )
        finally:
            if flood is not None:
                flood.stop()
            if churner is not None:
                churner.stop()
            if armed:
                try:
                    net.set_adversary(adv_idx, False)
                except Exception:
                    pass
            try:
                net.set_scenario(None)
            except Exception:
                pass
