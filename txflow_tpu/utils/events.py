"""EventBus: in-process pubsub (reference: tendermint libs/pubsub EventBus).

The fast path publishes per-tx commit events (txflowstate/execution.go:
190-195) and the block path publishes NewBlock/NewRound/validator-set
events (state/execution.go:456-481) to RPC websocket subscribers and the
tx indexer. Here: typed event names, thread-safe subscribe with per-
subscriber queues (non-blocking publish drops to slow subscribers beyond
capacity, like pubsub's buffered channels).
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Callable

# event types (reference types/events.go)
EventTx = "Tx"
EventNewBlock = "NewBlock"
EventNewBlockHeader = "NewBlockHeader"
EventNewRound = "NewRound"
EventNewRoundStep = "NewRoundStep"
EventCompleteProposal = "CompleteProposal"
EventVote = "Vote"
EventValidatorSetUpdates = "ValidatorSetUpdates"
EventEvidence = "Evidence"  # equivocation captured (types/evidence.py)


@dataclass
class Event:
    type: str
    data: object = None


class Subscription:
    def __init__(self, capacity: int = 1000):
        self._q: queue.Queue[Event] = queue.Queue(maxsize=capacity)

    def deliver(self, ev: Event) -> bool:
        try:
            self._q.put_nowait(ev)
            return True
        except queue.Full:
            return False

    def get(self, timeout: float | None = None) -> Event | None:
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def drain(self) -> list[Event]:
        out = []
        while True:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                return out


class EventBus:
    def __init__(self):
        self._mtx = threading.Lock()
        self._subs: dict[str, list[Subscription]] = {}
        self._callbacks: dict[str, list[Callable[[Event], None]]] = {}

    def subscribe(self, event_type: str, capacity: int = 1000) -> Subscription:
        sub = Subscription(capacity)
        with self._mtx:
            self._subs.setdefault(event_type, []).append(sub)
        return sub

    def subscribe_callback(self, event_type: str, fn: Callable[[Event], None]) -> None:
        with self._mtx:
            self._callbacks.setdefault(event_type, []).append(fn)

    def unsubscribe(self, event_type: str, sub: Subscription) -> None:
        with self._mtx:
            subs = self._subs.get(event_type, [])
            if sub in subs:
                subs.remove(sub)

    def publish(self, event_type: str, data: object = None) -> None:
        ev = Event(event_type, data)
        with self._mtx:
            subs = list(self._subs.get(event_type, []))
            cbs = list(self._callbacks.get(event_type, []))
        for s in subs:
            s.deliver(ev)
        for cb in cbs:
            cb(ev)


@dataclass
class EventDataTx:
    """Per-tx commit event payload (reference types.EventDataTx)."""

    height: int
    tx: bytes
    tx_hash: str
    result_code: int = 0
    result_data: bytes = b""
    result_log: str = ""
    tags: list = field(default_factory=list)  # (key, value) byte pairs


@dataclass
class EventDataNewBlock:
    block: object = None


@dataclass
class EventDataValidatorSetUpdates:
    updates: list = field(default_factory=list)
