"""Host-side utilities: WAL, caches, events, config, metrics, logging."""
