"""Failpoints for crash-consistency testing (reference: libs/fail).

The reference compiles ``fail.Fail()`` into the commit paths
(txflowstate/execution.go:87,95, state/execution.go, consensus/state.go)
and triggers them via env var. Here: named points armed programmatically
(tests) or via TXFLOW_FAIL=<name>[:<count>] in the environment; firing
raises ``FailpointError`` after the arm count reaches zero.
"""

from __future__ import annotations

import os
import threading


class FailpointError(RuntimeError):
    pass


_mtx = threading.Lock()
_armed: dict[str, int] = {}
_fired: set[str] = set()


def _load_env() -> None:
    spec = os.environ.get("TXFLOW_FAIL", "")
    if not spec:
        return
    name, _, cnt = spec.partition(":")
    _armed.setdefault(name, int(cnt) if cnt else 0)


_load_env()


def arm(name: str, after: int = 0) -> None:
    """Arm a failpoint to fire on the (after+1)-th hit."""
    with _mtx:
        _armed[name] = after


def disarm(name: str | None = None) -> None:
    with _mtx:
        if name is None:
            _armed.clear()
            _fired.clear()
        else:
            _armed.pop(name, None)
            _fired.discard(name)


def fail(name: str) -> None:
    with _mtx:
        if name not in _armed:
            return
        if _armed[name] > 0:
            _armed[name] -= 1
            return
        # STICKY once fired: a real crash kills the process, so retries of
        # the same code path (serialized consensus loops catch exceptions
        # and continue) must keep failing until the test disarms — else
        # the "crashed" operation quietly completes on the next pass and
        # the crash window closes itself
        _fired.add(name)
    raise FailpointError(f"failpoint {name} fired")


def fired(name: str) -> bool:
    with _mtx:
        return name in _fired
