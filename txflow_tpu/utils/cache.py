"""Fixed-size LRU set (reference txvotepool ``mapTxCache``, :388-451).

push() returns False when the key is already cached — refreshing its
recency, exactly like the reference's Push (list.MoveToBack before the
false return) — and at capacity the least-recently-pushed entry is
evicted. Implemented on a plain insertion-ordered dict (delete +
re-insert = move-to-back): measurably cheaper per push than the previous
OrderedDict, and the hot pools pay this once per ingest (a top-10
host-path item at bench rates, r5 instrumented profile).
"""

from __future__ import annotations

import sys
import threading
from collections import OrderedDict


def _gil_enabled() -> bool:
    """True unless this is a free-threaded (PEP 703) build running with
    the GIL actually disabled. sys._is_gil_enabled only exists on
    free-threaded builds (3.13+); its absence means a GIL build."""
    probe = getattr(sys, "_is_gil_enabled", None)
    if probe is None:
        return True
    try:
        return bool(probe())
    except Exception:
        return True


# the GIL is a property of the build + interpreter launch options, not of
# any one call site: weigh it once
_GIL_ENABLED = _gil_enabled()


class LRUCache:
    def __init__(self, size: int):
        if size <= 0:
            raise ValueError("cache size must be positive")
        self.size = size
        self._mtx = threading.Lock()
        self._map: dict[bytes, None] = {}

    def push(self, key: bytes) -> bool:
        """Add key; False if already present (recency refreshed)."""
        with self._mtx:
            m = self._map
            if key in m:
                del m[key]  # re-insert puts it at the back (MoveToBack)
                m[key] = None
                return False
            if len(m) >= self.size:
                del m[next(iter(m))]
            m[key] = None
            return True

    def remove(self, key: bytes) -> None:
        with self._mtx:
            self._map.pop(key, None)

    def reset(self) -> None:
        with self._mtx:
            self._map.clear()

    def __contains__(self, key: bytes) -> bool:
        with self._mtx:
            return key in self._map

    def __len__(self) -> int:
        with self._mtx:
            return len(self._map)


class UnlockedLRUCache:
    """LRUCache without the internal lock, for owners that already
    serialize every MUTATION under their own mutex (both pools mutate
    their dedup caches exclusively under the pool lock; the engine's
    committed-set under the engine lock). Lock-free READS (``in``) from
    other threads stay safe: membership tests on a plain dict never
    observe torn state under the GIL, and the reactor's in_cache peek
    tolerates stale answers by falling back to the authoritative
    check_tx path.

    The safety argument is CPython-specific and GIL-specific: ``in``,
    ``del``, and item assignment on a dict are single bytecode-dispatched
    C operations, and the GIL guarantees a reader never observes a dict
    mid-resize or mid-insert. It does NOT hold on free-threaded (PEP 703)
    builds, where an unsynchronized reader racing push()'s delete +
    re-insert pair is genuine undefined behavior. On such builds (checked
    once at construction via sys._is_gil_enabled) the constructor
    transparently returns a locked ``LRUCache`` instead — every call site
    keeps its semantics and pays the lock only where the GIL no longer
    provides it."""

    def __new__(cls, size: int):
        if not _GIL_ENABLED:
            return LRUCache(size)
        return object.__new__(cls)

    def __init__(self, size: int):
        if size <= 0:
            raise ValueError("cache size must be positive")
        self.size = size
        self._map: dict[bytes, None] = {}

    def push(self, key: bytes) -> bool:
        m = self._map
        if key in m:
            del m[key]  # re-insert = MoveToBack (reference mapTxCache)
            m[key] = None
            return False
        if len(m) >= self.size:
            del m[next(iter(m))]
        m[key] = None
        return True

    def remove(self, key: bytes) -> None:
        self._map.pop(key, None)

    def reset(self) -> None:
        self._map.clear()

    def __contains__(self, key: bytes) -> bool:
        return key in self._map

    def __len__(self) -> int:
        return len(self._map)


def make_lru(size: int):
    """The one construction seam for dedup caches — and the ONE place
    that weighs the CPython/GIL safety argument (checked once at import,
    module constant below). txlint's ``unlocked-lru`` rule forbids
    constructing UnlockedLRUCache directly anywhere else.

    size <= 0 means "cache disabled" (NopCache), matching the pools'
    config.cache_size contract. On GIL builds the owner-serialized
    lock-free cache is returned; on free-threaded builds every caller
    transparently gets the locked LRUCache instead."""
    if size <= 0:
        return NopCache()
    if _GIL_ENABLED:
        return UnlockedLRUCache(size)
    return LRUCache(size)


class NopCache:
    """Cache disabled (config.cache_size = 0): everything is new."""

    def push(self, key: bytes) -> bool:
        return True

    def remove(self, key: bytes) -> None:
        pass

    def reset(self) -> None:
        pass

    def __contains__(self, key: bytes) -> bool:
        return False

    def __len__(self) -> int:
        return 0


class LRUMap:
    """Fixed-size LRU key->value map (wire-segment dedup in the reactors)."""

    def __init__(self, size: int):
        if size <= 0:
            raise ValueError("cache size must be positive")
        self.size = size
        self._mtx = threading.Lock()
        self._map: OrderedDict[bytes, object] = OrderedDict()

    def get(self, key: bytes):
        with self._mtx:
            v = self._map.get(key)
            if v is not None:
                self._map.move_to_end(key)
            return v

    def peek(self, key: bytes):
        """Lock-free read with NO recency update. OrderedDict.get is the
        C-level dict lookup, atomic under the GIL, and concurrent put/
        evict mutations cannot corrupt a reader — worst case a racing
        peek misses a value another thread is inserting, which every
        caller must treat as a cache miss anyway. The hot gossip receive
        path peeks (12 reader threads at bench rates); recency then only
        advances on put, making eviction FIFO-ish for peek-heavy maps —
        fine for dedup caches."""
        return self._map.get(key)

    def put(self, key: bytes, value) -> None:
        with self._mtx:
            if key in self._map:
                self._map.move_to_end(key)
            elif len(self._map) >= self.size:
                self._map.popitem(last=False)
            self._map[key] = value
