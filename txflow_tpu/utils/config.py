"""Configuration (reference: tendermint TOML ``cfg.Config`` passed to NewNode).

Defaults mirror tendermint v0.31.2's: mempool size/caps
(txvotepool/txvotepool.go:198-208 reads config.Mempool), consensus timeouts
(consensus/state.go:809-816), instrumentation toggles. Plain dataclasses —
load/save as JSON or TOML-ish dicts; no CLI layer exists in the reference
(it is a library), and none is required here.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, asdict


@dataclass
class MempoolConfig:
    size: int = 5000
    max_txs_bytes: int = 1024 * 1024 * 1024  # 1GB
    cache_size: int = 10000
    max_msg_bytes: int = 1024 * 1024  # max gossip msg (consensus/reactor.go:28)
    broadcast: bool = True
    wal_dir: str = ""  # empty = WAL disabled

    @property
    def wal_enabled(self) -> bool:
        return self.wal_dir != ""


@dataclass
class ConsensusConfig:
    # all in seconds (reference uses ms in TOML)
    timeout_propose: float = 3.0
    timeout_propose_delta: float = 0.5
    timeout_prevote: float = 1.0
    timeout_prevote_delta: float = 0.5
    timeout_precommit: float = 1.0
    timeout_precommit_delta: float = 0.5
    timeout_commit: float = 1.0
    skip_timeout_commit: bool = False
    create_empty_blocks: bool = True
    create_empty_blocks_interval: float = 0.0
    peer_gossip_sleep: float = 0.1
    peer_query_maj23_sleep: float = 2.0
    wal_dir: str = ""

    def propose_timeout(self, round_: int) -> float:
        return self.timeout_propose + self.timeout_propose_delta * round_

    def prevote_timeout(self, round_: int) -> float:
        return self.timeout_prevote + self.timeout_prevote_delta * round_

    def precommit_timeout(self, round_: int) -> float:
        return self.timeout_precommit + self.timeout_precommit_delta * round_


@dataclass
class P2PConfig:
    laddr: str = "tcp://0.0.0.0:26656"
    persistent_peers: str = ""
    max_num_inbound_peers: int = 40
    max_num_outbound_peers: int = 10
    send_rate: int = 5 * 1024 * 1024
    recv_rate: int = 5 * 1024 * 1024
    flush_throttle: float = 0.1
    pex: bool = True


@dataclass
class RPCConfig:
    laddr: str = "tcp://127.0.0.1:26657"
    max_open_connections: int = 900


@dataclass
class InstrumentationConfig:
    prometheus: bool = False
    prometheus_listen_addr: str = ":26660"
    namespace: str = "txflow"


@dataclass
class EngineConfig:
    """Fast-path aggregation engine (no reference analog; device batching).

    The reference processes votes one at a time (txflow/service.go:123-166);
    these knobs govern the batched device pipeline that replaces it.
    """

    max_batch: int = 16384  # votes per device step
    max_slots: int = 4096  # concurrent in-flight txs per step
    use_device: bool = True  # False = scalar golden verifier (debug)
    poll_interval: float = 0.002  # seconds to wait when the pool is empty
    # batch forming: hold a step for up to batch_wait while fewer than
    # min_batch votes are pending, so streaming arrivals coalesce into
    # device-sized batches instead of overhead-dominated tiny kernel calls
    min_batch: int = 256
    batch_wait: float = 0.004
    # light-load latency mode: while coalescing, if no new vote arrives
    # for this long and work is already pending, process what we have
    # instead of sitting out the full batch_wait — at 10% offered load a
    # tx's votes arrive as one burst and then stall, so waiting for
    # min_batch only adds latency (r4 verdict item 9: the reference's
    # headline is realtime per-tx commit, README.md:10). 0 disables.
    idle_flush: float = 0.002
    # backoff when a whole step was deferred to another engine's
    # in-flight verifies (shared VerifyCache claims): the owner's call
    # completes on the device-step / scalar-sweep timescale, so re-trying
    # sooner only burns the step preamble against its in-flight work
    defer_backoff: float = 0.005
    # verify pipeline: how many device verify calls the engine keeps in
    # flight via the verifier's submit/collect split (verifier.VerifyTicket).
    # At 2, batch N+1's host prep (drain + sign bytes + prepare_compact)
    # and batch N-1's commit routing overlap batch N's kernel execution;
    # tickets are collected in submission order, so commit certificates
    # stay bit-identical to the serial path. <=1 = serial reference loop.
    pipeline_depth: int = 2
    # adaptive pipeline depth: let an AdaptiveDepthController grow/shrink
    # the pipelined loop's in-flight ticket budget between
    # [pipeline_depth_min, pipeline_depth_max] from the live overlap
    # ratio (engine.adaptive; closes the ROADMAP static-depth item).
    # pipeline_depth above stays the starting point. Off by default:
    # deterministic depth is what the banked bench baselines were tuned
    # at, and the controller needs windows of steps to say anything.
    adaptive_depth: bool = False
    pipeline_depth_min: int = 2
    pipeline_depth_max: int = 8
    # shape-stable batch coalescing (engine.txflow._BatchCoalescer): when
    # the verifier exposes canonical buckets, dispatch only full-bucket
    # batches (zero padding waste, always-prewarmed shapes) and hold
    # partial ones until coalesce_linger elapses from the first held
    # vote, then flush whatever coalesced (padded to its bucket — still
    # a canonical shape). Scalar verifiers have no buckets and keep the
    # min_batch/batch_wait forming logic unchanged.
    coalesce: bool = True
    coalesce_linger: float = 0.004
    # prewarm every kernel shape the verify pipeline can produce at
    # start() (engine.shapes.ShapeWarmRegistry) so no cold compile lands
    # inside the pipeline. Off by default: tests build engines constantly
    # and the full warmup compiles the whole bucket ladder; bench/nodes
    # that own a device verifier opt in.
    prewarm_shapes: bool = False
    # background warmup (engine.shapes.BackgroundWarmer): serve from
    # start() with ZERO blocking compile — a side thread walks the shape
    # enumeration compiling cold shapes while batches whose shape is
    # still cold route through the scalar/CPU fallback, then promote to
    # the device the moment their shape lands. The streaming alternative
    # to prewarm_shapes' stop-the-world warmup.
    background_warmup: bool = False
    # persistent XLA compilation cache directory (JAX_COMPILATION_CACHE_DIR):
    # every compiled shape is banked on disk, so reruns — and background
    # warmup walks — load instead of compile. Empty = leave the process
    # environment alone.
    compilation_cache_dir: str = ""
    # overlap commit side-effects (TxStore persist, ABCI execute, pool
    # purge) with the next device verify call via a per-engine committer
    # thread (SURVEY §7 hard-part 5); False = reference-faithful inline
    # commits inside the step
    pipeline_commits: bool = True
    # group commit: the committer fences the ABCI app Commit once per up-to-
    # this-many fast-path txs instead of per tx (reference: strictly per tx,
    # txflowstate/execution.go:112-155). Each tx still gets its own
    # DeliverTx, TxStore certificate, mempool removal, and commit event —
    # only the app-Commit fence is amortized. Requires the app's hash to be
    # a function of applied txs, not of Commit call cadence (true of the
    # kvstore/counter apps and of the handshake replay path, which replays
    # per tx). 1 = reference-faithful.
    commit_interval: int = 1
    # mesh-sharded verify (parallel/mesh.py): shard each padded device
    # batch data-parallel across this many devices of the default
    # backend (one psum tally per step). 0 or 1 = single-device verify.
    # Bucket widths are rounded up to mesh divisibility by the verifier
    # and the coalescer, so the warm bucket ladder is unchanged in
    # count — only in width — and epoch restages stay zero-recompile.
    mesh_devices: int = 0
    # sharded host-prep pool (engine/hostprep.py): worker threads that
    # parallelize sign-bytes assembly and nibble/window prep. The native
    # prep (_prep.so) releases the GIL inside ctypes, so sharding rows
    # across workers is real parallelism even on GIL builds. 0 = serial
    # prep on the engine thread (reference behavior).
    host_prep_workers: int = 0
    # host-prep backend (engine.hostprep.make_host_pool): "thread" keeps
    # the caller-steals thread pool; "process" runs worker PROCESSES that
    # assemble sign-bytes/compact arrays into shared-memory segments —
    # past the GIL entirely, for the pure-Python prep slices threads
    # can't parallelize. Degrades to "thread" automatically when workers
    # can't spawn; assembled batches are byte-identical either way.
    host_prep_backend: str = "thread"
    # double-buffered device readback (parallel.staging.StagingRing):
    # depth of the readback ring on the device verifier. At 2, batch N's
    # device_put + dispatch overlaps batch N-1's packed readback (the
    # ring thread pulls results eagerly); <=1 restores the synchronous
    # readback at collect. Certificates are byte-identical either way —
    # the ring only moves WHERE np.asarray runs.
    staging_ring: int = 2
    # wide coalescer rungs (engine.txflow._BatchCoalescer): let the bulk
    # lane target bucket-ladder rungs ABOVE max_batch (the verifier's
    # ladder already compiles them) so per-call overhead amortizes over
    # bigger steps at sustained load. Gated by the AdaptiveLingerController
    # when adaptive_linger is on — wide rungs disarm the moment the SLO
    # bank runs hot, so latency never pays for the amortization. Off by
    # default: the banked bench baselines were tuned at the classic cap.
    wide_buckets: bool = False
    # deadline-aware verify lanes (engine.txflow): split the drain into
    # a PRIORITY lane — the pool's priority ingest log (admission fee
    # lanes), dispatched in small short-linger batches AHEAD of the bulk
    # backlog — and a BULK lane keeping today's throughput linger. With
    # no admission wiring the priority log stays empty and the lane
    # costs one decide(0) per fill pass.
    lane_split: bool = True
    # priority-lane linger: how long a partial priority batch may
    # coalesce before flushing (the deadline the lane exists to honor);
    # the bulk lane keeps coalesce_linger
    priority_linger: float = 0.001
    # largest priority-lane dispatch: bucket-ladder rungs at or under
    # this (rounded up to the mesh shard multiple, PR 10) are the lane's
    # full-batch targets; with no ladder (scalar verifier) the lane
    # dispatches at this cap
    priority_bucket_cap: int = 512
    # adaptive per-lane linger (engine.adaptive.AdaptiveLingerController):
    # steer both lane lingers from the live trace digest against
    # slo_budget_ms. Off by default — it needs an active tracer and
    # windows of traffic to say anything; bench.py --latency-slo opts in.
    adaptive_linger: bool = False
    slo_budget_ms: float = 50.0
    # speculative quorum commit (engine.txflow._route_result): at collect
    # time, route votes whose slot's device tally readback already shows
    # 2n/3 stake FIRST, so their commits leave for the committer before
    # the rest of the batch routes. The host TxVoteSet still decides
    # every quorum (the device bit is only a routing-ORDER hint, it may
    # be stale in either direction under pipelining) — certificates stay
    # byte-identical to the scalar golden path. Off by default: the
    # early exit reorders commits ACROSS txs within a batch, and the
    # serial-vs-pipelined golden tests pin strict commit order; the
    # latency bench and latency-sensitive deployments opt in.
    speculative_commit: bool = False


@dataclass
class TraceConfig:
    """Per-transaction tracing (trace/tracer.py; default-ON).

    ``sample_rate`` is 1-in-N txs by hash (deterministic across nodes
    and replays; 1 = trace every tx). ``enabled=False`` swaps in the
    zero-cost NullTracer — no ring, no histograms, no sampling checks
    beyond one attribute read. ``ring_capacity`` bounds the per-node
    span ring; old spans are overwritten (counted as dropped)."""

    enabled: bool = True
    sample_rate: int = 64
    seed: int = 0
    ring_capacity: int = 8192


@dataclass
class Config:
    chain_id: str = "txflow-chain"
    root_dir: str = ""
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    p2p: P2PConfig = field(default_factory=P2PConfig)
    rpc: RPCConfig = field(default_factory=RPCConfig)
    instrumentation: InstrumentationConfig = field(default_factory=InstrumentationConfig)
    engine: EngineConfig = field(default_factory=EngineConfig)
    trace: TraceConfig = field(default_factory=TraceConfig)

    def to_dict(self) -> dict:
        return asdict(self)

    def db_dir(self) -> str:
        return os.path.join(self.root_dir, "data") if self.root_dir else ""


def test_config(root_dir: str = "") -> Config:
    """Fast-timeout config for tests (reference cfg.ResetTestRoot)."""
    c = Config(root_dir=root_dir)
    c.consensus.timeout_propose = 0.4
    c.consensus.timeout_propose_delta = 0.2
    c.consensus.timeout_prevote = 0.2
    c.consensus.timeout_prevote_delta = 0.2
    c.consensus.timeout_precommit = 0.2
    c.consensus.timeout_precommit_delta = 0.2
    c.consensus.timeout_commit = 0.1
    c.consensus.skip_timeout_commit = True
    c.consensus.peer_gossip_sleep = 0.005
    c.mempool.cache_size = 1000
    return c
