"""Wall-clock seam for consensus-critical modules.

txlint's ``nondeterminism`` rule forbids raw ``time.time()`` /
``time.time_ns()`` (and unseeded rng) inside certificate- and
consensus-critical modules (types/vote_set, engine/txflow, consensus/*):
a timestamp read mid-decision is a per-node value that lands in signed
artifacts (proposal timestamps) and replay logs, and scattering direct
clock reads makes "pin the clock" impossible in tests and replays.

This module is the one sanctioned source: consensus code imports
``now_ns``/``now`` from here, tests monkeypatch here, and the lint pass
whitelists calls routed through these names. Keep it free of any other
dependency — it is imported by the lowest layers.
"""

from __future__ import annotations

import time


def now_ns() -> int:
    """Wall-clock nanoseconds (proposal timestamps, commit times)."""
    return time.time_ns()


def now() -> float:
    """Wall-clock seconds."""
    return time.time()
