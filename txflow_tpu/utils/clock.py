"""Wall-clock seam for consensus-critical modules.

txlint's ``nondeterminism`` rule forbids raw ``time.time()`` /
``time.time_ns()`` (and unseeded rng) inside certificate- and
consensus-critical modules (types/vote_set, engine/txflow, consensus/*):
a timestamp read mid-decision is a per-node value that lands in signed
artifacts (proposal timestamps) and replay logs, and scattering direct
clock reads makes "pin the clock" impossible in tests and replays.

This module is the one sanctioned source: consensus code imports
``now_ns``/``now`` from here, tests monkeypatch here, and the lint pass
whitelists calls routed through these names. Keep it free of any other
dependency — it is imported by the lowest layers.

The monotonic seams below exist for the tracing subsystem (trace/):
every timestamp that can land in a trace span must come from here, so a
replay can pin ONE module and get deterministic spans, and so txlint's
``trace-clock`` pass can forbid raw ``time.monotonic``/``perf_counter``
in the traced hot-path modules without whitelisting call sites one by
one.
"""

from __future__ import annotations

import time


def now_ns() -> int:
    """Wall-clock nanoseconds (proposal timestamps, commit times)."""
    return time.time_ns()


def now() -> float:
    """Wall-clock seconds."""
    return time.time()


def monotonic() -> float:
    """Monotonic seconds (deadlines, linger windows, trace spans)."""
    return time.monotonic()


def monotonic_ns() -> int:
    """Monotonic nanoseconds."""
    return time.monotonic_ns()


def perf_counter() -> float:
    """High-resolution monotonic seconds (stage timing, trace spans)."""
    return time.perf_counter()


def perf_counter_ns() -> int:
    """High-resolution monotonic nanoseconds."""
    return time.perf_counter_ns()
