"""Central registry of PRNG/hash domain-separation tags.

Every deterministic stream in this repo is seeded from
``sha256(tag | ... public inputs ...)``. The tags MUST be pairwise
distinct: two subsystems sharing a tag silently share (or perturb) a
stream — the committee-election kind of bug that only shows up as a
quorum fork months later. This module is the ONE place tags are spelled;
``_register`` fails fast at import on a duplicate name or duplicate tag
bytes, and the ``seed-domain`` txlint pass fails the tree on any inline
raw domain literal outside this file.

Adding a domain: register the tag here, import the constant at the use
site, and keep the byte layout of the derived seed at the use site (the
registry owns WHICH bytes prefix the stream, not how the suffix is
packed — endianness and field packing are caller contracts pinned by
tests/test_domains.py).
"""

from __future__ import annotations

_REGISTRY: dict[str, bytes] = {}


def _register(name: str, tag: bytes) -> bytes:
    if name in _REGISTRY:
        raise ValueError(f"duplicate domain name {name!r}")
    for other_name, other_tag in _REGISTRY.items():
        if other_tag == tag:
            raise ValueError(
                f"domain tag {tag!r} already registered as {other_name!r}"
            )
    _REGISTRY[name] = tag
    return tag


def registered_domains() -> dict[str, bytes]:
    """Snapshot of the registry (name -> tag), for tests and tooling."""
    return dict(_REGISTRY)


# -- the domains ------------------------------------------------------------

# Per-epoch committee sampling (committee/sampler.py): versioned so a
# future sampler change cannot silently elect a different committee for
# the same (chain_id, epoch).
COMMITTEE_V1 = _register("committee-sampler", b"txflow/committee/v1")

# Scenario grid axis streams (scenario/spec.py): one disjoint stream per
# (grid seed, axis, level) so no two axes — and no two levels of one
# axis — ever share randomness.
SCENARIO_AXIS = _register("scenario-axis", b"scenario")

# Chaos fault plans (faults/plan.py): one stream per directed link,
# reproducible from the spec seed alone.
FAULTPLAN_LINK = _register("faultplan-link", b"faultplan")

# Network weather (netem/shaper.py): per-directed-link jitter/loss
# streams, domain-separated from the fault planner so a shaper never
# consumes or perturbs chaos streams.
NETEM_LINK = _register("netem-link", b"netem")
