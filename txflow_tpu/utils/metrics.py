"""Prometheus-style metrics (reference: go-kit metrics per subsystem).

Mirrors the surface of consensus/metrics.go, txflowstate/metrics.go and the
mempool metrics: Gauge / Counter / Histogram with label support, a process
registry, and a text exposition dump compatible with the Prometheus format
served at the instrumentation endpoint (node/node.go:988-1007).
"""

from __future__ import annotations

import threading
from collections import defaultdict


class _Metric:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._mtx = threading.Lock()

    def _header(self, kind: str) -> str:
        # HELP before TYPE, help text with newlines/backslashes escaped
        # per the exposition-format spec — scrapers (and our own
        # parse_exposition) reject a bare newline inside a comment
        lines = []
        if self.help:
            esc = self.help.replace("\\", "\\\\").replace("\n", "\\n")
            lines.append(f"# HELP {self.name} {esc}")
        lines.append(f"# TYPE {self.name} {kind}")
        return "\n".join(lines) + "\n"


class Gauge(_Metric):
    def __init__(self, name: str, help_: str = ""):
        super().__init__(name, help_)
        self._v = 0.0

    def set(self, v: float) -> None:
        with self._mtx:
            self._v = v

    def add(self, v: float) -> None:
        with self._mtx:
            self._v += v

    def value(self) -> float:
        with self._mtx:
            return self._v

    def expose(self) -> str:
        return self._header("gauge") + f"{self.name} {self.value()}\n"


class Counter(_Metric):
    def __init__(self, name: str, help_: str = ""):
        super().__init__(name, help_)
        self._v = 0.0

    def add(self, v: float = 1.0) -> None:
        with self._mtx:
            self._v += v

    def value(self) -> float:
        with self._mtx:
            return self._v

    def expose(self) -> str:
        return self._header("counter") + f"{self.name} {self.value()}\n"


class Histogram(_Metric):
    """Fixed-bucket histogram (sum/count + cumulative buckets)."""

    DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0)

    def __init__(self, name: str, help_: str = "", buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_)
        self.buckets = tuple(buckets)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        with self._mtx:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    def expose(self) -> str:
        with self._mtx:
            lines = [self._header("histogram").rstrip("\n")]
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += self._counts[i]
                lines.append(f'{self.name}_bucket{{le="{b}"}} {cum}')
            cum += self._counts[-1]
            lines.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{self.name}_sum {self._sum}")
            lines.append(f"{self.name}_count {self._count}")
            return "\n".join(lines) + "\n"

    def quantile(self, q: float) -> float | None:
        """Estimated q-quantile (0 < q < 1) by linear interpolation
        inside the owning bucket — the standard histogram_quantile
        estimate, so a /health digest and a PromQL dashboard agree.
        None when empty; observations past the last finite bucket clamp
        to that bucket's upper bound (+Inf has no midpoint to guess)."""
        with self._mtx:
            if self._count == 0:
                return None
            rank = q * self._count
            cum = 0
            lo = 0.0
            for i, b in enumerate(self.buckets):
                prev = cum
                cum += self._counts[i]
                if cum >= rank:
                    frac = (rank - prev) / max(self._counts[i], 1)
                    return lo + (b - lo) * frac
                lo = b
            return float(self.buckets[-1]) if self.buckets else None


class Registry:
    def __init__(self, namespace: str = "txflow"):
        self.namespace = namespace
        self._mtx = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _reg(self, cls, subsystem: str, name: str, help_: str, **kw):
        full = f"{self.namespace}_{subsystem}_{name}"
        with self._mtx:
            m = self._metrics.get(full)
            if m is None:
                m = cls(full, help_, **kw)
                self._metrics[full] = m
            return m

    def gauge(self, subsystem: str, name: str, help_: str = "") -> Gauge:
        return self._reg(Gauge, subsystem, name, help_)

    def counter(self, subsystem: str, name: str, help_: str = "") -> Counter:
        return self._reg(Counter, subsystem, name, help_)

    def histogram(self, subsystem: str, name: str, help_: str = "", buckets=Histogram.DEFAULT_BUCKETS) -> Histogram:
        return self._reg(Histogram, subsystem, name, help_, buckets=buckets)

    def expose(self) -> str:
        with self._mtx:
            return "".join(m.expose() for m in self._metrics.values())


GLOBAL = Registry()


def parse_exposition(text: str) -> dict[str, dict]:
    """Parse a Prometheus text exposition back into per-family dicts.

    Scrape-compliance oracle for the tests (and the soak's metric
    assertions): every family maps to ``{"type": ..., "help": ...,
    "samples": {sample_name_or_(name, labels): value}}``. Histogram
    families additionally get ``"buckets"``: an ordered
    ``[(le_string, cumulative_count), ...]`` ending at ``+Inf``.
    Raises ValueError on lines a Prometheus scraper would reject."""
    families: dict[str, dict] = {}

    def family_of(sample_name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            base = sample_name.removesuffix(suffix)
            if base != sample_name and base in families:
                return base
        return sample_name

    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                raise ValueError(f"malformed comment line: {raw!r}")
            fam = families.setdefault(
                parts[2], {"type": None, "help": "", "samples": {}, "buckets": []}
            )
            if parts[1] == "TYPE":
                fam["type"] = parts[3] if len(parts) > 3 else "untyped"
            else:
                fam["help"] = parts[3] if len(parts) > 3 else ""
            continue
        # sample line: name[{labels}] value
        name_part, _, value_part = line.rpartition(" ")
        if not name_part:
            raise ValueError(f"malformed sample line: {raw!r}")
        value = float(value_part)  # ValueError on garbage
        labels = ""
        name = name_part
        if "{" in name_part:
            name, _, rest = name_part.partition("{")
            labels, close, trailer = rest.partition("}")
            if not close or trailer.strip():
                raise ValueError(f"malformed labels: {raw!r}")
        fam = families.setdefault(
            family_of(name), {"type": None, "help": "", "samples": {}, "buckets": []}
        )
        key = name if not labels else (name, labels)
        fam["samples"][key] = value
        if name.endswith("_bucket"):
            le = None
            for pair in labels.split(","):
                k, _, v = pair.partition("=")
                if k.strip() == "le":
                    le = v.strip().strip('"')
            if le is None:
                raise ValueError(f"histogram bucket without le label: {raw!r}")
            fam["buckets"].append((le, value))
    # structural checks a scraper enforces on histograms
    for base, fam in families.items():
        if fam["type"] != "histogram":
            continue
        buckets = fam["buckets"]
        if not buckets or buckets[-1][0] != "+Inf":
            raise ValueError(f"{base}: histogram missing +Inf bucket")
        counts = [c for _, c in buckets]
        if counts != sorted(counts):
            raise ValueError(f"{base}: bucket counts not cumulative")
        if fam["samples"].get(base + "_count") != buckets[-1][1]:
            raise ValueError(f"{base}: _count != +Inf cumulative count")
        if base + "_sum" not in fam["samples"]:
            raise ValueError(f"{base}: missing _sum")
    return families


class HealthMetrics:
    """Self-healing / degraded-mode metrics (health/ subsystem).

    Counters are monotonic event totals (watchdog firings, peer churn);
    gauges mirror current state (liveness verdict, verifier demotion
    state, in-flight stall depth) so the Prometheus exposition and the
    RPC ``/health`` endpoint read the same registry."""

    def __init__(self, registry: "Registry | None" = None):
        r = registry or GLOBAL
        self.healthy = r.gauge("health", "healthy", "1 = all progress signals live")
        self.watchdog_firings = r.counter("health", "watchdog_firings", "quorum-stall watchdog firings")
        self.watchdog_escalations = r.counter("health", "watchdog_escalations", "stall re-offers escalated to all peers")
        self.reoffered_votes = r.counter("health", "reoffered_votes", "votes re-offered by the watchdog")
        self.reoffered_txs = r.counter("health", "reoffered_txs", "txs re-offered by the watchdog")
        self.inflight_txs = r.gauge("health", "inflight_txs", "txs below quorum right now")
        self.oldest_stall_age = r.gauge("health", "oldest_stall_seconds", "age of the oldest sub-quorum tx")
        self.peer_evictions = r.counter("health", "peer_evictions", "peers evicted by score")
        self.peer_reconnects = r.counter("health", "peer_reconnects", "score-driven reconnects that succeeded")
        self.reconnect_failures = r.counter("health", "reconnect_failures", "reconnect attempts that failed")
        self.n_peers = r.gauge("health", "n_peers", "connected peers")
        self.verifier_demotions = r.gauge("health", "verifier_demotions", "device->fallback demotions")
        self.verifier_repromotions = r.gauge("health", "verifier_repromotions", "fallback->device re-promotions")
        self.verifier_device_failures = r.gauge("health", "verifier_device_failures", "device verify errors")
        self.verifier_fallback_calls = r.gauge("health", "verifier_fallback_calls", "batches served by the CPU fallback")
        self.verifier_device_healthy = r.gauge("health", "verifier_device_healthy", "1 = device lane serving")
        self.pipeline_overlap = r.gauge("health", "pipeline_overlap_ratio", "engine verify-pipeline overlap (device-busy / active)")
        self.warmup_cold_votes = r.gauge("health", "warmup_cold_fallback_votes", "votes served by the CPU fallback awaiting shape promotion")
        self.pipeline_depth_now = r.gauge("health", "pipeline_depth", "engine's current (possibly adaptive) pipeline depth")


class NetMetrics:
    """Network-weather metrics (p2p/adaptive.py + netem/ subsystems).

    All values mirror the switch's ``net_snapshot()`` — counters live as
    plain ints on estimators/shapers (bumped lock-free on hot paths) and
    are republished as absolute gauges on each health tick, so /metrics,
    /health's "network" section, and bench stamps read one source."""

    def __init__(self, registry: "Registry | None" = None):
        r = registry or GLOBAL
        self.peers = r.gauge("net", "peers", "peers with link estimators")
        self.quarantined = r.gauge("net", "quarantined_peers", "peers currently quarantined for bad weather")
        self.quarantine_transitions = r.gauge("net", "quarantine_transitions", "quarantine enter/leave events (all peers)")
        self.rtt_ms_max = r.gauge("net", "peer_rtt_ms_max", "worst per-peer smoothed RTT (ms)")
        self.loss_max = r.gauge("net", "peer_loss_max", "worst per-peer ping-loss EWMA")
        self.pings_sent = r.gauge("net", "pings_sent", "link probes sent (all peers)")
        self.pongs = r.gauge("net", "pongs", "link probe replies received (all peers)")
        self.ping_timeouts = r.gauge("net", "ping_timeouts", "link probes expired unanswered (all peers)")
        self.sendq_dropped = r.gauge("net", "sendq_dropped", "oldest-bulk frames dropped by bounded send queues")
        self.shaped_frames = r.gauge("net", "shaped_frames", "frames through the link shaper")
        self.shaped_dropped = r.gauge("net", "shaped_dropped", "frames lost by shaper weather (random loss)")
        self.shaped_flap_dropped = r.gauge("net", "shaped_flap_dropped", "frames lost in shaper flap down-windows")
        self.shaped_queue_dropped = r.gauge("net", "shaped_queue_dropped", "frames tail-dropped by shaper pacing queues")
        self.shaped_duplicated = r.gauge("net", "shaped_duplicated", "frames duplicated by the shaper")
        self.shaped_corrupted = r.gauge("net", "shaped_corrupted", "frames with a shaper-flipped payload byte")

    def refresh_from(self, snap: dict) -> None:
        """Republish a Switch.net_snapshot() as absolute gauge values."""
        peers = snap.get("peers", {})
        self.peers.set(len(peers))
        self.quarantined.set(snap.get("quarantined", 0))
        self.sendq_dropped.set(snap.get("sendq_dropped", 0))
        rtts = [p["rtt_ms"] for p in peers.values() if p.get("rtt_ms") is not None]
        self.rtt_ms_max.set(max(rtts) if rtts else 0.0)
        losses = [p.get("loss", 0.0) for p in peers.values()]
        self.loss_max.set(max(losses) if losses else 0.0)
        for field, attr in (
            ("transitions", self.quarantine_transitions),
            ("pings_sent", self.pings_sent),
            ("pongs", self.pongs),
            ("ping_timeouts", self.ping_timeouts),
        ):
            attr.set(sum(p.get(field, 0) for p in peers.values()))
        shaper = snap.get("shaper")
        if shaper is not None:
            total = shaper.get("total", {})
            self.shaped_frames.set(total.get("frames", 0))
            self.shaped_dropped.set(total.get("dropped", 0))
            self.shaped_flap_dropped.set(total.get("flap_dropped", 0))
            self.shaped_queue_dropped.set(total.get("queue_dropped", 0))
            self.shaped_duplicated.set(total.get("duplicated", 0))
            self.shaped_corrupted.set(total.get("corrupted", 0))


class ScenarioMetrics:
    """Scenario-grid observability (scenario/ subsystem).

    A node driven by a grid tile publishes which tile and how far along
    the walk — so an operator watching /metrics mid-soak can correlate a
    latency spike with "tile 7 of 12, flood + flapping" without parsing
    runner logs. The tile's string identity (axis levels) lives in the
    /health "scenario" section; gauges carry only the numeric shape."""

    def __init__(self, registry: "Registry | None" = None):
        r = registry or GLOBAL
        self.active = r.gauge("scenario", "active", "1 while a scenario tile drives this node")
        self.tile_index = r.gauge("scenario", "tile_index", "zero-based index of the running tile (-1 when idle)")
        self.tiles_total = r.gauge("scenario", "tiles_total", "tile count of the running grid walk")
        self.tile_started_unix = r.gauge("scenario", "tile_started_unix", "wall-clock start of the running tile (unix seconds)")

    def refresh_from(self, info: dict) -> None:
        """Republish a registry scenario-section dict (possibly empty)."""
        self.active.set(1.0 if info.get("active") else 0.0)
        self.tile_index.set(float(info.get("tile_index", -1)))
        self.tiles_total.set(float(info.get("tiles_total", 0)))
        self.tile_started_unix.set(float(info.get("started_unix", 0.0)))


class AdmissionMetrics:
    """Front-door admission metrics (admission/ subsystem).

    Every shed path counts: rejected traffic must be visible in the
    exposition, never a silent drop (ISSUE 6 acceptance). Gauges mirror
    the controller's cached overload verdict so dashboards and the
    429-emitting RPC read the same state."""

    def __init__(self, registry: "Registry | None" = None):
        r = registry or GLOBAL
        self.admitted_priority = r.counter("admission", "admitted_priority", "priority-lane txs admitted at the RPC edge")
        self.admitted_bulk = r.counter("admission", "admitted_bulk", "bulk-lane txs admitted at the RPC edge")
        self.rejected_dup = r.counter("admission", "rejected_dup", "replayed tx bytes rejected by the edge dedup")
        self.rejected_overload = r.counter("admission", "rejected_overload", "bulk txs shed at the RPC edge (429) under overload/headroom")
        self.rejected_gossip = r.counter("admission", "rejected_gossip", "gossiped bulk txs shed before CheckTx under overload")
        self.rejected_peer = r.counter("admission", "rejected_peer", "gossiped txs shed by the per-peer rate bucket")
        self.overloaded = r.gauge("admission", "overloaded", "1 = pool past high water (hysteresis)")
        self.occupancy = r.gauge("admission", "pool_occupancy", "pool fill fraction at the last pressure poll")
        # adaptive bulk rate (derived from the engine's live commit rate
        # with hysteresis; controller._effective_bulk_rate): the gauge is
        # what the token bucket is actually refilling at RIGHT NOW
        self.bulk_rate_effective = r.gauge("admission", "bulk_rate_effective", "current bulk token-bucket fill rate (tx/s)")
        self.commit_rate = r.gauge("admission", "commit_rate_observed", "EWMA of the engine commit rate the bulk bucket tracks (tx/s)")
        # per-sender fairness inside the priority lane (ISSUE 9 satellite,
        # closing the PR 6 follow-up): one sender flooding fee-bearing txs
        # must not starve other priority senders
        self.priority_sender_limited = r.counter("admission", "priority_sender_limited", "priority txs past their sender's token budget (demoted to bulk shed rules)")
        self.priority_sender_shed = r.counter("admission", "priority_sender_shed", "over-budget priority txs shed at the RPC edge (429)")
        self.priority_sender_tracked = r.gauge("admission", "priority_sender_tracked", "distinct priority senders in the fairness table")


class EpochMetrics:
    """Validator-set lifecycle metrics (epoch/ subsystem).

    Exposed as ``txflow_epoch_*``. Gauges describe the CURRENT epoch and
    set (number, size, powers, quorum); counters are monotonic lifecycle
    events (boundaries crossed, slashes, scheduled rotations). The node
    refreshes the set gauges on every update_state so a slash is visible
    the block its power change lands (see README runbook)."""

    def __init__(self, registry: "Registry | None" = None):
        r = registry or GLOBAL
        self.number = r.gauge("epoch", "number", "current epoch (0-based)")
        self.length = r.gauge("epoch", "length_blocks", "blocks per epoch (0 = epochs disabled)")
        self.validators = r.gauge("epoch", "validators", "validators in the current set")
        self.total_power = r.gauge("epoch", "total_voting_power", "total stake of the current set")
        self.quorum_power = r.gauge("epoch", "quorum_power", "2n/3+1 stake threshold of the current set")
        self.boundaries = r.counter("epoch", "boundaries_total", "epoch boundary blocks committed")
        self.slashes = r.counter("epoch", "slashes_total", "validators slashed at boundaries")
        self.rotations = r.counter("epoch", "rotations_total", "scheduled rotation entries applied at boundaries")
        self.pending_slashes = r.gauge("epoch", "pending_slashes", "offenders awaiting the next boundary")


class SyncMetrics:
    """Catch-up sync metrics (sync/ subsystem, ``txflow_sync_*``).

    The lag gauge and state gauge (0 idle / 1 syncing / 2 fallback) are
    the operator's first look at a recovering node; the Byzantine /
    timeout counters tell WHY a node keeps rotating servers. Server-side
    ``served_txs`` rides the same registry so one exposition shows both
    halves."""

    def __init__(self, registry: "Registry | None" = None):
        r = registry or GLOBAL
        self.lag = r.gauge("sync", "lag", "commits the best peer advert is ahead of us")
        self.state = r.gauge("sync", "state", "0=idle 1=syncing 2=consensus-block fallback")
        self.ranges_fetched = r.counter("sync", "ranges_fetched", "range responses verified and applied")
        self.txs_fetched = r.counter("sync", "txs_fetched", "committed txs fetched from peers (post-dedup)")
        self.txs_applied = r.counter("sync", "txs_applied", "fetched txs applied through the commit seam")
        self.verify_failures = r.counter("sync", "verify_failures", "fetched certificates failing re-verification")
        self.byzantine_strikes = r.counter("sync", "byzantine_strikes", "sync servers caught serving forged/truncated data")
        self.timeouts = r.counter("sync", "timeouts", "range requests that stalled past the timeout")
        self.rotations = r.counter("sync", "rotations", "serving-peer rotations (stall or strike)")
        self.fallbacks = r.counter("sync", "fallbacks", "degradations to the consensus-block fallback")
        self.served_txs = r.counter("sync", "served_txs", "committed txs this node served to catching-up peers")


class ByzantineMetrics:
    """Accountable vote gossip (health/byzantine.py, ``txflow_byzantine_*``).

    Strikes and quarantines are the unified ledger's totals across BOTH
    sources (gossip verdict attribution and sync-server forgery); the
    ``drop_*`` counters break out the O(1) ingest pre-checks so an
    operator can see WHAT a flooding peer was sending without reading
    per-peer /health detail. The Registry has no label support — one
    counter per drop reason, keyed in ``drop_counters`` for the ledger."""

    def __init__(self, registry: "Registry | None" = None):
        r = registry or GLOBAL
        self.strikes = r.counter("byzantine", "strikes", "misbehavior strikes recorded against peers (gossip + sync)")
        self.quarantines = r.counter("byzantine", "quarantines", "peer vote-traffic quarantines (circuit breaker trips)")
        self.invalid_votes = r.counter("byzantine", "invalid_votes", "device valid=False verdicts attributed to a relaying peer")
        self.drop_unknown_validator = r.counter("byzantine", "drop_unknown_validator", "votes dropped pre-verify: signer not in the validator set")
        self.drop_stale_height = r.counter("byzantine", "drop_stale_height", "votes dropped pre-verify: height behind the stale slack")
        self.drop_replayed_sig = r.counter("byzantine", "drop_replayed_sig", "votes dropped pre-verify: same peer re-sent an identical signature")
        self.drop_quarantined = r.counter("byzantine", "drop_quarantined", "vote segments dropped whole-frame from quarantined peers")
        self.drop_non_committee = r.counter("byzantine", "drop_non_committee", "votes dropped pre-verify: signer not in the epoch's tx-vote committee")
        self.quarantined_peers = r.gauge("byzantine", "quarantined_peers", "peers currently under vote-traffic quarantine")
        self.drop_counters = {
            "unknown_validator": self.drop_unknown_validator,
            "stale_height": self.drop_stale_height,
            "replayed_sig": self.drop_replayed_sig,
            "quarantined": self.drop_quarantined,
            "non_committee": self.drop_non_committee,
        }


class TxFlowMetrics:
    """Fast-path metrics (reference txflowstate/metrics.go:17-45)."""

    def __init__(self, registry: Registry | None = None):
        r = registry or GLOBAL
        self.height = r.gauge("txflow", "height", "committed fast-path height")
        self.committed_txs = r.counter("txflow", "committed_txs", "txs committed via fast path")
        self.committed_votes = r.counter("txflow", "committed_votes", "votes in committed quorums")
        self.verified_votes = r.counter("txflow", "verified_votes", "signatures batch-verified")
        self.invalid_votes = r.counter("txflow", "invalid_votes", "votes failing verification")
        self.batch_size = r.histogram("txflow", "batch_size", "device batch occupancy", buckets=(64, 256, 1024, 4096, 16384, 65536))
        # durable-path degradation (disk full / EIO): commits stay applied
        # in memory, the failure is surfaced loudly here + /health
        self.storage_errors = r.counter("txflow", "storage_errors", "durable writes failed (ENOSPC/EIO) — node degraded, not crashed")
        self.step_time = r.histogram("txflow", "step_seconds", "aggregation step wall time")
        self.tx_processing_time = r.histogram("txflow", "tx_processing_seconds", "ApplyTx wall time")
        # verify-pipeline observability (engine pipelined loop): depth is
        # the tickets currently in flight; overlap_ratio is device-busy
        # wall time over engine-active wall time (1.0 = the device never
        # waited on host prep/routing); device_idle is the accumulated
        # active time with NO verify call in flight — the gap the
        # pipeline exists to close. The *_seconds counters are the
        # per-stage breakdown profile_host.py prints.
        self.pipeline_depth = r.gauge("txflow", "pipeline_depth", "verify tickets in flight")
        self.pipeline_overlap_ratio = r.gauge("txflow", "pipeline_overlap_ratio", "device-busy / engine-active wall time")
        self.pipeline_device_idle = r.gauge("txflow", "pipeline_device_idle_seconds", "engine-active seconds with no verify in flight")
        self.pipeline_prep_seconds = r.counter("txflow", "pipeline_prep_seconds", "host batch-prep + dispatch seconds")
        self.pipeline_wait_seconds = r.counter("txflow", "pipeline_wait_seconds", "seconds blocked collecting tickets")
        self.pipeline_route_seconds = r.counter("txflow", "pipeline_route_seconds", "commit-routing seconds")
        # shape-stable batch coalescing (engine.txflow._BatchCoalescer):
        # full_batches dispatched at exactly a canonical bucket (zero
        # padding waste), linger_flushes dispatched partial by deadline
        self.coalesce_full_batches = r.counter("coalesce", "full_batches", "batches dispatched at a full canonical bucket")
        self.coalesce_linger_flushes = r.counter("coalesce", "linger_flushes", "partial buckets flushed by the linger deadline")
        # background shape warmup (engine.shapes.BackgroundWarmer): votes
        # the engine served via the scalar fallback while their device
        # shape was still compiling, and shapes promoted so far
        self.warmup_cold_fallback_votes = r.counter("warmup", "cold_fallback_votes", "votes served by the CPU fallback while their shape compiled")
        self.warmup_warm_shapes = r.gauge("warmup", "warm_shapes", "kernel shapes compiled and promoted")
        # adaptive pipeline depth (engine.adaptive.AdaptiveDepthController)
        self.pipeline_depth_target = r.gauge("txflow", "pipeline_depth_target", "adaptive controller's current depth target")
        self.pipeline_depth_changes = r.counter("txflow", "pipeline_depth_changes", "adaptive depth adjustments applied")
        # deadline-aware verify lanes (ISSUE 12): priority-lane dispatch
        # volume, speculative quorum commits and the route-tail seconds
        # the early exit removed, adaptive per-lane linger adjustments
        self.lane_prio_batches = r.counter("lanes", "prio_batches", "verify batches dispatched through the priority lane")
        self.lane_prio_votes = r.counter("lanes", "prio_votes", "votes dispatched through the priority lane")
        self.spec_commits = r.counter("txflow", "spec_commits", "commits routed early on the device quorum hint")
        self.spec_saved_seconds = r.counter("txflow", "spec_saved_seconds", "route-tail seconds removed by speculative commits")
        self.adaptive_linger_changes = r.counter("txflow", "adaptive_linger_changes", "adaptive lane-linger adjustments applied")
        # engine-side epoch churn (TxFlow.update_state): a rotation is one
        # validator-set swap observed by this engine; restages swap device
        # constants in place (zero recompiles), rebuilds construct a fresh
        # verifier (capacity exceeded / int32 cap / non-restagable type)
        self.epoch_rotations = r.counter("epoch", "engine_rotations_total", "validator-set changes applied by the engine")
        self.epoch_restages = r.counter("epoch", "engine_restages_total", "rotations served by an in-place verifier restage")
        self.epoch_rebuilds = r.counter("epoch", "engine_rebuilds_total", "rotations that forced a full verifier rebuild")
        self.epoch_votes_dropped = r.counter("epoch", "engine_votes_dropped_total", "in-flight votes discarded (validator left the set)")
        self.epoch_rotation_commits = r.counter("epoch", "engine_rotation_commits_total", "txs committed because rotation lowered the quorum")
