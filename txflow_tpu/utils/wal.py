"""Append-only write-ahead log with CRC framing.

Replaces the reference's autofile group WAL (tendermint libs/autofile, used
by txvotepool/txvotepool.go:100-123 and the consensus WAL). Frame format:
``crc32(payload) u32 | len(payload) u32 | payload`` — torn tails are
detected and truncated on replay, which is the crash-consistency property
the reference's tests assert via checksum (txvotepool_test.go:253) and
crashingWAL (consensus/replay_test.go:113-180).
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Iterator

from .failpoints import fail

_HDR = struct.Struct("<II")


class WAL:
    def __init__(self, path: str, sync: bool = False):
        self.path = path
        self.sync_on_write = sync
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab")

    def write(self, payload: bytes) -> None:
        fail("wal.write")  # ENOSPC/EIO drills (tests/test_diskfull.py)
        frame = _HDR.pack(zlib.crc32(payload), len(payload)) + payload
        self._f.write(frame)
        if self.sync_on_write:
            self.flush_and_sync()

    def write_sync(self, payload: bytes) -> None:
        self.write(payload)
        self.flush_and_sync()

    def flush_and_sync(self) -> None:
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()

    @property
    def size(self) -> int:
        self._f.flush()
        return os.path.getsize(self.path)

    def replay(self) -> Iterator[bytes]:
        """Yield intact frames; stop (and truncate) at the first torn one."""
        self._f.flush()
        good_end = 0
        with open(self.path, "rb") as f:
            while True:
                hdr = f.read(_HDR.size)
                if len(hdr) < _HDR.size:
                    break
                crc, length = _HDR.unpack(hdr)
                payload = f.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    break
                good_end = f.tell()
                yield payload
        if good_end < os.path.getsize(self.path):
            # torn tail from a crash mid-append: drop it so future appends
            # start at a frame boundary
            self._f.close()
            with open(self.path, "r+b") as f:
                f.truncate(good_end)
            self._f = open(self.path, "ab")
