"""Process-side host-prep core: the worker half of ``ProcHostPrepPool``.

The thread-backed ``engine.hostprep.HostPrepPool`` parallelizes host prep
only as far as the GIL allows: the native ``_prep.so`` and numpy release
it, but the pure-Python slices (per-row SHA-512 driving loop, sign-bytes
encoding when the C codec is absent) serialize. This module is the seam
past that wall: worker *processes* execute the two typed prep tasks —
compact ed25519 prep and canonical sign-bytes assembly — writing their
contiguous row shards **directly into ``multiprocessing.shared_memory``
buffers**, so the parent assembles the batch with zero IPC copies beyond
the one input marshal.

Design constraints, in order:

- **Import-light by construction.** A spawned worker imports THIS module
  only; the package ``__init__`` is docstring-only and everything heavy
  (jax, the engine) stays out of the chain. Task-specific deps
  (``types.tx_vote`` for sign bytes, ``native`` for the C fast paths)
  load lazily inside the task body, so a ``fork`` worker reuses the
  parent's modules and a ``spawn`` worker pays numpy + stdlib up front
  and the rest on first use.
- **Bit-identical contiguous shards.** Each task computes rows
  ``[lo, hi)`` of the SAME deterministic row function the serial paths
  use (``prep_rows_cat`` is also the engine-side numpy implementation —
  ``ops.ed25519_batch._prepare_compact_np`` delegates here), and writes
  them at row offset ``lo`` of the shared output arrays. Assembly order
  therefore never affects bytes; parity with the serial and thread-pool
  preps is pinned by tests/test_procprep.py.
- **Crash containment.** A worker that dies mid-shard only costs its
  shard: the parent notices the missing ack and recomputes the rows
  inline (engine.hostprep.ProcHostPrepPool), then stops routing typed
  work to processes.

Shared-memory protocol (one segment pair per ``map`` call): the parent
packs every input array back-to-back into one segment and preallocates
one output segment, then enqueues per-shard descriptors carrying the
segment NAMES plus an (offset, dtype, shape) table. Workers attach by
name (attachments cached per worker), build numpy views, run the task,
ack, and the parent copies the outputs out before unlinking both
segments — no segment outlives the call that created it.
"""

from __future__ import annotations

import hashlib

import numpy as np

from .utils.clock import monotonic

# ed25519 group order (crypto.ed25519.L restated here so workers never
# import beyond numpy + stdlib on the compact path; value pinned against
# the golden model by tests/test_procprep.py)
L = 2**252 + 27742317777372353535851937790883648493

_L_BE = np.frombuffer(L.to_bytes(32, "big"), np.uint8)

ZERO64 = bytes(64)


def nibbles_from_le_bytes(b: np.ndarray) -> np.ndarray:
    """[B, 32] little-endian uint8 scalars -> [B, 64] MSB-first nibbles."""
    rev = b[:, ::-1]
    out = np.empty((b.shape[0], 64), np.uint8)
    out[:, 0::2] = rev >> 4
    out[:, 1::2] = rev & 15
    return out


def cat_msgs(msgs: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
    """Concatenated-bytes form of a message list: (msg_cat u8, offs i64)."""
    n = len(msgs)
    offs = np.zeros(n + 1, np.int64)
    np.cumsum(np.fromiter((len(m) for m in msgs), np.int64, n), out=offs[1:])
    msg_cat = np.frombuffer(b"".join(msgs), np.uint8) if n else np.zeros(0, np.uint8)
    return msg_cat, offs


def cat_sigs(sigs: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
    """([n, 64] u8 signature rows, [n] bool length-ok mask).

    Wrong-length signatures become zero rows, so the mask MUST travel with
    the rows: a zero row alone is indistinguishable from an adversarial
    genuinely-all-zero 64-byte signature, which the serial prep treats as
    length-OK (S=0 passes ScMinimal and the hash runs over R=0) — byte
    parity of ``pre_ok``/``h_nibbles`` depends on keeping the two apart.
    """
    n = len(sigs)
    len_ok = np.fromiter((len(s) == 64 for s in sigs), bool, n)
    sig_cat = (
        b"".join(sigs)
        if bool(len_ok.all())
        else b"".join(s if len(s) == 64 else ZERO64 for s in sigs)
    )
    arr = (
        np.frombuffer(sig_cat, np.uint8).reshape(n, 64)
        if n
        else np.zeros((0, 64), np.uint8)
    )
    return arr, len_ok


def prep_rows_cat(
    msg_cat: np.ndarray,
    offs: np.ndarray,
    sig_arr: np.ndarray,
    sig_ok: np.ndarray,
    vi: np.ndarray,
    pub_arr: np.ndarray,
    key_ok: np.ndarray,
    lo: int = 0,
    hi: int | None = None,
) -> tuple[np.ndarray, ...]:
    """Compact ed25519 prep over rows ``[lo, hi)`` of the cat-form batch.

    THE numpy implementation: ``ops.ed25519_batch._prepare_compact_np``
    (serial and thread-pool shards) delegates its whole-batch case here,
    and process workers call it per shard — one row function, so every
    backend's assembled batch is bit-identical by construction. Returns
    ``(s_nib u8[m,64], h_nib u8[m,64], vidx i32[m], r_y u8[m,32],
    r_sign u8[m], pre_ok bool[m])`` for the ``m = hi - lo`` rows.

    Row semantics (pinned against ``_prepare_compact_py``): a row fails
    pre-check — and stays all-zero — on unknown validator index, bad
    signature length (zero row in ``sig_arr``; the packer zeroed it),
    off-curve/malformed key (``key_ok`` False) or non-minimal S; the
    SHA-512 + mod-L reduction runs only over surviving rows.
    """
    n = int(sig_arr.shape[0])
    if hi is None:
        hi = n
    lo = max(0, int(lo))
    hi = min(n, int(hi))
    m = hi - lo
    n_vals = int(pub_arr.shape[0])
    vi = np.asarray(vi, dtype=np.int64)[lo:hi]
    sig_all = np.ascontiguousarray(sig_arr[lo:hi])
    clipped = np.clip(vi, 0, max(n_vals - 1, 0))
    ok = (vi >= 0) & (vi < n_vals) & np.asarray(sig_ok, bool)[lo:hi]
    if n_vals:
        ok &= np.asarray(key_ok, bool)[clipped]
    else:
        ok &= False
    # ScMinimal (S < L), vectorized: compare big-endian byte rows
    # lexicographically — sign of the first differing byte decides
    s_be = sig_all[:, :31:-1]  # bytes 63..32: S, most-significant first
    diff = s_be.astype(np.int16) - _L_BE.astype(np.int16)
    nz = diff != 0
    first = np.where(nz.any(axis=1), nz.argmax(axis=1), 31)
    ok &= np.take_along_axis(diff, first[:, None], 1)[:, 0] < 0
    s_le = np.where(ok[:, None], sig_all[:, 32:], 0).astype(np.uint8)
    h_le = np.zeros((m, 32), np.uint8)
    sha512 = hashlib.sha512
    offs = np.asarray(offs, dtype=np.int64)
    mc = msg_cat
    for i in np.flatnonzero(ok):
        gi = lo + i
        sig_r = sig_all[i, :32].tobytes()
        pub = pub_arr[clipped[i]].tobytes()
        msg = mc[offs[gi] : offs[gi + 1]].tobytes()
        h = int.from_bytes(sha512(sig_r + pub + msg).digest(), "little") % L
        h_le[i] = np.frombuffer(h.to_bytes(32, "little"), np.uint8)
    # failed rows stay all-zero, matching the per-row oracle
    r_y = np.where(ok[:, None], sig_all[:, :32], 0).astype(np.uint8)
    r_sign = (r_y[:, 31] >> 7).astype(np.uint8)
    r_y[:, 31] &= 0x7F
    return (
        nibbles_from_le_bytes(s_le),
        nibbles_from_le_bytes(h_le),
        clipped.astype(np.int32),
        r_y,
        r_sign,
        ok,
    )


def prep_rows_cat_native(
    msg_cat,
    offs,
    sig_arr,
    sig_ok,
    vi,
    pub_arr,
    key_ok,
    lo: int = 0,
    hi: int | None = None,
):
    """Native-C variant of ``prep_rows_cat`` (same returns, same bytes —
    native/prep.c parity is pinned by tests/test_native_prep.py); returns
    None when the compiled module is unavailable in this process."""
    try:
        from . import native
    except Exception:
        return None
    if not native.available():
        return None
    n = int(sig_arr.shape[0])
    if hi is None:
        hi = n
    lo, hi = max(0, int(lo)), min(n, int(hi))
    n_vals = int(pub_arr.shape[0])
    vi = np.asarray(vi, dtype=np.int64)[lo:hi]
    clipped = np.clip(vi, 0, max(n_vals - 1, 0))
    idx_ok = (vi >= 0) & (vi < n_vals) & np.asarray(sig_ok, bool)[lo:hi]
    if n_vals:
        ok_in = (idx_ok & np.asarray(key_ok, bool)[clipped]).astype(np.uint8)
        pubs = np.ascontiguousarray(pub_arr[clipped])
    else:
        ok_in = np.zeros(hi - lo, np.uint8)
        pubs = np.zeros((hi - lo, 32), np.uint8)
    offs = np.asarray(offs, dtype=np.int64)
    base = offs[lo]
    sub_offs = np.ascontiguousarray(offs[lo : hi + 1] - base)
    sub_cat = np.ascontiguousarray(msg_cat[base : offs[hi]])
    sig_sub = np.ascontiguousarray(sig_arr[lo:hi])
    out = native.prep_batch(sub_cat, sub_offs, sig_sub, pubs, ok_in)
    if out is None:
        return None
    s_le, h_le, pre_ok = out
    r_y = np.where(pre_ok[:, None], sig_sub[:, :32], 0).astype(np.uint8)
    r_sign = (r_y[:, 31] >> 7).astype(np.uint8)
    r_y[:, 31] &= 0x7F
    return (
        nibbles_from_le_bytes(s_le),
        nibbles_from_le_bytes(h_le),
        clipped.astype(np.int32),
        r_y,
        r_sign,
        pre_ok.astype(bool),
    )


def sign_rows(
    heights: np.ndarray,
    ts_ns: np.ndarray,
    hash_cat: np.ndarray,
    hash_offs: np.ndarray,
    chain_id: str,
    lo: int,
    hi: int,
    out: np.ndarray,
    out_len: np.ndarray,
) -> None:
    """Canonical sign bytes for rows ``[lo, hi)`` into fixed-stride rows
    of ``out`` (lengths in ``out_len``) — the process-task twin of
    ``types.tx_vote.sign_bytes_many``'s miss path. Uses the native batch
    codec when this process has it, else the per-row Python encoder;
    both produce the same bytes (tests/test_native_prep.py)."""
    from .types.tx_vote import canonical_sign_bytes  # lazy: spawn-light top

    hs = [int(heights[i]) for i in range(lo, hi)]
    ts = [int(ts_ns[i]) for i in range(lo, hi)]
    hashes = [
        hash_cat[hash_offs[i] : hash_offs[i + 1]].tobytes().decode("utf-8", "surrogatepass")
        for i in range(lo, hi)
    ]
    batch = None
    try:
        from . import native

        batch = native.sign_bytes_batch(hs, hashes, ts, chain_id)
    except Exception:
        batch = None
    for j in range(hi - lo):
        sb = batch[j] if batch is not None else None
        if sb is None:
            sb = canonical_sign_bytes(chain_id, hs[j], hashes[j], ts[j])
        row = np.frombuffer(sb, np.uint8)
        out[lo + j, : len(row)] = row
        out_len[lo + j] = len(row)


def sign_bytes_stride(max_hash_len: int, chain_id: str) -> int:
    """Upper bound on one canonical sign-bytes row: fixed fields + varint
    headroom over the variable hash/chain-id parts."""
    return 80 + int(max_hash_len) + len(chain_id.encode())


# ---------------------------------------------------------------------------
# Shared-memory layout + worker loop


def pack_layout(arrays: dict[str, np.ndarray]) -> tuple[list[tuple], int]:
    """(name, dtype-str, shape, offset) table + total bytes for packing
    ``arrays`` back-to-back (8-byte aligned) into one shm segment."""
    layout = []
    off = 0
    for name, a in arrays.items():
        a = np.ascontiguousarray(a)
        layout.append((name, a.dtype.str, a.shape, off))
        off += int(a.nbytes + 7) & ~7
    return layout, max(off, 1)


def write_arrays(buf, layout: list[tuple], arrays: dict[str, np.ndarray]) -> None:
    for name, dt, shape, off in layout:
        a = np.ascontiguousarray(arrays[name])
        dst = np.ndarray(shape, dtype=np.dtype(dt), buffer=buf, offset=off)
        dst[...] = a


def views(buf, layout: list[tuple]) -> dict[str, np.ndarray]:
    return {
        name: np.ndarray(shape, dtype=np.dtype(dt), buffer=buf, offset=off)
        for name, dt, shape, off in layout
    }


def run_task(task: str, ins: dict, outs: dict, lo: int, hi: int) -> None:
    """Execute one typed shard against input/output array views.

    ``compact``: ed25519 compact prep rows (native when available, numpy
    otherwise — identical bytes either way). ``signbytes``: canonical
    sign-bytes rows. Both write ONLY rows [lo, hi) of the outputs."""
    if task == "compact":
        args = (
            ins["msg_cat"], ins["offs"], ins["sig_arr"], ins["sig_ok"],
            ins["vi"], ins["pub_arr"], ins["key_ok"],
        )
        rows = prep_rows_cat_native(*args, lo=lo, hi=hi)
        if rows is None:
            rows = prep_rows_cat(*args, lo=lo, hi=hi)
        s_nib, h_nib, vidx, r_y, r_sign, pre_ok = rows
        outs["s_nib"][lo:hi] = s_nib
        outs["h_nib"][lo:hi] = h_nib
        outs["vidx"][lo:hi] = vidx
        outs["r_y"][lo:hi] = r_y
        outs["r_sign"][lo:hi] = r_sign
        outs["pre_ok"][lo:hi] = pre_ok.astype(np.uint8)
    elif task == "signbytes":
        sign_rows(
            ins["heights"], ins["ts_ns"], ins["hash_cat"], ins["hash_offs"],
            ins["chain_id"], lo, hi, outs["rows"], outs["lens"],
        )
    else:  # unknown task: the parent's version skew guard catches this
        raise ValueError(f"unknown prep task {task!r}")


def worker_main(task_q, done_q) -> None:
    """Worker-process loop: attach shm by name, run shards, ack.

    Descriptors: ``("task", task, shard_id, in_name, in_layout, out_name,
    out_layout, lo, hi, extra)`` — ``extra`` carries small non-array
    inputs (chain_id). ``None`` is the shutdown sentinel. Acks:
    ``("ready", pid)`` once at startup, then ``(shard_id, err_str|None,
    busy_s)`` per shard. Segment attachments are cached per call name and
    dropped after each shard (segments never outlive their call)."""
    import os
    from multiprocessing import shared_memory

    done_q.put(("ready", os.getpid()))
    while True:
        item = task_q.get()
        if item is None:
            return
        (_tag, task, shard_id, in_name, in_layout, out_name, out_layout,
         lo, hi, extra) = item
        t0 = monotonic()
        err = None
        seg_in = seg_out = None
        try:
            seg_in = shared_memory.SharedMemory(name=in_name)
            seg_out = shared_memory.SharedMemory(name=out_name)
            ins = views(seg_in.buf, in_layout)
            if extra:
                ins = {**ins, **extra}
            outs = views(seg_out.buf, out_layout)
            run_task(task, ins, outs, lo, hi)
            del ins, outs
        except BaseException as exc:  # ack the failure; parent recomputes
            err = f"{type(exc).__name__}: {exc}"
        finally:
            # drop numpy views BEFORE closing (close invalidates the buf)
            for seg in (seg_in, seg_out):
                if seg is not None:
                    try:
                        seg.close()
                    except BufferError:
                        pass  # a view survived; the unlink still reclaims
        done_q.put((shard_id, err, monotonic() - t0))
