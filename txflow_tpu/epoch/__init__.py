"""Epoch subsystem: deterministic validator-set lifecycle.

The fast path commits a tx the instant accumulated TxVotes cross 2n/3 of
total stake — which is only safe if every node agrees on *which* stake
table is in force at every height. This package makes the table a
deterministic function of the committed chain:

- ``EpochConfig``   — epoch length, slash fraction, scheduled rotation
  change sets (config.py);
- ``EpochManager``  — accumulates slashable offenses from committed
  evidence and, at each epoch boundary block, emits one merged validator
  change set (slashes + scheduled joins/leaves/re-weights). The change
  set is injected into the block's persisted EndBlock responses, so the
  H+2 effect rule, state-store snapshots, and crash-replay all apply it
  through the exact same code path as app-driven updates (manager.py).

Everything downstream (engine in-flight re-evaluation, verifier
re-staging) keys off ``Node.update_state`` observing the new set — the
epoch layer itself never reaches into the hot path.
"""

from .config import EpochConfig
from .manager import EpochManager

__all__ = ["EpochConfig", "EpochManager"]
