"""EpochConfig: validator-set lifecycle tunables.

One dataclass (the AdmissionConfig / HealthConfig pattern) so a node
assembly, LocalNet, or a drill can swap the whole epoch posture at once.
Everything here must be identical across nodes — the manager derives
validator changes purely from (config, committed chain), and any
divergence would fork the validator set.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class EpochConfig:
    # blocks per epoch; 0 disables the subsystem entirely (genesis set
    # stays frozen, evidence keeps stopping at pool admission + gossip)
    length: int = 0

    # fraction of a slashed validator's power burned per offense epoch.
    # 1.0 (default) zeroes the offender — power 0 removes it from the
    # set, Tendermint-style. Partial fractions floor toward zero, so a
    # repeat offender always reaches removal in finitely many epochs.
    slash_fraction: float = 1.0

    # scheduled rotation: {epoch_number: [(pub_key_bytes, power), ...]}.
    # The change set is applied at the boundary block that *ends* that
    # epoch (height == (epoch_number + 1) * length), taking effect at
    # boundary + 2 per the H+2 validator-update rule. Power 0 = leave,
    # new key = join, existing key = re-weight — exactly the
    # ``ValidatorSet.update_with_change_set`` contract.
    schedule: dict = field(default_factory=dict)

    # per-epoch tx-vote committee sampling (committee/): 0 (default)
    # disables — every validator signs and certificates carry the full
    # 2n/3 vote set (seed behavior, byte-parity with the scalar golden
    # path). When > 0, each epoch's tx-vote committee is the
    # deterministic stake-proportional sample of that epoch's validator
    # set, seeded by sha256 over (chain_id, epoch) so every node derives
    # the identical committee with no extra messages; certificates then
    # carry >2/3 of COMMITTEE stake and verify cost is flat in validator
    # count. Works with length=0 too (a static epoch-0 committee).
    committee_size: int = 0

    # safety floor on committee size: the sample never holds fewer than
    # this many members (and is the full set whenever the full set is at
    # or below the floor) — a tiny committee is cheap to corrupt
    committee_min_size: int = 4

    # safety floor on committee stake: keep drawing past committee_size
    # until the sample holds at least this fraction of the full set's
    # total power (0.0 = size target only). Guards long-tail stake
    # tables where `committee_size` minnows could under-represent stake.
    committee_min_stake_frac: float = 0.0

    def committee_enabled(self) -> bool:
        return self.committee_size > 0

    def epoch_of(self, height: int) -> int:
        """Epoch containing ``height`` (0-based; heights start at 1)."""
        if self.length <= 0 or height <= 0:
            return 0
        return (height - 1) // self.length

    def is_boundary(self, height: int) -> bool:
        """True when ``height`` is the last block of its epoch — the
        block whose EndBlock carries the epoch's merged change set."""
        return self.length > 0 and height > 0 and height % self.length == 0
