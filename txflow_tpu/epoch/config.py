"""EpochConfig: validator-set lifecycle tunables.

One dataclass (the AdmissionConfig / HealthConfig pattern) so a node
assembly, LocalNet, or a drill can swap the whole epoch posture at once.
Everything here must be identical across nodes — the manager derives
validator changes purely from (config, committed chain), and any
divergence would fork the validator set.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class EpochConfig:
    # blocks per epoch; 0 disables the subsystem entirely (genesis set
    # stays frozen, evidence keeps stopping at pool admission + gossip)
    length: int = 0

    # fraction of a slashed validator's power burned per offense epoch.
    # 1.0 (default) zeroes the offender — power 0 removes it from the
    # set, Tendermint-style. Partial fractions floor toward zero, so a
    # repeat offender always reaches removal in finitely many epochs.
    slash_fraction: float = 1.0

    # scheduled rotation: {epoch_number: [(pub_key_bytes, power), ...]}.
    # The change set is applied at the boundary block that *ends* that
    # epoch (height == (epoch_number + 1) * length), taking effect at
    # boundary + 2 per the H+2 validator-update rule. Power 0 = leave,
    # new key = join, existing key = re-weight — exactly the
    # ``ValidatorSet.update_with_change_set`` contract.
    schedule: dict = field(default_factory=dict)

    def epoch_of(self, height: int) -> int:
        """Epoch containing ``height`` (0-based; heights start at 1)."""
        if self.length <= 0 or height <= 0:
            return 0
        return (height - 1) // self.length

    def is_boundary(self, height: int) -> bool:
        """True when ``height`` is the last block of its epoch — the
        block whose EndBlock carries the epoch's merged change set."""
        return self.length > 0 and height > 0 and height % self.length == 0
