"""EpochManager: evidence → slashing + scheduled rotation, committed in
blocks.

Determinism is the whole design. The manager never gossips, never votes,
and holds no authority of its own: it is a pure-ish fold over the
committed chain. Every node runs the same fold over the same blocks with
the same ``EpochConfig``, so every node computes the identical change
set at the identical boundary height. The change set is handed to
``BlockExecutor.apply_block`` which *merges it into the block's
persisted EndBlock validator updates* before ``save_abci_responses`` —
from there the existing machinery (``update_state`` H+2 rule,
per-height validator snapshots in the state store, crash-replay via the
persisted responses) applies it exactly as if the app had asked for it.

Slashing: every committed ``DuplicateBlockVoteEvidence`` marks its
validator for one offense in the current epoch. At the boundary block
the offender's power drops to ``floor(power * (1 - slash_fraction))``
(0 removes). Offenses are deduplicated per (validator, epoch) — ten
equivocations in one epoch cost the same as one; a fresh offense next
epoch slashes again from the already-reduced power.

Restart: pending offenses live only in memory, so ``rebuild`` re-folds
the committed blocks of the current (unfinished) epoch after a crash or
handshake catch-up. Blocks from *finished* epochs need no replay — their
boundary change sets are already baked into the persisted responses.
"""

from __future__ import annotations

from ..analysis.lockgraph import make_lock
from .config import EpochConfig


class EpochManager:
    def __init__(self, cfg: EpochConfig, metrics=None):
        self.cfg = cfg
        self.metrics = metrics
        self._mtx = make_lock("epoch.EpochManager._mtx")
        # addr -> first offense height in the current epoch (dedup per
        # validator per epoch; cleared at each boundary)
        self._pending: dict[bytes, int] = {}
        # highest block whose evidence has been folded in (idempotence
        # guard: apply_block and rebuild may both see a block)
        self._observed_height = 0
        # observability (mirrored into txflow_epoch_* gauges by the node)
        self.slashes_applied = 0
        self.rotations_applied = 0
        self.boundaries_crossed = 0
        self.last_boundary_height = 0
        self.last_slashed: list[str] = []

    # -- chain fold --

    def end_block_updates(self, block, state, app_updates) -> list:
        """Called by apply_block for EVERY committed block, in height
        order. Folds the block's evidence into the pending-offense map;
        at a boundary height, returns the epoch's merged change set
        ``[(pub_key, power), ...]`` to append to the block's EndBlock
        validator updates (empty list off-boundary). ``app_updates`` are
        the app's own EndBlock updates for this block, needed so slash
        arithmetic sees the power the update will actually apply to."""
        if self.cfg.length <= 0:
            return []
        with self._mtx:
            if block.height > self._observed_height:
                for ev in block.evidence:
                    self._pending.setdefault(ev.validator_address, block.height)
                self._observed_height = block.height
            if not self.cfg.is_boundary(block.height):
                self._export_pending_locked()
                return []
            changes = self._boundary_changes_locked(block.height, state, app_updates)
            self._pending.clear()
            self.boundaries_crossed += 1
            self.last_boundary_height = block.height
            self._export_pending_locked()
        return changes

    def _boundary_changes_locked(self, height, state, app_updates) -> list:
        """Merged change set for the boundary at ``height``: scheduled
        rotation first (config order), then slashes in address order —
        so a slash always wins over a same-block scheduled re-weight.
        Deterministic across nodes by construction."""
        # the set these updates will be applied to: next_validators plus
        # the app's own updates from this block (update_with_change_set
        # applies serially, so slash powers must be computed against the
        # post-app-update powers to land where intended)
        working = state.next_validators
        if app_updates:
            try:
                working = working.update_with_change_set(list(app_updates))
            except ValueError:
                working = state.next_validators
        epoch_ending = self.cfg.epoch_of(height)
        changes: list = []
        scheduled = self.cfg.schedule.get(epoch_ending, ())
        for pub_key, power in scheduled:
            changes.append((pub_key, int(power)))
        if scheduled:
            self.rotations_applied += len(scheduled)
        slashed: list[str] = []
        for addr in sorted(self._pending):
            _, val = working.get_by_address(addr)
            if val is None:
                continue  # already rotated/slashed out
            new_power = int(val.voting_power * (1.0 - self.cfg.slash_fraction))
            changes.append((val.pub_key, max(0, new_power)))
            slashed.append(addr.hex())
        if slashed:
            self.slashes_applied += len(slashed)
            self.last_slashed = slashed
            if self.metrics is not None:
                self.metrics.slashes.add(len(slashed))
        if scheduled and self.metrics is not None:
            self.metrics.rotations.add(len(scheduled))
        return self._sanitize(working, changes)

    @staticmethod
    def _sanitize(working, changes) -> list:
        """A change set must never halt block application or empty the
        validator set (liveness beats punishment). Trial-apply entries
        serially: a removal that would empty the set degrades to power 1
        (the offender keeps a token stake until someone else can hold
        quorum); a removal of an unknown key or a malformed entry is
        dropped. All nodes fold the same entries in the same order, so
        the sanitized set is identical everywhere."""
        from ..crypto.hash import address_hash

        out: list = []
        cur = working
        for pub_key, power in changes:
            try:
                cur = cur.update_with_change_set([(pub_key, power)])
                out.append((pub_key, power))
            except ValueError:
                if power == 0:
                    _, val = cur.get_by_address(address_hash(pub_key))
                    if val is not None:  # empty-set case, not unknown-key
                        cur = cur.update_with_change_set([(pub_key, 1)])
                        out.append((pub_key, 1))
        return out

    # -- restart --

    def rebuild(self, block_store, height: int) -> None:
        """Re-fold the committed blocks of the current unfinished epoch
        (boundary+1 .. height) after restart/catch-up, restoring the
        pending-offense map the crash dropped."""
        if self.cfg.length <= 0 or height <= 0:
            return
        last_boundary = height - (height % self.cfg.length)
        with self._mtx:
            self._pending.clear()
            for h in range(last_boundary + 1, height + 1):
                block = block_store.load_block(h)
                if block is None:
                    continue
                for ev in block.evidence:
                    self._pending.setdefault(ev.validator_address, h)
            self._observed_height = max(self._observed_height, height)
            self.last_boundary_height = max(
                self.last_boundary_height, last_boundary
            )
            self._export_pending_locked()

    # -- observability --

    def _export_pending_locked(self) -> None:
        if self.metrics is not None:
            self.metrics.pending_slashes.set(len(self._pending))

    def snapshot(self) -> dict:
        """The ``/health`` view: what a slash event looks like from the
        outside (see README runbook)."""
        with self._mtx:
            return {
                "length": self.cfg.length,
                "epoch": self.cfg.epoch_of(self._observed_height),
                "observed_height": self._observed_height,
                "last_boundary_height": self.last_boundary_height,
                "boundaries_crossed": self.boundaries_crossed,
                "pending_slashes": len(self._pending),
                "pending_addrs": sorted(a.hex() for a in self._pending),
                "slashes_applied": self.slashes_applied,
                "rotations_applied": self.rotations_applied,
                "last_slashed": list(self.last_slashed),
            }
