"""Mesh construction + shard_map'd verify/tally.

Sharding layout (tpu-first, not a translation of the reference's per-peer
goroutines):

- vote-batch axis ("votes"): fully sharded — every per-vote array
  (scalar nibbles, pubkey window tables, R encodings, masks, slots, powers)
  is split across devices; the curve kernel runs embarrassingly parallel.
- tx-slot stake vector: computed as per-shard partial segment-sums, then
  ``psum`` over the mesh axis — one ICI collective per step — so every
  shard holds the identical global tally and quorum mask (replicated out).

This function is what ``__graft_entry__.dryrun_multichip`` compiles over an
N-virtual-device mesh, and what the engine uses on a real multi-chip slice.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..ops import ed25519_batch, tally

VOTE_AXIS = "votes"


def _axis_size(axis_name: str) -> int:
    """Static (Python-int) mesh-axis size inside a shard_map'd function.

    ``jax.lax.axis_size`` only exists on newer jax; 0.4.x exposes the
    bound frame through ``jax.core.axis_frame`` (which returns the size
    directly on 0.4.37, a frame object with ``.size`` on other builds)."""
    size = getattr(jax.lax, "axis_size", None)
    if size is not None:
        return int(size(axis_name))
    from jax import core

    frame = core.axis_frame(axis_name)
    return int(frame if isinstance(frame, int) else frame.size)


def make_mesh(n_devices: int | None = None, axis_name: str = VOTE_AXIS) -> Mesh:
    """1-D mesh over the first n_devices (default: all) local devices."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    import numpy as np

    return Mesh(np.array(devs), (axis_name,))


def sharded_verify_and_tally(mesh: Mesh, axis_name: str = VOTE_AXIS):
    """jit(shard_map) of verify+tally: votes sharded, tally psum-replicated.

    Returns f(verify_inputs_tuple, tx_slot, power, prior_stake, quorum) ->
    (valid[B] sharded, stake[n_slots] replicated, maj23[n_slots] replicated)
    with n_slots taken from prior_stake's shape (jit re-specializes per
    shape). B must be divisible by mesh.size (the verifier pads to buckets
    that are).
    """
    inner = tally.verify_and_tally(ed25519_batch.verify_kernel, axis_name=axis_name)

    vote_specs = (P(axis_name), P(axis_name), P(axis_name), P(axis_name), P(axis_name), P(axis_name))
    f = shard_map(
        inner,
        mesh=mesh,
        in_specs=(vote_specs, P(axis_name), P(axis_name), P(), P()),
        out_specs=(P(axis_name), P(), P()),
        # VMA checker ON: the scalar-mul loop carry is pvary'd to the vote
        # axis at init (ops.curve.double_scalar_mul), so its variance type
        # is consistent throughout.
    )
    return jax.jit(f)


import functools


@functools.lru_cache(maxsize=8)
def sharded_compact_step_cached(mesh: Mesh, axis_name: str = VOTE_AXIS):
    """Process-wide shared jit of the sharded step (Mesh is hashable).

    Shared for the same reason as ``tally.compact_step_jit``: N in-proc
    nodes over one mesh must reuse one compiled program per shape."""
    return sharded_compact_step(mesh, axis_name)


def sharded_compact_step(mesh: Mesh, axis_name: str = VOTE_AXIS):
    """jit(shard_map) of the compact fused step (ops.tally.compact_step).

    Per-vote arrays shard over the vote axis; the per-epoch table/power
    constants and the prior-stake/quorum scalars are replicated; per-shard
    partial stake tallies psum over ICI. Same call signature as the
    single-device compact step.
    """
    inner = tally.compact_step(axis_name=axis_name)
    v = P(axis_name)
    f = shard_map(
        inner,
        mesh=mesh,
        in_specs=(v, v, v, v, v, v, v, P(), P(), P(), P()),
        out_specs=(v, P(), P()),
    )
    return jax.jit(f)


@functools.lru_cache(maxsize=8)
def sharded_compact_step_packed_cached(mesh: Mesh, axis_name: str = VOTE_AXIS):
    """Packed-output sharded step (single D2H readback; tally.compact_step_
    packed docstring). Per-shard output [B/n + 2*S] int32, sharded over the
    vote axis -> host sees [B + 2*S*n]; the stake/maj segments repeat the
    psum-replicated global per shard."""
    inner = tally.compact_step_packed(axis_name=axis_name)
    v = P(axis_name)
    f = shard_map(
        inner,
        mesh=mesh,
        in_specs=(v, v, v, v, v, v, v, P(), P(), P(), P()),
        out_specs=v,
    )
    return jax.jit(f)


def ring_tally(stake_partial, axis_name: str = VOTE_AXIS):
    """All-reduce a per-shard partial stake tally around the ICI ring.

    The ``psum`` the compact step uses lets XLA pick the collective; this
    is the explicit ring formulation (the ring-attention analog for the
    vote axis): N-1 ``ppermute`` rotations, each shard accumulating its
    neighbor's partial, after which every shard holds the global tally.
    Useful when the tally should overlap with other per-shard work on
    real ICI (XLA schedules each hop independently) and as the pattern
    template for future ring-style kernels.
    """
    n = _axis_size(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def hop(_, carry):
        rotating, total = carry
        rotating = jax.lax.ppermute(rotating, axis_name, perm)
        return rotating, total + rotating

    _, total = jax.lax.fori_loop(
        0, n - 1, hop, (stake_partial, stake_partial)
    )
    return total


def sharded_ring_step(mesh: Mesh, axis_name: str = VOTE_AXIS):
    """Compact fused step with the ring all-reduce instead of psum.

    Bit-identical tallies to ``sharded_compact_step`` (integer addition is
    associative/commutative and every shard contributes exactly once) —
    pinned by tests/test_verifier.py's mesh parity test.

    Output layout difference, for honesty with the static VMA checker: a
    ppermute chain does not PROVE replication the way psum does, so the
    stake/maj outputs are declared per-shard — shape [n_shards * S], each
    shard's identical copy of the global concatenated; take shard 0's
    slice. (The checker stays ON; suppressing it was round-2 review
    finding #7 and is not coming back.)
    """
    from ..ops import ed25519_batch

    def inner(s_nib, h_nib, val_idx, r_y, r_sign, pre_ok, tx_slot,
              tables, powers, prior_stake, quorum):
        valid = ed25519_batch.verify_kernel_gather(
            s_nib, h_nib, val_idx, tables, r_y, r_sign, pre_ok,
            axis_name=axis_name,
        )
        power = jnp.take(powers, val_idx)
        partial = tally.tally_kernel(
            valid, tx_slot, power, prior_stake.shape[0]
        )
        total = prior_stake + ring_tally(partial, axis_name)
        return valid, total, total >= quorum

    v = P(axis_name)
    f = shard_map(
        inner,
        mesh=mesh,
        in_specs=(v, v, v, v, v, v, v, P(), P(), P(), P()),
        out_specs=(v, v, v),
    )
    return jax.jit(f)
