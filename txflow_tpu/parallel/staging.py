"""Double-buffered device readback: overlap batch N-1's D2H with batch N.

The verify pipeline's one blocking host<->device synchronization is the
packed-result readback (``np.asarray(packed)`` in the ticket's
``result()``). Serially that readback sits BETWEEN steps: the engine
cannot stage batch N (device_put + dispatch) until batch N-1's bytes have
crossed back. A ``StagingRing`` breaks that ordering: every dispatched
device array enters the ring, a dedicated readback thread pulls it to
host EAGERLY (device->host DMA overlapping whatever the caller does
next), and the ticket's ``result()`` waits on the slot instead of issuing
the transfer itself. With depth 2 — classic double buffering (see the
Pallas guide's double-buffer pattern for the on-chip analog) — batch N's
staging runs while batch N-1's readback is in flight.

Correctness envelope:

- **Byte parity is structural.** The ring changes WHERE ``np.asarray``
  runs, never what it reads: the same device array yields the same host
  bytes from any thread, and tickets are still collected in submission
  order by the engine. Certificates stay byte-identical to the scalar
  golden path (pinned by tests/test_staging_ring.py).
- **Bounded in-flight, never blocking.** A counting semaphore caps
  un-awaited slots at ``depth``; a submit past the cap runs its readback
  synchronously on the caller (accounted as ``sync_readbacks``) instead
  of waiting for a permit. Blocking would deadlock engines that share
  the ring: each fills `pipeline_depth` batches ahead of its collector
  on ONE loop thread, so when every permit holder is itself parked in
  ``submit``, the ``result()`` calls that release permits never run.
  Degrading keeps buffers bounded and costs only that batch's overlap.
- **Errors surface at the waiter.** A readback that raises (device OOM,
  backend teardown) is captured in the slot and re-raised from
  ``wait()`` — the thread never dies with the error, and the engine's
  drain-on-stop still settles every slot.

The ``hidden_s`` stat is the headline: readback seconds that ran while
the caller was NOT blocked in ``wait()`` — the time double-buffering
actually removed from the critical path (trace/report.py shows it as
``readback_overlap_hidden``).
"""

from __future__ import annotations

import threading

import numpy as np

from ..analysis.lockgraph import make_lock
from ..analysis.racegraph import shared_field
from ..utils.clock import monotonic


class StageSlot:
    """One in-flight readback: device array in, host array (or error) out.

    No lock guards the buffer fields: ownership moves caller -> readback
    thread -> waiter, with the ring queue (under ``_q_mtx``) and the
    ``_done`` Event's set()/wait() pair as the happens-before edges. The
    race auditor sees this as sanctioned handoffs, not a lockset."""

    __slots__ = (
        "_dev", "_host", "_error", "_done", "readback_s", "_waited",
        "_queued", "_sh",
    )

    def __init__(self, dev):
        self._dev = dev
        self._host = None
        self._error: BaseException | None = None
        self._done = threading.Event()
        self.readback_s = 0.0
        self._waited = False
        self._queued = False
        self._sh = shared_field("parallel.StageSlot.buffer")  # txlint: shared(handoff)
        self._sh.note_write()

    def _run(self) -> None:
        t0 = monotonic()
        self._sh.note_write()
        try:
            self._host = np.asarray(self._dev)
        except BaseException as exc:  # re-raised at wait()
            self._error = exc
        finally:
            self._dev = None  # drop the device ref as soon as bytes land
            self.readback_s = monotonic() - t0
            self._sh.handoff(
                "Event set()/wait() is the happens-before edge to the waiter"
            )
            self._done.set()

    def wait(self):
        """Block until the readback lands; returns the host array."""
        self._done.wait()
        self._sh.note_read()
        if self._error is not None:
            raise self._error
        return self._host


class StagingRing:
    """Depth-bounded readback ring with one eager readback thread.

    One ring per device verifier (it serializes D2H transfers in
    submission order, which is also the transfer-engine's natural
    order); all engines sharing the verifier share the ring. ``close()``
    drains the queue so every submitted slot still completes — stopping
    an engine never abandons an in-flight readback.
    """

    def __init__(self, depth: int = 2, name: str = "staging"):
        self.depth = max(1, int(depth))
        self._sem = threading.Semaphore(self.depth)
        self._q: list[StageSlot | None] = []
        self._q_mtx = make_lock("parallel.StagingRing._q_mtx")
        self._q_cv = threading.Condition(self._q_mtx)
        self._stats_mtx = make_lock("parallel.StagingRing._stats_mtx")
        # queue + in-flight count: submitters, waiters, and the readback
        # thread all cross here
        self._sh_q = shared_field("parallel.StagingRing.queue")  # txlint: shared(self._q_mtx)
        self._sh_stats = shared_field("parallel.StagingRing.stats")  # txlint: shared(self._stats_mtx)
        self._closed = False
        self.slots_total = 0
        self.readback_s = 0.0
        self.result_wait_s = 0.0
        self.hidden_s = 0.0
        self.sync_readbacks = 0
        self._in_flight = 0
        self._thread = threading.Thread(
            target=self._loop, name=f"{name}-readback", daemon=True
        )
        self._thread.start()

    def submit(self, dev) -> StageSlot:
        """Enter a device array into the ring; returns its slot.

        NEVER blocks: with ``depth`` earlier slots still un-awaited (or
        the ring closed) the readback runs synchronously on the caller
        instead — permits are released by ``result()``, and the engines
        sharing the ring each fill ahead of their own collector on one
        loop thread, so a blocking acquire here can park every
        permit-holder at once (deadlock)."""
        if not self._sem.acquire(blocking=False):
            # ring full: this batch forgoes overlap, buffers stay bounded
            return self._sync_slot(dev, fallback=True)
        slot = StageSlot(dev)
        with self._q_cv:
            if self._closed:
                self._sem.release()
                # closed: degrade to a synchronous slot so the caller
                # still gets its bytes (drain path, never lossy)
                return self._sync_slot(dev, fallback=False)
            slot._queued = True
            slot._sh.handoff(
                "queued under _q_mtx; readback thread is sole owner "
                "until _done.set()"
            )
            self._sh_q.note_write()
            self._q.append(slot)
            self._in_flight += 1
            self._q_cv.notify()
        with self._stats_mtx:
            self._sh_stats.note_write()
            self.slots_total += 1
        return slot

    def _sync_slot(self, dev, *, fallback: bool) -> StageSlot:
        slot = StageSlot(dev)
        slot._run()
        with self._stats_mtx:
            self._sh_stats.note_write()
            self.slots_total += 1
            self.readback_s += slot.readback_s
            if fallback:
                self.sync_readbacks += 1
        return slot

    def result(self, slot: StageSlot):
        """Wait on a slot with overlap accounting; returns the host array.

        The hidden-overlap ledger: a slot whose readback took ``d``
        seconds while the caller blocked here only ``w`` seconds had
        ``max(d - w, 0)`` of its transfer hidden behind caller work —
        with a synchronous readback the caller would have eaten all of
        ``d`` on the critical path."""
        t0 = monotonic()
        try:
            host = slot.wait()
        finally:
            w = monotonic() - t0
            release = False
            with self._q_mtx:
                self._sh_q.note_write()
                if slot._queued and not slot._waited:
                    slot._waited = True
                    self._in_flight -= 1
                    release = True
            if release:
                # synchronous slots hold no permit and were accounted at
                # submit (their readback ran ON the caller: nothing hidden)
                self._sem.release()
                with self._stats_mtx:
                    self._sh_stats.note_write()
                    self.result_wait_s += w
                    self.readback_s += slot.readback_s
                    self.hidden_s += max(slot.readback_s - w, 0.0)
        return host

    def _loop(self) -> None:
        while True:
            with self._q_cv:
                while not self._q and not self._closed:
                    self._q_cv.wait()
                if not self._q and self._closed:
                    return
                self._sh_q.note_write()
                slot = self._q.pop(0)
            if slot is None:
                return
            slot._run()

    def stats(self) -> dict:
        with self._stats_mtx, self._q_mtx:
            self._sh_stats.note_read()
            self._sh_q.note_read()
            return {
                "depth": self.depth,
                "slots_total": self.slots_total,
                "readback_s": self.readback_s,
                "result_wait_s": self.result_wait_s,
                "hidden_s": self.hidden_s,
                "sync_readbacks": self.sync_readbacks,
                "in_flight": self._in_flight,
            }

    def close(self, timeout: float = 5.0) -> None:
        """Stop the readback thread after draining queued slots.

        Slots already submitted still complete (their waiters may be
        other engines mid-collect); new submits degrade to synchronous
        readback. Idempotent."""
        with self._q_cv:
            if self._closed:
                return
            self._sh_q.note_write()
            self._closed = True
            self._q_cv.notify_all()
        self._thread.join(timeout=timeout)
