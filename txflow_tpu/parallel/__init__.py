"""Device-mesh parallelism for the vote-batch axis.

The reference has no data parallelism at all — votes are verified one at a
time under a mutex (reference types/vote_set.go:85-131). Here the "long
dimension" (concurrent in-flight tx x validator votes, SURVEY.md §5) is
sharded over a ``jax.sharding.Mesh``: each device verifies its shard of the
batch and partial stake tallies are combined with a single ``psum`` over
ICI — the workload's analog of sequence parallelism.
"""

from .mesh import (
    VOTE_AXIS,
    make_mesh,
    sharded_compact_step,
    sharded_compact_step_packed_cached,
    sharded_verify_and_tally,
)

__all__ = [
    "make_mesh",
    "sharded_compact_step",
    "sharded_compact_step_packed_cached",
    "sharded_verify_and_tally",
    "VOTE_AXIS",
]
