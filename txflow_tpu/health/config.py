"""HealthConfig: tunables for the self-healing liveness layer.

One dataclass covers all three health drives (monitor cadence, quorum-
stall watchdog, peer scoring + reconnect backoff) so a node assembly or a
chaos rig can swap the whole posture at once. Defaults are conservative:
on a healthy in-proc net the watchdog never fires (quorum forms in
milliseconds, the deadline is seconds) and peer scoring never evicts
(eviction additionally requires a reconnector — see peers.py — so a
plain node without reconnect wiring can only observe, never amputate).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class HealthConfig:
    # monitor cadence: one tick drives the watchdog, the peer scorer and
    # the degraded-mode gauge refresh
    tick_interval: float = 0.25

    # -- quorum-stall watchdog --
    watchdog: bool = True
    # a tx below 2n/3 whose stake has not advanced for this long is
    # stalled; each firing re-offers its known votes + tx bytes, and the
    # timer re-arms so escalation happens one deadline later
    stall_timeout: float = 2.0
    # escalation: firing 0 targets ONE peer (round-robin); later firings
    # for the same stuck tx target every peer
    max_reoffer_votes: int = 512  # votes per re-offer frame

    # -- peer scoring --
    peer_scoring: bool = True
    # staleness: nothing received from the peer for this long WHILE we
    # kept handing it frames (a quiet idle link is not stale)
    stale_after: float = 2.0
    min_sends_for_stale: int = 3
    send_fail_penalty: float = 2.0  # per failed send (transport/backpressure)
    stale_penalty: float = 1.0  # per tick while stale
    dup_penalty: float = 0.02  # per duplicate beyond fresh traffic
    recv_reward: float = 0.5  # per tick with inbound progress
    score_max: float = 4.0  # reward ceiling
    score_floor: float = -8.0  # at/below: evict (if a reconnector is wired)
    # per tick while the adaptive transport (p2p/adaptive.py) holds the
    # peer in slow-peer quarantine: bad weather that PERSISTS walks the
    # peer to the score floor and through the same eviction + jittered-
    # backoff reconnect as any other misbehavior — no second eviction path
    quarantine_penalty: float = 0.5

    # also re-dial peers that vanished WITHOUT a score eviction (reactor
    # error on a corrupted frame, transport teardown): same jittered
    # backoff path. Off by default — TCP assemblies already heal through
    # the PEX ensure-loop, and drills that stop peers on purpose expect
    # them to stay down; netem rigs (in-proc pipes have no PEX) turn it on
    redial_lost_peers: bool = False

    # -- reconnect backoff (jittered, capped exponential) --
    reconnect_base: float = 0.25
    reconnect_cap: float = 5.0
    reconnect_jitter: float = 0.25  # uniform +-fraction of the delay
    seed: int = 0  # jitter PRNG seed (deterministic drills)
