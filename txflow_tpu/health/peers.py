"""Peer scoring + reconnect backoff: the switch's fire-and-forget peer
set becomes managed.

Score inputs per tick (deltas of ``Peer.stats``, bumped lock-free by the
switch's send/recv loops and the gossip reactors):

- send failures (transport error or queue-full backpressure): large
  penalty — the peer is not draining;
- staleness: nothing received for ``stale_after`` while we kept handing
  the peer frames (quiet idle links are NOT stale; a black-holed link —
  e.g. a chaos partition, where the sender sees success — is);
- duplicate deliveries in excess of fresh traffic: small penalty (gossip
  legitimately delivers each vote 2-3x via independent forwarders);
- inbound progress: reward, capped.

At/below ``score_floor`` the peer is evicted — but ONLY when a
reconnector is wired: an eviction without a way back would turn one bad
interval into a permanent amputation, so an unwired node observes scores
without acting on them. Evicted peers re-dial on a jittered, capped
exponential backoff; the backoff level resets once a reconnected peer
shows inbound progress again.
"""

from __future__ import annotations

import random
import time
from typing import Callable

from .config import HealthConfig
from .registry import DegradedModeRegistry


class PeerScoreError(Exception):
    """Eviction reason handed to Switch.stop_peer (shows in peer logs)."""


class _PeerTrack:
    __slots__ = (
        "score",
        "send_attempts",
        "send_fail",
        "recv_count",
        "duplicates",
        "last_progress",
        "sends_since_progress",
    )

    def __init__(self, now: float):
        self.score = 0.0
        self.send_attempts = 0
        self.send_fail = 0
        self.recv_count = 0
        self.duplicates = 0
        self.last_progress = now
        self.sends_since_progress = 0


class PeerScoreBoard:
    def __init__(
        self,
        switch,
        cfg: HealthConfig,
        registry: DegradedModeRegistry,
        reconnector: Callable[[str], bool] | None = None,
    ):
        self.switch = switch
        self.cfg = cfg
        self.registry = registry
        # reconnector(node_id) -> bool: re-establish the link to node_id.
        # LocalNet wires in-memory re-pipes; TCP assemblies get the
        # address-book-backed default (p2p.pex.book_reconnector), which
        # Node auto-wires whenever the switch has a node key and a PEX
        # book.
        self.reconnector = reconnector
        self._tracks: dict[str, _PeerTrack] = {}
        self._backoff_level: dict[str, int] = {}
        self._pending: dict[str, float] = {}  # node_id -> reconnect due time
        self._expected: set[str] = set()
        self._rng = random.Random(cfg.seed)

    def set_expected(self, node_ids) -> None:
        """Roster of peers this node should always hold a live link to.
        Needed by ``redial_lost_peers``: a link torn down before any tick
        observed it (e.g. a weather-corrupted frame during startup gossip)
        leaves no track behind, so track cleanup alone can never re-dial
        it — the roster is the ground truth the tick compares against."""
        self._expected = {nid for nid in node_ids if nid != self.switch.node_id}

    # -- scoring --

    def scores(self) -> dict[str, float]:
        return {nid: round(t.score, 2) for nid, t in self._tracks.items()}

    def tick(self, now: float | None = None) -> None:
        if now is None:
            now = time.monotonic()
        cfg = self.cfg
        peers = self.switch.peers()
        live_ids = set()
        for peer in peers:
            nid = peer.node_id
            live_ids.add(nid)
            tr = self._tracks.get(nid)
            if tr is None:
                tr = self._tracks[nid] = _PeerTrack(now)
            st = peer.stats
            # snapshot-and-diff: the loops bump ints without locks.
            # Staleness tracks send ATTEMPTS (pre-interception), because
            # a chaos-partitioned link black-holes frames while reporting
            # success — attempts are the proof we kept talking
            attempts, send_fail = st.send_attempts, st.send_fail
            recv_count, dups = st.recv_count, st.duplicates
            d_att = attempts - tr.send_attempts
            d_fail = send_fail - tr.send_fail
            d_recv = recv_count - tr.recv_count
            d_dup = dups - tr.duplicates
            tr.send_attempts, tr.send_fail = attempts, send_fail
            tr.recv_count, tr.duplicates = recv_count, dups
            delta = -cfg.send_fail_penalty * d_fail
            delta -= cfg.dup_penalty * max(0, d_dup - max(d_recv - d_dup, 0))
            if d_recv > 0:
                delta += cfg.recv_reward
                tr.last_progress = now
                tr.sends_since_progress = 0
                # inbound progress after a reconnect clears the penalty
                self._backoff_level.pop(nid, None)
            else:
                tr.sends_since_progress += d_att
            if (
                now - tr.last_progress > cfg.stale_after
                and tr.sends_since_progress >= cfg.min_sends_for_stale
            ):
                delta -= cfg.stale_penalty
            # slow-peer quarantine (p2p/adaptive.py): sustained bad link
            # weather bleeds score until the floor evicts through the
            # normal reconnect/backoff machinery
            net = getattr(peer, "net", None)
            if net is not None and net.quarantined:
                delta -= cfg.quarantine_penalty
            tr.score = min(cfg.score_max, tr.score + delta)
            if tr.score <= cfg.score_floor and self.reconnector is not None:
                self._evict(peer, now)
                live_ids.discard(nid)  # evicted this tick: not live
        # forget tracks for peers that left by other causes; their backoff
        # level survives so a flapping peer keeps its penalty
        for nid in list(self._tracks):
            if nid not in live_ids:
                del self._tracks[nid]
                # a peer lost to a reactor/transport error (e.g. a link-
                # corrupted frame failing decode) never went through
                # _evict: without PEX (in-proc pipes) nobody would ever
                # re-dial it — opt in to healing through the same
                # jittered-backoff path
                if cfg.redial_lost_peers:
                    self._schedule_redial(nid, now)
        # roster check: an expected peer with no live link, no track and
        # no pending redial died before a tick ever saw it — a track-
        # cleanup heuristic alone can never heal that
        if cfg.redial_lost_peers:
            for nid in self._expected:
                if nid not in live_ids:
                    self._schedule_redial(nid, now)
        self._drain_reconnects(now)

    def _schedule_redial(self, nid: str, now: float) -> None:
        if self.reconnector is None or nid in self._pending:
            return
        level = self._backoff_level.get(nid, 0)
        self._backoff_level[nid] = level + 1
        self._pending[nid] = now + self._backoff_delay(level)

    # -- external penalties (sync Byzantine scoring) --

    def punish(self, nid: str, amount: float, now: float | None = None) -> None:
        """Apply an out-of-band score penalty (e.g. the sync client caught
        this peer serving a forged certificate). Crossing the floor evicts
        immediately instead of waiting for the next tick, so a Byzantine
        sync server can't keep serving poison for another tick interval."""
        if now is None:
            now = time.monotonic()
        tr = self._tracks.get(nid)
        if tr is None:
            tr = self._tracks[nid] = _PeerTrack(now)
        tr.score -= amount
        if tr.score <= self.cfg.score_floor and self.reconnector is not None:
            peer = self.switch.get_peer(nid)
            if peer is not None:
                self._evict(peer, now)

    # -- eviction + reconnect --

    def _evict(self, peer, now: float) -> None:
        nid = peer.node_id
        self._tracks.pop(nid, None)
        level = self._backoff_level.get(nid, 0)
        self._backoff_level[nid] = level + 1
        self.switch.stop_peer(peer, reason=PeerScoreError(f"score floor ({nid})"))
        self.registry.note_peer_evicted()
        self._pending[nid] = now + self._backoff_delay(level)

    def _backoff_delay(self, level: int) -> float:
        cfg = self.cfg
        base = min(cfg.reconnect_base * (2.0**level), cfg.reconnect_cap)
        jitter = 1.0 + cfg.reconnect_jitter * (2.0 * self._rng.random() - 1.0)
        return base * jitter

    def _drain_reconnects(self, now: float) -> None:
        for nid, due in list(self._pending.items()):
            if self.switch.get_peer(nid) is not None:
                # reconnected some other way (inbound dial, operator)
                del self._pending[nid]
                continue
            if now < due:
                continue
            ok = False
            if self.reconnector is not None:
                try:
                    ok = bool(self.reconnector(nid))
                except Exception:
                    ok = False
            if ok:
                # a fresh track starts at score 0; the backoff level only
                # resets once the reconnected peer shows inbound progress
                # again (tick() clears it on the first d_recv > 0)
                del self._pending[nid]
                self.registry.note_peer_reconnected()
            else:
                self.registry.note_reconnect_failed()
                level = self._backoff_level.get(nid, 1)
                self._backoff_level[nid] = level + 1
                self._pending[nid] = now + self._backoff_delay(level)
