"""Self-healing liveness layer (PR 2).

The fast path's liveness rests entirely on the asynchronous TxVote flood
reaching 2n/3 stake — there are no view changes to fall back on. This
package detects stalls and heals them without restarts:

- ``HealthMonitor``: per-node driver thread (monitor.py);
- ``QuorumStallWatchdog``: sub-quorum deadline -> targeted re-offers
  (watchdog.py);
- ``PeerScoreBoard``: peer scoring -> eviction + backoff reconnects
  (peers.py);
- ``DegradedModeRegistry``: metrics + the RPC /health payload
  (registry.py);
- ``HealthConfig``: the tunables (config.py).
"""

from .config import HealthConfig
from .monitor import HealthMonitor
from .peers import PeerScoreBoard, PeerScoreError
from .registry import DegradedModeRegistry
from .watchdog import QuorumStallWatchdog

__all__ = [
    "HealthConfig",
    "HealthMonitor",
    "PeerScoreBoard",
    "PeerScoreError",
    "DegradedModeRegistry",
    "QuorumStallWatchdog",
]
