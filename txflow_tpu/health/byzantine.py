"""ByzantineLedger: one per-node ledger of peer misbehavior on the vote
gossip path, unifying what used to be two disconnected mechanisms:

- the sync client's Byzantine strikes (sync/manager.py ``_strike`` — a
  peer caught serving forged certificates), previously a private
  ``_banned`` dict locked inside the sync subsystem;
- NEW gossip accountability: every per-vote ``valid=False`` bit the
  batched verifier produces is attributed back to the peer whose
  delivery put that vote in the pool (its ingest *origin*), and every
  O(1) ingest pre-check drop (unknown validator, stale height, replayed
  signature) is counted against the relaying peer.

Both write the same per-peer record and the same scoreboard
(health/peers.py ``PeerScoreBoard.punish`` — score floor -> evict ->
jittered-backoff reconnect), surfaced as the ``byzantine`` section of
``/health`` and the ``txflow_byzantine_*`` metrics family.

The circuit breaker: each peer's *judged* gossip events (pre-check
drops + device verdicts attributed to it) form a decaying window; when
the window holds enough samples and the bad fraction crosses
``max_bad_rate``, the peer is quarantined for ``quarantine_secs`` — the
reactor then drops its whole MSG_VOTES frames at the front door, BEFORE
decode and before the pool, so a flooding peer stops costing device
dispatches (and host decodes) the moment the breaker trips.

Attribution is by ORIGIN, not by the full sender set: the origin is the
peer whose delivery actually created the pool entry — the delivery that
cost the device slot. Later duplicate senders (honest gossip redundancy
racing the verdict) cost nothing on the device and are not struck, so an
honest node that innocently relays a flooder's garbage one hop is not
punished for the flooder's crime (it loses at most the rare races where
its relay arrived first).

Replays (same peer re-sending a signature it already delivered) are
counted and surfaced but do NOT feed the breaker by default
(``quarantine_replays``): the quorum-stall watchdog's re-offer frames
are legitimate same-peer repeats, and a replay never reaches the device
anyway (pool signature dedup + the verifier's verdict cache make it
O(1)). Drills and deployments that want replay floods quarantined opt
in.

The ledger is lock-cheap by design: every note_* call is a few dict
operations under one small mutex — it runs on gossip receive threads
and at the tail of the engine's route stage, never under the pool lock.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..analysis.lockgraph import make_lock
from ..analysis.racegraph import shared_field
from ..utils.clock import monotonic
from ..utils.metrics import ByzantineMetrics

# pre-check drop reasons (counter keys; also the /health breakdown)
DROP_UNKNOWN_VALIDATOR = "unknown_validator"
DROP_STALE_HEIGHT = "stale_height"
DROP_REPLAYED_SIG = "replayed_sig"
DROP_QUARANTINED = "quarantined"
# committee mode only: the signer is a real validator but not in the
# epoch's sampled tx-vote committee. Honest peers never relay these —
# non-committee votes are pre-dropped at every hop and never enter the
# pool or the wire cache — so an exact-height non-committee vote is
# manufactured traffic and feeds the breaker.
DROP_NON_COMMITTEE = "non_committee"

_BREAKER_REASONS = (DROP_UNKNOWN_VALIDATOR, DROP_STALE_HEIGHT, DROP_NON_COMMITTEE)

# committee_rescale floors: scaling the breaker thresholds down by the
# committee fraction must never make the breaker hair-triggered — below
# these, one honest race (e.g. a vote crossing an epoch boundary in
# flight) could quarantine a well-behaved peer.
_MIN_SAMPLES_FLOOR = 8
_BAD_RATE_FLOOR = 0.2


@dataclass
class ByzantineConfig:
    # scoreboard points per invalid-signature verdict attributed to a
    # peer (cumulative; the floor at -8.0 evicts through the normal
    # reconnect/backoff machinery once a reconnector is wired)
    strike_penalty: float = 0.75
    # one-shot punishment when the breaker trips (matches the sync
    # client's byzantine_penalty posture: crosses the floor immediately)
    quarantine_penalty: float = 16.0
    # how long a tripped peer's MSG_VOTES frames are dropped at ingest
    quarantine_secs: float = 30.0
    # circuit breaker: judged-event window with exponential decay —
    # once a peer's window holds >= min_samples and bad/total >=
    # max_bad_rate, the peer is quarantined. Judged events are kept
    # ingests + breaker-reason drops + attributed verdicts; the window
    # halves (count and bad together, preserving the ratio) whenever it
    # reaches `window`, so old behavior ages out instead of pinning a
    # reformed peer at its worst hour.
    window: int = 256
    min_samples: int = 32
    max_bad_rate: float = 0.5
    # stale-height pre-check slack: a vote whose height is more than
    # this many blocks behind our state is dropped before the pool.
    # Generous by default — watchdog re-offers and catch-up regossip
    # legitimately carry somewhat-old heights; the byzantine stale
    # spammer is hundreds of blocks behind.
    stale_height_slack: int = 32
    # count same-peer identical re-sends toward the breaker. Off by
    # default (see module docstring: watchdog re-offers are honest
    # same-peer repeats); replay-flood drills opt in.
    quarantine_replays: bool = False
    replay_min_samples: int = 256
    replay_max_rate: float = 0.9


class _PeerRecord:
    __slots__ = (
        "node_id", "relayed", "invalid", "strikes", "quarantines",
        "sync_strikes", "drops", "quarantined_until",
        "win_events", "win_bad", "win_replay",
    )

    def __init__(self, node_id: str):
        self.node_id = node_id
        self.relayed = 0  # votes this peer delivered that we kept
        self.invalid = 0  # device verdicts valid=False attributed here
        self.strikes = 0  # invalid verdicts + breaker trips + sync strikes
        self.quarantines = 0
        self.sync_strikes = 0
        self.drops: dict[str, int] = {}
        self.quarantined_until = 0.0
        self.win_events = 0
        self.win_bad = 0
        self.win_replay = 0


class ByzantineLedger:
    def __init__(
        self,
        cfg: ByzantineConfig | None = None,
        scoreboard=None,  # PeerScoreBoard | None (wired post-health)
        metrics_registry=None,
    ):
        self.cfg = cfg or ByzantineConfig()
        self.scoreboard = scoreboard
        self.metrics = ByzantineMetrics(metrics_registry)
        # committee fraction for the breaker thresholds (1.0 = full-set
        # mode). Stored as a FRACTION, not precomputed thresholds: the
        # soak/drill rigs arm the breaker by mutating cfg.min_samples at
        # runtime, so the effective values must be derived from the live
        # cfg at judge time — see _eff_thresholds / committee_rescale.
        self._committee_frac = 1.0
        self._mtx = make_lock("health.ByzantineLedger._mtx")
        # peer records + pid map + totals + committee fraction: gossip
        # receive threads, the engine route tail, the sync client, and
        # the node's epoch thread all cross here
        self._sh_state = shared_field("health.ByzantineLedger.records")  # txlint: shared(self._mtx)
        self._peers: dict[str, _PeerRecord] = {}
        self._pids: dict[int, str] = {}  # pool sender id -> node_id
        # process totals (cheap snapshot without walking peers)
        self._total_strikes = 0
        self._total_quarantines = 0
        self._total_pre_drops = 0

    # -- committee scaling (epoch boundary, committee mode only) --

    def committee_rescale(self, fraction: float) -> tuple[int, float]:
        """Restate the breaker thresholds in committee terms: when only
        ``fraction`` of validators sign tx votes, a flooding peer's
        judged-event stream shrinks by the same fraction, so the
        configured full-set thresholds would take 1/fraction as long to
        trip. Scale ``min_samples`` and ``max_bad_rate`` by the
        committee fraction (floors keep the breaker from turning
        hair-triggered at tiny committees). Called by the node at each
        epoch boundary with ``committee.size / full_set.size``; a
        fraction >= 1.0 (full-set mode) restores the configured values.
        Returns the effective ``(min_samples, max_bad_rate)``."""
        f = min(max(float(fraction), 0.0), 1.0)
        with self._mtx:
            self._sh_state.note_write()
            self._committee_frac = f
            return self._eff_thresholds_locked()

    def _eff_thresholds_locked(self) -> tuple[int, float]:
        """Under _mtx (``_committee_frac`` is written by the node's epoch
        thread while gossip receive threads judge — the race auditor
        caught the old unlocked read here): effective breaker thresholds
        under the current committee fraction, derived from the LIVE cfg
        values (drills arm the breaker by mutating cfg mid-run)."""
        f = self._committee_frac
        if f >= 1.0:
            return self.cfg.min_samples, self.cfg.max_bad_rate
        return (
            max(_MIN_SAMPLES_FLOOR, int(round(self.cfg.min_samples * f))),
            max(_BAD_RATE_FLOOR, self.cfg.max_bad_rate * f),
        )

    # -- peer identity --

    def register_peer(self, pid: int, node_id: str) -> None:
        """Bind a pool sender id (the reactor's small int) to the peer's
        node_id so engine-side verdict attribution can reach the
        scoreboard, which keys on node ids."""
        with self._mtx:
            self._sh_state.note_write()
            self._pids[pid] = node_id
            if node_id not in self._peers:
                self._peers[node_id] = _PeerRecord(node_id)

    def _rec(self, node_id: str) -> _PeerRecord:
        rec = self._peers.get(node_id)
        if rec is None:
            rec = self._peers[node_id] = _PeerRecord(node_id)
        return rec

    # -- quarantine gate (reactor front door, O(1)) --

    def quarantined(self, node_id: str, now: float | None = None) -> bool:
        if now is None:
            now = monotonic()
        with self._mtx:
            self._sh_state.note_read()
            rec = self._peers.get(node_id)
            return rec is not None and now < rec.quarantined_until

    # -- ingest accounting (reactor receive path, one call per frame) --

    def note_frame(
        self, node_id: str, kept: int, drops: dict[str, int] | None = None,
        now: float | None = None,
    ) -> None:
        """One gossip frame's verdict from the pre-check filter: `kept`
        votes went on to the pool, `drops` maps reason -> count for the
        rest. Breaker-reason drops count as bad window events."""
        if now is None:
            now = monotonic()
        trip = None
        m = self.metrics
        with self._mtx:
            self._sh_state.note_write()
            rec = self._rec(node_id)
            rec.relayed += kept
            rec.win_events += kept
            if drops:
                for reason, n in drops.items():
                    if n <= 0:
                        continue
                    rec.drops[reason] = rec.drops.get(reason, 0) + n
                    if reason != DROP_QUARANTINED:
                        self._total_pre_drops += n
                    if reason in _BREAKER_REASONS:
                        rec.win_events += n
                        rec.win_bad += n
                    elif reason == DROP_REPLAYED_SIG:
                        rec.win_events += n
                        rec.win_replay += n
                        if self.cfg.quarantine_replays:
                            rec.win_bad += n
            trip = self._judge_locked(rec, now)
        if drops:
            for reason, n in drops.items():
                ctr = m.drop_counters.get(reason)
                if ctr is not None and n > 0:
                    ctr.add(n)
        if trip is not None:
            self._after_trip(trip)

    # -- verdict attribution (engine route tail, one call per batch) --

    def note_invalid_origins(
        self, origins: list[int], now: float | None = None
    ) -> None:
        """Device verdicts: each entry is the pool sender id that
        originated one valid=False vote. Unknown / local origins (id 0,
        RPC, WAL replay) are skipped — there is no peer to strike."""
        if now is None:
            now = monotonic()
        per_peer: dict[str, int] = {}
        with self._mtx:
            self._sh_state.note_write()
            for pid in origins:
                nid = self._pids.get(pid)
                if nid is None:
                    continue
                per_peer[nid] = per_peer.get(nid, 0) + 1
            trips = []
            for nid, n in per_peer.items():
                rec = self._rec(nid)
                rec.invalid += n
                rec.strikes += n
                self._total_strikes += n
                rec.win_events += n
                rec.win_bad += n
                trip = self._judge_locked(rec, now)
                if trip is not None:
                    trips.append(trip)
        if per_peer:
            n_total = sum(per_peer.values())
            self.metrics.invalid_votes.add(n_total)
            self.metrics.strikes.add(n_total)
            sb = self.scoreboard
            if sb is not None:
                for nid, n in per_peer.items():
                    sb.punish(nid, self.cfg.strike_penalty * n, now=now)
        for trip in trips:
            self._after_trip(trip)

    # -- sync unification (SyncManager._strike byzantine branch) --

    def note_sync_strike(self, node_id: str, now: float | None = None) -> None:
        """A sync server was caught serving forged/truncated data (the
        PR 9 machinery). The sync client keeps its own ban + advert
        bookkeeping AND applies its own scoreboard penalty; this records
        the strike on the unified ledger and quarantines the peer's VOTE
        traffic too — a peer proven to forge certificates has no
        business feeding our verify batches. No scoreboard punish here:
        the caller already did, and double-charging one offense would
        misstate the score history."""
        if now is None:
            now = monotonic()
        with self._mtx:
            self._sh_state.note_write()
            rec = self._rec(node_id)
            rec.sync_strikes += 1
            rec.strikes += 1
            self._total_strikes += 1
            trip = self._trip_locked(rec, now)
        self.metrics.strikes.add(1)
        self._after_trip(trip, punish=False)

    # -- the breaker --

    def _judge_locked(self, rec: _PeerRecord, now: float):
        """Under _mtx: decay the window and trip the breaker if the
        peer's judged-bad fraction crossed the line. Returns the trip
        (node_id) or None; side effects outside the lock."""
        cfg = self.cfg
        trip = None
        if now >= rec.quarantined_until:
            eff_min, eff_rate = self._eff_thresholds_locked()
            bad_trip = (
                rec.win_events >= eff_min
                and rec.win_bad / rec.win_events >= eff_rate
            )
            replay_trip = (
                cfg.quarantine_replays
                and rec.win_events >= cfg.replay_min_samples
                and rec.win_replay / rec.win_events >= cfg.replay_max_rate
            )
            if bad_trip or replay_trip:
                trip = self._trip_locked(rec, now)
        if rec.win_events >= cfg.window:
            # exponential decay, ratio-preserving: old sins age out
            rec.win_events //= 2
            rec.win_bad //= 2
            rec.win_replay //= 2
        return trip

    def _trip_locked(self, rec: _PeerRecord, now: float):
        rec.quarantined_until = now + self.cfg.quarantine_secs
        rec.quarantines += 1
        rec.strikes += 1
        self._total_quarantines += 1
        self._total_strikes += 1
        # fresh window after the sentence: the peer is judged anew
        rec.win_events = rec.win_bad = rec.win_replay = 0
        return rec.node_id

    def _after_trip(self, node_id: str | None, punish: bool = True) -> None:
        if node_id is None:
            return
        self.metrics.quarantines.add(1)
        self.metrics.strikes.add(1)
        sb = self.scoreboard
        if punish and sb is not None:
            sb.punish(node_id, self.cfg.quarantine_penalty)

    # -- introspection (/health "byzantine" section) --

    def strikes_of(self, node_id: str) -> int:
        with self._mtx:
            self._sh_state.note_read()
            rec = self._peers.get(node_id)
            return rec.strikes if rec is not None else 0

    def snapshot(self, now: float | None = None) -> dict:
        if now is None:
            now = monotonic()
        with self._mtx:
            self._sh_state.note_read()
            peers = {}
            quarantined = []
            for nid, rec in self._peers.items():
                q = now < rec.quarantined_until
                if q:
                    quarantined.append(nid)
                if not (
                    rec.strikes or rec.drops or rec.invalid or rec.relayed
                ):
                    continue  # registered but silent: keep /health small
                peers[nid] = {
                    "relayed": rec.relayed,
                    "invalid": rec.invalid,
                    "strikes": rec.strikes,
                    "sync_strikes": rec.sync_strikes,
                    "quarantines": rec.quarantines,
                    "quarantined": q,
                    "drops": dict(rec.drops),
                }
            snap = {
                "strikes": self._total_strikes,
                "quarantines": self._total_quarantines,
                "pre_verify_drops": self._total_pre_drops,
                "quarantined_peers": quarantined,
                "breaker": dict(
                    zip(
                        ("min_samples", "max_bad_rate"),
                        self._eff_thresholds_locked(),
                    )
                ),
                "peers": peers,
            }
        self.metrics.quarantined_peers.set(float(len(quarantined)))
        return snap
