"""Quorum-stall watchdog: targeted anti-entropy for the fast path.

The paper's fast path has no view changes — a tx whose TxVote flood
never reaches 2n/3 stake just sits in the engine's in-flight map forever.
The watchdog detects that (stake not advancing past ``stall_timeout``)
and re-offers what THIS node knows for the stuck tx — its pool votes
(pre-serialized wire segments, joined into one MSG_VOTES frame) and the
raw tx bytes — directly to peers, bypassing the cursor walks' sender
suppression: the suppressed peer may be exactly the one that lost the
frame. Escalation: the first firing targets one peer (round-robin);
while the same tx stays stuck, later firings target every peer. Each
firing re-arms the deadline, so escalation is paced, not a flood.
"""

from __future__ import annotations

import time

from ..p2p.base import CHANNEL_MEMPOOL, CHANNEL_TXVOTE
from ..reactors.mempool_reactor import encode_tx_batch
from ..reactors.txvote_reactor import _MSG_VOTES_B
from .config import HealthConfig
from .registry import DegradedModeRegistry


class _Stall:
    __slots__ = ("first", "since", "stake", "level")

    def __init__(self, now: float, stake: int):
        self.first = now  # stall onset: reported age survives re-arms
        self.since = now
        self.stake = stake
        self.level = 0  # escalation: 0 = one peer, >0 = all peers


class QuorumStallWatchdog:
    def __init__(
        self,
        engine,
        tx_vote_pool,
        mempool,
        switch,
        cfg: HealthConfig,
        registry: DegradedModeRegistry,
    ):
        self.engine = engine
        self.tx_vote_pool = tx_vote_pool
        self.mempool = mempool
        self.switch = switch
        self.cfg = cfg
        self.registry = registry
        self._stalls: dict[str, _Stall] = {}
        self._rr = 0  # round-robin cursor for single-peer re-offers

    def tick(self, now: float | None = None) -> None:
        if now is None:
            now = time.monotonic()
        inflight = self.engine.inflight_snapshot()
        seen = set()
        oldest = 0.0
        for tx_hash, stake in inflight:
            seen.add(tx_hash)
            st = self._stalls.get(tx_hash)
            if st is None:
                self._stalls[tx_hash] = _Stall(now, stake)
                continue
            if stake > st.stake:
                # quorum is advancing: re-arm and de-escalate
                st.stake = stake
                st.first = now
                st.since = now
                st.level = 0
                continue
            oldest = max(oldest, now - st.first)
            if now - st.since >= self.cfg.stall_timeout:
                self._reoffer(tx_hash, st.level)
                st.level += 1
                st.since = now  # pace the escalation ladder
        # committed / purged txs leave the map
        for tx_hash in list(self._stalls):
            if tx_hash not in seen:
                del self._stalls[tx_hash]
        self.registry.set_watchdog_state(len(inflight), oldest)

    # -- the re-offer itself --

    def _reoffer(self, tx_hash: str, level: int) -> None:
        peers = self.switch.peers()
        if not peers:
            return
        if level == 0:
            self._rr += 1
            targets = [peers[self._rr % len(peers)]]
        else:
            targets = peers
        segs = self.tx_vote_pool.segs_for_tx(tx_hash, self.cfg.max_reoffer_votes)
        votes_sent = 0
        if segs:
            frame = _MSG_VOTES_B + b"".join(segs)
            for p in targets:
                if p.try_send(CHANNEL_TXVOTE, frame):
                    votes_sent += len(segs)
        txs_sent = 0
        try:
            tx_key = bytes.fromhex(tx_hash)
        except ValueError:
            tx_key = None
        if tx_key is not None:
            tx = self.mempool.get_tx(tx_key)
            if tx is not None:
                frame = encode_tx_batch([tx])
                for p in targets:
                    if p.try_send(CHANNEL_MEMPOOL, frame):
                        txs_sent += 1
        self.registry.note_watchdog_fired(
            escalated=level > 0, votes=votes_sent, txs=txs_sent
        )
