"""HealthMonitor: the per-node self-healing driver.

One background thread ticks three drives against the node's live
surfaces:

1. the quorum-stall watchdog (engine in-flight map -> targeted vote/tx
   re-offers, watchdog.py);
2. the peer scorer (Peer.stats deltas -> eviction + backoff reconnects,
   peers.py);
3. the degraded-mode registry refresh (verifier counters, progress
   cursors, churn totals -> metrics gauges + the /health snapshot,
   registry.py).

The monitor is assembly-owned (Node builds one when
``NodeConfig.health``), holds no protocol state, and can be stopped or
never started without affecting the data path — healing is strictly
additive: re-offers are dedup'd by receivers, evictions require a
reconnector, and all reads are thread-safe node surfaces.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from .config import HealthConfig
from .peers import PeerScoreBoard
from .registry import DegradedModeRegistry
from .watchdog import QuorumStallWatchdog


class HealthMonitor:
    def __init__(self, node, cfg: HealthConfig | None = None):
        self.node = node
        self.cfg = cfg or HealthConfig()
        self.registry = DegradedModeRegistry(node.metrics_registry)
        self.registry._stall_timeout_hint = self.cfg.stall_timeout
        self.scoreboard = PeerScoreBoard(node.switch, self.cfg, self.registry)
        self.watchdog = QuorumStallWatchdog(
            node.txflow,
            node.tx_vote_pool,
            node.mempool,
            node.switch,
            self.cfg,
            self.registry,
        )
        self._running = threading.Event()
        self._thread: threading.Thread | None = None

    def set_reconnector(self, fn: Callable[[str], bool] | None) -> None:
        """Wire the re-dial hook; eviction stays disabled without one."""
        self.scoreboard.reconnector = fn

    def set_expected_peers(self, node_ids) -> None:
        """Full-mesh roster for redial_lost_peers (see PeerScoreBoard)."""
        self.scoreboard.set_expected(node_ids)

    # -- lifecycle --

    def start(self) -> None:
        if self._running.is_set():
            return
        self._running.set()
        self._thread = threading.Thread(
            target=self._run, name=f"health-{self.node.node_id}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._running.clear()
        t = self._thread
        self._thread = None
        if t is not None:
            t.join(timeout=2)

    def _run(self) -> None:
        cfg = self.cfg
        while self._running.is_set():
            now = time.monotonic()
            try:
                if cfg.watchdog:
                    self.watchdog.tick(now)
                if cfg.peer_scoring:
                    self.scoreboard.tick(now)
                self.registry.refresh(self.node)
            except Exception:
                # the healer must never kill itself on a transient race
                # with node shutdown; next tick re-reads everything
                pass
            time.sleep(cfg.tick_interval)

    # -- operator surface --

    def snapshot(self) -> dict:
        return self.registry.snapshot(peer_scores=self.scoreboard.scores())
