"""DegradedModeRegistry: one aggregation point for "how degraded is this
node" — ResilientVoteVerifier counters, quorum-stall watchdog firings,
and peer churn — mirrored into ``utils.metrics`` health gauges and
snapshotted as the RPC ``/health`` payload.

The registry owns no threads: the HealthMonitor tick calls ``refresh``
and the watchdog / peer scorer call the ``note_*`` event hooks. Events
are double-counted on purpose into both plain ints (cheap snapshot) and
the metrics registry (Prometheus exposition) so ``/health`` and
``/metrics`` can never disagree about totals.
"""

from __future__ import annotations

import threading

from ..analysis.lockgraph import make_lock

from ..utils.metrics import HealthMetrics, NetMetrics, Registry, ScenarioMetrics


class DegradedModeRegistry:
    def __init__(self, metrics_registry: Registry):
        self.metrics = HealthMetrics(metrics_registry)
        self.net_metrics = NetMetrics(metrics_registry)
        self.scenario_metrics = ScenarioMetrics(metrics_registry)
        self._mtx = make_lock("health.DegradedModeRegistry._mtx")
        # event totals (watchdog + peer scorer hooks)
        self.watchdog_firings = 0
        self.watchdog_escalations = 0
        self.reoffered_votes = 0
        self.reoffered_txs = 0
        self.peer_evictions = 0
        self.peer_reconnects = 0
        self.reconnect_failures = 0
        # state refreshed each tick
        self._progress: dict = {}
        self._verifier: dict = {}
        self._peers: dict = {}
        self._epoch: dict = {}
        self._sync: dict = {}
        self._storage: dict = {}
        self._network: dict = {}
        self._byzantine: dict = {}
        self._scenario: dict = {}
        self._watchdog_state: dict = {"inflight": 0, "oldest_stall_age": 0.0}
        self._healthy = True

    # -- event hooks --

    def note_watchdog_fired(self, escalated: bool, votes: int, txs: int) -> None:
        with self._mtx:
            self.watchdog_firings += 1
            if escalated:
                self.watchdog_escalations += 1
            self.reoffered_votes += votes
            self.reoffered_txs += txs
        m = self.metrics
        m.watchdog_firings.add(1)
        if escalated:
            m.watchdog_escalations.add(1)
        if votes:
            m.reoffered_votes.add(votes)
        if txs:
            m.reoffered_txs.add(txs)

    def note_peer_evicted(self) -> None:
        with self._mtx:
            self.peer_evictions += 1
        self.metrics.peer_evictions.add(1)

    def note_peer_reconnected(self) -> None:
        with self._mtx:
            self.peer_reconnects += 1
        self.metrics.peer_reconnects.add(1)

    def note_reconnect_failed(self) -> None:
        with self._mtx:
            self.reconnect_failures += 1
        self.metrics.reconnect_failures.add(1)

    def set_scenario(self, info: dict | None) -> None:
        """Publish (or clear, with ``None``/``{}``) the scenario-grid
        tile currently driving this node (scenario/ runner, via the
        procnode ``{"cmd": "scenario"}`` control). The dict lands
        verbatim as the ``/health`` "scenario" section; the numeric
        shape is mirrored into the ``txflow_scenario_*`` gauges."""
        info = dict(info or {})
        with self._mtx:
            self._scenario = info
        self.scenario_metrics.refresh_from(info)

    # -- tick refresh --

    def set_watchdog_state(self, inflight: int, oldest_stall_age: float) -> None:
        with self._mtx:
            self._watchdog_state = {
                "inflight": inflight,
                "oldest_stall_age": round(oldest_stall_age, 3),
            }
        self.metrics.inflight_txs.set(inflight)
        self.metrics.oldest_stall_age.set(oldest_stall_age)

    def refresh(self, node) -> None:
        """Pull the per-subsystem progress signals off the node. Runs on
        the monitor thread; every read below is a thread-safe node
        surface (pool seq counters, metrics gauges, switch peer list)."""
        verifier = getattr(node.txflow, "verifier", None)
        vstate: dict = {}
        if verifier is not None and hasattr(verifier, "device_healthy"):
            vstate = {
                "device_healthy": bool(verifier.device_healthy),
                "demotions": verifier.demotions,
                "repromotions": verifier.repromotions,
                "device_failures": verifier.device_failures,
                "fallback_calls": verifier.fallback_calls,
                "last_error": repr(verifier.last_error)
                if verifier.last_error is not None
                else None,
            }
            m = self.metrics
            m.verifier_demotions.set(vstate["demotions"])
            m.verifier_repromotions.set(vstate["repromotions"])
            m.verifier_device_failures.set(vstate["device_failures"])
            m.verifier_fallback_calls.set(vstate["fallback_calls"])
            m.verifier_device_healthy.set(1.0 if vstate["device_healthy"] else 0.0)
        n_peers = node.switch.n_peers()
        self.metrics.n_peers.set(n_peers)
        # network weather (p2p/adaptive.py + netem/): per-peer RTT/loss/
        # backlog, quarantine state, and shaper counters — republished as
        # txflow_net_* gauges and the /health "network" section
        network: dict = {}
        net_snapshot = getattr(node.switch, "net_snapshot", None)
        if net_snapshot is not None:
            network = net_snapshot()
            self.net_metrics.refresh_from(network)
        progress = {
            "fast_path_height": node.committed_height_view,
            "consensus_height": (
                node.consensus.state.last_block_height
                if node.consensus is not None
                else None
            ),
            "mempool_seq": node.mempool.seq(),
            "mempool_size": node.mempool.size(),
            "txvote_seq": node.tx_vote_pool.seq(),
            "txvotepool_size": node.tx_vote_pool.size(),
            "committed_txs": int(node.metrics.committed_txs.value()),
        }
        pipe = getattr(node.txflow, "pipeline_stats", None)
        if pipe is not None:
            # verify-pipeline health: a collapsing overlap ratio with a
            # healthy device lane means the engine is host-bound, not
            # device-bound — a different remediation than demotion
            stats = pipe()
            progress["pipeline"] = stats
            if stats["overlap_ratio"] is not None:
                self.metrics.pipeline_overlap.set(stats["overlap_ratio"])
            self.metrics.pipeline_depth_now.set(stats.get("depth") or 0)
            # shape-lifecycle health: sustained cold-fallback growth means
            # the warmer is behind (or wedged) and the node is serving on
            # the slow path — visible here before throughput graphs sag
            coalesce = stats.get("coalesce")
            if coalesce is not None:
                self.metrics.warmup_cold_votes.set(
                    coalesce.get("cold_fallback_votes", 0)
                )
        # the liveness verdict: degraded when the device lane is demoted,
        # a tx has been stalled past ~2 deadlines, or the node has no
        # peers while work is pending
        # validator-set lifecycle (epoch/): operators read slash events
        # and the current epoch from /health without scraping /metrics
        em = getattr(node, "epoch_manager", None)
        epoch_state = em.snapshot() if em is not None else {}
        rot = getattr(node.txflow, "last_rotation", None)
        if rot is not None:
            epoch_state["last_engine_rotation"] = dict(rot)
        # catch-up sync (sync/manager.py): lag + state machine snapshot.
        # "syncing" is self-healing and stays healthy; "fallback" means
        # no peer can serve this node — degraded until the consensus
        # block path (or a recovered peer) closes the gap
        sm = getattr(node, "sync_manager", None)
        sync_state = sm.snapshot() if sm is not None else {}
        # accountable vote gossip (health/byzantine.py): the unified
        # strike ledger — gossip verdict strikes, pre-verify drops by
        # reason, sync forgery strikes, and active quarantines — in one
        # section, so "who is attacking this node and what is it
        # costing" never requires correlating three subsystems
        bl = getattr(node, "byzantine_ledger", None)
        byz_state = bl.snapshot() if bl is not None else {}
        # durable-path degradation (engine save / pool WALs): a node that
        # cannot persist commits is loudly degraded, never silently lossy
        storage_state = {
            "degraded": bool(getattr(node.txflow, "storage_degraded", False)),
            "errors": getattr(node.txflow, "storage_errors", 0),
            "last_error": getattr(node.txflow, "storage_last_error", ""),
            "mempool_wal_degraded": bool(getattr(node.mempool, "wal_degraded", False)),
            "txvote_wal_degraded": bool(
                getattr(node.tx_vote_pool, "wal_degraded", False)
            ),
        }
        storage_degraded = (
            storage_state["degraded"]
            or storage_state["mempool_wal_degraded"]
            or storage_state["txvote_wal_degraded"]
        )
        stalled = self._watchdog_state["oldest_stall_age"]
        healthy = (
            (not vstate or vstate["device_healthy"])
            and stalled < 2 * max(self._stall_timeout_hint, 0.001)
            and not (n_peers == 0 and progress["txvotepool_size"] > 0)
            and sync_state.get("state") != "fallback"
            and not storage_degraded
        )
        with self._mtx:
            self._progress = progress
            self._verifier = vstate
            self._peers = {"n_peers": n_peers}
            self._epoch = epoch_state
            self._sync = sync_state
            self._storage = storage_state
            self._network = network
            self._byzantine = byz_state
            self._healthy = healthy
        self.metrics.healthy.set(1.0 if healthy else 0.0)

    _stall_timeout_hint: float = 2.0  # monitor sets this from its config

    # -- snapshots --

    @property
    def healthy(self) -> bool:
        with self._mtx:
            return self._healthy

    def snapshot(self, peer_scores: dict | None = None) -> dict:
        with self._mtx:
            return {
                "healthy": self._healthy,
                "watchdog": {
                    "firings": self.watchdog_firings,
                    "escalations": self.watchdog_escalations,
                    "reoffered_votes": self.reoffered_votes,
                    "reoffered_txs": self.reoffered_txs,
                    **self._watchdog_state,
                },
                "peers": {
                    **self._peers,
                    "evictions": self.peer_evictions,
                    "reconnects": self.peer_reconnects,
                    "reconnect_failures": self.reconnect_failures,
                    "scores": peer_scores or {},
                },
                "verifier": dict(self._verifier),
                "progress": dict(self._progress),
                "epoch": dict(self._epoch),
                "sync": dict(self._sync),
                "storage": dict(self._storage),
                "network": dict(self._network),
                "byzantine": dict(self._byzantine),
                "scenario": dict(self._scenario),
            }
