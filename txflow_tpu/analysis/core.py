"""txlint core: violation model, suppression parsing, pass driver.

A *pass* inspects one parsed module and yields ``Violation`` objects; the
driver attaches suppressions and splits the result into active vs
suppressed. Suppressions are source comments:

    <flagged line>  # txlint: allow(lock-blocking) -- one-line justification

- the comment suppresses the named rule(s) (comma-separated, or ``*``)
  for any violation whose flagged node overlaps that physical line;
- the ``-- justification`` part is REQUIRED: an allow() without one is
  itself a violation (rule ``bad-suppression``), so every suppression in
  the tree documents why the invariant doesn't apply. Unknown rule ids
  are flagged the same way.

Passes are registered in ``passes.py`` / ``twins.py``; ``tools/lint.py``
is the CLI and ``tests/test_lint.py`` the tier-1 gate.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path

RULES = {
    "lock-blocking": "blocking call while holding a Lock/RLock",
    "nondeterminism": "wall-clock/rng/set-order dependence in a consensus-critical module",
    "thread-join": "Thread neither daemonized nor joined on a stop()/close() path",
    "hotpath-sync": "host-sync / recompile hazard inside a pipelined engine loop",
    "unlocked-lru": "direct UnlockedLRUCache construction outside utils.cache.make_lru",
    "trace-clock": "raw time.* timestamp source in a traced hot-path module (use the utils.clock seam)",
    "twin-path": "hand-synced twin changed without its registered parity test",
    "bad-suppression": "txlint suppression without a justification or with an unknown rule",
    "host-sync": "implicit device->host sync in a hot module outside the sanctioned readback seams",
    "recompile-hazard": "dispatch shape arg does not provably flow from the bucket ladder / warm registry",
    "seed-domain": "inline PRNG domain literal outside the utils.domains registry (or a duplicate tag)",
    "shared-decl": "shared_field() without a valid `# txlint: shared(...)` intent annotation (or a dangling one)",
    "stale-suppression": "txlint allow() comment that no longer suppresses anything",
}

_ALLOW_RE = re.compile(
    r"#\s*txlint:\s*allow\(([^)]*)\)(?:\s*--\s*(\S.*))?"
)


@dataclasses.dataclass
class Violation:
    rule: str
    path: str  # repo-relative
    line: int
    message: str
    suppressed: bool = False
    justification: str = ""

    def format(self) -> str:
        tag = " [suppressed]" if self.suppressed else ""
        return f"{self.path}:{self.line}: {self.rule}: {self.message}{tag}"


@dataclasses.dataclass
class _Suppression:
    line: int
    rules: set[str]  # {"*"} = all
    justification: str
    used: bool = False  # matched at least one flagged (rule, line)

    def covers(self, rule: str) -> bool:
        return "*" in self.rules or rule in self.rules


class ModuleSource:
    """One parsed module: source text, AST, and its suppression table."""

    def __init__(self, path: str, text: str):
        self.path = path  # repo-relative, forward slashes
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.suppressions: list[_Suppression] = []
        self.suppression_errors: list[Violation] = []
        doc_lines = _docstring_lines(self.tree)
        for i, line in enumerate(self.lines, 1):
            if i in doc_lines:
                continue  # a docstring EXAMPLE must never suppress (or go stale)
            m = _ALLOW_RE.search(line)
            if m is None:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            just = (m.group(2) or "").strip()
            bad = [r for r in rules if r != "*" and r not in RULES]
            if not just:
                self.suppression_errors.append(
                    Violation(
                        "bad-suppression", path, i,
                        "allow() needs a justification after `--`, e.g. "
                        "allow(lock-blocking) -- write lock exists to serialize this",
                    )
                )
            elif bad:
                self.suppression_errors.append(
                    Violation(
                        "bad-suppression", path, i,
                        f"unknown rule id(s) {sorted(bad)} in allow()",
                    )
                )
            else:
                self.suppressions.append(_Suppression(i, rules, just))

    def suppression_for(
        self, rule: str, lineno: int, end_lineno: int | None = None
    ) -> _Suppression | None:
        """A suppression covers a violation when it sits on any physical
        line the flagged node spans (clamped to a few lines so a comment
        deep inside a big block can't blanket the whole block)."""
        end = min(end_lineno or lineno, lineno + 4)
        for s in self.suppressions:
            if lineno <= s.line <= end and s.covers(rule):
                s.used = True
                return s
        return None

    def stale_suppressions(self) -> list[Violation]:
        """allow() comments that matched nothing this run — dead weight
        that silently blankets whatever lands on that line next. Only
        meaningful after the FULL default pass set ran."""
        return [
            Violation(
                "stale-suppression", self.path, s.line,
                f"allow({', '.join(sorted(s.rules))}) suppresses nothing — "
                "the flagged code moved or was fixed; delete the comment "
                "(tools/lint.py --prune-suppressions)",
            )
            for s in self.suppressions
            if not s.used
        ]

    def line_suppressed(self, rule: str, lineno: int) -> bool:
        return self.suppression_for(rule, lineno) is not None


def _docstring_lines(tree: ast.AST) -> set[int]:
    """Physical lines covered by module/class/function docstrings."""
    out: set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(
            node, (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            continue
        body = getattr(node, "body", [])
        if (
            body
            and isinstance(body[0], ast.Expr)
            and isinstance(body[0].value, ast.Constant)
            and isinstance(body[0].value.value, str)
        ):
            doc = body[0].value
            out.update(range(doc.lineno, (doc.end_lineno or doc.lineno) + 1))
    return out


class LintPass:
    """Base: subclasses set ``name`` and implement run(module) -> list."""

    name = "base"

    def run(self, module: ModuleSource) -> list[Violation]:  # pragma: no cover
        raise NotImplementedError

    def finalize(self, repo_root: Path) -> list[Violation]:
        """Tree-level checks after every module ran (twin pins)."""
        return []


def default_passes() -> list[LintPass]:
    from . import passes as _p
    from .twins import TwinPathPass

    return [
        _p.LockDisciplinePass(),
        _p.DeterminismPass(),
        _p.ThreadLifecyclePass(),
        _p.HotPathPass(),
        _p.UnlockedLRUPass(),
        _p.TraceClockPass(),
        _p.HostSyncPass(),
        _p.RecompileHazardPass(),
        _p.SeedDomainPass(),
        _p.SharedDeclPass(),
        TwinPathPass(),
    ]


def iter_source_files(repo_root: Path) -> list[Path]:
    """The lint scope: the package itself. Tests/tools/bench are allowed
    to sleep, join, and use wall clocks freely."""
    pkg = repo_root / "txflow_tpu"
    return sorted(p for p in pkg.rglob("*.py"))


def lint_tree(
    repo_root: Path, lint_passes: list[LintPass] | None = None
) -> dict:
    """Run all passes over the tree. Returns a report dict:
    {"violations": [...active...], "suppressed": [...], "errors": [...],
    "files_scanned": n}."""
    repo_root = Path(repo_root)
    # stale-suppression only means something when every pass that could
    # consume a suppression actually ran
    check_stale = lint_passes is None
    lint_passes = lint_passes if lint_passes is not None else default_passes()
    active: list[Violation] = []
    suppressed: list[Violation] = []
    errors: list[str] = []
    n_files = 0
    for path in iter_source_files(repo_root):
        rel = path.relative_to(repo_root).as_posix()
        try:
            module = ModuleSource(rel, path.read_text())
        except SyntaxError as e:  # pragma: no cover - tree always parses
            errors.append(f"{rel}: syntax error: {e}")
            continue
        n_files += 1
        active.extend(module.suppression_errors)
        for p in lint_passes:
            for v in p.run(module):
                s = module.suppression_for(v.rule, v.line)
                if s is not None:
                    v.suppressed = True
                    v.justification = s.justification
                    suppressed.append(v)
                else:
                    active.append(v)
        if check_stale:
            active.extend(module.stale_suppressions())
    for p in lint_passes:
        active.extend(p.finalize(repo_root))
    active.sort(key=lambda v: (v.path, v.line, v.rule))
    suppressed.sort(key=lambda v: (v.path, v.line, v.rule))
    return {
        "violations": active,
        "suppressed": suppressed,
        "errors": errors,
        "files_scanned": n_files,
    }


def lint_source(
    text: str, virtual_path: str, lint_passes: list[LintPass] | None = None
) -> tuple[list[Violation], list[Violation]]:
    """Lint one source string as if it lived at virtual_path (module-scoped
    passes key off the path). Fixture-test entry point. Returns
    (active, suppressed)."""
    module = ModuleSource(virtual_path, text)
    check_stale = lint_passes is None
    lint_passes = lint_passes if lint_passes is not None else default_passes()
    active: list[Violation] = list(module.suppression_errors)
    suppressed: list[Violation] = []
    for p in lint_passes:
        for v in p.run(module):
            s = module.suppression_for(v.rule, v.line)
            if s is not None:
                v.suppressed = True
                v.justification = s.justification
                suppressed.append(v)
            else:
                active.append(v)
    if check_stale:
        active.extend(module.stale_suppressions())
    return active, suppressed


def report_to_json(report: dict) -> dict:
    return {
        "files_scanned": report["files_scanned"],
        "errors": report["errors"],
        "counts": _counts(report["violations"]),
        "suppressed_counts": _counts(report["suppressed"]),
        "violations": [dataclasses.asdict(v) for v in report["violations"]],
        "suppressed": [dataclasses.asdict(v) for v in report["suppressed"]],
    }


def _counts(violations: list[Violation]) -> dict:
    out: dict[str, int] = {}
    for v in violations:
        out[v.rule] = out.get(v.rule, 0) + 1
    return out
