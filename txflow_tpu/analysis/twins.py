"""twin-path: pin hand-synced duplicate logic to its parity tests.

The pools deliberately keep an inlined, non-raising batch twin of their
scalar ingest path (``check_tx_many`` vs ``check_tx``/``_ingest_locked``
— see the 64-item lock-group rationale in pool/txvotepool.py). The twins
MUST evolve together, and the only mechanical guard is the parity tests
that replay both paths against each other.

This pass pins each twin function's AST fingerprint together with its
registered parity test file's content hash in ``twins.json`` (committed).
If a twin function changes while every registered parity test file is
byte-identical to the pinned state, the lint fails: whoever edited the
twin must extend/touch the parity tests, then re-record with
``tools/lint.py --update-pins``. Any other drift from the pinned state
(parity file changed, function renamed/moved) also fails, with a message
pointing at ``--update-pins`` — the pin file is an acknowledgment log,
so it must be rewritten in the same change.

Fingerprints are ``ast.dump`` hashes (no line numbers), so moving a twin
within its file or editing unrelated code never trips the rule.
"""

from __future__ import annotations

import ast
import hashlib
import json
from pathlib import Path

from .core import LintPass, Violation

PIN_FILE = Path(__file__).with_name("twins.json")


def _func_fingerprint(repo_root: Path, spec: str) -> str | None:
    """spec = "rel/path.py::ClassName.func" or "rel/path.py::func"."""
    rel, _, qual = spec.partition("::")
    path = repo_root / rel
    if not path.exists():
        return None
    tree = ast.parse(path.read_text(), filename=rel)
    parts = qual.split(".")
    node: ast.AST = tree
    for p in parts:
        found = None
        for child in getattr(node, "body", []):
            if (
                isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
                and child.name == p
            ):
                found = child
                break
        if found is None:
            return None
        node = found
    return hashlib.sha256(ast.dump(node).encode()).hexdigest()


def _file_fingerprint(repo_root: Path, rel: str) -> str | None:
    path = repo_root / rel
    if not path.exists():
        return None
    return hashlib.sha256(path.read_bytes()).hexdigest()


def load_pins(pin_file: Path = PIN_FILE) -> dict:
    if not pin_file.exists():
        return {"twins": {}}
    return json.loads(pin_file.read_text())


def update_pins(repo_root: Path, pin_file: Path = PIN_FILE) -> dict:
    """Recompute every fingerprint in the pin file from the current tree
    and rewrite it (the acknowledgment step after a twin+test change)."""
    pins = load_pins(pin_file)
    for twin in pins["twins"].values():
        for spec in twin["functions"]:
            twin["functions"][spec] = _func_fingerprint(repo_root, spec)
        for rel in twin["parity_tests"]:
            twin["parity_tests"][rel] = _file_fingerprint(repo_root, rel)
    pin_file.write_text(json.dumps(pins, indent=2, sort_keys=True) + "\n")
    return pins


class TwinPathPass(LintPass):
    name = "twin-path"

    def __init__(self, pin_file: Path = PIN_FILE):
        self.pin_file = pin_file

    def run(self, module):  # file-level pass: everything happens in finalize
        return []

    def finalize(self, repo_root: Path) -> list[Violation]:
        pins = load_pins(self.pin_file)
        out: list[Violation] = []
        pin_rel = self.pin_file.name
        for twin_name, twin in pins.get("twins", {}).items():
            changed_funcs: list[str] = []
            missing: list[str] = []
            for spec, pinned in twin["functions"].items():
                now = _func_fingerprint(repo_root, spec)
                if now is None:
                    missing.append(spec)
                elif now != pinned:
                    changed_funcs.append(spec)
            tests_changed = False
            for rel, pinned in twin["parity_tests"].items():
                now = _file_fingerprint(repo_root, rel)
                if now is None:
                    missing.append(rel)
                elif now != pinned:
                    tests_changed = True
            if missing:
                out.append(
                    Violation(
                        "twin-path", pin_rel, 1,
                        f"twin '{twin_name}': pinned target(s) not found: "
                        f"{missing} — fix the spec in analysis/twins.json and "
                        "run tools/lint.py --update-pins",
                    )
                )
                continue
            if changed_funcs and not tests_changed:
                out.append(
                    Violation(
                        "twin-path", pin_rel, 1,
                        f"twin '{twin_name}' changed ({changed_funcs}) but its "
                        f"parity tests {list(twin['parity_tests'])} are "
                        "byte-identical to the pinned state — hand-synced twins "
                        "must be re-proven: update the parity tests, then run "
                        "tools/lint.py --update-pins",
                    )
                )
            elif changed_funcs or tests_changed:
                out.append(
                    Violation(
                        "twin-path", pin_rel, 1,
                        f"twin '{twin_name}' pins are stale (functions changed: "
                        f"{changed_funcs or 'no'}, parity tests changed: "
                        f"{tests_changed}) — run tools/lint.py --update-pins to "
                        "acknowledge the paired change",
                    )
                )
        return out
