"""txlint static passes (see core.RULES for the rule inventory).

Every pass is heuristic AST analysis tuned to THIS repo's idioms — lock
attributes are named ``*_mtx``/``*_lock``/``*_cond``, blocking surfaces
are a known vocabulary (ticket.result, sendall, check_tx_sync, save_tx,
...), hot loops live in named TxFlow methods. The goal is a zero-noise
gate over this tree, not a general-purpose linter: false negatives are
the runtime auditor's job (analysis.lockgraph), false positives are
suppressed inline with a justification.
"""

from __future__ import annotations

import ast
import re

from .core import LintPass, ModuleSource, Violation

# ---------------------------------------------------------------------------
# lock-blocking
# ---------------------------------------------------------------------------

# attribute names that read as a mutex when used in `with ...:`
_LOCK_SEGMENTS = {"mtx", "mu", "lock", "rlock", "wlock", "lk", "cv", "cond", "condition"}

# receiver-name patterns
_QUEUE_RE = re.compile(r"(^|[._])(q|queue|jobs|inbox|outbox)$|queue", re.I)
_SOCKISH_RE = re.compile(r"sock|conn|peer", re.I)
_WAL_RE = re.compile(r"wal", re.I)

# method names that are a blocking round trip / durability point wherever
# they appear (socket ABCI calls, store writes, pool condition waits)
_BLOCKING_ATTRS = {
    "check_tx_sync": "ABCI CheckTx round trip",
    "deliver_tx_sync": "ABCI DeliverTx round trip",
    "commit_sync": "ABCI Commit round trip",
    "flush_sync": "ABCI Flush round trip",
    "query_sync": "ABCI Query round trip",
    "info_sync": "ABCI Info round trip",
    "apply_tx": "ABCI apply round trip",
    "apply_tx_batch": "ABCI apply round trip",
    "save_tx": "store write (fsync at height edges)",
    "save_txs_batch": "store write (fsync at height edges)",
    "set_many": "db batch write (possible fsync)",
    "mark_block_committed": "store write",
    "wait_for_new": "pool condition wait",
    "block_until_ready": "device sync",
    "sendall": "socket write",
    "recv": "socket read",
    "recv_into": "socket read",
    "accept": "socket accept",
}


def _expr_str(node: ast.AST) -> str:
    """Dotted-name rendering of simple receiver expressions ("self._mtx",
    "self.pool.cache"); empty string for anything fancier."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_lockish(expr: str) -> bool:
    last = expr.rsplit(".", 1)[-1]
    segs = set(last.strip("_").lower().split("_"))
    if segs & _LOCK_SEGMENTS:
        return True
    return last.lower().endswith(("lock", "mtx"))


def _numeric_const(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and isinstance(node.value, (int, float))


def _blocking_reason(call: ast.Call, held: tuple[str, ...]) -> str | None:
    """Why this call is blocking, or None. `held` = dotted lock exprs of
    the enclosing with-blocks (used to allow cond.wait on the held cond)."""
    func = call.func
    if isinstance(func, ast.Name):
        if func.id == "sleep":
            return "sleep()"
        return None
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    recv = _expr_str(func.value)
    if attr == "sleep":
        return f"{recv or '?'}.sleep()"
    if attr == "result" and not call.args and not call.keywords:
        return "ticket.result() — blocks on the in-flight device verify"
    if attr in _BLOCKING_ATTRS:
        return f".{attr}() — {_BLOCKING_ATTRS[attr]}"
    if attr == "join":
        # thread-like join: no args, timeout kwarg, or one numeric arg.
        # (str.join / os.path.join always take a non-numeric argument.)
        if not call.args and not call.keywords:
            return ".join() — thread join"
        if any(k.arg == "timeout" for k in call.keywords):
            return ".join(timeout=...) — thread join"
        if len(call.args) == 1 and _numeric_const(call.args[0]):
            return ".join(t) — thread join"
        return None
    if attr == "get" and _QUEUE_RE.search(recv):
        for k in call.keywords:
            if (
                k.arg == "block"
                and isinstance(k.value, ast.Constant)
                and k.value.value is False
            ):
                return None
        return f"{recv}.get() — queue wait"
    if attr == "put" and any(k.arg == "timeout" for k in call.keywords):
        return f"{recv}.put(timeout=...) — bounded queue wait"
    if attr in ("send", "connect") and _SOCKISH_RE.search(recv):
        return f"{recv}.{attr}() — socket/peer I/O"
    if attr == "write" and _WAL_RE.search(recv):
        return f"{recv}.write() — WAL append"
    if attr in ("wait", "wait_for"):
        # cond.wait() on the lock you hold RELEASES it — that's the one
        # sanctioned blocking call under a lock
        if recv and recv in held:
            return None
        return f"{recv or '?'}.{attr}() — event/condition wait"
    return None


class LockDisciplinePass(LintPass):
    """No blocking call while lexically inside `with <lock>:`.

    Two detection layers per class:
    1. direct: a blocking call (vocabulary above) inside a lock scope;
    2. taint: a `self.m()` call inside a lock scope where method `m`
       (fixpoint over same-class `self.` calls) contains an unsuppressed
       blocking call — catching effects buried one or more frames below
       the `with`. Suppressing the seed line sanctions the whole chain.
    """

    name = "lock-blocking"

    def run(self, module: ModuleSource) -> list[Violation]:
        out: list[Violation] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._run_class(module, node))
        # module-level functions (rare; no self-taint possible)
        for node in module.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(self._walk_func(module, node, tainted={}, seeds={}))
        return out

    # -- class-level taint fixpoint --

    def _run_class(self, module: ModuleSource, cls: ast.ClassDef) -> list[Violation]:
        methods = {
            n.name: n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        # seed: method -> (line, reason) of its first unsuppressed blocking call
        seeds: dict[str, tuple[int, str]] = {}
        calls: dict[str, set[str]] = {name: set() for name in methods}
        for name, fn in methods.items():
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                reason = _blocking_reason(sub, held=())
                if reason is not None and not module.line_suppressed(
                    self.name, sub.lineno
                ):
                    seeds.setdefault(name, (sub.lineno, reason))
                f = sub.func
                if (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"
                    and f.attr in methods
                ):
                    calls[name].add(f.attr)
        # fixpoint: tainted = transitively reaches a seed via self. calls
        tainted: dict[str, tuple[int, str]] = dict(seeds)
        changed = True
        while changed:
            changed = False
            for name in methods:
                if name in tainted:
                    continue
                for callee in calls[name]:
                    if callee in tainted:
                        line, reason = tainted[callee]
                        tainted[name] = (line, reason)
                        changed = True
                        break
        out: list[Violation] = []
        for fn in methods.values():
            out.extend(self._walk_func(module, fn, tainted=tainted, seeds=seeds))
        return out

    # -- lexical lock-scope walk --

    def _walk_func(
        self,
        module: ModuleSource,
        fn: ast.AST,
        tainted: dict[str, tuple[int, str]],
        seeds: dict[str, tuple[int, str]],
    ) -> list[Violation]:
        out: list[Violation] = []

        def visit(node: ast.AST, held: tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                if node is not fn:
                    return  # nested defs execute later, outside this scope
            if isinstance(node, ast.With):
                new_held = held
                for item in node.items:
                    expr = _expr_str(item.context_expr)
                    if expr and _is_lockish(expr):
                        new_held = new_held + (expr,)
                for child in ast.iter_child_nodes(node):
                    visit(child, new_held)
                return
            if isinstance(node, ast.Call) and held:
                reason = _blocking_reason(node, held)
                if reason is not None:
                    out.append(
                        Violation(
                            self.name, module.path, node.lineno,
                            f"{reason} while holding {held[-1]}",
                        )
                    )
                else:
                    f = node.func
                    if (
                        isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)
                        and f.value.id == "self"
                        and f.attr in tainted
                    ):
                        line, why = tainted[f.attr]
                        out.append(
                            Violation(
                                self.name, module.path, node.lineno,
                                f"self.{f.attr}() while holding {held[-1]} — "
                                f"reaches blocking {why} (line {line})",
                            )
                        )
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, ())
        return out


# ---------------------------------------------------------------------------
# nondeterminism
# ---------------------------------------------------------------------------

# consensus-critical scope: certificate contents and commit decisions must
# be reproducible across nodes/replays
_DETERMINISM_SCOPE = (
    "txflow_tpu/types/vote_set.py",
    "txflow_tpu/engine/txflow.py",
    "txflow_tpu/consensus/",
    # committee election must be identical on every node — any clock or
    # rng leak here forks the committee (and thus the quorum) silently
    "txflow_tpu/committee/",
)

_CLOCK_SEAM = "txflow_tpu/utils/clock.py"


class DeterminismPass(LintPass):
    """No wall clock, unseeded rng, or set-iteration-order dependence in
    consensus-critical modules, except through the utils.clock seam."""

    name = "nondeterminism"

    def run(self, module: ModuleSource) -> list[Violation]:
        if module.path == _CLOCK_SEAM:
            return []  # the seam itself wraps the wall clock
        if not module.path.startswith(_DETERMINISM_SCOPE):
            return []
        out: list[Violation] = []
        seam_names = self._seam_imports(module)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                out.extend(self._check_call(module, node, seam_names))
            elif isinstance(node, (ast.For, ast.comprehension)):
                it = node.iter
                if _is_set_expr(it):
                    line = getattr(node, "lineno", getattr(it, "lineno", 1))
                    out.append(
                        Violation(
                            self.name, module.path, line,
                            "iteration over a set — order varies per process "
                            "(PYTHONHASHSEED); sort or use an ordered container",
                        )
                    )
        return out

    def _seam_imports(self, module: ModuleSource) -> set[str]:
        """Names bound from utils.clock — calls through them are allowed."""
        names: set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom) and node.module and (
                node.module.endswith("utils.clock") or node.module == "clock"
            ):
                for a in node.names:
                    names.add(a.asname or a.name)
        return names

    def _check_call(
        self, module: ModuleSource, call: ast.Call, seam: set[str]
    ) -> list[Violation]:
        func = call.func
        name = _expr_str(func) if isinstance(func, (ast.Attribute, ast.Name)) else ""
        root = name.split(".", 1)[0]
        if root in seam:
            return []
        if name in ("time.time", "time.time_ns"):
            return [
                Violation(
                    self.name, module.path, call.lineno,
                    f"{name}() in a consensus-critical module — route through "
                    "utils.clock so replays/tests can pin the clock",
                )
            ]
        if root == "random":
            # random.Random(seed) is the sanctioned seeded constructor
            if name == "random.Random" and call.args:
                return []
            return [
                Violation(
                    self.name, module.path, call.lineno,
                    f"{name}() — unseeded process-global rng in a "
                    "consensus-critical module",
                )
            ]
        if root in ("uuid", "secrets") or name == "os.urandom":
            return [
                Violation(
                    self.name, module.path, call.lineno,
                    f"{name}() — nondeterministic value source in a "
                    "consensus-critical module",
                )
            ]
        return []


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in ("set", "frozenset")
    return False


# ---------------------------------------------------------------------------
# thread-join
# ---------------------------------------------------------------------------


class ThreadLifecyclePass(LintPass):
    """Every Thread(...) created in a class must be daemon=True or joined
    somewhere in the same class (stop()/close()/join-on-name)."""

    name = "thread-join"

    def run(self, module: ModuleSource) -> list[Violation]:
        out: list[Violation] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._run_class(module, node))
        return out

    def _run_class(self, module: ModuleSource, cls: ast.ClassDef) -> list[Violation]:
        creations: list[ast.Call] = []
        joins = False
        for sub in ast.walk(cls):
            if isinstance(sub, ast.Call):
                f = sub.func
                fname = _expr_str(f) if isinstance(f, (ast.Attribute, ast.Name)) else ""
                if fname.endswith("Thread") and fname.split(".", 1)[0] in (
                    "threading", "Thread", "_t",
                ):
                    creations.append(sub)
                elif isinstance(f, ast.Attribute) and f.attr == "join":
                    joins = True
        out: list[Violation] = []
        for call in creations:
            daemon = any(
                k.arg == "daemon"
                and isinstance(k.value, ast.Constant)
                and k.value.value is True
                for k in call.keywords
            )
            if daemon or joins:
                continue
            out.append(
                Violation(
                    self.name, module.path, call.lineno,
                    f"Thread created in {cls.name} is neither daemon=True nor "
                    "joined anywhere in the class — a leaked thread outlives "
                    "stop() and keeps the process alive",
                )
            )
        return out


# ---------------------------------------------------------------------------
# hotpath-sync
# ---------------------------------------------------------------------------

# the pipelined engine loops: one host sync here stalls every in-flight
# ticket behind it (COMPONENTS.md "Verify pipeline")
_HOT_FUNCS = {
    "txflow_tpu/engine/txflow.py": {
        "_run_pipelined", "_form_batch", "step", "_prep_batch",
        "_submit_prep", "_collect", "_route_result",
        # lane-split + speculative-commit helpers (ISSUE 12): all run
        # inside the fill/route stages of the pipelined loop
        "_prio_pending", "_bulk_pending", "_bulk_quantum",
        "_steer_lingers", "_sign_bytes_proc",
    },
    # the staging ring's whole point is that the ONLY np.asarray lives
    # in its dedicated readback thread (StageSlot._run): the caller-
    # facing enter/exit paths must never force the transfer themselves,
    # or the ring silently degrades to the synchronous readback it
    # replaced. (StagingRing.submit's bounded semaphore wait is
    # backpressure by contract — this pin is about device syncs, not
    # blocking in general.)
    "txflow_tpu/parallel/staging.py": {"submit", "result"},
}

_HOT_ATTRS = {
    "item": ".item() forces a device->host readback per element",
    "asarray": "np.asarray on a device array is a blocking transfer",
    "device_get": "explicit host readback",
    "block_until_ready": "full device sync",
}


# admit-path functions that must never block: they run inline on every
# RPC handler thread and the gossip receive path, so one blocking call
# stalls the whole front door (the shed path must stay O(1) — that is
# the backpressure contract). Checked against the same blocking-call
# vocabulary as lock-blocking, with NO lock held.
_HOT_NOBLOCK_FUNCS = {
    "txflow_tpu/admission/controller.py": {
        "admit_rpc", "admit_gossip", "lane_of", "overloaded",
        "_bulk_shed", "_bulk_rate_exceeded", "forget", "gossip_paused",
        "_sample_commit_rate", "_effective_bulk_rate", "_peer_rate_exceeded",
        "_priority_sender_exceeded", "_storage_degraded",
    },
    # host-prep pool enqueue: called from inside the engine's batch-prep
    # window on every drain. One job alloc + one lock-free SimpleQueue
    # put — if submit ever grows a lock or a bounded wait, the pool
    # serializes the very path it exists to parallelize.
    "txflow_tpu/engine/hostprep.py": {"submit"},
    # the shaper's send sits INSIDE every switch send-loop iteration: it
    # must only draw from the seeded rng, push onto the delivery heap and
    # return — the wire wait lives in the shaper's own deliver thread.
    # A blocking call here turns weather latency into sender stall.
    "txflow_tpu/netem/shaper.py": {"send", "try_send"},
    # the accountable-gossip ledger sits on the vote-gossip receive path
    # (quarantine gate + per-frame accounting) and the engine's verdict
    # routing (invalid-origin attribution). A Byzantine flood IS the load
    # these run under — a blocking call here hands the attacker a stall
    # primitive on the exact path built to absorb them.
    "txflow_tpu/health/byzantine.py": {
        "quarantined", "note_frame", "note_invalid_origins",
        "register_peer", "note_sync_strike", "strikes_of",
        "_judge_locked", "_trip_locked",
    },
    # committee resolution sits on the vote-gossip pre-check path (the
    # reactor's StateView.committee read resolves through these on every
    # epoch swap) and inside the engine's update_state: a cache miss
    # re-samples with pure sha256 arithmetic — never a lock wait, never
    # I/O. One blocking call here stalls every gossip receive thread at
    # once at the epoch boundary.
    "txflow_tpu/committee/sampler.py": {
        "sample_committee", "committee_seed", "committee_at",
        "for_vote_height", "epoch_for_vote_height",
    },
}


class HotPathPass(LintPass):
    name = "hotpath-sync"

    def run(self, module: ModuleSource) -> list[Violation]:
        hot = _HOT_FUNCS.get(module.path, set())
        noblock = _HOT_NOBLOCK_FUNCS.get(module.path, set())
        if not hot and not noblock:
            return []
        out: list[Violation] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name in hot:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
                        attr = sub.func.attr
                        if attr in _HOT_ATTRS:
                            out.append(
                                Violation(
                                    self.name, module.path, sub.lineno,
                                    f".{attr}() in hot function {node.name}: "
                                    f"{_HOT_ATTRS[attr]}",
                                )
                            )
            if node.name in noblock:
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Call):
                        continue
                    reason = _blocking_reason(sub, held=())
                    if reason is not None:
                        out.append(
                            Violation(
                                self.name, module.path, sub.lineno,
                                f"blocking {reason} in admit-path function "
                                f"{node.name}: the front door must shed, "
                                f"never stall",
                            )
                        )
        return out


# ---------------------------------------------------------------------------
# trace-clock
# ---------------------------------------------------------------------------

# traced hot-path scope: every module the per-tx tracer (trace/) stamps
# spans in. Timestamps here MUST come through the utils.clock seam, or a
# test that pins the clock sees half the spans on a different timeline
# and cross-node merge (trace/export.py) loses alignment. engine/ is
# scoped to the ONE traced file: execution.py keeps perf_counter for its
# untraced ABCI accounting.
_TRACE_SCOPE = (
    "txflow_tpu/engine/txflow.py",
    "txflow_tpu/engine/hostprep.py",
    # the linger controller's cadence gate shares the engine's traced
    # timeline (maybe_observe takes `now` from the caller, but any future
    # internal timestamp must come through the same seam)
    "txflow_tpu/engine/adaptive.py",
    # worker-process prep core: shard busy_s rides the done-queue acks
    # into pool stats that sit next to traced engine spans — same seam
    # so a pinned-clock test keeps both on one timeline
    "txflow_tpu/prep_proc.py",
    # staging-ring overlap ledger (hidden_s/readback_s) is compared
    # against traced device spans in report.py — same seam required
    "txflow_tpu/parallel/staging.py",
    "txflow_tpu/trace/",
    "txflow_tpu/admission/controller.py",
    "txflow_tpu/pool/",
    "txflow_tpu/reactors/",
    "txflow_tpu/sync/",
    # committee sampling + batched cert verify ride the reactor pre-check
    # and sync verify paths above — same traced timeline, same seam
    "txflow_tpu/committee/",
    # weather timestamps (due times, flap schedule) must share the traced
    # timeline: a pinned-clock test that shapes links would otherwise see
    # deliveries scheduled on a clock the spans don't use
    "txflow_tpu/netem/",
    # quarantine expiry and breaker windows live on the gossip receive
    # path's timeline: a pinned-clock drill must be able to walk a peer
    # into and out of quarantine deterministically
    "txflow_tpu/health/byzantine.py",
)

# the forbidden time.* names: every raw timestamp source. time.sleep is
# fine — pacing isn't a span timestamp.
_RAW_CLOCK_NAMES = {
    "time", "time_ns", "monotonic", "monotonic_ns",
    "perf_counter", "perf_counter_ns",
}


class TraceClockPass(LintPass):
    """No raw ``time.*`` timestamp source in a traced hot-path module.

    Flags attribute references (not just calls — passing ``time.monotonic``
    as a callback smuggles the raw clock just as effectively) and
    ``from time import ...`` of the timestamp names. The seam module
    itself (utils/clock.py) is outside the scope by construction."""

    name = "trace-clock"

    def run(self, module: ModuleSource) -> list[Violation]:
        if module.path == "txflow_tpu/utils/clock.py":
            return []  # the seam wraps the raw clock
        if not module.path.startswith(_TRACE_SCOPE):
            return []
        out: list[Violation] = []
        for node in ast.walk(module.tree):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "time"
                and node.attr in _RAW_CLOCK_NAMES
            ):
                out.append(
                    Violation(
                        self.name, module.path, node.lineno,
                        f"time.{node.attr} in a traced hot-path module — "
                        "route through utils.clock so pinned-clock tests and "
                        "cross-node trace merge stay on one timeline",
                    )
                )
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name in _RAW_CLOCK_NAMES:
                        out.append(
                            Violation(
                                self.name, module.path, node.lineno,
                                f"from time import {a.name} in a traced "
                                "hot-path module — route through utils.clock",
                            )
                        )
        return out


# ---------------------------------------------------------------------------
# unlocked-lru
# ---------------------------------------------------------------------------


class UnlockedLRUPass(LintPass):
    """UnlockedLRUCache carries a CPython/GIL safety argument; the ONE
    place allowed to weigh it is utils.cache.make_lru."""

    name = "unlocked-lru"

    def run(self, module: ModuleSource) -> list[Violation]:
        if module.path == "txflow_tpu/utils/cache.py":
            return []
        out: list[Violation] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                f = node.func
                fname = _expr_str(f) if isinstance(f, (ast.Attribute, ast.Name)) else ""
                if fname.rsplit(".", 1)[-1] == "UnlockedLRUCache":
                    out.append(
                        Violation(
                            self.name, module.path, node.lineno,
                            "direct UnlockedLRUCache(...) — construct via "
                            "utils.cache.make_lru so the GIL check lives in "
                            "one place",
                        )
                    )
        return out
