"""txlint static passes (see core.RULES for the rule inventory).

Every pass is heuristic AST analysis tuned to THIS repo's idioms — lock
attributes are named ``*_mtx``/``*_lock``/``*_cond``, blocking surfaces
are a known vocabulary (ticket.result, sendall, check_tx_sync, save_tx,
...), hot loops live in named TxFlow methods. The goal is a zero-noise
gate over this tree, not a general-purpose linter: false negatives are
the runtime auditor's job (analysis.lockgraph), false positives are
suppressed inline with a justification.
"""

from __future__ import annotations

import ast
import re

from .core import LintPass, ModuleSource, Violation

# ---------------------------------------------------------------------------
# lock-blocking
# ---------------------------------------------------------------------------

# attribute names that read as a mutex when used in `with ...:`
_LOCK_SEGMENTS = {"mtx", "mu", "lock", "rlock", "wlock", "lk", "cv", "cond", "condition"}

# receiver-name patterns
_QUEUE_RE = re.compile(r"(^|[._])(q|queue|jobs|inbox|outbox)$|queue", re.I)
_SOCKISH_RE = re.compile(r"sock|conn|peer", re.I)
_WAL_RE = re.compile(r"wal", re.I)

# method names that are a blocking round trip / durability point wherever
# they appear (socket ABCI calls, store writes, pool condition waits)
_BLOCKING_ATTRS = {
    "check_tx_sync": "ABCI CheckTx round trip",
    "deliver_tx_sync": "ABCI DeliverTx round trip",
    "commit_sync": "ABCI Commit round trip",
    "flush_sync": "ABCI Flush round trip",
    "query_sync": "ABCI Query round trip",
    "info_sync": "ABCI Info round trip",
    "apply_tx": "ABCI apply round trip",
    "apply_tx_batch": "ABCI apply round trip",
    "save_tx": "store write (fsync at height edges)",
    "save_txs_batch": "store write (fsync at height edges)",
    "set_many": "db batch write (possible fsync)",
    "mark_block_committed": "store write",
    "wait_for_new": "pool condition wait",
    "block_until_ready": "device sync",
    "sendall": "socket write",
    "recv": "socket read",
    "recv_into": "socket read",
    "accept": "socket accept",
}


def _expr_str(node: ast.AST) -> str:
    """Dotted-name rendering of simple receiver expressions ("self._mtx",
    "self.pool.cache"); empty string for anything fancier."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_lockish(expr: str) -> bool:
    last = expr.rsplit(".", 1)[-1]
    segs = set(last.strip("_").lower().split("_"))
    if segs & _LOCK_SEGMENTS:
        return True
    return last.lower().endswith(("lock", "mtx"))


def _numeric_const(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and isinstance(node.value, (int, float))


def _blocking_reason(call: ast.Call, held: tuple[str, ...]) -> str | None:
    """Why this call is blocking, or None. `held` = dotted lock exprs of
    the enclosing with-blocks (used to allow cond.wait on the held cond)."""
    func = call.func
    if isinstance(func, ast.Name):
        if func.id == "sleep":
            return "sleep()"
        return None
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    recv = _expr_str(func.value)
    if attr == "sleep":
        return f"{recv or '?'}.sleep()"
    if attr == "result" and not call.args and not call.keywords:
        return "ticket.result() — blocks on the in-flight device verify"
    if attr in _BLOCKING_ATTRS:
        return f".{attr}() — {_BLOCKING_ATTRS[attr]}"
    if attr == "join":
        # thread-like join: no args, timeout kwarg, or one numeric arg.
        # (str.join / os.path.join always take a non-numeric argument.)
        if not call.args and not call.keywords:
            return ".join() — thread join"
        if any(k.arg == "timeout" for k in call.keywords):
            return ".join(timeout=...) — thread join"
        if len(call.args) == 1 and _numeric_const(call.args[0]):
            return ".join(t) — thread join"
        return None
    if attr == "get" and _QUEUE_RE.search(recv):
        for k in call.keywords:
            if (
                k.arg == "block"
                and isinstance(k.value, ast.Constant)
                and k.value.value is False
            ):
                return None
        return f"{recv}.get() — queue wait"
    if attr == "put" and any(k.arg == "timeout" for k in call.keywords):
        return f"{recv}.put(timeout=...) — bounded queue wait"
    if attr in ("send", "connect") and _SOCKISH_RE.search(recv):
        return f"{recv}.{attr}() — socket/peer I/O"
    if attr == "write" and _WAL_RE.search(recv):
        return f"{recv}.write() — WAL append"
    if attr in ("wait", "wait_for"):
        # cond.wait() on the lock you hold RELEASES it — that's the one
        # sanctioned blocking call under a lock
        if recv and recv in held:
            return None
        return f"{recv or '?'}.{attr}() — event/condition wait"
    return None


class LockDisciplinePass(LintPass):
    """No blocking call while lexically inside `with <lock>:`.

    Two detection layers per class:
    1. direct: a blocking call (vocabulary above) inside a lock scope;
    2. taint: a `self.m()` call inside a lock scope where method `m`
       (fixpoint over same-class `self.` calls) contains an unsuppressed
       blocking call — catching effects buried one or more frames below
       the `with`. Suppressing the seed line sanctions the whole chain.
    """

    name = "lock-blocking"

    def run(self, module: ModuleSource) -> list[Violation]:
        out: list[Violation] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._run_class(module, node))
        # module-level functions (rare; no self-taint possible)
        for node in module.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(self._walk_func(module, node, tainted={}, seeds={}))
        return out

    # -- class-level taint fixpoint --

    def _run_class(self, module: ModuleSource, cls: ast.ClassDef) -> list[Violation]:
        methods = {
            n.name: n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        # seed: method -> (line, reason) of its first unsuppressed blocking call
        seeds: dict[str, tuple[int, str]] = {}
        calls: dict[str, set[str]] = {name: set() for name in methods}
        for name, fn in methods.items():
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                reason = _blocking_reason(sub, held=())
                if reason is not None and not module.line_suppressed(
                    self.name, sub.lineno
                ):
                    seeds.setdefault(name, (sub.lineno, reason))
                f = sub.func
                if (
                    isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self"
                    and f.attr in methods
                ):
                    calls[name].add(f.attr)
        # fixpoint: tainted = transitively reaches a seed via self. calls
        tainted: dict[str, tuple[int, str]] = dict(seeds)
        changed = True
        while changed:
            changed = False
            for name in methods:
                if name in tainted:
                    continue
                for callee in calls[name]:
                    if callee in tainted:
                        line, reason = tainted[callee]
                        tainted[name] = (line, reason)
                        changed = True
                        break
        out: list[Violation] = []
        for fn in methods.values():
            out.extend(self._walk_func(module, fn, tainted=tainted, seeds=seeds))
        return out

    # -- lexical lock-scope walk --

    def _walk_func(
        self,
        module: ModuleSource,
        fn: ast.AST,
        tainted: dict[str, tuple[int, str]],
        seeds: dict[str, tuple[int, str]],
    ) -> list[Violation]:
        out: list[Violation] = []

        def visit(node: ast.AST, held: tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                if node is not fn:
                    return  # nested defs execute later, outside this scope
            if isinstance(node, ast.With):
                new_held = held
                for item in node.items:
                    expr = _expr_str(item.context_expr)
                    if expr and _is_lockish(expr):
                        new_held = new_held + (expr,)
                for child in ast.iter_child_nodes(node):
                    visit(child, new_held)
                return
            if isinstance(node, ast.Call) and held:
                reason = _blocking_reason(node, held)
                if reason is not None:
                    out.append(
                        Violation(
                            self.name, module.path, node.lineno,
                            f"{reason} while holding {held[-1]}",
                        )
                    )
                else:
                    f = node.func
                    if (
                        isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)
                        and f.value.id == "self"
                        and f.attr in tainted
                    ):
                        line, why = tainted[f.attr]
                        out.append(
                            Violation(
                                self.name, module.path, node.lineno,
                                f"self.{f.attr}() while holding {held[-1]} — "
                                f"reaches blocking {why} (line {line})",
                            )
                        )
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in fn.body:
            visit(stmt, ())
        return out


# ---------------------------------------------------------------------------
# nondeterminism
# ---------------------------------------------------------------------------

# consensus-critical scope: certificate contents and commit decisions must
# be reproducible across nodes/replays
_DETERMINISM_SCOPE = (
    "txflow_tpu/types/vote_set.py",
    "txflow_tpu/engine/txflow.py",
    "txflow_tpu/consensus/",
    # committee election must be identical on every node — any clock or
    # rng leak here forks the committee (and thus the quorum) silently
    "txflow_tpu/committee/",
)

_CLOCK_SEAM = "txflow_tpu/utils/clock.py"


class DeterminismPass(LintPass):
    """No wall clock, unseeded rng, or set-iteration-order dependence in
    consensus-critical modules, except through the utils.clock seam."""

    name = "nondeterminism"

    def run(self, module: ModuleSource) -> list[Violation]:
        if module.path == _CLOCK_SEAM:
            return []  # the seam itself wraps the wall clock
        if not module.path.startswith(_DETERMINISM_SCOPE):
            return []
        out: list[Violation] = []
        seam_names = self._seam_imports(module)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                out.extend(self._check_call(module, node, seam_names))
            elif isinstance(node, (ast.For, ast.comprehension)):
                it = node.iter
                if _is_set_expr(it):
                    line = getattr(node, "lineno", getattr(it, "lineno", 1))
                    out.append(
                        Violation(
                            self.name, module.path, line,
                            "iteration over a set — order varies per process "
                            "(PYTHONHASHSEED); sort or use an ordered container",
                        )
                    )
        return out

    def _seam_imports(self, module: ModuleSource) -> set[str]:
        """Names bound from utils.clock — calls through them are allowed."""
        names: set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom) and node.module and (
                node.module.endswith("utils.clock") or node.module == "clock"
            ):
                for a in node.names:
                    names.add(a.asname or a.name)
        return names

    def _check_call(
        self, module: ModuleSource, call: ast.Call, seam: set[str]
    ) -> list[Violation]:
        func = call.func
        name = _expr_str(func) if isinstance(func, (ast.Attribute, ast.Name)) else ""
        root = name.split(".", 1)[0]
        if root in seam:
            return []
        if name in ("time.time", "time.time_ns"):
            return [
                Violation(
                    self.name, module.path, call.lineno,
                    f"{name}() in a consensus-critical module — route through "
                    "utils.clock so replays/tests can pin the clock",
                )
            ]
        if root == "random":
            # random.Random(seed) is the sanctioned seeded constructor
            if name == "random.Random" and call.args:
                return []
            return [
                Violation(
                    self.name, module.path, call.lineno,
                    f"{name}() — unseeded process-global rng in a "
                    "consensus-critical module",
                )
            ]
        if root in ("uuid", "secrets") or name == "os.urandom":
            return [
                Violation(
                    self.name, module.path, call.lineno,
                    f"{name}() — nondeterministic value source in a "
                    "consensus-critical module",
                )
            ]
        return []


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in ("set", "frozenset")
    return False


# ---------------------------------------------------------------------------
# thread-join
# ---------------------------------------------------------------------------


class ThreadLifecyclePass(LintPass):
    """Every Thread(...) created in a class must be daemon=True or joined
    somewhere in the same class (stop()/close()/join-on-name)."""

    name = "thread-join"

    def run(self, module: ModuleSource) -> list[Violation]:
        out: list[Violation] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._run_class(module, node))
        return out

    def _run_class(self, module: ModuleSource, cls: ast.ClassDef) -> list[Violation]:
        creations: list[ast.Call] = []
        joins = False
        for sub in ast.walk(cls):
            if isinstance(sub, ast.Call):
                f = sub.func
                fname = _expr_str(f) if isinstance(f, (ast.Attribute, ast.Name)) else ""
                if fname.endswith("Thread") and fname.split(".", 1)[0] in (
                    "threading", "Thread", "_t",
                ):
                    creations.append(sub)
                elif isinstance(f, ast.Attribute) and f.attr == "join":
                    joins = True
        out: list[Violation] = []
        for call in creations:
            daemon = any(
                k.arg == "daemon"
                and isinstance(k.value, ast.Constant)
                and k.value.value is True
                for k in call.keywords
            )
            if daemon or joins:
                continue
            out.append(
                Violation(
                    self.name, module.path, call.lineno,
                    f"Thread created in {cls.name} is neither daemon=True nor "
                    "joined anywhere in the class — a leaked thread outlives "
                    "stop() and keeps the process alive",
                )
            )
        return out


# ---------------------------------------------------------------------------
# hotpath-sync
# ---------------------------------------------------------------------------

# the pipelined engine loops: one host sync here stalls every in-flight
# ticket behind it (COMPONENTS.md "Verify pipeline")
_HOT_FUNCS = {
    "txflow_tpu/engine/txflow.py": {
        "_run_pipelined", "_form_batch", "step", "_prep_batch",
        "_submit_prep", "_collect", "_route_result",
        # lane-split + speculative-commit helpers (ISSUE 12): all run
        # inside the fill/route stages of the pipelined loop
        "_prio_pending", "_bulk_pending", "_bulk_quantum",
        "_steer_lingers", "_sign_bytes_proc",
    },
    # the staging ring's whole point is that the ONLY np.asarray lives
    # in its dedicated readback thread (StageSlot._run): the caller-
    # facing enter/exit paths must never force the transfer themselves,
    # or the ring silently degrades to the synchronous readback it
    # replaced. (StagingRing.submit's bounded semaphore wait is
    # backpressure by contract — this pin is about device syncs, not
    # blocking in general.)
    "txflow_tpu/parallel/staging.py": {"submit", "result"},
}

_HOT_ATTRS = {
    "item": ".item() forces a device->host readback per element",
    "asarray": "np.asarray on a device array is a blocking transfer",
    "device_get": "explicit host readback",
    "block_until_ready": "full device sync",
}


# admit-path functions that must never block: they run inline on every
# RPC handler thread and the gossip receive path, so one blocking call
# stalls the whole front door (the shed path must stay O(1) — that is
# the backpressure contract). Checked against the same blocking-call
# vocabulary as lock-blocking, with NO lock held.
_HOT_NOBLOCK_FUNCS = {
    "txflow_tpu/admission/controller.py": {
        "admit_rpc", "admit_gossip", "lane_of", "overloaded",
        "_bulk_shed", "_bulk_rate_exceeded", "forget", "gossip_paused",
        "_sample_commit_rate", "_effective_bulk_rate", "_peer_rate_exceeded",
        "_priority_sender_exceeded", "_storage_degraded",
    },
    # host-prep pool enqueue: called from inside the engine's batch-prep
    # window on every drain. One job alloc + one lock-free SimpleQueue
    # put — if submit ever grows a lock or a bounded wait, the pool
    # serializes the very path it exists to parallelize.
    "txflow_tpu/engine/hostprep.py": {"submit"},
    # the shaper's send sits INSIDE every switch send-loop iteration: it
    # must only draw from the seeded rng, push onto the delivery heap and
    # return — the wire wait lives in the shaper's own deliver thread.
    # A blocking call here turns weather latency into sender stall.
    "txflow_tpu/netem/shaper.py": {"send", "try_send"},
    # the accountable-gossip ledger sits on the vote-gossip receive path
    # (quarantine gate + per-frame accounting) and the engine's verdict
    # routing (invalid-origin attribution). A Byzantine flood IS the load
    # these run under — a blocking call here hands the attacker a stall
    # primitive on the exact path built to absorb them.
    "txflow_tpu/health/byzantine.py": {
        "quarantined", "note_frame", "note_invalid_origins",
        "register_peer", "note_sync_strike", "strikes_of",
        "_judge_locked", "_trip_locked",
    },
    # committee resolution sits on the vote-gossip pre-check path (the
    # reactor's StateView.committee read resolves through these on every
    # epoch swap) and inside the engine's update_state: a cache miss
    # re-samples with pure sha256 arithmetic — never a lock wait, never
    # I/O. One blocking call here stalls every gossip receive thread at
    # once at the epoch boundary.
    "txflow_tpu/committee/sampler.py": {
        "sample_committee", "committee_seed", "committee_at",
        "for_vote_height", "epoch_for_vote_height",
    },
}


class HotPathPass(LintPass):
    name = "hotpath-sync"

    def run(self, module: ModuleSource) -> list[Violation]:
        hot = _HOT_FUNCS.get(module.path, set())
        noblock = _HOT_NOBLOCK_FUNCS.get(module.path, set())
        if not hot and not noblock:
            return []
        out: list[Violation] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name in hot:
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute):
                        attr = sub.func.attr
                        if attr in _HOT_ATTRS:
                            out.append(
                                Violation(
                                    self.name, module.path, sub.lineno,
                                    f".{attr}() in hot function {node.name}: "
                                    f"{_HOT_ATTRS[attr]}",
                                )
                            )
            if node.name in noblock:
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Call):
                        continue
                    reason = _blocking_reason(sub, held=())
                    if reason is not None:
                        out.append(
                            Violation(
                                self.name, module.path, sub.lineno,
                                f"blocking {reason} in admit-path function "
                                f"{node.name}: the front door must shed, "
                                f"never stall",
                            )
                        )
        return out


# ---------------------------------------------------------------------------
# trace-clock
# ---------------------------------------------------------------------------

# traced hot-path scope: every module the per-tx tracer (trace/) stamps
# spans in. Timestamps here MUST come through the utils.clock seam, or a
# test that pins the clock sees half the spans on a different timeline
# and cross-node merge (trace/export.py) loses alignment. engine/ is
# scoped to the ONE traced file: execution.py keeps perf_counter for its
# untraced ABCI accounting.
_TRACE_SCOPE = (
    "txflow_tpu/engine/txflow.py",
    "txflow_tpu/engine/hostprep.py",
    # the linger controller's cadence gate shares the engine's traced
    # timeline (maybe_observe takes `now` from the caller, but any future
    # internal timestamp must come through the same seam)
    "txflow_tpu/engine/adaptive.py",
    # worker-process prep core: shard busy_s rides the done-queue acks
    # into pool stats that sit next to traced engine spans — same seam
    # so a pinned-clock test keeps both on one timeline
    "txflow_tpu/prep_proc.py",
    # staging-ring overlap ledger (hidden_s/readback_s) is compared
    # against traced device spans in report.py — same seam required
    "txflow_tpu/parallel/staging.py",
    "txflow_tpu/trace/",
    "txflow_tpu/admission/controller.py",
    "txflow_tpu/pool/",
    "txflow_tpu/reactors/",
    "txflow_tpu/sync/",
    # committee sampling + batched cert verify ride the reactor pre-check
    # and sync verify paths above — same traced timeline, same seam
    "txflow_tpu/committee/",
    # weather timestamps (due times, flap schedule) must share the traced
    # timeline: a pinned-clock test that shapes links would otherwise see
    # deliveries scheduled on a clock the spans don't use
    "txflow_tpu/netem/",
    # quarantine expiry and breaker windows live on the gossip receive
    # path's timeline: a pinned-clock drill must be able to walk a peer
    # into and out of quarantine deterministically
    "txflow_tpu/health/byzantine.py",
)

# the forbidden time.* names: every raw timestamp source. time.sleep is
# fine — pacing isn't a span timestamp.
_RAW_CLOCK_NAMES = {
    "time", "time_ns", "monotonic", "monotonic_ns",
    "perf_counter", "perf_counter_ns",
}


class TraceClockPass(LintPass):
    """No raw ``time.*`` timestamp source in a traced hot-path module.

    Flags attribute references (not just calls — passing ``time.monotonic``
    as a callback smuggles the raw clock just as effectively) and
    ``from time import ...`` of the timestamp names. The seam module
    itself (utils/clock.py) is outside the scope by construction."""

    name = "trace-clock"

    def run(self, module: ModuleSource) -> list[Violation]:
        if module.path == "txflow_tpu/utils/clock.py":
            return []  # the seam wraps the raw clock
        if not module.path.startswith(_TRACE_SCOPE):
            return []
        out: list[Violation] = []
        for node in ast.walk(module.tree):
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "time"
                and node.attr in _RAW_CLOCK_NAMES
            ):
                out.append(
                    Violation(
                        self.name, module.path, node.lineno,
                        f"time.{node.attr} in a traced hot-path module — "
                        "route through utils.clock so pinned-clock tests and "
                        "cross-node trace merge stay on one timeline",
                    )
                )
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name in _RAW_CLOCK_NAMES:
                        out.append(
                            Violation(
                                self.name, module.path, node.lineno,
                                f"from time import {a.name} in a traced "
                                "hot-path module — route through utils.clock",
                            )
                        )
        return out


# ---------------------------------------------------------------------------
# unlocked-lru
# ---------------------------------------------------------------------------


class UnlockedLRUPass(LintPass):
    """UnlockedLRUCache carries a CPython/GIL safety argument; the ONE
    place allowed to weigh it is utils.cache.make_lru."""

    name = "unlocked-lru"

    def run(self, module: ModuleSource) -> list[Violation]:
        if module.path == "txflow_tpu/utils/cache.py":
            return []
        out: list[Violation] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                f = node.func
                fname = _expr_str(f) if isinstance(f, (ast.Attribute, ast.Name)) else ""
                if fname.rsplit(".", 1)[-1] == "UnlockedLRUCache":
                    out.append(
                        Violation(
                            self.name, module.path, node.lineno,
                            "direct UnlockedLRUCache(...) — construct via "
                            "utils.cache.make_lru so the GIL check lives in "
                            "one place",
                        )
                    )
        return out


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------

# Module-wide implicit device->host sync hunt: hotpath-sync pins the
# enumerated engine-loop functions; this pass covers the REST of the hot
# modules, where a float()/int()/np.asarray on a device value is just as
# much a stall — it only hides better because the function isn't on the
# pipelined loop (yet). Device provenance is tracked per function:
# results of jnp.* expressions, calls to *_jit/*_fused/*_kernel names,
# and the verifier's jitted `self._fn` dispatch.
_HOSTSYNC_SCOPE = (
    "txflow_tpu/engine/",
    "txflow_tpu/ops/",
    "txflow_tpu/parallel/",
    "txflow_tpu/committee/",
    "txflow_tpu/verifier.py",
)

# sanctioned readback seams: the named functions EXIST to be the one
# blocking transfer on their path (COMPONENTS.md "Verify pipeline")
_HOSTSYNC_SEAMS = {
    # the staging ring's dedicated readback thread
    ("txflow_tpu/parallel/staging.py", "_run"),
    # the verifier's single ring-aware blocking readback
    ("txflow_tpu/verifier.py", "_force_readback"),
    # convenience host API: prepared batch in, bool[B] out, by contract
    ("txflow_tpu/ops/ed25519_batch.py", "verify_batch"),
    # certificate tally: ONE fused device call, one readback, batched
    ("txflow_tpu/committee/certverify.py", "verify_and_tally"),
}

_DEVICE_ROOTS = {"jnp"}
_DEVICE_FN_SUFFIXES = ("_jit", "_fused", "_kernel")
_DEVICE_ATTRS = {"_fn"}  # the verifier's jitted dispatch callable


def _device_producer_call(call: ast.Call) -> bool:
    f = call.func
    while isinstance(f, ast.Call):  # _kernel()(...) — unwrap to the maker
        f = f.func
    name = _expr_str(f) if isinstance(f, (ast.Attribute, ast.Name)) else ""
    if not name:
        return False
    root = name.split(".", 1)[0]
    last = name.rsplit(".", 1)[-1]
    if root in _DEVICE_ROOTS or name.startswith("jax.numpy."):
        return True
    return last.endswith(_DEVICE_FN_SUFFIXES) or last in _DEVICE_ATTRS


def _device_flavored(node: ast.AST, tainted: set[str]) -> bool:
    """True when the expression's value plausibly lives on device."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in tainted:
            return True
        if isinstance(sub, ast.Attribute):
            expr = _expr_str(sub)
            if expr.split(".", 1)[0] in _DEVICE_ROOTS or expr.startswith(
                "jax.numpy."
            ):
                return True
        if isinstance(sub, ast.Call) and _device_producer_call(sub):
            return True
    return False


class HostSyncPass(LintPass):
    """Implicit host syncs on device values in hot modules, outside the
    sanctioned StagingRing/readback seams.

    Flags, per function: ``.item()`` / ``.block_until_ready()`` /
    ``jax.device_get`` unconditionally, and ``float(x)`` / ``int(x)`` /
    ``np.asarray(x)`` when ``x`` is device-flavored (a jnp expression, a
    call to a jitted kernel, or a local bound from one)."""

    name = "host-sync"

    def run(self, module: ModuleSource) -> list[Violation]:
        if not module.path.startswith(_HOSTSYNC_SCOPE):
            return []
        hot = _HOT_FUNCS.get(module.path, set())
        out: list[Violation] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name in hot:
                continue  # hotpath-sync already pins these, don't double-report
            if (module.path, node.name) in _HOSTSYNC_SEAMS:
                continue
            out.extend(self._check_func(module, node))
        return out

    def _check_func(self, module: ModuleSource, fn: ast.AST) -> list[Violation]:
        tainted = self._tainted_names(fn)
        out: list[Violation] = []
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            if isinstance(f, ast.Attribute):
                recv = _expr_str(f.value)
                if f.attr == "item" and not sub.args:
                    out.append(self._v(module, sub,
                                       ".item() — per-element device readback"))
                elif f.attr == "block_until_ready":
                    out.append(self._v(module, sub,
                                       ".block_until_ready() — full device sync"))
                elif f.attr == "device_get":
                    out.append(self._v(module, sub,
                                       "device_get — explicit host readback"))
                elif (
                    f.attr == "asarray"
                    and recv.split(".", 1)[0] in ("np", "numpy")
                    and sub.args
                    and _device_flavored(sub.args[0], tainted)
                ):
                    out.append(self._v(
                        module, sub,
                        "np.asarray on a device value — blocking transfer",
                    ))
            elif isinstance(f, ast.Name) and f.id in ("float", "int"):
                if sub.args and _device_flavored(sub.args[0], tainted):
                    out.append(self._v(
                        module, sub,
                        f"{f.id}() on a device value — scalar readback sync",
                    ))
        return out

    def _tainted_names(self, fn: ast.AST) -> set[str]:
        tainted: set[str] = set()
        for _ in range(4):  # tiny fixpoint: chains of assignments
            before = len(tainted)
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Assign) and _device_flavored(
                    sub.value, tainted
                ):
                    for tgt in sub.targets:
                        for t in ast.walk(tgt):
                            if isinstance(t, ast.Name):
                                tainted.add(t.id)
            if len(tainted) == before:
                break
        return tainted

    def _v(self, module: ModuleSource, node: ast.AST, why: str) -> Violation:
        return Violation(
            self.name, module.path, node.lineno,
            f"{why}; route through the StagingRing/_force_readback seam "
            "or move off the hot module",
        )


# ---------------------------------------------------------------------------
# recompile-hazard
# ---------------------------------------------------------------------------

# The zero-recompile contract (engine/shapes.py): every compiled shape
# must come off the bucket ladder or the warm registry. A dispatch-site
# shape arg that doesn't provably flow from the blessed helpers is a
# latent recompile — it works until the first unbucketed batch size, then
# costs a full XLA compile mid-flight.
_SHAPE_SCOPE = (
    "txflow_tpu/verifier.py",
    "txflow_tpu/engine/shapes.py",
    "txflow_tpu/engine/txflow.py",
    "txflow_tpu/parallel/mesh.py",
    "txflow_tpu/committee/certverify.py",
)

# blessed shape sources: the ladder + prediction helpers
_SHAPE_FUNCS = {
    "bucket_size", "_generating_size", "predicted_shapes",
    "shapes_for_batch", "enumerate_shapes", "_rung",
}

# blessed shape-carrying attributes (ladder config, not raw input sizes)
_SHAPE_ATTRS = {"buckets", "miss_buckets", "max_batch", "capacity", "_n_shards"}


class RecompileHazardPass(LintPass):
    """Shape args at dispatch sinks must provably flow from the bucket
    ladder. Sinks: ``_pad(x, P)``'s pad width and ``shapes_used.add(t)``'s
    tuple elements. Provenance propagates through assignments, BinOps
    with a ladder-derived operand (``pad = b - n``), subscripts of
    blessed attrs (``self.buckets[0]``), min/max, and conditionals."""

    name = "recompile-hazard"

    def run(self, module: ModuleSource) -> list[Violation]:
        if module.path not in _SHAPE_SCOPE:
            return []
        out: list[Violation] = []
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(self._check_func(module, node))
        return out

    def _check_func(self, module: ModuleSource, fn: ast.AST) -> list[Violation]:
        safe = self._safe_names(fn)
        out: list[Violation] = []
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            fname = _expr_str(f) if isinstance(f, (ast.Attribute, ast.Name)) else ""
            last = fname.rsplit(".", 1)[-1]
            if last == "_pad" and len(sub.args) >= 2:
                if not self._is_safe(sub.args[1], safe):
                    out.append(Violation(
                        self.name, module.path, sub.lineno,
                        "_pad width does not flow from the bucket ladder "
                        "(bucket_size/ShapeWarmRegistry) — every new raw "
                        "size is a fresh XLA compile",
                    ))
            elif (
                last == "add"
                and isinstance(f, ast.Attribute)
                and _expr_str(f.value).rsplit(".", 1)[-1] == "shapes_used"
                and sub.args
            ):
                arg = sub.args[0]
                elts = arg.elts if isinstance(arg, ast.Tuple) else [arg]
                for e in elts:
                    if not self._is_safe(e, safe):
                        out.append(Violation(
                            self.name, module.path, sub.lineno,
                            "shapes_used entry element does not flow from "
                            "the bucket ladder — the warm registry would "
                            "bank an unreachable (or unbounded) shape",
                        ))
                        break
        return out

    def _safe_names(self, fn: ast.AST) -> set[str]:
        safe: set[str] = set()
        for _ in range(6):
            before = len(safe)
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Assign):
                    if self._is_safe(sub.value, safe):
                        for tgt in sub.targets:
                            for t in ast.walk(tgt):
                                if isinstance(t, ast.Name):
                                    safe.add(t.id)
                elif isinstance(sub, (ast.For,)) and self._is_safe(
                    sub.iter, safe
                ):
                    for t in ast.walk(sub.target):
                        if isinstance(t, ast.Name):
                            safe.add(t.id)
            if len(safe) == before:
                break
        return safe

    def _is_safe(self, node: ast.AST, safe: set[str]) -> bool:
        if isinstance(node, ast.Constant):
            return isinstance(node.value, (int, str))
        if isinstance(node, ast.Name):
            return node.id in safe
        if isinstance(node, ast.Attribute):
            return node.attr in _SHAPE_ATTRS
        if isinstance(node, ast.Subscript):
            return self._is_safe(node.value, safe)
        if isinstance(node, ast.UnaryOp):
            return self._is_safe(node.operand, safe)
        if isinstance(node, ast.IfExp):
            return self._is_safe(node.body, safe) and self._is_safe(
                node.orelse, safe
            )
        if isinstance(node, ast.Tuple):
            return all(self._is_safe(e, safe) for e in node.elts)
        if isinstance(node, ast.BinOp):
            # ladder provenance survives arithmetic with raw sizes
            # (pad = b - n) but a bare-constant operand does not bless
            # the other side (n + 1 is still a raw size)
            return self._ladderish(node.left, safe) or self._ladderish(
                node.right, safe
            )
        if isinstance(node, ast.Call):
            fname = (
                _expr_str(node.func)
                if isinstance(node.func, (ast.Attribute, ast.Name))
                else ""
            )
            last = fname.rsplit(".", 1)[-1]
            if last in _SHAPE_FUNCS:
                return True
            if last in ("min", "max"):
                return any(self._ladderish(a, safe) for a in node.args)
        return False

    def _ladderish(self, node: ast.AST, safe: set[str]) -> bool:
        return not isinstance(node, ast.Constant) and self._is_safe(node, safe)


# ---------------------------------------------------------------------------
# seed-domain
# ---------------------------------------------------------------------------

_DOMAINS_MODULE = "txflow_tpu/utils/domains.py"


def _domain_tag_literal(value) -> bool:
    """A bytes literal that reads as a PRNG domain tag: a pipe-separated
    domain format (not a bare joiner/suffix starting with '|') or the
    versioned txflow/ namespace."""
    if not isinstance(value, bytes):
        return False
    if value.startswith(b"txflow/"):
        return True
    return b"|" in value and not value.startswith(b"|")


class SeedDomainPass(LintPass):
    """Every PRNG domain tag lives in utils.domains (the ONE registry,
    duplicate-checked at import): an inline raw domain literal inside a
    sha256()/update() call can silently collide with a registered stream.
    The registry itself is also re-checked statically for duplicate
    literals, so a broken registry fails lint even if never imported."""

    name = "seed-domain"

    def run(self, module: ModuleSource) -> list[Violation]:
        if module.path == _DOMAINS_MODULE:
            return self._check_registry(module)
        out: list[Violation] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            fname = _expr_str(f) if isinstance(f, (ast.Attribute, ast.Name)) else ""
            last = fname.rsplit(".", 1)[-1]
            if last not in ("sha256", "update"):
                continue
            for arg in node.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Constant) and _domain_tag_literal(
                        sub.value
                    ):
                        out.append(Violation(
                            self.name, module.path, sub.lineno,
                            f"inline PRNG domain literal {sub.value!r} — "
                            "register the tag in utils.domains and import "
                            "it, so collisions fail fast in one place",
                        ))
        return out

    def _check_registry(self, module: ModuleSource) -> list[Violation]:
        out: list[Violation] = []
        names: dict[str, int] = {}
        tags: dict[bytes, int] = {}
        for node in ast.walk(module.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "_register"
                and len(node.args) == 2
            ):
                continue
            nm, tag = node.args
            if isinstance(nm, ast.Constant) and isinstance(nm.value, str):
                if nm.value in names:
                    out.append(Violation(
                        self.name, module.path, node.lineno,
                        f"duplicate domain name {nm.value!r} "
                        f"(first registered line {names[nm.value]})",
                    ))
                else:
                    names[nm.value] = node.lineno
            if isinstance(tag, ast.Constant) and isinstance(tag.value, bytes):
                if tag.value in tags:
                    out.append(Violation(
                        self.name, module.path, node.lineno,
                        f"duplicate domain tag {tag.value!r} "
                        f"(first registered line {tags[tag.value]})",
                    ))
                else:
                    tags[tag.value] = node.lineno
        return out


# ---------------------------------------------------------------------------
# shared-decl
# ---------------------------------------------------------------------------

_SHARED_RE = re.compile(r"#\s*txlint:\s*shared\(([^)]*)\)")


class SharedDeclPass(LintPass):
    """Every ``shared_field(...)`` declaration carries the static intent
    annotation ``# txlint: shared(<lock>)`` naming the lock that is
    supposed to guard the field (or ``handoff`` for ownership-transfer
    protocols) — and every such annotation sits on a real declaration.
    The runtime race auditor then checks the intent against what threads
    actually held."""

    name = "shared-decl"

    def run(self, module: ModuleSource) -> list[Violation]:
        if module.path.startswith("txflow_tpu/analysis/"):
            return []  # the auditor's own docs spell the annotation
        annotations: dict[int, str] = {}
        for i, line in enumerate(module.lines, 1):
            m = _SHARED_RE.search(line)
            if m is not None:
                annotations[i] = m.group(1).strip()
        out: list[Violation] = []
        used: set[int] = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            fname = _expr_str(f) if isinstance(f, (ast.Attribute, ast.Name)) else ""
            if fname.rsplit(".", 1)[-1] != "shared_field":
                continue
            span = range(node.lineno, (node.end_lineno or node.lineno) + 1)
            ann_line = next((i for i in span if i in annotations), None)
            if ann_line is None:
                out.append(Violation(
                    self.name, module.path, node.lineno,
                    "shared_field() without a `# txlint: shared(<lock>)` "
                    "annotation naming the guarding lock (or `handoff`)",
                ))
                continue
            used.add(ann_line)
            expr = annotations[ann_line]
            if expr != "handoff" and not _is_lockish(expr):
                out.append(Violation(
                    self.name, module.path, ann_line,
                    f"shared({expr}) names neither a lock-like expression "
                    "nor `handoff`",
                ))
        for i in sorted(set(annotations) - used):
            out.append(Violation(
                self.name, module.path, i,
                "dangling `# txlint: shared(...)` annotation — no "
                "shared_field() declaration on this line",
            ))
        return out
