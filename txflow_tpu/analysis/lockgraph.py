"""Runtime lock-order auditor (the dynamic half of txlint).

Static analysis sees lexical lock scopes; it cannot see an acquisition
ORDER inverted across two call chains, or a blocking call reached three
frames below a ``with self._mtx:``. This module closes that gap with an
opt-in instrumented lock:

- ``make_lock(name)`` / ``make_rlock(name)`` return plain
  ``threading.Lock``/``RLock`` objects unless ``TXFLOW_LOCK_AUDIT=1`` is
  set in the environment (checked at construction — zero overhead when
  off, which is the production default). When auditing is on they return
  wrappers that record, per thread, the stack of held audited locks and,
  globally, every (held -> acquired) edge of the acquisition graph.
- ``LockAuditor.cycles()`` finds cycles in that graph: two threads that
  ever acquire the same two locks in opposite orders are one unlucky
  preemption away from deadlock, even if the test run never deadlocked.
- ``note_blocking(desc)`` is the blocking-call probe: call sites that
  perform known-blocking work (socket round trips, device readbacks,
  ``time.sleep`` via ``install_probes()``) report themselves, and the
  auditor records a violation when any audited lock is held — unless the
  lock was constructed with ``allow_blocking=True``, the explicit marker
  for locks whose JOB is to serialize a blocking region (a connection
  write lock, a signer's request lock, a store's durability point).

tier-1 enables auditing via ``tests/conftest.py`` and fails the run on
any cycle or blocking violation (see ``pytest_sessionfinish`` there).

The wrappers implement the private ``threading.Condition`` protocol
(``_release_save`` / ``_acquire_restore`` / ``_is_owned``) so an audited
RLock can back a Condition (pool ingest logs do this); a ``wait()``
releases the lock, so the held-stack bookkeeping mirrors that.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
import traceback

_ENV = "TXFLOW_LOCK_AUDIT"

# bound the edge/violation tables so a pathological run cannot grow them
# without limit; real graphs are tiny (one node per lock SITE, not instance
# count x threads)
_MAX_EDGES = 100_000
_MAX_VIOLATIONS = 1_000


def audit_enabled() -> bool:
    """True when TXFLOW_LOCK_AUDIT=1 — re-read per call so conftest can
    set it before any lock is constructed, without import-order games."""
    return os.environ.get(_ENV, "") == "1"


class LockAuditor:
    """Acquisition-graph recorder shared by every audited lock.

    Nodes are lock INSTANCES (a monotonic token per wrapper — ids would
    be reused after GC and could fabricate phantom cycles); names label
    them in reports. A cycle among instances is a real deadlock order,
    not a same-name coincidence across independent object graphs (two
    nodes of a LocalNet each own a pool lock named "pool.Mempool";
    opposite orders across *different* nodes' locks are harmless and must
    not fire)."""

    def __init__(self) -> None:
        self._mtx = threading.Lock()  # guards the tables below, never held
        # while user code runs — acquire/record/release only
        self._tls = threading.local()
        self._names: dict[int, str] = {}  # token -> name
        self._edges: dict[tuple[int, int], int] = {}  # (held, acquired) -> count
        self._edge_sites: dict[tuple[int, int], str] = {}
        self._violations: list[dict] = []
        self._tokens = itertools.count(1)

    # -- wrapper callbacks --

    def register(self, name: str) -> int:
        tok = next(self._tokens)
        with self._mtx:
            self._names[tok] = name
        return tok

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def note_acquire(self, lock: "_AuditedLockBase") -> None:
        held = self._held()
        if held:
            tok = lock._tok
            new_edges = []
            for h in held:
                if h._tok != tok:
                    new_edges.append((h._tok, tok))
            if new_edges:
                site = None
                with self._mtx:
                    for e in new_edges:
                        n = self._edges.get(e)
                        if n is None and len(self._edges) >= _MAX_EDGES:
                            continue
                        self._edges[e] = (n or 0) + 1
                        if n is None:
                            if site is None:
                                site = _short_stack()
                            self._edge_sites[e] = site
        held.append(lock)

    def note_release(self, lock: "_AuditedLockBase") -> None:
        held = self._held()
        # release order can differ from acquire order (rare but legal);
        # remove the newest matching entry
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    def drop_all(self, lock: "_AuditedLockBase") -> int:
        """Condition _release_save: an RLock's wait() releases EVERY
        recursion level at once. Returns how many entries were dropped so
        _acquire_restore can push them back."""
        held = self._held()
        n = 0
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                n += 1
        return n

    def push_n(self, lock: "_AuditedLockBase", n: int) -> None:
        for _ in range(max(n, 1)):
            self.note_acquire(lock)

    # -- probes --

    def note_blocking(self, desc: str) -> None:
        """Record a violation if the calling thread holds any audited lock
        not marked allow_blocking."""
        held = getattr(self._tls, "held", None)
        if not held:
            return
        if getattr(self._tls, "sanctioned", 0):
            return  # inside a sanctioned_blocking() region
        bad = [l for l in held if not l._allow_blocking]
        if not bad:
            return
        with self._mtx:
            if len(self._violations) >= _MAX_VIOLATIONS:
                return
            self._violations.append(
                {
                    "desc": desc,
                    "held": [l._name for l in bad],
                    "thread": threading.current_thread().name,
                    "stack": _short_stack(),
                }
            )

    # -- reporting --

    def cycles(self) -> list[list[str]]:
        """Cycles in the acquisition graph, as name lists. Iterative DFS
        with an on-path set; one cycle reported per back edge found."""
        with self._mtx:
            edges = list(self._edges)
            names = dict(self._names)
        adj: dict[int, list[int]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)
        out: list[list[str]] = []
        seen_cycles: set[tuple] = set()
        visited: set[int] = set()
        for root in list(adj):
            if root in visited:
                continue
            # stack of (node, iterator over successors); path = on-stack nodes
            path: list[int] = []
            on_path: set[int] = set()
            stack = [(root, iter(adj.get(root, ())))]
            path.append(root)
            on_path.add(root)
            while stack:
                node, it = stack[-1]
                advanced = False
                for nxt in it:
                    if nxt in on_path:
                        i = path.index(nxt)
                        cyc = path[i:] + [nxt]
                        key = tuple(sorted(set(cyc)))
                        if key not in seen_cycles:
                            seen_cycles.add(key)
                            out.append([names.get(t, f"lock#{t}") for t in cyc])
                        continue
                    if nxt in visited:
                        continue
                    stack.append((nxt, iter(adj.get(nxt, ()))))
                    path.append(nxt)
                    on_path.add(nxt)
                    advanced = True
                    break
                if not advanced:
                    stack.pop()
                    visited.add(node)
                    on_path.discard(node)
                    path.pop()
        return out

    def blocking_violations(self) -> list[dict]:
        with self._mtx:
            return list(self._violations)

    def report(self) -> dict:
        with self._mtx:
            names = dict(self._names)
            edges = [
                {
                    "from": names.get(a, f"lock#{a}"),
                    "to": names.get(b, f"lock#{b}"),
                    "count": n,
                    "first_site": self._edge_sites.get((a, b), ""),
                }
                for (a, b), n in self._edges.items()
            ]
            violations = list(self._violations)
        return {
            "locks": sorted(set(names.values())),
            "edges": edges,
            "cycles": self.cycles(),
            "blocking_violations": violations,
        }

    def reset(self) -> None:
        with self._mtx:
            self._edges.clear()
            self._edge_sites.clear()
            self._violations.clear()


_DEFAULT = LockAuditor()


def default_auditor() -> LockAuditor:
    return _DEFAULT


class _AuditedLockBase:
    _name: str
    _tok: int
    _allow_blocking: bool
    _auditor: LockAuditor

    def __init__(self, name: str, allow_blocking: bool, auditor: LockAuditor | None):
        self._name = name
        self._allow_blocking = allow_blocking
        self._auditor = auditor if auditor is not None else _DEFAULT
        self._tok = self._auditor.register(name)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._auditor.note_acquire(self)
        return ok

    def release(self) -> None:
        self._auditor.note_release(self)
        self._inner.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _at_fork_reinit(self) -> None:
        self._inner._at_fork_reinit()

    def __repr__(self) -> str:
        return f"<audited {type(self._inner).__name__} {self._name!r}>"


class AuditedLock(_AuditedLockBase):
    def __init__(
        self,
        name: str,
        allow_blocking: bool = False,
        auditor: LockAuditor | None = None,
    ):
        self._inner = threading.Lock()
        super().__init__(name, allow_blocking, auditor)

    # Condition protocol (a plain Lock has no _is_owned; Condition falls
    # back to a try-acquire probe when these are absent, so provide the
    # pair that must exist for correct bookkeeping)
    def _release_save(self):
        n = self._auditor.drop_all(self)
        self._inner.release()
        return n

    def _acquire_restore(self, n) -> None:
        self._inner.acquire()
        self._auditor.push_n(self, n if isinstance(n, int) else 1)


class AuditedRLock(_AuditedLockBase):
    def __init__(
        self,
        name: str,
        allow_blocking: bool = False,
        auditor: LockAuditor | None = None,
    ):
        self._inner = threading.RLock()
        super().__init__(name, allow_blocking, auditor)

    def locked(self) -> bool:  # RLock has no locked() before 3.12's _is_owned
        return self._inner._is_owned()

    # Condition protocol: delegate to the real RLock (which releases all
    # recursion levels in _release_save) and mirror in the held stack
    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self):
        n = self._auditor.drop_all(self)
        state = self._inner._release_save()
        return (state, n)

    def _acquire_restore(self, saved) -> None:
        state, n = saved
        self._inner._acquire_restore(state)
        self._auditor.push_n(self, n)


def make_lock(name: str, allow_blocking: bool = False):
    """A threading.Lock, audited when TXFLOW_LOCK_AUDIT=1.

    allow_blocking marks locks that intentionally guard blocking work
    (serialized socket writes, fsync points): note_blocking() under them
    is sanctioned and not reported."""
    if audit_enabled():
        return AuditedLock(name, allow_blocking)
    return threading.Lock()


def make_rlock(name: str, allow_blocking: bool = False):
    """A threading.RLock, audited when TXFLOW_LOCK_AUDIT=1."""
    if audit_enabled():
        return AuditedRLock(name, allow_blocking)
    return threading.RLock()


def note_blocking(desc: str) -> None:
    """Blocking-call probe for the default auditor. Cheap no-op when
    nothing is held or auditing is off (the thread-local held list only
    ever populates via audited locks)."""
    _DEFAULT.note_blocking(desc)


class _Sanction:
    """Thread-scoped sanction: probes inside the region don't report.
    The runtime counterpart of a static ``allow(lock-blocking)``
    suppression comment — for regions where holding a lock across
    blocking work IS the contract (the app-Commit fence under the
    mempool lock)."""

    __slots__ = ("_aud",)

    def __init__(self, aud: LockAuditor):
        self._aud = aud

    def __enter__(self) -> "_Sanction":
        tls = self._aud._tls
        tls.sanctioned = getattr(tls, "sanctioned", 0) + 1
        return self

    def __exit__(self, *exc) -> None:
        self._aud._tls.sanctioned -= 1


def sanctioned_blocking(justification: str, auditor: LockAuditor | None = None) -> _Sanction:
    """Context manager marking a deliberate lock-held-across-blocking
    region. `justification` is required (and deliberately unused): the
    call site must say WHY, exactly like a static suppression comment."""
    assert justification, "sanctioned_blocking() requires a justification"
    return _Sanction(auditor if auditor is not None else _DEFAULT)


_probes_installed = False
_orig_sleep = time.sleep


def install_probes() -> None:
    """Patch time.sleep to self-report through note_blocking. Idempotent;
    test-only (conftest), never called on production paths."""
    global _probes_installed
    if _probes_installed:
        return
    _probes_installed = True

    def _audited_sleep(secs):
        _DEFAULT.note_blocking(f"time.sleep({secs!r})")
        _orig_sleep(secs)

    time.sleep = _audited_sleep


def uninstall_probes() -> None:
    global _probes_installed
    if _probes_installed:
        time.sleep = _orig_sleep
        _probes_installed = False


def _short_stack(limit: int = 6) -> str:
    """Compact call-site summary: the few frames above the lock wrapper,
    file:line only (full stacks bloat reports and pin test internals)."""
    frames = traceback.extract_stack()[:-3]
    tail = frames[-limit:]
    return " <- ".join(f"{os.path.basename(f.filename)}:{f.lineno}" for f in reversed(tail))
