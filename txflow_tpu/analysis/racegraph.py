"""Runtime lockset race auditor (the Eraser half of txlint's dynamic side).

The lock-order auditor (``lockgraph``) proves the locks we DO take are
taken in a consistent order; it says nothing about state touched with no
lock at all. This module closes that gap with per-field lockset
intersection à la Eraser (Savage et al., SOSP '97 — the lineage behind
Go's race detector): every *declared* shared-mutable field records, on
each access, the set of audited locks the accessing thread currently
holds. The field's *candidate lockset* starts as the first cross-thread
access's held set and is intersected on every subsequent access; a field
whose candidate set empties while at least two threads touched it (with
at least one write) has no lock consistently protecting it — a race
report, even if this run never interleaved badly.

Surface:

- ``shared_field(name)`` — declare one shared-mutable field of one
  instance. Returns a no-op handle unless ``TXFLOW_RACE_AUDIT=1`` (and
  the lock audit is on — locksets come from lockgraph's held-stack), so
  production paths pay one attribute access per probe. Declaration sites
  carry the static intent annotation ``# txlint: shared(self._mtx)``
  naming the lock that is SUPPOSED to guard the field (checked by the
  ``shared-decl`` static pass; the runtime auditor then verifies the
  intent against what threads actually held).
- ``handle.note_read()`` / ``handle.note_write()`` — access probes,
  placed inside the methods that touch the field (Python has no cheap
  per-access memory instrumentation; the probes live where the field's
  OWN class touches it, which is every access for lock-disciplined
  code).
- ``handle.handoff(reason)`` — sanctioned ownership transfer: resets the
  field to virgin so the NEXT accessing thread becomes its exclusive
  owner. This is the runtime counterpart of a suppression comment, for
  protocols that synchronize by handoff rather than by a lock: the
  deferred-apply ownership transfer (executor seam), and ticket/slot
  handoffs where an Event's set/wait pair is the happens-before edge
  (StagingRing slots: caller -> readback thread -> caller).

State machine per field (Eraser fig. 3): VIRGIN -> EXCLUSIVE(owner
thread; no lockset refinement — single-thread init needs no lock) ->
SHARED (second thread read it: refine lockset but don't report, read-only
sharing is benign) -> SHARED-MODIFIED (a write while shared: refine and
REPORT when the lockset is empty). Reports carry both sites — the access
that emptied the set and the last access from a different thread — and
are deduped per (field name, racy site).

tier-1 arms this for the whole suite via tests/conftest.py
(``TXFLOW_RACE_AUDIT`` defaults to 1 there) and fails the run on any race
report, mirroring the lock-audit gate. ``tools/lint.py --race-report``
pretty-prints the report the gate dumps.
"""

from __future__ import annotations

import os
import sys
import threading
import traceback

from .lockgraph import LockAuditor, audit_enabled as _lock_audit_enabled
from .lockgraph import default_auditor as _default_lock_auditor

_ENV = "TXFLOW_RACE_AUDIT"

# field states (Eraser)
_VIRGIN = 0
_EXCLUSIVE = 1
_SHARED = 2
_SHARED_MOD = 3

_STATE_NAMES = {
    _VIRGIN: "virgin",
    _EXCLUSIVE: "exclusive",
    _SHARED: "shared-read",
    _SHARED_MOD: "shared-modified",
}

_MAX_RACES = 200


def audit_enabled() -> bool:
    """True when TXFLOW_RACE_AUDIT=1 AND the lock audit is on (locksets
    are read from lockgraph's held-stack; without audited locks every
    set would be empty and every field would read as racy)."""
    return os.environ.get(_ENV, "") == "1" and _lock_audit_enabled()


class RaceAuditor:
    """Shared bookkeeping for every declared field: race reports (deduped
    per (field, racy site)) and a per-field-NAME summary for the gate.

    One plain (never audited — bookkeeping must not add edges to the
    graph it audits) lock guards the tables; it is held only across
    dict/set updates, never across user code."""

    def __init__(self, lock_auditor: LockAuditor | None = None):
        self._mtx = threading.Lock()
        self._lock_auditor = lock_auditor
        self._races: list[dict] = []
        self._race_keys: set[tuple] = set()
        # name -> aggregate over every field instance declared under it
        self._summary: dict[str, dict] = {}

    # -- lockset source --

    def _held_tokens(self) -> tuple[frozenset, dict]:
        aud = self._lock_auditor or _default_lock_auditor()
        held = getattr(aud._tls, "held", None)
        if not held:
            return frozenset(), {}
        toks = frozenset(l._tok for l in held)
        names = {l._tok: l._name for l in held}
        return toks, names

    # -- declaration --

    def declare(self, name: str) -> "SharedField":
        field = SharedField(name, self)
        with self._mtx:
            s = self._summary.setdefault(
                name,
                {
                    "fields": 0, "reads": 0, "writes": 0, "handoffs": 0,
                    "max_threads": 0, "lockset": None, "racy": 0,
                },
            )
            s["fields"] += 1
        return field

    # -- access (called by SharedField under its own state lock) --

    def _note_summary(self, field: "SharedField", write: bool) -> None:
        s = self._summary[field.name]
        s["writes" if write else "reads"] += 1
        s["max_threads"] = max(s["max_threads"], len(field._threads))
        if field._state in (_SHARED, _SHARED_MOD):
            names = sorted(field._lock_names.get(t, f"lock#{t}")
                           for t in (field._lockset or ()))
            # the gate reads this: the narrowest lockset any instance of
            # this field name was ever down to while actually shared
            prev = s["lockset"]
            if prev is None or len(names) < len(prev):
                s["lockset"] = names

    def _report(self, field: "SharedField", write: bool, site: tuple,
                prev_site: tuple | None, prev_thread: str | None) -> None:
        # one report per (field, racy site): a hot loop hitting the same
        # unlocked access pairs itself with a new prev_site every lap,
        # so keying on the pair would flood the report with duplicates
        key = (field.name, site)
        with self._mtx:
            if key in self._race_keys or len(self._races) >= _MAX_RACES:
                return
            self._race_keys.add(key)
            self._summary[field.name]["racy"] += 1
            self._races.append(
                {
                    "field": field.name,
                    "access": "write" if write else "read",
                    "site": _fmt_site(site),
                    "other_site": _fmt_site(prev_site),
                    "other_thread": prev_thread or "?",
                    "thread": threading.current_thread().name,
                    "stack": _short_stack(),
                }
            )

    # -- reporting --

    def races(self) -> list[dict]:
        with self._mtx:
            return list(self._races)

    def report(self) -> dict:
        with self._mtx:
            summary = {
                name: dict(s) for name, s in sorted(self._summary.items())
            }
            races = list(self._races)
        return {"fields": summary, "races": races}

    def reset(self) -> None:
        with self._mtx:
            self._races.clear()
            self._race_keys.clear()
            for s in self._summary.values():
                s["racy"] = 0


_DEFAULT = RaceAuditor()


def default_race_auditor() -> RaceAuditor:
    return _DEFAULT


class SharedField:
    """Per-instance Eraser state for one declared field.

    A tiny per-field plain lock guards the state words; it is a leaf
    (held only across the state update, no user code, no other lock)."""

    __slots__ = (
        "name", "_auditor", "_state", "_owner", "_threads", "_lockset",
        "_lock_names", "_last_site", "_last_thread", "_mtx",
    )

    def __init__(self, name: str, auditor: RaceAuditor):
        self.name = name
        self._auditor = auditor
        self._state = _VIRGIN
        self._owner: int | None = None
        self._threads: set[int] = set()
        self._lockset: frozenset | None = None
        self._lock_names: dict = {}
        self._last_site: tuple | None = None
        self._last_thread: str | None = None
        self._mtx = threading.Lock()

    def note_read(self) -> None:
        self._access(False)

    def note_write(self) -> None:
        self._access(True)

    def handoff(self, reason: str) -> None:
        """Sanctioned ownership transfer (see module docstring). The
        reason is required exactly like a suppression justification."""
        assert reason, "handoff() requires a justification"
        aud = self._auditor
        with self._mtx:
            self._state = _VIRGIN
            self._owner = None
            self._lockset = None
            self._last_site = None
            self._last_thread = None
        with aud._mtx:
            aud._summary[self.name]["handoffs"] += 1

    def _access(self, write: bool) -> None:
        tid = threading.get_ident()
        aud = self._auditor
        held, held_names = aud._held_tokens()
        f = sys._getframe(2)  # the caller of note_read/note_write
        site = (f.f_code.co_filename, f.f_lineno)
        report_prev = None
        with self._mtx:
            st = self._state
            self._threads.add(tid)
            if st == _VIRGIN:
                self._state = _EXCLUSIVE
                self._owner = tid
            elif st == _EXCLUSIVE:
                if tid != self._owner:
                    # first cross-thread access: candidate lockset is
                    # whatever this thread holds right now
                    self._lockset = held
                    self._lock_names.update(held_names)
                    self._state = _SHARED_MOD if write else _SHARED
                    if self._state == _SHARED_MOD and not held:
                        report_prev = (self._last_site, self._last_thread)
            else:
                self._lockset = (
                    held if self._lockset is None else self._lockset & held
                )
                self._lock_names.update(held_names)
                if write and st == _SHARED:
                    self._state = _SHARED_MOD
                if self._state == _SHARED_MOD and not self._lockset:
                    report_prev = (self._last_site, self._last_thread)
            prev_site, prev_thread = self._last_site, self._last_thread
            self._last_site = site
            self._last_thread = threading.current_thread().name
            with aud._mtx:
                aud._note_summary(self, write)
        if report_prev is not None:
            aud._report(self, write, site, prev_site, prev_thread)

    def snapshot(self) -> dict:
        with self._mtx:
            return {
                "name": self.name,
                "state": _STATE_NAMES[self._state],
                "threads": len(self._threads),
                "lockset": sorted(
                    self._lock_names.get(t, f"lock#{t}")
                    for t in (self._lockset or ())
                ) if self._lockset is not None else None,
            }


class _NullField:
    """Audit-off handle: every probe is one no-op method call."""

    __slots__ = ()
    name = "<race-audit-off>"

    def note_read(self) -> None:
        pass

    def note_write(self) -> None:
        pass

    def handoff(self, reason: str) -> None:
        pass


NULL_FIELD = _NullField()


def shared_field(name: str, auditor: RaceAuditor | None = None):
    """Declare one shared-mutable field. Returns the no-op handle unless
    the race audit is armed (see audit_enabled). Sites carry the static
    ``# txlint: shared(<lock>)`` intent annotation."""
    if not audit_enabled():
        return NULL_FIELD
    return (auditor if auditor is not None else _DEFAULT).declare(name)


def _fmt_site(site: tuple | None) -> str:
    if site is None:
        return "?"
    return f"{os.path.basename(site[0])}:{site[1]}"


def _short_stack(limit: int = 6) -> str:
    frames = traceback.extract_stack()[:-2]
    tail = frames[-limit:]
    return " <- ".join(
        f"{os.path.basename(f.filename)}:{f.lineno}" for f in reversed(tail)
    )
