"""txlint: project-invariant static analysis + runtime lock auditing.

Static side (``core`` + ``passes`` + ``twins``, driven by ``tools/lint.py``
and gated by ``tests/test_lint.py``): AST passes that mechanically enforce
the concurrency/determinism invariants this repo's hot path depends on —
no blocking call under a lock, no wall-clock/rng in consensus-critical
modules, every thread daemonized or joined, no host-sync in the pipelined
engine loops, lock-free LRU construction routed through the one factory
that owns the GIL assumption, and hand-synced twin code paths pinned to
their parity tests.

Runtime side, two auditors:

- ``lockgraph``: an opt-in audited lock wrapper (``TXFLOW_LOCK_AUDIT=1``)
  that records the cross-thread lock acquisition graph, flags ordering
  cycles (potential deadlocks) and blocking calls made while holding a
  lock.
- ``racegraph``: Eraser-style lockset race auditing
  (``TXFLOW_RACE_AUDIT=1``, rides on lockgraph's held-lock tracking) over
  fields declared shared-mutable via ``shared_field`` + the
  ``# txlint: shared(lock)`` intent annotation, with a sanctioned
  ``handoff()`` API for ownership-transfer protocols.

Import surface is deliberately split: ``lockgraph`` is imported by hot
runtime modules (engine/pools/p2p) and stays dependency-light; the AST
machinery is only pulled in by the lint tooling.
"""

from .lockgraph import (  # noqa: F401
    LockAuditor,
    audit_enabled,
    default_auditor,
    make_lock,
    make_rlock,
    note_blocking,
    sanctioned_blocking,
)
from .racegraph import (  # noqa: F401
    NULL_FIELD,
    RaceAuditor,
    SharedField,
    default_race_auditor,
    shared_field,
)
