/* Native host prep for the batched TPU vote verifier.
 *
 * The device kernel (ops/ed25519_batch.py) needs, per vote: S (the
 * signature scalar, checked S < L), and h = SHA-512(R || A || msg) mod L.
 * Doing that in a per-vote Python loop measured ~12 us/vote — the
 * dominant host cost of a verify step once sign-bytes are cached (r3
 * bench profile, single-core host). This module does the whole batch in
 * one C call (~1 us/vote): SHA-512 (FIPS 180-4, written from the spec),
 * the ScMinimal S < L comparison, and reduction mod the ed25519 group
 * order L = 2^252 + c via repeated folding at bit 252 (2^252 === -c mod L,
 * with sign tracking; <= 4 folds bring a 512-bit value under 2^252).
 *
 * The reference has no native code at all — it verifies one signature at
 * a time in pure Go (reference types/tx_vote.go:110-119); this file is
 * part of the TPU rebuild's host runtime, not a port.
 *
 * Build: cc -O3 -shared -fPIC -o _prep.so prep.c   (see native/__init__.py)
 * Parity: tests/test_native_prep.py pins sha512 against hashlib and the
 * batch outputs against the pure-Python prepare path, including S >= L,
 * short signatures, and extreme digests.
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

/* ------------------------------------------------------------------ */
/* SHA-512                                                             */
/* ------------------------------------------------------------------ */

static const uint64_t KTAB[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL, 0xe9b5dba58189dbbcULL,
    0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL, 0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL,
    0xd807aa98a3030242ULL, 0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL, 0xc19bf174cf692694ULL,
    0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL, 0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL,
    0x2de92c6f592b0275ULL, 0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL, 0xbf597fc7beef0ee4ULL,
    0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL, 0x06ca6351e003826fULL, 0x142929670a0e6e70ULL,
    0x27b70a8546d22ffcULL, 0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL, 0x92722c851482353bULL,
    0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL, 0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL,
    0xd192e819d6ef5218ULL, 0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL, 0x34b0bcb5e19b48a8ULL,
    0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL, 0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL,
    0x748f82ee5defb2fcULL, 0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL, 0xc67178f2e372532bULL,
    0xca273eceea26619cULL, 0xd186b8c721c0c207ULL, 0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL,
    0x06f067aa72176fbaULL, 0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL, 0x431d67c49c100d4cULL,
    0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL, 0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL,
};

static const uint64_t H0[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL,
};

static inline uint64_t rotr64(uint64_t x, int n) {
    return (x >> n) | (x << (64 - n));
}

static inline uint64_t load_be64(const uint8_t *p) {
    return ((uint64_t)p[0] << 56) | ((uint64_t)p[1] << 48) |
           ((uint64_t)p[2] << 40) | ((uint64_t)p[3] << 32) |
           ((uint64_t)p[4] << 24) | ((uint64_t)p[5] << 16) |
           ((uint64_t)p[6] << 8) | (uint64_t)p[7];
}

static inline void store_be64(uint8_t *p, uint64_t v) {
    for (int i = 7; i >= 0; --i) {
        p[i] = (uint8_t)(v & 0xff);
        v >>= 8;
    }
}

static void sha512_block(uint64_t st[8], const uint8_t *blk) {
    uint64_t w[80];
    for (int t = 0; t < 16; ++t) w[t] = load_be64(blk + 8 * t);
    for (int t = 16; t < 80; ++t) {
        uint64_t s0 = rotr64(w[t - 15], 1) ^ rotr64(w[t - 15], 8) ^ (w[t - 15] >> 7);
        uint64_t s1 = rotr64(w[t - 2], 19) ^ rotr64(w[t - 2], 61) ^ (w[t - 2] >> 6);
        w[t] = w[t - 16] + s0 + w[t - 7] + s1;
    }
    uint64_t a = st[0], b = st[1], c = st[2], d = st[3];
    uint64_t e = st[4], f = st[5], g = st[6], h = st[7];
    for (int t = 0; t < 80; ++t) {
        uint64_t S1 = rotr64(e, 14) ^ rotr64(e, 18) ^ rotr64(e, 41);
        uint64_t ch = (e & f) ^ (~e & g);
        uint64_t t1 = h + S1 + ch + KTAB[t] + w[t];
        uint64_t S0 = rotr64(a, 28) ^ rotr64(a, 34) ^ rotr64(a, 39);
        uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint64_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    st[0] += a; st[1] += b; st[2] += c; st[3] += d;
    st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

typedef struct {
    uint64_t st[8];
    uint8_t buf[128];
    uint64_t total;  /* bytes fed (message lengths here are far below 2^61) */
    size_t fill;
} sha512_ctx;

static void sha512_init(sha512_ctx *c) {
    memcpy(c->st, H0, sizeof(H0));
    c->total = 0;
    c->fill = 0;
}

static void sha512_update(sha512_ctx *c, const uint8_t *data, size_t len) {
    c->total += len;
    if (c->fill) {
        size_t take = 128 - c->fill;
        if (take > len) take = len;
        memcpy(c->buf + c->fill, data, take);
        c->fill += take;
        data += take;
        len -= take;
        if (c->fill == 128) {
            sha512_block(c->st, c->buf);
            c->fill = 0;
        }
    }
    while (len >= 128) {
        sha512_block(c->st, data);
        data += 128;
        len -= 128;
    }
    if (len) {
        memcpy(c->buf, data, len);
        c->fill = len;
    }
}

static void sha512_final(sha512_ctx *c, uint8_t out[64]) {
    uint64_t bits = c->total * 8;
    uint8_t pad = 0x80;
    sha512_update(c, &pad, 1);
    uint8_t z[128];
    memset(z, 0, sizeof(z));
    size_t padlen = (c->fill <= 112) ? (112 - c->fill) : (240 - c->fill);
    sha512_update(c, z, padlen);
    uint8_t lenb[16];
    memset(lenb, 0, 8);
    store_be64(lenb + 8, bits);
    sha512_update(c, lenb, 16);
    /* fill is now 0: exactly block-aligned */
    for (int i = 0; i < 8; ++i) store_be64(out + 8 * i, c->st[i]);
}

/* exported for the parity test */
void txflow_sha512(const uint8_t *data, size_t len, uint8_t out[64]) {
    sha512_ctx c;
    sha512_init(&c);
    sha512_update(&c, data, len);
    sha512_final(&c, out);
}

/* ------------------------------------------------------------------ */
/* Reduction mod L = 2^252 + c                                         */
/* ------------------------------------------------------------------ */

#define C0 0x5812631a5cf5d3edULL
#define C1 0x14def9dea2f79cd6ULL
static const uint64_t L_LIMBS[4] = {C0, C1, 0ULL, 0x1000000000000000ULL};

/* big = little-endian uint64 limb vectors; lengths are fixed small */

static int big_cmp(const uint64_t *a, const uint64_t *b, int n) {
    for (int i = n - 1; i >= 0; --i) {
        if (a[i] != b[i]) return a[i] > b[i] ? 1 : -1;
    }
    return 0;
}

/* r = a - b (a >= b), n limbs */
static void big_sub(uint64_t *r, const uint64_t *a, const uint64_t *b, int n) {
    unsigned __int128 borrow = 0;
    for (int i = 0; i < n; ++i) {
        unsigned __int128 d = (unsigned __int128)a[i] - b[i] - borrow;
        r[i] = (uint64_t)d;
        borrow = (d >> 64) & 1; /* two's-complement borrow flag */
    }
}

static int big_is_zero(const uint64_t *a, int n) {
    for (int i = 0; i < n; ++i)
        if (a[i]) return 0;
    return 1;
}

/* h_le[32] = (512-bit little-endian digest) mod L */
static void reduce_mod_l(const uint8_t digest[64], uint8_t h_le[32]) {
    uint64_t v[8];
    for (int i = 0; i < 8; ++i) {
        uint64_t x = 0;
        for (int j = 7; j >= 0; --j) x = (x << 8) | digest[8 * i + j];
        v[i] = x;
    }
    int nv = 8;  /* live limbs of v */
    int neg = 0;
    /* fold at bit 252: v = lo - c*hi (sign tracked); <= 4 folds suffice
       (512 -> 385 -> 258 -> <252 bits) */
    for (int it = 0; it < 6; ++it) {
        /* hi = v >> 252: limb 3 bits 60.., then limbs 4.. */
        uint64_t hi[5] = {0, 0, 0, 0, 0};
        int hi_n = 0;
        if (nv > 3) {
            for (int i = 3; i < nv; ++i) {
                uint64_t lo_part = v[i] >> 60;
                uint64_t hi_part = (i + 1 < nv) ? (v[i + 1] << 4) : 0;
                hi[i - 3] = lo_part | hi_part;
            }
            hi_n = nv - 3;
            while (hi_n > 0 && hi[hi_n - 1] == 0) --hi_n;
        }
        if (hi_n == 0) break;
        /* lo = v & (2^252 - 1) */
        uint64_t lo[4] = {v[0], v[1], v[2], v[3] & 0x0fffffffffffffffULL};
        /* chi = c * hi  (c is 2 limbs, hi up to 5 -> product up to 7) */
        uint64_t chi[8] = {0};
        for (int i = 0; i < hi_n; ++i) {
            unsigned __int128 carry = 0;
            unsigned __int128 p0 = (unsigned __int128)hi[i] * C0 + chi[i];
            chi[i] = (uint64_t)p0;
            carry = p0 >> 64;
            unsigned __int128 p1 = (unsigned __int128)hi[i] * C1 + chi[i + 1] + carry;
            chi[i + 1] = (uint64_t)p1;
            carry = p1 >> 64;
            int k = i + 2;
            while (carry) {
                unsigned __int128 s = (unsigned __int128)chi[k] + carry;
                chi[k] = (uint64_t)s;
                carry = s >> 64;
                ++k;
            }
        }
        int chi_n = hi_n + 2;
        while (chi_n > 0 && chi[chi_n - 1] == 0) --chi_n;
        /* v = |lo - chi|, sign flips when chi > lo */
        int n = chi_n > 4 ? chi_n : 4;
        uint64_t lo_ext[8] = {0}, res[8] = {0};
        memcpy(lo_ext, lo, sizeof(lo));
        if (big_cmp(lo_ext, chi, n) >= 0) {
            big_sub(res, lo_ext, chi, n);
        } else {
            big_sub(res, chi, lo_ext, n);
            neg = !neg;
        }
        memcpy(v, res, sizeof(v));
        nv = n;
        while (nv > 1 && v[nv - 1] == 0) --nv;
    }
    /* v < 2^252 <= L now; fold sign back into [0, L) */
    uint64_t r[4] = {v[0], v[1], v[2], v[3]};
    if (neg && !big_is_zero(r, 4)) {
        uint64_t t[4];
        big_sub(t, L_LIMBS, r, 4);
        memcpy(r, t, sizeof(r));
    }
    for (int i = 0; i < 4; ++i) {
        uint64_t x = r[i];
        for (int j = 0; j < 8; ++j) {
            h_le[8 * i + j] = (uint8_t)(x & 0xff);
            x >>= 8;
        }
    }
}

/* S < L check on a 32-byte little-endian scalar */
static int sc_minimal(const uint8_t s[32]) {
    uint64_t limbs[4];
    for (int i = 0; i < 4; ++i) {
        uint64_t x = 0;
        for (int j = 7; j >= 0; --j) x = (x << 8) | s[8 * i + j];
        limbs[i] = x;
    }
    return big_cmp(limbs, L_LIMBS, 4) < 0;
}

/* ------------------------------------------------------------------ */
/* Batch entry point                                                   */
/* ------------------------------------------------------------------ */

/* For each vote i with ok_in[i] != 0:
 *   sigs[i*64 .. +64]  = R || S          (already length-validated host-side)
 *   pubs[i*32 .. +32]  = A               (pre-gathered per vote)
 *   msgs[offs[i] .. offs[i+1]]           = sign bytes
 * Outputs: s_le/h_le [i*32 .. +32], ok_out[i] = ok_in && S < L.
 */
void txflow_prep_batch(const uint8_t *msgs, const int64_t *offs,
                       const uint8_t *sigs, const uint8_t *pubs,
                       const uint8_t *ok_in, int64_t n, uint8_t *s_le,
                       uint8_t *h_le, uint8_t *ok_out) {
    for (int64_t i = 0; i < n; ++i) {
        ok_out[i] = 0;
        if (!ok_in[i]) continue;
        const uint8_t *sig = sigs + 64 * i;
        if (!sc_minimal(sig + 32)) continue;
        sha512_ctx c;
        uint8_t digest[64];
        sha512_init(&c);
        sha512_update(&c, sig, 32);                       /* R */
        sha512_update(&c, pubs + 32 * i, 32);             /* A */
        sha512_update(&c, msgs + offs[i],
                      (size_t)(offs[i + 1] - offs[i]));   /* msg */
        sha512_final(&c, digest);
        reduce_mod_l(digest, h_le + 32 * i);
        memcpy(s_le + 32 * i, sig + 32, 32);
        ok_out[i] = 1;
    }
}

/* Batched SHA-256-free helper: digest(R||A||msg) only, for reuse/testing */
void txflow_h_batch(const uint8_t *msgs, const int64_t *offs,
                    const uint8_t *sigs, const uint8_t *pubs, int64_t n,
                    uint8_t *digests) {
    for (int64_t i = 0; i < n; ++i) {
        sha512_ctx c;
        sha512_init(&c);
        sha512_update(&c, sigs + 64 * i, 32);
        sha512_update(&c, pubs + 32 * i, 32);
        sha512_update(&c, msgs + offs[i], (size_t)(offs[i + 1] - offs[i]));
        sha512_final(&c, digests + 64 * i);
    }
}
