/* Native batch canonical sign-bytes for TxVotes.
 *
 * canonical_sign_bytes (types/tx_vote.py) is the per-vote amino encoding
 * of CanonicalTxVote{Height fixed64, TxHash, TxKey(zeroed), Timestamp,
 * ChainID} that the verifier hashes. The hand-tightened Python runs in
 * ~4 us per FRESH vote (it is cached afterwards, but every vote is fresh
 * exactly once per object) — at bench rates that is a top-5 host cost
 * (r5 instrumented profile). This batch form does the whole drain batch
 * in one C call (~0.1 us/vote).
 *
 * Wire layout is pinned by the golden vectors in tests/test_tx_vote.py
 * and the native/Python parity test (tests/test_native_prep.py):
 *   uvarint(len(body)) || body, where body =
 *     [0x09 u64le(height)]            if height != 0     (field 1 fixed64)
 *     [0x12 uvarint(len) hash-ascii]  if len != 0        (field 2)
 *     [0x1a 0x20 32x00]               always             (field 3, zeroed
 *                                      TxKey — the reference's
 *                                      canonicalization quirk)
 *     [0x22 uvarint(len) time-body]   if body != empty   (field 4)
 *     [0x2a uvarint(len) chain-id]    if len != 0        (field 5)
 *   time-body = [0x08 uvarint(seconds as u64)] if seconds != 0
 *               [0x10 uvarint(nanos)]          if nanos != 0
 *   with (seconds, nanos) = floor-divmod(unix_ns, 1e9) — Go Time.Unix
 *   semantics for negative times, matching codec/amino.py.
 *
 * The reference has no native code (pure Go, types/tx_vote.go:177-192);
 * this is the TPU rebuild's host runtime, not a port.
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

static inline size_t put_uvarint(uint8_t *out, uint64_t n) {
    size_t i = 0;
    while (n > 0x7F) {
        out[i++] = (uint8_t)(n & 0x7F) | 0x80;
        n >>= 7;
    }
    out[i++] = (uint8_t)n;
    return i;
}

/* One vote's sign bytes into out (caller guarantees capacity); returns
 * total length (length prefix included). */
static size_t sign_bytes_one(
    uint8_t *out,
    int64_t height,
    const uint8_t *hash, int32_t hash_len,
    int64_t ts_ns,
    const uint8_t *chain, int32_t chain_len) {
    uint8_t body[512];
    size_t n = 0;

    if (height != 0) {
        body[n++] = 0x09;
        uint64_t h = (uint64_t)height;
        for (int i = 0; i < 8; i++) body[n++] = (uint8_t)(h >> (8 * i));
    }
    if (hash_len > 0) {
        body[n++] = 0x12;
        n += put_uvarint(body + n, (uint64_t)hash_len);
        memcpy(body + n, hash, (size_t)hash_len);
        n += (size_t)hash_len;
    }
    body[n++] = 0x1a;
    body[n++] = 0x20;
    memset(body + n, 0, 32);
    n += 32;

    /* floor divmod for negative timestamps (Go Time.Unix semantics) */
    int64_t seconds = ts_ns / 1000000000LL;
    int64_t nanos = ts_ns % 1000000000LL;
    if (nanos < 0) {
        nanos += 1000000000LL;
        seconds -= 1;
    }
    uint8_t ts_body[24];
    size_t tn = 0;
    if (seconds != 0) {
        ts_body[tn++] = 0x08;
        tn += put_uvarint(ts_body + tn, (uint64_t)seconds);
    }
    if (nanos != 0) {
        ts_body[tn++] = 0x10;
        tn += put_uvarint(ts_body + tn, (uint64_t)nanos);
    }
    if (tn > 0) {
        body[n++] = 0x22;
        n += put_uvarint(body + n, (uint64_t)tn);
        memcpy(body + n, ts_body, tn);
        n += tn;
    }
    if (chain_len > 0) {
        body[n++] = 0x2a;
        n += put_uvarint(body + n, (uint64_t)chain_len);
        memcpy(body + n, chain, (size_t)chain_len);
        n += (size_t)chain_len;
    }

    size_t pl = put_uvarint(out, (uint64_t)n);
    memcpy(out + pl, body, n);
    return pl + n;
}

/* Batch API: hashes packed at fixed stride (ASCII, per-item lengths).
 * out is n_votes * out_stride bytes; out_lens receives each total. A
 * vote whose encoding would exceed out_stride gets out_lens = -1 (the
 * caller falls back to Python for it — cannot happen for real votes:
 * 64-char hashes + chain ids < 300 bytes). */
void txflow_sign_bytes_batch(
    int64_t n_votes,
    const int64_t *heights,
    const uint8_t *hashes, int64_t hash_stride, const int32_t *hash_lens,
    const int64_t *timestamps,
    const uint8_t *chain, int32_t chain_len,
    uint8_t *out, int64_t out_stride, int32_t *out_lens) {
    /* HARD bounds, independent of out_stride: sign_bytes_one assembles
     * into a 512-byte stack body, so attacker-length fields must be
     * rejected HERE (r5 review: a gossiped unsigned vote with a 5000-char
     * tx_hash reached this path before any signature check and smashed
     * the stack). Real hashes are 64 ASCII chars; chain ids are short.
     * Worst accepted case: 9 + 2+256 + 34 + 2+22 + 2+128 + prefix < 512. */
    if (chain_len < 0 || chain_len > 128) {
        for (int64_t i = 0; i < n_votes; i++) out_lens[i] = -1;
        return;
    }
    for (int64_t i = 0; i < n_votes; i++) {
        int64_t need = 72 + hash_lens[i] + chain_len;
        if (hash_lens[i] < 0 || hash_lens[i] > 256 || need > out_stride) {
            out_lens[i] = -1;
            continue;
        }
        out_lens[i] = (int32_t)sign_bytes_one(
            out + i * out_stride,
            heights[i],
            hashes + i * hash_stride, hash_lens[i],
            timestamps[i],
            chain, chain_len);
    }
}
