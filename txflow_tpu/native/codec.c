/* Native batch canonical sign-bytes for TxVotes.
 *
 * canonical_sign_bytes (types/tx_vote.py) is the per-vote amino encoding
 * of CanonicalTxVote{Height fixed64, TxHash, TxKey(zeroed), Timestamp,
 * ChainID} that the verifier hashes. The hand-tightened Python runs in
 * ~4 us per FRESH vote (it is cached afterwards, but every vote is fresh
 * exactly once per object) — at bench rates that is a top-5 host cost
 * (r5 instrumented profile). This batch form does the whole drain batch
 * in one C call (~0.1 us/vote).
 *
 * Wire layout is pinned by the golden vectors in tests/test_tx_vote.py
 * and the native/Python parity test (tests/test_native_prep.py):
 *   uvarint(len(body)) || body, where body =
 *     [0x09 u64le(height)]            if height != 0     (field 1 fixed64)
 *     [0x12 uvarint(len) hash-ascii]  if len != 0        (field 2)
 *     [0x1a 0x20 32x00]               always             (field 3, zeroed
 *                                      TxKey — the reference's
 *                                      canonicalization quirk)
 *     [0x22 uvarint(len) time-body]   if body != empty   (field 4)
 *     [0x2a uvarint(len) chain-id]    if len != 0        (field 5)
 *   time-body = [0x08 uvarint(seconds as u64)] if seconds != 0
 *               [0x10 uvarint(nanos)]          if nanos != 0
 *   with (seconds, nanos) = floor-divmod(unix_ns, 1e9) — Go Time.Unix
 *   semantics for negative times, matching codec/amino.py.
 *
 * The reference has no native code (pure Go, types/tx_vote.go:177-192);
 * this is the TPU rebuild's host runtime, not a port.
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

#ifndef INT64_MAX
#define INT64_MAX 0x7fffffffffffffffLL
#define INT64_MIN (-INT64_MAX - 1)
#endif

static inline size_t put_uvarint(uint8_t *out, uint64_t n) {
    size_t i = 0;
    while (n > 0x7F) {
        out[i++] = (uint8_t)(n & 0x7F) | 0x80;
        n >>= 7;
    }
    out[i++] = (uint8_t)n;
    return i;
}

/* One vote's sign bytes into out (caller guarantees capacity); returns
 * total length (length prefix included). */
static size_t sign_bytes_one(
    uint8_t *out,
    int64_t height,
    const uint8_t *hash, int32_t hash_len,
    int64_t ts_ns,
    const uint8_t *chain, int32_t chain_len) {
    uint8_t body[512];
    size_t n = 0;

    if (height != 0) {
        body[n++] = 0x09;
        uint64_t h = (uint64_t)height;
        for (int i = 0; i < 8; i++) body[n++] = (uint8_t)(h >> (8 * i));
    }
    if (hash_len > 0) {
        body[n++] = 0x12;
        n += put_uvarint(body + n, (uint64_t)hash_len);
        memcpy(body + n, hash, (size_t)hash_len);
        n += (size_t)hash_len;
    }
    body[n++] = 0x1a;
    body[n++] = 0x20;
    memset(body + n, 0, 32);
    n += 32;

    /* floor divmod for negative timestamps (Go Time.Unix semantics) */
    int64_t seconds = ts_ns / 1000000000LL;
    int64_t nanos = ts_ns % 1000000000LL;
    if (nanos < 0) {
        nanos += 1000000000LL;
        seconds -= 1;
    }
    uint8_t ts_body[24];
    size_t tn = 0;
    if (seconds != 0) {
        ts_body[tn++] = 0x08;
        tn += put_uvarint(ts_body + tn, (uint64_t)seconds);
    }
    if (nanos != 0) {
        ts_body[tn++] = 0x10;
        tn += put_uvarint(ts_body + tn, (uint64_t)nanos);
    }
    if (tn > 0) {
        body[n++] = 0x22;
        n += put_uvarint(body + n, (uint64_t)tn);
        memcpy(body + n, ts_body, tn);
        n += tn;
    }
    if (chain_len > 0) {
        body[n++] = 0x2a;
        n += put_uvarint(body + n, (uint64_t)chain_len);
        memcpy(body + n, chain, (size_t)chain_len);
        n += (size_t)chain_len;
    }

    size_t pl = put_uvarint(out, (uint64_t)n);
    memcpy(out + pl, body, n);
    return pl + n;
}

/* Batch API: hashes packed at fixed stride (ASCII, per-item lengths).
 * out is n_votes * out_stride bytes; out_lens receives each total. A
 * vote whose encoding would exceed out_stride gets out_lens = -1 (the
 * caller falls back to Python for it — cannot happen for real votes:
 * 64-char hashes + chain ids < 300 bytes). */
void txflow_sign_bytes_batch(
    int64_t n_votes,
    const int64_t *heights,
    const uint8_t *hashes, int64_t hash_stride, const int32_t *hash_lens,
    const int64_t *timestamps,
    const uint8_t *chain, int32_t chain_len,
    uint8_t *out, int64_t out_stride, int32_t *out_lens) {
    /* HARD bounds, independent of out_stride: sign_bytes_one assembles
     * into a 512-byte stack body, so attacker-length fields must be
     * rejected HERE (r5 review: a gossiped unsigned vote with a 5000-char
     * tx_hash reached this path before any signature check and smashed
     * the stack). Real hashes are 64 ASCII chars; chain ids are short.
     * Worst accepted case: 9 + 2+256 + 34 + 2+22 + 2+128 + prefix < 512. */
    if (chain_len < 0 || chain_len > 128) {
        for (int64_t i = 0; i < n_votes; i++) out_lens[i] = -1;
        return;
    }
    for (int64_t i = 0; i < n_votes; i++) {
        int64_t need = 72 + hash_lens[i] + chain_len;
        if (hash_lens[i] < 0 || hash_lens[i] > 256 || need > out_stride) {
            out_lens[i] = -1;
            continue;
        }
        out_lens[i] = (int32_t)sign_bytes_one(
            out + i * out_stride,
            heights[i],
            hashes + i * hash_stride, hash_lens[i],
            timestamps[i],
            chain, chain_len);
    }
}

/* ------------------------------------------------------------------ */
/* Batch TxVote wire decode                                            */
/* ------------------------------------------------------------------ */
/* Field LOCATOR for the amino TxVote wire form: mirrors the accept-set
 * of types/tx_vote.py decode_tx_vote EXACTLY (pinned by the fuzz parity
 * test in tests/test_fuzz_codec.py). C locates the fields and computes
 * the canonical flag; Python slices the bytes and builds the TxVote
 * (including the strict UTF-8 validation of tx_hash, which happens for
 * free in the str construction Python must do anyway). Decode measured
 * ~6 us/vote in Python — once per unique gossiped vote per process. */

/* uvarint with Go binary.Uvarint overflow rules.
 * Returns bytes consumed (>0), 0 on error. *minimal = last group != 0. */
static size_t get_uvarint(
    const uint8_t *p, size_t avail, uint64_t *out, int *minimal) {
    uint64_t n = 0;
    int shift = 0;
    size_t i = 0;
    for (;;) {
        if (i >= avail) return 0;                /* truncated */
        uint8_t b = p[i++];
        if (shift == 63 && b > 1) return 0;      /* overflows 64 bits */
        n |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) {
            *out = n;
            *minimal = (b != 0);
            return i;
        }
        shift += 7;
        if (shift > 63) return 0;
    }
}

/* time.Time body: (seconds*1e9 + nanos, canonical). 0 ok, -1 error. */
static int decode_ts_body(
    const uint8_t *p, size_t len, int64_t *ts_out, int *canon_out) {
    size_t pos = 0;
    int64_t seconds = 0;
    uint64_t nanos = 0;
    int canonical = 1;
    uint64_t prev = 0;
    if (len == 0) { *ts_out = 0; *canon_out = 0; return 0; }
    while (pos < len) {
        uint64_t key; int mini;
        size_t c = get_uvarint(p + pos, len - pos, &key, &mini);
        if (!c) return -1;
        pos += c;
        if (!mini) canonical = 0;
        uint64_t fnum = key >> 3;
        uint64_t typ3 = key & 7;
        if (fnum <= prev) canonical = 0;
        prev = fnum;
        if (typ3 == 0) {
            uint64_t v;
            c = get_uvarint(p + pos, len - pos, &v, &mini);
            if (!c) return -1;
            pos += c;
            if (!mini) canonical = 0;
            if (fnum == 1) {
                seconds = (int64_t)v;  /* two's complement, like Python */
                if (seconds == 0) canonical = 0;
            } else if (fnum == 2) {
                nanos = v;
                if (!(v > 0 && v < 1000000000ULL)) canonical = 0;
            } else {
                canonical = 0;
            }
        } else if (typ3 == 1) {
            if (pos + 8 > len) return -1;
            pos += 8;
            canonical = 0;
        } else if (typ3 == 2) {
            uint64_t ln;
            c = get_uvarint(p + pos, len - pos, &ln, &mini);
            if (!c) return -1;
            pos += c;
            if (!mini) canonical = 0;
            if (ln > len || pos + ln > len) return -1;
            pos += ln;
            canonical = 0;
        } else {
            return -1;
        }
    }
    /* seconds * 1e9 + nanos with Python bigint semantics: compute the
     * exact sum in 128-bit and fall back to the Python decoder whenever
     * it does not fit int64 (hostile seconds OR nanos — r5 review
     * reproduced a silent divergence when only seconds was guarded:
     * compiler-equipped and compiler-less nodes would disagree on the
     * same wire bytes). Real votes are nowhere near these bounds. */
    {
        __int128 total = (__int128)seconds * 1000000000LL + (__int128)nanos;
        if (total > (__int128)INT64_MAX || total < (__int128)INT64_MIN)
            return -2; /* caller: python fallback */
        *ts_out = (int64_t)total;
    }
    *canon_out = canonical;
    return 0;
}

/* flags: bit0 = parsed ok; bit1 = canonical; bit2 = needs python
 * fallback (rare exactness corner).  Offsets are GLOBAL into buf;
 * *_off = -1 means absent. */
void txflow_decode_votes(
    const uint8_t *buf, const int64_t *offsets, int64_t n,
    int64_t *heights, int64_t *timestamps,
    int32_t *hash_off, int32_t *hash_len,
    int32_t *key_off,
    int32_t *addr_off, int32_t *addr_len,
    int32_t *sig_off, int32_t *sig_len,
    uint8_t *flags) {
    for (int64_t i = 0; i < n; i++) {
        const uint8_t *p = buf + offsets[i];
        size_t end = (size_t)(offsets[i + 1] - offsets[i]);
        size_t pos = 0;
        int canonical = 1, ok = 1, py_fallback = 0;
        uint64_t prev_fnum = 0;
        heights[i] = 0;
        timestamps[i] = 0;
        hash_off[i] = -1; hash_len[i] = 0;
        key_off[i] = -1;
        addr_off[i] = -1; addr_len[i] = 0;
        sig_off[i] = -1; sig_len[i] = 0;
        while (pos < end) {
            uint64_t key; int mini;
            size_t c = get_uvarint(p + pos, end - pos, &key, &mini);
            if (!c) { ok = 0; break; }
            pos += c;
            if (!mini) canonical = 0;
            uint64_t fnum = key >> 3;
            uint64_t typ3 = key & 7;
            if (fnum <= prev_fnum) canonical = 0;
            prev_fnum = fnum;
            if (typ3 == 2) {
                uint64_t ln;
                c = get_uvarint(p + pos, end - pos, &ln, &mini);
                if (!c) { ok = 0; break; }
                pos += c;
                if (!mini) canonical = 0;
                if (ln > end || pos + ln > end) { ok = 0; break; }
                int32_t off = (int32_t)(offsets[i] + (int64_t)pos);
                if (fnum == 2) {
                    hash_off[i] = off; hash_len[i] = (int32_t)ln;
                    if (ln == 0) canonical = 0;
                } else if (fnum == 3) {
                    if (ln != 32) { ok = 0; break; }  /* Go array error */
                    key_off[i] = off;
                } else if (fnum == 4) {
                    int canon2;
                    int r = decode_ts_body(p + pos, ln, &timestamps[i], &canon2);
                    if (r == -1) { ok = 0; break; }
                    if (r == -2) { py_fallback = 1; break; }
                    if (!canon2) canonical = 0;
                } else if (fnum == 5) {
                    addr_off[i] = off; addr_len[i] = (int32_t)ln;
                    if (ln == 0) canonical = 0;
                } else if (fnum == 6) {
                    sig_off[i] = off; sig_len[i] = (int32_t)ln;
                    if (ln == 0) canonical = 0;
                } else {
                    canonical = 0;  /* unknown BYTELEN: skipped */
                }
                pos += ln;
            } else if (typ3 == 0) {
                uint64_t v;
                c = get_uvarint(p + pos, end - pos, &v, &mini);
                if (!c) { ok = 0; break; }
                pos += c;
                if (!mini) canonical = 0;
                if (fnum == 1) {
                    heights[i] = (int64_t)v;  /* two's complement */
                    if (heights[i] == 0) canonical = 0;
                } else {
                    canonical = 0;  /* unknown varint: skipped */
                }
            } else if (typ3 == 1) {
                if (pos + 8 > end) { ok = 0; break; }
                pos += 8;
                canonical = 0;
            } else {
                ok = 0;
                break;
            }
        }
        flags[i] = (uint8_t)((ok ? 1 : 0) | (canonical ? 2 : 0) |
                             (py_fallback ? 4 : 0));
    }
}
