"""Native host-runtime pieces, built on demand with the system C compiler.

``prep.c`` implements the batched verify prep (SHA-512 + mod-L + ScMinimal)
that feeds the device kernel; the Python fallback in ops/ed25519_batch.py
remains both the parity oracle and the no-compiler path. The library is
(re)built lazily the first time it is needed — one ``cc -O3 -shared`` per
source change, cached as ``_prep.so`` next to the source.

No pip/apt dependencies: plain ctypes against a cc-built shared object.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "prep.c")
_SRC_CODEC = os.path.join(_DIR, "codec.c")
_SO = os.path.join(_DIR, "_prep.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    """Compile prep.c -> _prep.so if missing or stale. True on success."""
    # codec.c is optional: a tree without it still builds prep.c alone
    # (sign_bytes_batch then reports unavailable via the hasattr check)
    srcs = [s for s in (_SRC, _SRC_CODEC) if os.path.exists(s)]
    if not srcs:
        return False
    try:
        if os.path.exists(_SO) and os.path.getmtime(_SO) >= max(
            os.path.getmtime(s) for s in srcs
        ):
            return True
    except OSError:
        return False
    tmp = _SO + ".tmp%d" % os.getpid()
    for cc in ("cc", "gcc", "g++"):
        try:
            r = subprocess.run(
                [cc, "-O3", "-shared", "-fPIC", "-o", tmp] + srcs,
                capture_output=True,
                timeout=120,
            )
        except (OSError, subprocess.TimeoutExpired):
            continue
        if r.returncode == 0:
            os.replace(tmp, _SO)  # atomic vs concurrent builders
            return True
    try:
        os.unlink(tmp)
    except OSError:
        pass
    return False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.txflow_prep_batch.argtypes = [
            u8p, i64p, u8p, u8p, u8p, ctypes.c_int64, u8p, u8p, u8p,
        ]
        lib.txflow_prep_batch.restype = None
        lib.txflow_sha512.argtypes = [u8p, ctypes.c_size_t, u8p]
        lib.txflow_sha512.restype = None
        i32p = ctypes.POINTER(ctypes.c_int32)
        # codec.c symbols are OPTIONAL (the .so may have been built
        # without it): ctypes attribute access raises on a missing
        # symbol, which would otherwise break available() entirely and
        # make the hasattr fallbacks downstream unreachable (r5 review)
        try:
            lib.txflow_sign_bytes_batch.argtypes = [
                ctypes.c_int64,  # n_votes
                i64p,  # heights
                u8p, ctypes.c_int64, i32p,  # hashes, stride, lens
                i64p,  # timestamps
                u8p, ctypes.c_int32,  # chain, len
                u8p, ctypes.c_int64, i32p,  # out, stride, lens
            ]
            lib.txflow_sign_bytes_batch.restype = None
            lib.txflow_decode_votes.argtypes = [
                u8p, i64p, ctypes.c_int64,  # buf, offsets, n
                i64p, i64p,  # heights, timestamps
                i32p, i32p,  # hash off/len
                i32p,  # key off
                i32p, i32p,  # addr off/len
                i32p, i32p,  # sig off/len
                u8p,  # flags
            ]
            lib.txflow_decode_votes.restype = None
        except AttributeError:
            pass
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def sha512(data: bytes) -> bytes:
    """One-shot SHA-512 through the native module (parity-test surface)."""
    lib = _load()
    assert lib is not None
    buf = np.frombuffer(data, np.uint8) if data else np.zeros(0, np.uint8)
    out = np.zeros(64, np.uint8)
    lib.txflow_sha512(_u8p(np.ascontiguousarray(buf)), len(data), _u8p(out))
    return out.tobytes()


def prep_batch(
    msgs_concat: np.ndarray,
    offsets: np.ndarray,
    sigs: np.ndarray,
    pubs: np.ndarray,
    ok_in: np.ndarray,
):
    """Batched S/h prep: returns (s_le [n,32], h_le [n,32], ok [n] bool).

    msgs_concat: uint8[total]; offsets: int64[n+1]; sigs: uint8[n,64];
    pubs: uint8[n,32] (pre-gathered per vote); ok_in: uint8[n] (host checks:
    signature length, validator index range, key decompresses).
    """
    lib = _load()
    assert lib is not None
    n = len(ok_in)
    s_le = np.zeros((n, 32), np.uint8)
    h_le = np.zeros((n, 32), np.uint8)
    ok = np.zeros(n, np.uint8)
    lib.txflow_prep_batch(
        _u8p(np.ascontiguousarray(msgs_concat)),
        np.ascontiguousarray(offsets, np.int64).ctypes.data_as(
            ctypes.POINTER(ctypes.c_int64)
        ),
        _u8p(np.ascontiguousarray(sigs)),
        _u8p(np.ascontiguousarray(pubs)),
        _u8p(np.ascontiguousarray(ok_in, np.uint8)),
        n,
        _u8p(s_le),
        _u8p(h_le),
        _u8p(ok),
    )
    return s_le, h_le, ok.astype(bool)


def sign_bytes_batch(
    heights: list[int],
    tx_hashes: list[str],
    timestamps: list[int],
    chain_id: str,
) -> list[bytes | None] | None:
    """Batched canonical sign bytes (codec.c).

    None when the native library is unavailable; otherwise a per-vote
    list where an item is None if its fields exceed the C-side bounds
    (hash > 256 chars / chain id > 128 bytes — possible only for hostile
    votes; real hashes are 64 chars). Callers Python-fallback per item.
    """
    lib = _load()
    if lib is None or not hasattr(lib, "txflow_sign_bytes_batch"):
        return None
    n = len(heights)
    if n == 0:
        return []
    chain = chain_id.encode()
    hb = [h.encode() for h in tx_hashes]
    hash_stride = max(len(b) for b in hb) or 1
    hashes = np.zeros((n, hash_stride), np.uint8)
    hash_lens = np.zeros(n, np.int32)
    for i, b in enumerate(hb):
        hashes[i, : len(b)] = np.frombuffer(b, np.uint8)
        hash_lens[i] = len(b)
    out_stride = 96 + hash_stride + len(chain)
    out = np.zeros((n, out_stride), np.uint8)
    out_lens = np.zeros(n, np.int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.txflow_sign_bytes_batch(
        n,
        np.ascontiguousarray(heights, np.int64).ctypes.data_as(i64p),
        _u8p(hashes), hash_stride, hash_lens.ctypes.data_as(i32p),
        np.ascontiguousarray(timestamps, np.int64).ctypes.data_as(i64p),
        _u8p(np.frombuffer(chain, np.uint8)) if chain else _u8p(np.zeros(1, np.uint8)),
        len(chain),
        _u8p(out), out_stride, out_lens.ctypes.data_as(i32p),
    )
    ob = out.tobytes()
    # per-item None for oversized fields (the C side hard-rejects them —
    # a hostile vote must only cost ITS OWN Python fallback, not the
    # whole batch's)
    return [
        ob[i * out_stride : i * out_stride + out_lens[i]]
        if out_lens[i] >= 0
        else None
        for i in range(n)
    ]


def decode_votes_fields(segs: list[bytes]):
    """Batch field-location pass for amino TxVote segments (codec.c).

    Returns (heights, timestamps, hash_off, hash_len, key_off, addr_off,
    addr_len, sig_off, sig_len, flags, concat) — offsets into ``concat``;
    flags bit0 = parsed ok, bit1 = canonical wire, bit2 = exactness
    corner needing the Python decoder. None when native is unavailable.
    The caller (types.tx_vote.decode_tx_votes_many) slices fields and
    builds the TxVote objects.
    """
    lib = _load()
    if lib is None or not hasattr(lib, "txflow_decode_votes"):
        return None
    n = len(segs)
    concat = b"".join(segs)
    offsets = np.zeros(n + 1, np.int64)
    np.cumsum([len(s) for s in segs], out=offsets[1:])
    buf = (
        np.frombuffer(concat, np.uint8)
        if concat
        else np.zeros(1, np.uint8)
    )
    heights = np.zeros(n, np.int64)
    timestamps = np.zeros(n, np.int64)
    i32 = lambda: np.zeros(n, np.int32)  # noqa: E731
    hash_off, hash_len = i32(), i32()
    key_off = i32()
    addr_off, addr_len = i32(), i32()
    sig_off, sig_len = i32(), i32()
    flags = np.zeros(n, np.uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.txflow_decode_votes(
        _u8p(buf),
        offsets.ctypes.data_as(i64p),
        n,
        heights.ctypes.data_as(i64p),
        timestamps.ctypes.data_as(i64p),
        hash_off.ctypes.data_as(i32p), hash_len.ctypes.data_as(i32p),
        key_off.ctypes.data_as(i32p),
        addr_off.ctypes.data_as(i32p), addr_len.ctypes.data_as(i32p),
        sig_off.ctypes.data_as(i32p), sig_len.ctypes.data_as(i32p),
        _u8p(flags),
    )
    return (
        heights, timestamps, hash_off, hash_len, key_off,
        addr_off, addr_len, sig_off, sig_len, flags, concat,
    )
