"""Native host-runtime pieces, built on demand with the system C compiler.

``prep.c`` implements the batched verify prep (SHA-512 + mod-L + ScMinimal)
that feeds the device kernel; the Python fallback in ops/ed25519_batch.py
remains both the parity oracle and the no-compiler path. The library is
(re)built lazily the first time it is needed — one ``cc -O3 -shared`` per
source change, cached as ``_prep.so`` next to the source.

No pip/apt dependencies: plain ctypes against a cc-built shared object.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "prep.c")
_SRC_CODEC = os.path.join(_DIR, "codec.c")
_SO = os.path.join(_DIR, "_prep.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    """Compile prep.c -> _prep.so if missing or stale. True on success."""
    # codec.c is optional: a tree without it still builds prep.c alone
    # (sign_bytes_batch then reports unavailable via the hasattr check)
    srcs = [s for s in (_SRC, _SRC_CODEC) if os.path.exists(s)]
    if not srcs:
        return False
    try:
        if os.path.exists(_SO) and os.path.getmtime(_SO) >= max(
            os.path.getmtime(s) for s in srcs
        ):
            return True
    except OSError:
        return False
    tmp = _SO + ".tmp%d" % os.getpid()
    for cc in ("cc", "gcc", "g++"):
        try:
            r = subprocess.run(
                [cc, "-O3", "-shared", "-fPIC", "-o", tmp] + srcs,
                capture_output=True,
                timeout=120,
            )
        except (OSError, subprocess.TimeoutExpired):
            continue
        if r.returncode == 0:
            os.replace(tmp, _SO)  # atomic vs concurrent builders
            return True
    try:
        os.unlink(tmp)
    except OSError:
        pass
    return False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.txflow_prep_batch.argtypes = [
            u8p, i64p, u8p, u8p, u8p, ctypes.c_int64, u8p, u8p, u8p,
        ]
        lib.txflow_prep_batch.restype = None
        lib.txflow_sha512.argtypes = [u8p, ctypes.c_size_t, u8p]
        lib.txflow_sha512.restype = None
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.txflow_sign_bytes_batch.argtypes = [
            ctypes.c_int64,  # n_votes
            i64p,  # heights
            u8p, ctypes.c_int64, i32p,  # hashes, stride, lens
            i64p,  # timestamps
            u8p, ctypes.c_int32,  # chain, len
            u8p, ctypes.c_int64, i32p,  # out, stride, lens
        ]
        lib.txflow_sign_bytes_batch.restype = None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def sha512(data: bytes) -> bytes:
    """One-shot SHA-512 through the native module (parity-test surface)."""
    lib = _load()
    assert lib is not None
    buf = np.frombuffer(data, np.uint8) if data else np.zeros(0, np.uint8)
    out = np.zeros(64, np.uint8)
    lib.txflow_sha512(_u8p(np.ascontiguousarray(buf)), len(data), _u8p(out))
    return out.tobytes()


def prep_batch(
    msgs_concat: np.ndarray,
    offsets: np.ndarray,
    sigs: np.ndarray,
    pubs: np.ndarray,
    ok_in: np.ndarray,
):
    """Batched S/h prep: returns (s_le [n,32], h_le [n,32], ok [n] bool).

    msgs_concat: uint8[total]; offsets: int64[n+1]; sigs: uint8[n,64];
    pubs: uint8[n,32] (pre-gathered per vote); ok_in: uint8[n] (host checks:
    signature length, validator index range, key decompresses).
    """
    lib = _load()
    assert lib is not None
    n = len(ok_in)
    s_le = np.zeros((n, 32), np.uint8)
    h_le = np.zeros((n, 32), np.uint8)
    ok = np.zeros(n, np.uint8)
    lib.txflow_prep_batch(
        _u8p(np.ascontiguousarray(msgs_concat)),
        np.ascontiguousarray(offsets, np.int64).ctypes.data_as(
            ctypes.POINTER(ctypes.c_int64)
        ),
        _u8p(np.ascontiguousarray(sigs)),
        _u8p(np.ascontiguousarray(pubs)),
        _u8p(np.ascontiguousarray(ok_in, np.uint8)),
        n,
        _u8p(s_le),
        _u8p(h_le),
        _u8p(ok),
    )
    return s_le, h_le, ok.astype(bool)


def sign_bytes_batch(
    heights: list[int],
    tx_hashes: list[str],
    timestamps: list[int],
    chain_id: str,
) -> list[bytes | None] | None:
    """Batched canonical sign bytes (codec.c).

    None when the native library is unavailable; otherwise a per-vote
    list where an item is None if its fields exceed the C-side bounds
    (hash > 256 chars / chain id > 128 bytes — possible only for hostile
    votes; real hashes are 64 chars). Callers Python-fallback per item.
    """
    lib = _load()
    if lib is None or not hasattr(lib, "txflow_sign_bytes_batch"):
        return None
    n = len(heights)
    if n == 0:
        return []
    chain = chain_id.encode()
    hb = [h.encode() for h in tx_hashes]
    hash_stride = max(len(b) for b in hb) or 1
    hashes = np.zeros((n, hash_stride), np.uint8)
    hash_lens = np.zeros(n, np.int32)
    for i, b in enumerate(hb):
        hashes[i, : len(b)] = np.frombuffer(b, np.uint8)
        hash_lens[i] = len(b)
    out_stride = 96 + hash_stride + len(chain)
    out = np.zeros((n, out_stride), np.uint8)
    out_lens = np.zeros(n, np.int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.txflow_sign_bytes_batch(
        n,
        np.ascontiguousarray(heights, np.int64).ctypes.data_as(i64p),
        _u8p(hashes), hash_stride, hash_lens.ctypes.data_as(i32p),
        np.ascontiguousarray(timestamps, np.int64).ctypes.data_as(i64p),
        _u8p(np.frombuffer(chain, np.uint8)) if chain else _u8p(np.zeros(1, np.uint8)),
        len(chain),
        _u8p(out), out_stride, out_lens.ctypes.data_as(i32p),
    )
    ob = out.tobytes()
    # per-item None for oversized fields (the C side hard-rejects them —
    # a hostile vote must only cost ITS OWN Python fallback, not the
    # whole batch's)
    return [
        ob[i * out_stride : i * out_stride + out_lens[i]]
        if out_lens[i] >= 0
        else None
        for i in range(n)
    ]
