"""Operator/client RPC surface (reference rpc core subset + Prometheus)."""

from .server import RPCServer

__all__ = ["RPCServer"]
