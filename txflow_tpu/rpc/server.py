"""RPC server: the operator/client HTTP surface (reference node/node.go:
878-1007 — RPC listeners + the Prometheus metrics server).

Minimal JSON-over-HTTP core mirroring the tendermint RPC methods the
reference exposes for the fast path, plus the Prometheus text exposition:

- GET/POST /broadcast_tx?tx=0x.. | ?tx="str"   -> submit a tx (CheckTx)
- GET  /status                                  -> node/chain/height info
- GET  /tx?hash=HEX                             -> committed-tx lookup
      (fast-path certificate: votes + commit presence)
- GET  /subscribe_tx?hash=HEX&timeout=SECS      -> long-poll until the tx
      commits (the WS tx-subscription analog; resolves on EITHER path)
- GET  /block?height=N                          -> block + hashes
- GET  /blockchain                              -> store height + base
- GET  /validators                              -> current validator set
- GET  /abci_query?path=P&data=0x..             -> app query
- GET  /metrics                                 -> Prometheus exposition
- GET  /health                                  -> degraded-mode + trace digest
- GET  /trace                                   -> span ring dump (trace/)

Served by a stdlib ThreadingHTTPServer — the runtime dependency story
stays 'none'; handlers only touch thread-safe node surfaces.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..admission import ErrDuplicateTx, ErrOverloaded
from ..pool.mempool import ErrMempoolIsFull, ErrTxInCache


class RPCError(Exception):
    """Raise from a route handler to control the HTTP status/headers of
    the reply (the generic handler-exception path is a blanket 500)."""

    def __init__(self, status: int, body: dict, headers: dict | None = None):
        super().__init__(f"rpc error {status}")
        self.status = status
        self.body = body
        self.headers = headers or {}


def _parse_tx_param(raw: str) -> bytes:
    """tendermint-style tx param: 0x-hex or a (possibly quoted) string."""
    if raw.startswith("0x") or raw.startswith("0X"):
        return bytes.fromhex(raw[2:])
    if len(raw) >= 2 and raw[0] == raw[-1] == '"':
        raw = raw[1:-1]
    return raw.encode()


def _event_json(ev) -> dict:
    """JSON-safe projection of an event-bus payload for WS streaming."""
    d = ev.data
    if hasattr(d, "tx_hash"):
        return {
            "type": ev.type,
            "height": d.height,
            "hash": d.tx_hash,
            "code": d.result_code,
        }
    blk = getattr(d, "block", None)
    if blk is not None:
        return {
            "type": ev.type,
            "height": blk.height,
            "hash": blk.hash().hex().upper(),
        }
    return {"type": ev.type}


# request-body and concurrency caps (reference MaxOpenConnections /
# request limits, node/node.go:925-929)
MAX_BODY_BYTES = 1 << 20
MAX_OPEN_CONNECTIONS = 128


class _BoundedHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a hard cap on concurrent connections:
    past MAX_OPEN_CONNECTIONS the listener sheds new sockets with a
    best-effort 503 instead of spawning an unbounded thread per
    connection (a connection flood would otherwise exhaust threads/
    filedescriptors). Shed connections are COUNTED — a silent bare reset
    made overload invisible to both clients and dashboards."""

    daemon_threads = True
    # bounded kernel accept backlog: under a connection flood the excess
    # queues (briefly) in the kernel instead of growing handler state
    request_queue_size = 64

    _REJECT_BODY = json.dumps({"error": "too many open connections"}).encode()
    _REJECT_RESPONSE = (
        b"HTTP/1.1 503 Service Unavailable\r\n"
        b"Content-Type: application/json\r\n"
        b"Content-Length: " + str(len(_REJECT_BODY)).encode() + b"\r\n"
        b"Retry-After: 1\r\n"
        b"Connection: close\r\n\r\n" + _REJECT_BODY
    )

    def __init__(self, addr, handler, metrics_registry=None):
        self._conn_sem = threading.Semaphore(MAX_OPEN_CONNECTIONS)
        self._rejected = None
        if metrics_registry is not None:
            self._rejected = metrics_registry.counter(
                "rpc", "rejected_total",
                "connections shed at the RPC listener (over the open-conn cap)",
            )
        super().__init__(addr, handler)

    def process_request(self, request, client_address):
        if not self._conn_sem.acquire(blocking=False):
            if self._rejected is not None:
                self._rejected.add(1)
            try:
                # minimal pre-built 503 so the client sees backpressure,
                # not a bare RST; best-effort (the flood case is exactly
                # when sends may fail)
                request.sendall(self._REJECT_RESPONSE)
            except OSError:
                pass
            try:
                request.close()
            except OSError:
                pass
            return
        super().process_request(request, client_address)

    def process_request_thread(self, request, client_address):
        try:
            super().process_request_thread(request, client_address)
        finally:
            self._conn_sem.release()


class RPCServer:
    def __init__(self, node, host: str = "127.0.0.1", port: int = 0, debug=None):
        """debug: expose /debug/* hooks. Default: only on loopback binds —
        the reference likewise serves pprof only when ProfListenAddress is
        explicitly configured (node/node.go:724-728); an open profiling/
        trace-to-arbitrary-dir endpoint must never face a network."""
        self.node = node
        self.debug = (host in ("127.0.0.1", "::1", "localhost")) if debug is None else debug
        rpc = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # Read timeout: without it, an idle client parks its handler
            # thread in readline() forever, and MAX_OPEN_CONNECTIONS
            # permits are never released — 128 silent sockets would
            # hard-lock the whole RPC (r5 review). The reference pairs
            # MaxOpenConnections with read timeouts the same way. The
            # websocket path lifts it after the upgrade (long-lived).
            timeout = 30

            def log_message(self, fmt, *args):  # quiet by default
                pass

            def _reply(self, obj, code=200, headers=None):
                payload = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(payload)

            def _reply_text(self, text: str, code=200):
                payload = text.encode()
                self.send_response(code)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_POST(self):
                # Body size cap (reference caps request sizes via its RPC
                # server config, node/node.go:925-929): an oversized body
                # is rejected with 413 and the connection dropped — partly
                # reading it and dispatching anyway would desync keep-
                # alive framing, and reading it all would buffer
                # attacker-sized payloads.
                try:
                    n = int(self.headers.get("Content-Length", "0") or "0")
                except ValueError:
                    n = 0
                if n > MAX_BODY_BYTES:
                    # tell the client explicitly (Connection: close) and
                    # drain a bounded slice of the in-flight body before
                    # closing — an immediate close with unread bytes in
                    # the receive buffer emits RST and destroys the 413
                    # before the client reads it (r5 review)
                    self.close_connection = True
                    payload = json.dumps(
                        {"error": "request body too large"}
                    ).encode()
                    self.send_response(413)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.send_header("Connection", "close")
                    self.end_headers()
                    self.wfile.write(payload)
                    self.wfile.flush()
                    try:
                        self.connection.settimeout(2)
                        remaining = min(n, 4 * MAX_BODY_BYTES)
                        while remaining > 0:
                            got = self.rfile.read(min(remaining, 65536))
                            if not got:
                                break
                            remaining -= len(got)
                    except OSError:
                        pass
                    return
                chunked = bool(self.headers.get("Transfer-Encoding"))
                if chunked:
                    # chunked bodies are not parsed: dispatch, then drop
                    # the connection so unread chunk bytes can never be
                    # misread as the next request line (and the size cap
                    # cannot be bypassed by omitting Content-Length)
                    self.close_connection = True
                # drain the body BEFORE dispatch: with keep-alive enabled,
                # unread body bytes would be parsed as the next request
                # line on this connection
                try:
                    if n > 0:
                        self.rfile.read(n)
                except OSError:
                    pass
                self.do_GET()
                if chunked:
                    # bounded drain of unread chunk bytes before close —
                    # close() with data in the receive buffer emits RST,
                    # which can destroy the response in flight (same
                    # hazard the 413 path drains for)
                    try:
                        self.wfile.flush()
                        self.connection.settimeout(2)
                        for _ in range(64):
                            if not self.rfile.read(65536):
                                break
                    except OSError:
                        pass

            def do_GET(self):
                try:
                    parsed = urllib.parse.urlparse(self.path)
                    q = {
                        k: v[0]
                        for k, v in urllib.parse.parse_qs(parsed.query).items()
                    }
                    route = parsed.path.rstrip("/") or "/"
                    if route == "/websocket":
                        # event-stream upgrade (reference WS subscriptions,
                        # node/node.go:914-922); takes over the socket
                        rpc._serve_websocket(self)
                        self.close_connection = True
                        return
                    handler = rpc._routes.get(route)
                    if handler is None:
                        self._reply({"error": f"unknown path {route}"}, 404)
                        return
                    result = handler(q)
                    if route == "/metrics":
                        self._reply_text(result)
                    else:
                        self._reply({"result": result})
                except RPCError as e:
                    # typed status replies (429 overload + Retry-After)
                    self._reply(e.body, e.status, e.headers)
                except Exception as e:
                    self._reply({"error": repr(e)}, 500)

        self._httpd = _BoundedHTTPServer(
            (host, port), Handler,
            metrics_registry=getattr(node, "metrics_registry", None),
        )
        self.addr = self._httpd.server_address
        self._thread: threading.Thread | None = None
        self._routes = {
            "/broadcast_tx": self._broadcast_tx,
            "/broadcast_tx_sync": self._broadcast_tx,
            "/broadcast_tx_commit": self._broadcast_tx_commit,
            "/status": self._status,
            "/tx": self._tx,
            "/subscribe_tx": self._subscribe_tx,
            "/block": self._block,
            "/blockchain": self._blockchain,
            "/validators": self._validators,
            "/abci_query": self._abci_query,
            "/tx_search": self._tx_search,
            "/metrics": self._metrics,
            "/health": self._health,
            "/trace": self._trace,
            # rpccore.Routes parity (reference node/node.go:898-986)
            "/commit": self._commit,
            "/genesis": self._genesis,
            "/net_info": self._net_info,
            "/commit_log": self._commit_log,
            "/block_results": self._block_results,
            "/unconfirmed_txs": self._unconfirmed_txs,
            "/num_unconfirmed_txs": self._num_unconfirmed_txs,
            "/consensus_state": self._consensus_state,
            "/dump_consensus_state": self._dump_consensus_state,
            "/broadcast_evidence": self._broadcast_evidence,
        }
        if self.debug:
            # profiling hooks (reference links net/http/pprof and starts a
            # JAX-profiler-analog on demand, node/node.go:724-728)
            self._routes["/debug/stacks"] = self._debug_stacks
            self._routes["/debug/jax_profile"] = self._debug_jax_profile

    # -- lifecycle --

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="rpc-http", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- handlers --

    @staticmethod
    def _dup_result(key: bytes) -> dict:
        """The ONE duplicate-submission reply: edge-dedup hits and
        mempool-cache hits both answer through here, so the two paths are
        byte-identical on the wire (ISSUE 6 satellite)."""
        return {"hash": key.hex().upper(), "code": 0, "duplicate": True}

    @staticmethod
    def _overload_error(retry_after: float) -> RPCError:
        return RPCError(
            429,
            {"error": "overloaded", "retry_after": retry_after},
            {"Retry-After": str(max(1, int(round(retry_after))))},
        )

    def _broadcast_tx(self, q: dict) -> dict:
        tx = _parse_tx_param(q["tx"])
        key = hashlib.sha256(tx).digest()
        adm = getattr(self.node, "admission", None)
        if adm is not None:
            try:
                adm.admit_rpc(tx, key)
            except ErrDuplicateTx:
                return self._dup_result(key)
            except ErrOverloaded as e:
                raise self._overload_error(e.retry_after)
        try:
            self.node.broadcast_tx(tx)
        except ErrTxInCache:
            # first sighting at THIS edge but the pool already has it
            # (e.g. it arrived by gossip): same dup verdict as the edge
            return self._dup_result(key)
        except ErrMempoolIsFull:
            # pool rejected after edge admit: release the dedup slot so
            # the client's post-Retry-After resubmit isn't dup-bounced
            if adm is not None:
                adm.forget(key)
            raise self._overload_error(
                adm.cfg.retry_after if adm is not None else 1.0
            )
        except Exception:
            if adm is not None:
                adm.forget(key)
            raise
        return {"hash": key.hex().upper(), "code": 0}

    def _broadcast_tx_commit(self, q: dict) -> dict:
        """Submit + wait for the commit in one call (tendermint's
        broadcast_tx_commit; resolves via EITHER commit path)."""
        res = self._broadcast_tx(q)
        sub = self._subscribe_tx(
            {"hash": res["hash"], "timeout": q.get("timeout", "30")}
        )
        return {**res, **sub}

    def _status(self, q: dict) -> dict:
        node = self.node
        from .. import version

        return {
            "node_info": {
                "id": node.node_id,
                "network": node.chain_id,
                "protocol_version": {
                    "p2p": version.P2P_PROTOCOL,
                    "block": version.BLOCK_PROTOCOL,
                    "app": version.ABCI_SEMVER,
                },
                "version": version.SEMVER,
            },
            "sync_info": {
                "latest_block_height": node.block_store.height(),
                "latest_app_hash": node.chain_state.app_hash.hex(),
                "fast_path_height": node.committed_height_view,
            },
            "validator_info": {
                "address": (
                    node.priv_val.get_address().hex().upper()
                    if node.priv_val
                    else ""
                ),
            },
            "health": self._health_summary(),
        }

    def _health_summary(self) -> dict:
        """Degraded-mode digest for /status: verifier + watchdog counters
        without the full per-peer detail of /health."""
        mon = getattr(self.node, "health", None)
        if mon is None:
            return {"monitored": False}
        snap = mon.snapshot()
        return {
            "monitored": True,
            "healthy": snap["healthy"],
            "watchdog_firings": snap["watchdog"]["firings"],
            "peer_reconnects": snap["peers"]["reconnects"],
            "verifier": snap["verifier"],
        }

    def _health(self, q: dict) -> dict:
        """Full degraded-mode registry snapshot (health/registry.py) plus
        the trace digest (p50/p99/p999 per span family + leak counters);
        {} sections when the node runs without a monitor/tracer."""
        mon = getattr(self.node, "health", None)
        out = dict(mon.snapshot()) if mon is not None else {}
        tracer = getattr(self.node, "tracer", None)
        if tracer is not None:
            out["trace"] = tracer.digest()
        return out

    def _trace(self, q: dict) -> dict:
        """Span-ring dump for cross-node merge (tools/trace_export.py,
        tools/soak.py --overload leak assertion)."""
        tracer = getattr(self.node, "tracer", None)
        if tracer is None:
            return {
                "node": self.node.node_id,
                "base_wall_ns": 0,
                "base_mono": 0.0,
                "spans": [],
                "open_spans": 0,
            }
        return tracer.dump(self.node.node_id)

    def _tx(self, q: dict) -> dict:
        tx_hash = q["hash"].upper()
        votes = self.node.tx_store.load_tx_votes(tx_hash)
        commit = self.node.tx_store.load_tx_commit(tx_hash)
        committed = self.node.txflow.is_tx_committed(tx_hash)
        return {
            "hash": tx_hash,
            "committed": committed,
            "votes": len(votes) if votes else 0,
            "has_commit_cert": commit is not None,
        }

    # -- WebSocket event streaming (RFC 6455 server side, no deps) --

    _WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

    def _serve_websocket(self, handler) -> None:
        """Upgrade + event pump. Client subscribes with one JSON text
        frame {"subscribe": "Tx" | "NewBlock"}; the server then streams
        each matching event as a JSON text frame until the client closes.
        The reference serves the same capability via its WS RPC
        subscriptions (node/node.go:914-922)."""
        import base64
        import hashlib as _hl
        import struct as _st

        key = handler.headers.get("Sec-WebSocket-Key", "")
        if handler.headers.get("Upgrade", "").lower() != "websocket" or not key:
            handler.send_response(400)
            handler.end_headers()
            return
        # long-lived stream: lift the HTTP read timeout set on the
        # handler class (idle subscribers are legitimate here; the pump
        # has its own liveness handling)
        try:
            handler.connection.settimeout(None)
        except OSError:
            pass
        accept = base64.b64encode(
            _hl.sha1((key + self._WS_GUID).encode()).digest()
        ).decode()
        handler.wfile.write(
            (
                "HTTP/1.1 101 Switching Protocols\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Accept: {accept}\r\n\r\n"
            ).encode()
        )
        handler.wfile.flush()
        conn, wf = handler.connection, handler.wfile

        # Bounded IO from here on: a stalled client (suspended process,
        # half-dead link) must not wedge send_frame forever — the pump
        # would hold wlock, the reader's pong path would block behind it,
        # and unsubscribe would never run. Sends now fail after the
        # timeout; reads below retry on it (idle is normal for a reader).
        conn.settimeout(30.0)

        # Drain whatever the handshake's buffered reader already pulled
        # off the socket (a pipelining client's first frames can sit in
        # handler.rfile): everything after this comes from conn.recv,
        # which — unlike BufferedReader.read under a timeout — never
        # discards partially-read data.
        rf = handler.rfile
        buffered = b""
        try:
            conn.settimeout(0.001)
            while True:
                peeked = rf.peek(1)
                if not peeked:
                    break
                buffered += rf.read(len(peeked))
        except (TimeoutError, OSError):
            pass  # rfile buffer empty: the raw peek hit the socket
        finally:
            conn.settimeout(30.0)

        # one writer lock: the event pump and the reader thread's pongs
        # both send frames
        wlock = threading.Lock()

        def send_frame(opcode: int, payload: bytes) -> None:
            hdr = bytes([0x80 | opcode])
            n = len(payload)
            if n < 126:
                hdr += bytes([n])
            elif n < 1 << 16:
                hdr += bytes([126]) + _st.pack(">H", n)
            else:
                hdr += bytes([127]) + _st.pack(">Q", n)
            with wlock:
                wf.write(hdr + payload)
                wf.flush()

        def read_exact(n: int, deadline: float | None = None) -> bytes:
            """EOF mid-frame is a close, never a partial read or a
            mid-frame resume: a short read would desync RFC6455 framing
            for the rest of the connection (r3 advisor medium). Timeouts
            between frames are idle, not errors — retry (listen-only
            clients are legitimate), unless a deadline is given."""
            nonlocal buffered
            out = b""
            while len(out) < n:
                if buffered:
                    take = min(n - len(out), len(buffered))
                    out += buffered[:take]
                    buffered = buffered[take:]
                    continue
                try:
                    chunk = conn.recv(n - len(out))
                except TimeoutError:
                    if deadline is not None and time.monotonic() > deadline:
                        raise ConnectionError("websocket read deadline")
                    continue  # idle poll; partial bytes stay in `out`
                if not chunk:
                    raise ConnectionError("websocket closed")
                out += chunk
            return out

        def recv_frame(deadline: float | None = None):
            b0 = read_exact(1, deadline)[0]
            opcode = b0 & 0x0F
            b1 = read_exact(1, deadline)[0]
            n = b1 & 0x7F
            if n == 126:
                (n,) = _st.unpack(">H", read_exact(2, deadline))
            elif n == 127:
                (n,) = _st.unpack(">Q", read_exact(8, deadline))
            # inbound frames are a small JSON subscribe + <=125-byte
            # control frames: a client-declared 64-bit length must not
            # make read_exact buffer unbounded memory
            if n > 1 << 20:
                raise ConnectionError(f"websocket frame too large ({n} bytes)")
            mask = read_exact(4, deadline) if b1 & 0x80 else b""  # clients MUST mask
            data = read_exact(n, deadline) if n else b""
            if mask:
                data = bytes(c ^ mask[i % 4] for i, c in enumerate(data))
            return opcode, data

        try:
            # the subscribe frame must arrive promptly; after that the
            # client may stay silent forever (listen-only)
            opcode, data = recv_frame(deadline=time.monotonic() + 30.0)
            if opcode != 1:  # expect a text subscribe frame
                send_frame(8, b"")
                return
            req = json.loads(data or b"{}")
            event_type = req.get("subscribe", "Tx")
            if event_type not in ("Tx", "NewBlock"):
                send_frame(1, json.dumps({"error": "unknown event"}).encode())
                send_frame(8, b"")
                return
            # the subscription must be released on EVERY exit (an ack
            # write to a just-reset connection raises before the pump
            # starts): everything past subscribe() runs under the finally
            sub = self.node.event_bus.subscribe(event_type)
            try:
                send_frame(1, json.dumps({"subscribed": event_type}).encode())

                # reader thread: blocking control-frame loop (ping/close).
                # Event delivery must not gate on client chatter — the old
                # interleaved 0.5 s recv poll capped delivery at ~2
                # events/s and a timeout landing mid-frame desynced the
                # framing.
                closed = threading.Event()

                def reader() -> None:
                    try:
                        while True:
                            op, payload = recv_frame()
                            if op == 8:  # close
                                return
                            if op == 9:  # ping -> pong
                                send_frame(10, payload)
                    except (ConnectionError, OSError, _st.error):
                        pass
                    finally:
                        closed.set()

                rt = threading.Thread(target=reader, name="ws-reader", daemon=True)
                rt.start()
                while not closed.is_set():
                    ev = sub.get(timeout=0.5)
                    if ev is not None:
                        send_frame(1, json.dumps(_event_json(ev)).encode())
            finally:
                self.node.event_bus.unsubscribe(event_type, sub)
                # handler return closes the socket, unblocking the reader
        except (BrokenPipeError, ConnectionError, OSError):
            pass

    def _subscribe_tx(self, q: dict) -> dict:
        """Long-poll tx-commit subscription (the WS subscribe analog:
        reference EventDataTx over the event bus, node/node.go:914-922)."""
        tx_hash = q["hash"].upper()
        timeout = min(float(q.get("timeout", "25")), 60.0)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.node.txflow.is_tx_committed(tx_hash):
                return {"hash": tx_hash, "committed": True}
            time.sleep(0.02)
        return {"hash": tx_hash, "committed": False, "timeout": True}

    def _block(self, q: dict) -> dict:
        height = int(q["height"])
        block = self.node.block_store.load_block(height)
        if block is None:
            raise ValueError(f"no block at height {height}")
        return {
            "height": block.height,
            "hash": block.hash().hex().upper(),
            "num_txs": len(block.txs),
            "num_vtxs": len(block.vtxs),
            "txs": [tx.hex() for tx in block.txs],
            "vtxs": [tx.hex() for tx in block.vtxs],
            "app_hash": block.header.app_hash.hex(),
            "proposer": block.header.proposer_address.hex().upper(),
        }

    def _blockchain(self, q: dict) -> dict:
        store = self.node.block_store
        return {"base": store.base(), "height": store.height()}

    # -- rpccore.Routes parity (reference node/node.go:898-986) --

    def _commit(self, q: dict) -> dict:
        """Block header + the commit that sealed it — the light-client /
        commit-certificate flow (reference rpccore /commit). Defaults to
        the latest committed height; serves the SEEN commit for the head
        (the canonical commit lives in the NEXT block's LastCommit)."""
        store = self.node.block_store
        height = int(q.get("height", store.height()))
        block = store.load_block(height)
        if block is None:
            raise ValueError(f"no block at height {height}")
        commit = store.load_block_commit(height)
        canonical = commit is not None
        if commit is None:
            commit = store.load_seen_commit(height)
        if commit is None:
            raise ValueError(f"no commit for height {height}")
        h = block.header
        return {
            "header": {
                "chain_id": h.chain_id,
                "height": h.height,
                "time_ns": h.time_ns,
                "last_block_id": h.last_block_id.hex().upper(),
                "app_hash": h.app_hash.hex(),
                "validators_hash": h.validators_hash.hex(),
                "evidence_hash": h.evidence_hash.hex(),
                "proposer_address": h.proposer_address.hex().upper(),
            },
            "block_id": block.hash().hex().upper(),
            "canonical": canonical,
            "commit": {
                "block_id": commit.block_id.hex().upper(),
                "precommits": [
                    {
                        "height": v.height,
                        "round": v.round,
                        "block_id": v.block_id.hex().upper(),
                        "timestamp_ns": v.timestamp_ns,
                        "validator_address": v.validator_address.hex().upper(),
                        "signature": (v.signature or b"").hex(),
                    }
                    for v in commit.precommits
                ],
            },
        }

    def _genesis(self, q: dict) -> dict:
        import json as _json

        return {"genesis": _json.loads(self.node.genesis.to_json())}

    def _commit_log(self, q: dict) -> dict:
        """This node's fast-path commit-order log (store S: rows). There
        is no GLOBAL total order across fast-path nodes (sync/manager.py)
        — each node's log is its own decision order — so cross-node
        checks compare committed SETS plus per-node prefix stability; the
        WAN matrix (tools/soak.py --wan-matrix) reads this per scenario.
        ``start``/``count`` window the read; ``count=0`` returns just the
        total + digest-to-date (cheap prefix-equality probe)."""
        store = self.node.tx_store
        total = store.seq_count()
        start = max(int(q.get("start", 0)), 0)
        count = int(q.get("count", max(total - start, 0)))
        hashes = [h for _seq, h in store.committed_range(start, count)]
        digest = hashlib.sha256()
        for h in store.committed_range(0, total):
            digest.update(h[1].encode())
        return {
            "total": total,
            "start": start,
            "hashes": hashes,
            "digest": digest.hexdigest(),
        }

    def _net_info(self, q: dict) -> dict:
        peers = self.node.switch.peers()
        return {
            "listening": True,
            "n_peers": len(peers),
            "peers": [
                {
                    "node_id": p.node_id,
                    "is_outbound": p.outbound,
                }
                for p in peers
            ],
        }

    def _block_results(self, q: dict) -> dict:
        """Per-tx ABCI results for a committed block (reference rpccore
        /block_results, served from the persisted ABCIResponses)."""
        height = int(q["height"])
        raw = self.node.state_store.load_abci_responses(height)
        if raw is None:
            raise ValueError(f"no results for height {height}")
        import json as _json

        d = _json.loads(raw)
        return {
            "height": height,
            "deliver_tx": d.get("deliver_tx", []),
            "validator_updates": d.get("validator_updates", []),
        }

    def _unconfirmed_txs(self, q: dict) -> dict:
        limit = min(int(q.get("limit", "30")), 100)
        txs = self.node.mempool.reap_max_txs(limit)
        return {
            "n_txs": len(txs),
            "total": self.node.mempool.size(),
            "total_bytes": self.node.mempool.txs_bytes(),
            "txs": [tx.hex() for tx in txs],
        }

    def _num_unconfirmed_txs(self, q: dict) -> dict:
        return {
            "total": self.node.mempool.size(),
            "total_bytes": self.node.mempool.txs_bytes(),
            "vote_pool": self.node.tx_vote_pool.size(),
        }

    def _round_state_obj(self, full: bool) -> dict:
        cs = self.node.consensus
        if cs is None:
            raise ValueError("consensus is disabled on this node")
        rs = cs.round_state()
        out = {
            "height": rs.height,
            "round": rs.round,
            "step": int(rs.step),
            "start_time_ns": rs.start_time_ns,
            "locked_round": rs.locked_round,
            "valid_round": rs.valid_round,
            "proposal": rs.proposal is not None,
            "proposal_block": (
                rs.proposal_block.hash().hex().upper()
                if rs.proposal_block is not None
                else ""
            ),
        }
        if full:
            _, _, votes = cs.current_round_data()
            out["votes"] = [
                {
                    "height": v.height,
                    "round": v.round,
                    "type": v.type,
                    "block_id": v.block_id.hex().upper(),
                    "validator_address": v.validator_address.hex().upper(),
                }
                for v in votes
            ]
            out["validators"] = [
                {"address": v.address.hex().upper(), "power": v.voting_power}
                for v in (rs.validators or [])
            ]
        return out

    def _consensus_state(self, q: dict) -> dict:
        return {"round_state": self._round_state_obj(full=False)}

    def _dump_consensus_state(self, q: dict) -> dict:
        return {"round_state": self._round_state_obj(full=True)}

    def _broadcast_evidence(self, q: dict) -> dict:
        """Submit evidence (hex of the wire form); verified + gossiped via
        the evidence pool (reference rpccore /broadcast_evidence)."""
        from ..types.evidence import decode_evidence

        raw = q["evidence"]
        ev = decode_evidence(bytes.fromhex(raw[2:] if raw.startswith("0x") else raw))
        added, err = self.node.evidence_pool.add(ev)
        if err is not None:
            raise ValueError(f"invalid evidence: {err}")
        return {"hash": ev.hash().hex().upper(), "added": added}

    def _validators(self, q: dict) -> dict:
        vs = self.node.chain_state.validators
        return {
            "count": len(vs),
            "total_power": vs.total_voting_power(),
            "validators": [
                {
                    "address": v.address.hex().upper(),
                    "pub_key": v.pub_key.hex(),
                    "power": v.voting_power,
                }
                for v in vs
            ],
        }

    def _abci_query(self, q: dict) -> dict:
        data = q.get("data", "")
        raw = bytes.fromhex(data[2:]) if data.startswith("0x") else data.encode()
        res = self.node.proxy_app.query.query_sync(q.get("path", ""), raw)
        return {
            "code": res.code,
            "key": (res.key or b"").hex(),
            "value": (res.value or b"").hex(),
            "height": res.height,
        }

    def _tx_search(self, q: dict) -> dict:
        """Indexer queries (reference tx indexer service): by height or by
        tag (?height=N | ?key=app.key&value=hex-or-str)."""
        idx = self.node.tx_indexer
        if idx is None:
            raise ValueError("tx indexing is disabled on this node")
        if "height" in q:
            hashes = idx.by_height(int(q["height"]))
        elif "key" in q:
            val = q.get("value", "")
            vraw = bytes.fromhex(val[2:]) if val.startswith("0x") else val.encode()
            hashes = idx.search(q["key"].encode(), vraw)
        else:
            raise ValueError("tx_search needs ?height= or ?key=&value=")
        return {"txs": [idx.get(h) for h in hashes], "total": len(hashes)}

    def _debug_stacks(self, q: dict) -> dict:
        """All-thread stack dump — the pprof-goroutine analog for a Python
        runtime (reference serves net/http/pprof when ProfListenAddress is
        set)."""
        import sys
        import traceback

        frames = sys._current_frames()
        stacks = {}
        for t in threading.enumerate():
            f = frames.get(t.ident)
            stacks[t.name] = (
                traceback.format_stack(f) if f is not None else ["<no frame>"]
            )
        return {"threads": stacks, "count": len(stacks)}

    def _debug_jax_profile(self, q: dict) -> dict:
        """Start/stop a JAX profiler trace (the XLA-level tracing hook):
        ?action=start&dir=/tmp/trace | ?action=stop."""
        import jax.profiler

        import os.path

        action = q.get("action", "start")
        if action == "start":
            trace_dir = q.get("dir", "/tmp/txflow-jax-trace")
            # confine trace output: profiling must not become an
            # arbitrary-path write primitive
            if not os.path.abspath(trace_dir).startswith("/tmp/"):
                raise ValueError("trace dir must live under /tmp/")
            jax.profiler.start_trace(trace_dir)
            return {"tracing": True, "dir": trace_dir}
        jax.profiler.stop_trace()
        return {"tracing": False}

    def _metrics(self, q: dict) -> str:
        return self.node.metrics_registry.expose()
