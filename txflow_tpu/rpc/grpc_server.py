"""Simplified gRPC broadcast API (reference node/node.go:972-986).

The reference exposes tendermint's ``core_grpc.BroadcastAPI`` — Ping and
BroadcastTx — "for convenience to app devs" next to the HTTP/WS RPC. Same
surface here: a grpcio server with hand-rolled proto3 message codecs (the
messages are tiny and stable; no generated stubs, no protoc step):

  service BroadcastAPI {             # rpc/grpc/types.proto, pkg core_grpc
    rpc Ping(RequestPing) returns (ResponsePing)
    rpc BroadcastTx(RequestBroadcastTx) returns (ResponseBroadcastTx)
  }
  message RequestBroadcastTx { bytes tx = 1 }
  message ResponseBroadcastTx {
    ResponseCheckTx   check_tx   = 1   # { uint32 code = 1, bytes data = 2, string log = 3 }
    ResponseDeliverTx deliver_tx = 2   # same shape
  }

BroadcastTx here submits through the node's fast path and, like the
reference's gRPC handler (BroadcastAPI.BroadcastTx runs CheckTx and
DeliverTx to completion), waits for the commit so the response carries
the executed DeliverTx result.
"""

from __future__ import annotations

import time

from ..codec import amino

_SERVICE = "core_grpc.BroadcastAPI"


def _field(fnum: int, typ3: int, payload: bytes) -> bytes:
    return bytes(amino.field_key(fnum, typ3)) + payload


def encode_check_deliver(code: int, data: bytes, log: str) -> bytes:
    """proto3 body shared by ResponseCheckTx / ResponseDeliverTx."""
    out = bytearray()
    if code:
        out += _field(1, amino.TYP3_VARINT, amino.uvarint(code))
    if data:
        out += _field(2, amino.TYP3_BYTELEN, amino.length_prefixed(data))
    if log:
        out += _field(3, amino.TYP3_BYTELEN, amino.length_prefixed(log.encode()))
    return bytes(out)


def decode_request_broadcast_tx(body: bytes) -> bytes:
    r = amino.AminoReader(body)
    tx = b""
    while not r.eof():
        fnum, typ3 = r.read_field_key()
        if fnum == 1 and typ3 == amino.TYP3_BYTELEN:
            tx = r.read_bytes()
        else:
            r.skip_field(typ3)
    return tx


class GRPCBroadcastServer:
    """grpcio server wrapping a Node; start() binds an ephemeral port."""

    def __init__(self, node, host: str = "127.0.0.1", port: int = 0):
        self.node = node
        self.host = host
        self.port = port
        self._server = None

    # -- handlers --

    def _ping(self, request: bytes, context) -> bytes:
        return b""  # ResponsePing{}

    def _broadcast_tx(self, request: bytes, context) -> bytes:
        tx = decode_request_broadcast_tx(request)
        check_code, check_log = 0, ""
        try:
            self.node.broadcast_tx(tx)
        except Exception as e:
            check_code, check_log = 1, str(e)
        delivered = False
        if check_code == 0:
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline and not self.node.is_committed(tx):
                time.sleep(0.02)
            if self.node.is_committed(tx):
                delivered = True
            else:
                check_code, check_log = 1, "commit timeout"
        check = encode_check_deliver(check_code, b"", check_log)
        out = bytearray()
        out += _field(1, amino.TYP3_BYTELEN, amino.length_prefixed(check))
        if delivered:
            # a clean DeliverTx (code 0, no data/log) encodes to an EMPTY
            # proto3 body — the field must still be present on success
            out += _field(
                2, amino.TYP3_BYTELEN,
                amino.length_prefixed(encode_check_deliver(0, b"", "")),
            )
        return bytes(out)

    # -- lifecycle --

    def start(self) -> tuple[str, int]:
        import grpc

        outer = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, details):
                ident = lambda b: b  # raw-bytes (de)serializers
                if details.method == f"/{_SERVICE}/Ping":
                    return grpc.unary_unary_rpc_method_handler(
                        outer._ping, request_deserializer=ident,
                        response_serializer=ident,
                    )
                if details.method == f"/{_SERVICE}/BroadcastTx":
                    return grpc.unary_unary_rpc_method_handler(
                        outer._broadcast_tx, request_deserializer=ident,
                        response_serializer=ident,
                    )
                return None

        from concurrent import futures

        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        self._server.add_generic_rpc_handlers((Handler(),))
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        self._server.start()
        return self.host, self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1)
            self._server = None
