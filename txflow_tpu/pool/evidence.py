"""EvidencePool: verified, deduped equivocation evidence awaiting
operator action / gossip (the slot the reference fills with tendermint's
upstream evidence pool + reactor, node/node.go:354-367 — here rebuilt for
both the block path AND the fast path's conflicting TxVotes).
"""

from __future__ import annotations

import threading

from ..types.validator import ValidatorSet
from ..utils.events import EventBus, EventEvidence

# evidence older than this many heights below the current one is pruned
# (upstream ConsensusParams.Evidence.MaxAge analog)
MAX_AGE_HEIGHTS = 100000


class EvidencePool:
    def __init__(
        self,
        chain_id: str,
        val_set_provider,  # () -> ValidatorSet for verification
        event_bus: EventBus | None = None,
        db=None,  # durable committed-marker store (shared with BlockStore)
        val_set_at=None,  # (height) -> ValidatorSet | None: epoch lookup
    ):
        self.chain_id = chain_id
        self._val_set_provider = val_set_provider
        # epoch-correct admission: evidence must verify against the set
        # of the epoch the offending vote was CAST in, or a rotated-out
        # double-signer's proof would bounce (and a fresh joiner could be
        # framed for pre-join heights). None = legacy current-set check.
        self._val_set_at = val_set_at
        self.event_bus = event_bus
        self._mtx = threading.Lock()
        self._pending: dict[bytes, object] = {}  # hash -> evidence
        # committed markers: in-memory set backed by durable `EV:<hash>`
        # rows when a db is given. The reference checks a persisted store
        # (state/validation.go:148); a memory-only set diverges after
        # fast-sync/restart — an archival node rejects a re-included proof
        # that a freshly-synced node would accept (r3 advisor low).
        self._committed: set[bytes] = set()
        self._db = db
        self.on_add = lambda ev: None  # reactor hook: gossip new evidence

    def add(self, ev) -> tuple[bool, str | None]:
        """Verify + admit one piece of evidence; returns (added, err)."""
        h = ev.hash()
        with self._mtx:
            if h in self._pending or h in self._committed:
                return False, None  # known: not an error
        if self._db is not None and self._db.has(b"EV:" + h):
            return False, None  # committed before a restart
        val_set: ValidatorSet | None = None
        if self._val_set_at is not None:
            val_set = self._val_set_at(ev.height())
        if val_set is None:
            val_set = self._val_set_provider()
        _, val = val_set.get_by_address(ev.validator_address)
        if val is None:
            return False, "evidence names an unknown validator"
        err = ev.verify(self.chain_id, val.pub_key)
        if err is not None:
            return False, err
        with self._mtx:
            if h in self._pending or h in self._committed:
                return False, None
            self._pending[h] = ev
        if self.event_bus is not None:
            self.event_bus.publish(EventEvidence, ev)
        try:
            self.on_add(ev)
        except Exception:
            pass
        return True, None

    def pending(self) -> list:
        with self._mtx:
            return list(self._pending.values())

    def size(self) -> int:
        with self._mtx:
            return len(self._pending)

    def has(self, ev) -> bool:
        h = ev.hash()
        with self._mtx:
            if h in self._pending or h in self._committed:
                return True
        return self._db is not None and self._db.has(b"EV:" + h)

    def is_committed(self, ev) -> bool:
        h = ev.hash()
        with self._mtx:
            if h in self._committed:
                return True
        return self._db is not None and self._db.has(b"EV:" + h)

    def drop(self, ev) -> None:
        """Remove evidence that turned out unusable (e.g. its validator
        left the set before it could be proposed)."""
        with self._mtx:
            self._pending.pop(ev.hash(), None)

    def mark_committed(self, evs: list) -> None:
        """Evidence landed on-chain (or was otherwise handled): stop
        gossiping it but remember it so it cannot be re-admitted."""
        with self._mtx:
            for ev in evs:
                h = ev.hash()
                self._pending.pop(h, None)
                self._committed.add(h)
                if self._db is not None:
                    self._db.set(b"EV:" + h, b"1")

    def prune(self, current_height: int) -> int:
        """Drop pending evidence older than MAX_AGE_HEIGHTS."""
        cutoff = current_height - MAX_AGE_HEIGHTS
        with self._mtx:
            stale = [h for h, ev in self._pending.items() if ev.height() < cutoff]
            for h in stale:
                del self._pending[h]
            return len(stale)
