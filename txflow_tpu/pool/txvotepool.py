"""TxVotePool: pending TxVotes (reference txvotepool/txvotepool.go).

Semantics preserved from the reference:
- dedup key is **sha256(signature)** (:467-469) — two votes for the same tx
  by the same validator but different sign-bytes are distinct pool entries;
- size / total-bytes caps checked before cache (:198-208);
- max single-vote size derived from the gossip msg cap (:211);
- cache hit records the new sender for in-pool votes then rejects (:213-228);
- WAL append of accepted votes (:232-243);
- ``update(height, votes)`` pushes committed votes into the cache, removes
  them from the pool and re-arms the availability notification (:329-359);
- per-height TxsAvailable firing, once (:273-307).

The batched consumer adds ``drain_batch`` — a snapshot of up to N votes in
insertion order *without* removing them (removal happens on commit/purge,
exactly like the reference's checkMaj23Routine walking the CList without
popping).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..codec import amino
from ..crypto.hash import sha256
from ..trace.tracer import NULL_TRACER, SPAN_VOTE_INGEST
from ..types import TxVote, decode_tx_vote, encode_tx_vote
from ..utils.cache import make_lru
from ..utils.clock import monotonic
from ..utils.config import MempoolConfig
from ..utils.failpoints import FailpointError
from ..utils.wal import WAL
from .base import COMPACT_THRESHOLD, IngestLogPool
from .mempool import (
    LANE_PRIORITY,
    ErrMempoolIsFull,
    ErrTxInCache,
    ErrTxTooLarge,
    TxInfo,
)

UNKNOWN_PEER_ID = 0

# amino overhead allowance for a wrapped vote message (reference
# calcMaxTxSize subtracts the TxMessage envelope from MaxMsgBytes).
_MSG_OVERHEAD = 8


def vote_key(vote: TxVote) -> bytes:
    """sha256(signature) — the reference's txVoteKey (:467-469)."""
    return vote.vote_key()  # cached on the immutable vote


@dataclass(slots=True)
class _PoolVote:
    height: int
    vote: TxVote
    senders: set[int] = field(default_factory=set)
    size: int = 0  # encoded wire size, cached so removals never re-encode
    # uvarint-length-prefixed wire form, built once at ingest: the gossip
    # batch frame is a plain b"".join of these, so per-peer broadcast
    # walks never re-serialize (r4 profile: lp+append per vote per peer)
    seg: bytes = b""
    # ingest-time admission lane (LANE_PRIORITY or -1): the partition
    # key for the engine's lane-split drain. Frozen at ingest so the
    # priority log and bulk_entries_from stay an exact partition of the
    # main log even if the lane hook's answer drifts later (mempool
    # eviction, late tx arrival) — a vote is delivered by EXACTLY the
    # log its ingest classified it into. Set by BOTH ingest twins.
    lane: int = -1
    # ORIGIN: the sender whose delivery created this entry (first element
    # of `senders`, frozen at ingest). Invalid-signature verdicts are
    # attributed to the origin, not the whole sender set — later
    # duplicate senders never cost a device slot, and striking them
    # would punish honest gossip redundancy (health/byzantine.py).
    # UNKNOWN_PEER_ID = local/RPC/WAL ingest: no peer to strike.
    origin: int = 0


class TxVotePool(IngestLogPool):
    def __init__(self, config: MempoolConfig, height: int = 0, wal_path: str = ""):
        super().__init__()  # _mtx/_cond/_seq + compacted ingest log
        self.config = config
        self.height = height
        self._votes: dict[bytes, _PoolVote] = self._items  # vote_key -> entry
        # secondary index: tx_hash -> {vote_key: None} (an insertion-
        # ordered set), so segs_for_tx is O(votes-for-tx) instead of a
        # full O(pool) scan — the quorum-stall watchdog calls it per
        # stalled tx, and at bench depth the scan was the whole pool.
        # Maintained by BOTH ingest paths (check_tx's _ingest_locked and
        # the inlined check_tx_many twin) and every removal path.
        self._by_tx: dict[str, dict[bytes, None]] = {}
        self._votes_bytes = 0
        # vote-pool lanes: a vote inherits its tx's admission lane via the
        # lane_of_vote hook (Node wires mempool.lane_of_key over the
        # vote's tx_key); the priority log lets the verify engine drain
        # priority-tx votes ahead of a deep bulk backlog — the same
        # compacted-ingest-log design as Mempool._prio_log. Hook faults
        # demote to bulk: a hostile vote must not error the ingest path.
        self.lane_of_vote = None
        self._prio_log: list[bytes] = []
        self._prio_log_base = 0  # absolute position of _prio_log[0]
        # per-tx tracing (trace/tracer.py): vote arrival markers feed the
        # network-residual attribution; wired by the node, NULL_TRACER =
        # one attribute check per accepted vote
        self.tracer = NULL_TRACER
        self.cache = make_lru(config.cache_size)
        self._txs_available = threading.Event()
        self._notified_txs_available = False
        self._notify_available = False
        self.wal: WAL | None = None
        # see Mempool.wal_degraded: failed appends degrade loudly, once
        self.wal_degraded = False
        self.wal_errors = 0
        if wal_path:
            self.init_wal(wal_path)

    # -- WAL (reference InitWAL :100-123) --

    def init_wal(self, path: str) -> None:
        self.wal = WAL(path)

    def replay_wal(self) -> int:
        """Re-ingest votes from the WAL (crash recovery); returns count."""
        if self.wal is None:
            return 0
        n = 0
        for payload in self.wal.replay():
            try:
                vote = decode_tx_vote(payload)
            except Exception:
                continue
            try:
                self.check_tx(vote, write_wal=False)
                n += 1
            except (ErrTxInCache, ErrMempoolIsFull, ErrTxTooLarge):
                continue
        return n

    def close_wal(self) -> None:
        if self.wal is not None:
            self.wal.close()
            self.wal = None

    # -- introspection --

    def size(self) -> int:
        with self._mtx:
            return len(self._votes)

    def txs_bytes(self) -> int:
        with self._mtx:
            return self._votes_bytes

    def txs_available(self) -> threading.Event:
        self._notify_available = True
        return self._txs_available

    def enable_txs_available(self) -> None:
        self._notify_available = True

    def has(self, key: bytes) -> bool:
        with self._mtx:
            return key in self._votes

    def has_sender(self, key: bytes, sender_id: int) -> bool:
        with self._mtx:
            entry = self._votes.get(key)
            return entry is not None and sender_id in entry.senders

    def in_cache(self, key: bytes) -> bool:
        """Non-mutating dedup-cache membership: True means a check_tx for
        this key would be rejected with ErrTxInCache RIGHT NOW. The gossip
        receive path uses this to skip the raise-and-catch round trip for
        re-deliveries of already-committed votes (~2 extra full check_tx
        exceptions per vote per node at bench rates, r5 profile)."""
        return key in self.cache

    def has_sender_many(self, keys: list[bytes], sender_id: int) -> list[bool]:
        """has_sender for a whole gossip-walk batch under ONE lock hold
        (the per-peer broadcast walk paid a lock acquisition per vote per
        peer — r5 instrumented profile)."""
        with self._mtx:
            votes = self._votes
            out = []
            for k in keys:
                entry = votes.get(k)
                out.append(entry is not None and sender_id in entry.senders)
            return out

    # add_sender return codes (truthiness preserved for old callers:
    # 0 is still "fall back to check_tx")
    SENDER_GONE = 0  # pool no longer holds the vote
    SENDER_ADDED = 1  # new sender recorded
    SENDER_REPEAT = 2  # this peer ALREADY sent this signature (replay)

    def add_sender(self, key: bytes, sender_id: int) -> int:
        """Record that a peer holds this vote without re-ingesting it (the
        reactor's wire-level dup fast path). Returns SENDER_GONE when the
        pool no longer holds the vote — the caller must fall back to a
        real check_tx so pool-level re-accept policy stays authoritative.
        SENDER_REPEAT distinguishes the same peer re-sending an identical
        signature (replay accounting, health/byzantine.py) from a first
        delivery by an additional peer (honest gossip redundancy)."""
        with self._mtx:
            entry = self._votes.get(key)
            if entry is None:
                return self.SENDER_GONE
            if sender_id in entry.senders:
                return self.SENDER_REPEAT
            entry.senders.add(sender_id)
            return self.SENDER_ADDED

    def origins_of(self, keys: list[bytes]) -> list[int]:
        """Ingest origin (pool sender id) for each key, one lock hold;
        UNKNOWN_PEER_ID for keys already removed or locally ingested.
        The engine calls this for the invalid slice of a verify batch
        just before removing it, while still holding its own lock — so
        the entries are guaranteed present and attribution is exact."""
        with self._mtx:
            votes = self._votes
            out = []
            for k in keys:
                entry = votes.get(k)
                out.append(UNKNOWN_PEER_ID if entry is None else entry.origin)
            return out

    def _lane_quiet(self, vote: TxVote) -> int:
        """lane_of_vote with the hook-fault demotion applied (any error,
        or no hook, means bulk)."""
        if self.lane_of_vote is None:
            return -1
        try:
            return self.lane_of_vote(vote)
        except Exception:
            return -1

    def _evict_bulk_locked(self) -> bool:
        """Evict the OLDEST bulk-lane vote to make room for a priority
        vote (call under _mtx, pool full). Bulk occupancy must never
        block priority ingest: under overload the vote pool fills with
        bulk votes, and a bounced priority vote is a quorum signature
        lost — the sign walk has already moved past the tx. The evicted
        vote leaves the dedup cache too, so peer regossip re-delivers it
        once the pool drains (same retryability as a full-pool bounce)."""
        for k, e in self._votes.items():
            if self._lane_quiet(e.vote) == LANE_PRIORITY:
                continue
            self._votes.pop(k)
            self._votes_bytes -= e.size
            self._index_discard(k, e)
            self.cache.remove(k)
            return True
        return False

    # -- ingest (reference CheckTx/CheckTxWithInfo :180-261) --

    def check_tx(
        self, vote: TxVote, tx_info: TxInfo | None = None, write_wal: bool = True
    ) -> None:
        """Raises on rejection; returns None when the vote entered the pool."""
        tx_info = tx_info or TxInfo(UNKNOWN_PEER_ID)
        encoded = encode_tx_vote(vote)
        with self._mtx:
            self._ingest_locked(vote, encoded, vote_key(vote), tx_info, write_wal)
            self._notify_txs_available()

    def check_tx_many(
        self,
        votes: list[TxVote],
        tx_info: TxInfo | None = None,
        write_wal: bool = True,
    ) -> list[Exception | None]:
        """Frame-batched ingest: per-vote acceptance decisions identical
        to check_tx (same order, same errors — returned, not raised),
        with bounded lock holds (64-vote groups) and one waiter wakeup
        per group. Encode/hash for cache-miss votes runs inside the lock
        group — in the gossip path those caches are always primed at
        decode, so the in-lock work is dict stores and accounting; only
        locally constructed votes pay an in-lock encode (~1 us each,
        r5 microbench: the out-of-lock prepped-tuple design cost more in
        packaging than it saved in lock width)."""
        tx_info = tx_info or TxInfo(UNKNOWN_PEER_ID)
        out: list[Exception | None] = [None] * len(votes)
        # Inlined non-raising twin of _ingest_locked (keep the two in
        # sync): the wrapper-per-vote form — prepped tuples, try/except,
        # enumerate — measured 5.7 us/vote against the core's 4.4
        # (r5 microbench), i.e. more than half the ingest cost was
        # packaging. Error objects are built only on actual rejection.
        sid = tx_info.sender_id
        cfg = self.config
        max_size = cfg.max_msg_bytes - _MSG_OVERHEAD
        cache_push = self.cache.push
        votes_d = self._votes
        log_append = self._log_append_quiet  # one _log_notify per group
        lane_of = self.lane_of_vote
        prio_append = self._prio_log.append
        wal = self.wal if write_wal and not self.wal_degraded else None
        oset = object.__setattr__
        new = _PoolVote.__new__
        # bounded lock holds: a whole gossip frame under one lock starved
        # the drain/purge/inject paths for milliseconds (r5 instrumented
        # profile) — 64 votes ≈ a few hundred µs, keeping the pool fair
        for base in range(0, len(votes), 64):
            accepted = False
            with self._mtx:
                for i in range(base, min(base + 64, len(votes))):
                    vote = votes[i]
                    encoded = vote._wire_cache
                    if encoded is None:
                        encoded = encode_tx_vote(vote)
                    vote_size = len(encoded)
                    lane = -1
                    if lane_of is not None:
                        try:
                            lane = lane_of(vote)
                        except Exception:
                            lane = -1
                    while (
                        len(votes_d) >= cfg.size
                        or vote_size + self._votes_bytes > cfg.max_txs_bytes
                    ):
                        if lane != LANE_PRIORITY or not self._evict_bulk_locked():
                            break
                    if (
                        len(votes_d) >= cfg.size
                        or vote_size + self._votes_bytes > cfg.max_txs_bytes
                    ):
                        out[i] = ErrMempoolIsFull(
                            len(votes_d), cfg.size,
                            self._votes_bytes, cfg.max_txs_bytes,
                        )
                        continue
                    if vote_size > max_size:
                        out[i] = ErrTxTooLarge(max_size, vote_size)
                        continue
                    key = vote._vk_cache
                    if key is None:
                        key = vote.vote_key()
                    if not cache_push(key):
                        entry = votes_d.get(key)
                        if entry is not None:
                            entry.senders.add(sid)
                        out[i] = ErrTxInCache()
                        continue
                    if wal is not None:
                        try:
                            wal.write(encoded)  # txlint: allow(lock-blocking) -- WAL append order must match ingest-log order; buffered write, fsync only if sync_on_write
                        except (OSError, FailpointError):
                            self.wal_degraded = True
                            self.wal_errors += 1
                            wal = None
                    seg = vote._seg_cache
                    if seg is None:
                        seg = amino.length_prefixed(encoded)
                        oset(vote, "_seg_cache", seg)
                    entry = new(_PoolVote)
                    entry.height = self.height
                    entry.vote = vote
                    entry.senders = {sid}
                    entry.size = vote_size
                    entry.seg = seg
                    entry.lane = lane
                    entry.origin = sid
                    votes_d[key] = entry
                    by_tx = self._by_tx.get(vote.tx_hash)
                    if by_tx is None:
                        by_tx = self._by_tx[vote.tx_hash] = {}
                    by_tx[key] = None
                    log_append(key)
                    if lane == LANE_PRIORITY:
                        prio_append(key)
                    self._votes_bytes += vote_size
                    accepted = True
                    tr = self.tracer
                    if tr.active and tr.sampled(vote.tx_hash):
                        t = monotonic()
                        tr.span(vote.tx_hash, SPAN_VOTE_INGEST, t, t)
                if accepted:  # an all-dup group must not wake consumers
                    self._log_notify()
                    self._notify_txs_available()
        return out

    def _ingest_locked(
        self,
        vote: TxVote,
        encoded: bytes,
        key: bytes,
        tx_info: TxInfo,
        write_wal: bool,
    ) -> None:
        """One vote's acceptance decision + insertion (under self._mtx);
        availability notification is the caller's (so frames notify once)."""
        vote_size = len(encoded)
        lane = self._lane_quiet(vote)
        while (
            len(self._votes) >= self.config.size
            or vote_size + self._votes_bytes > self.config.max_txs_bytes
        ):
            if lane != LANE_PRIORITY or not self._evict_bulk_locked():
                break
        if (
            len(self._votes) >= self.config.size
            or vote_size + self._votes_bytes > self.config.max_txs_bytes
        ):
            raise ErrMempoolIsFull(
                len(self._votes),
                self.config.size,
                self._votes_bytes,
                self.config.max_txs_bytes,
            )
        max_size = self.config.max_msg_bytes - _MSG_OVERHEAD
        if vote_size > max_size:
            raise ErrTxTooLarge(max_size, vote_size)
        if not self.cache.push(key):
            entry = self._votes.get(key)
            if entry is not None:
                entry.senders.add(tx_info.sender_id)
            raise ErrTxInCache()
        if self.wal is not None and write_wal and not self.wal_degraded:
            try:
                self.wal.write(encoded)  # txlint: allow(lock-blocking) -- WAL append order must match ingest-log order; buffered write, fsync only if sync_on_write
            except (OSError, FailpointError):
                self.wal_degraded = True
                self.wal_errors += 1
        seg = vote._seg_cache
        if seg is None:
            seg = amino.length_prefixed(encoded)
            object.__setattr__(vote, "_seg_cache", seg)
        entry = _PoolVote(
            self.height, vote, {tx_info.sender_id}, vote_size, seg=seg,
            lane=lane, origin=tx_info.sender_id,
        )
        self._votes[key] = entry
        by_tx = self._by_tx.get(vote.tx_hash)
        if by_tx is None:
            by_tx = self._by_tx[vote.tx_hash] = {}
        by_tx[key] = None
        self._log_append(key)
        if lane == LANE_PRIORITY:
            self._prio_log.append(key)
        self._votes_bytes += vote_size
        tr = self.tracer
        if tr.active and tr.sampled(vote.tx_hash):
            t = monotonic()
            tr.span(vote.tx_hash, SPAN_VOTE_INGEST, t, t)

    def _notify_txs_available(self) -> None:
        if self._notify_available and not self._notified_txs_available:
            self._notified_txs_available = True
            self._txs_available.set()

    # -- consumption --

    def reap_max_txs(self, max_: int) -> list[TxVote]:
        with self._mtx:
            if max_ < 0:
                max_ = len(self._votes)
            return [e.vote for e in list(self._votes.values())[:max_]]

    def drain_batch(self, max_: int, skip: set[bytes] | None = None) -> list[tuple[bytes, TxVote]]:
        """Snapshot up to max_ (key, vote) pairs in order, skipping keys."""
        out = []
        with self._mtx:
            for k, e in self._votes.items():
                if skip is not None and k in skip:
                    continue
                out.append((k, e.vote))
                if len(out) >= max_:
                    break
        return out

    def entries(self, after: int = 0, limit: int = -1) -> list[tuple[bytes, TxVote]]:
        """Snapshot of (key, vote) pairs in insertion order (gossip walk)."""
        with self._mtx:
            items = [(k, e.vote) for k, e in self._votes.items()]
        if limit >= 0:
            return items[after : after + limit]
        return items[after:]

    def entries_from(
        self, cursor: int, limit: int = 256
    ) -> tuple[list[tuple[bytes, TxVote, int, bytes]], int]:
        """Stable-cursor walk of live votes: (key, vote, height, wire seg)
        tuples; see IngestLogPool._entries_from for the cursor contract."""
        raw, pos = self._entries_from(cursor, limit)
        return [(k, e.vote, e.height, e.seg) for k, e in raw], pos

    def prio_seq(self) -> int:
        """Monotonic priority-ingest counter (seq()'s twin for the
        priority log): prio_seq - cursor over-counts only by removed-
        not-yet-walked entries, the same safe pending estimate the main
        log's seq gives the engine's coalescer."""
        with self._mtx:
            return self._prio_log_base + len(self._prio_log)

    def bulk_entries_from(
        self, cursor: int, limit: int = 256
    ) -> tuple[list[tuple[bytes, TxVote, int, bytes]], int]:
        """entries_from over bulk-lane votes only: the main-log walk,
        skipping entries whose INGEST-time lane was priority — those are
        the priority log's to deliver (priority_entries_from), so the
        two walks form an exact partition of the pool and the engine's
        lane-split drain visits every vote exactly once. The cursor
        still advances over skipped/dead entries (stable-cursor
        contract, IngestLogPool)."""
        out: list[tuple[bytes, TxVote, int, bytes]] = []
        with self._mtx:
            pos = max(cursor, self._log_base)
            while pos - self._log_base < len(self._log) and len(out) < limit:
                key = self._log[pos - self._log_base]
                e = self._votes.get(key)
                if e is not None and e.lane != LANE_PRIORITY:
                    out.append((key, e.vote, e.height, e.seg))
                pos += 1
        return out, pos

    def priority_entries_from(
        self, cursor: int, limit: int = 256
    ) -> tuple[list[tuple[bytes, TxVote, int, bytes]], int]:
        """entries_from over priority-lane votes only: same tuple shape
        and cursor contract, walking the priority ingest log — O(priority
        backlog), independent of how deep the bulk vote backlog is. The
        verify engine drains this BEFORE the main log so priority txs
        reach quorum at a flat latency under overload."""
        out: list[tuple[bytes, TxVote, int, bytes]] = []
        with self._mtx:
            pos = max(cursor, self._prio_log_base)
            while pos - self._prio_log_base < len(self._prio_log) and len(out) < limit:
                key = self._prio_log[pos - self._prio_log_base]
                e = self._votes.get(key)
                if e is not None:
                    out.append((key, e.vote, e.height, e.seg))
                pos += 1
        return out, pos

    def _prio_compact(self) -> None:
        """_log_compact's twin for the priority log (call under _mtx)."""
        log = self._prio_log
        n = 0
        while n < len(log) and log[n] not in self._votes:
            n += 1
        if n >= COMPACT_THRESHOLD:
            del log[:n]
            self._prio_log_base += n

    def segs_for_tx(self, tx_hash: str, limit: int = 512) -> list[bytes]:
        """Wire segments of every live vote for one tx (the quorum-stall
        watchdog's targeted re-offer input, health/watchdog.py). Walks the
        per-tx index, so cost is O(votes for this tx) — a stalled node with
        a deep pool no longer pays an O(pool) scan per watchdog firing."""
        out: list[bytes] = []
        with self._mtx:
            by_tx = self._by_tx.get(tx_hash)
            if by_tx is None:
                return out
            for k in by_tx:
                entry = self._votes.get(k)
                if entry is not None:
                    out.append(entry.seg)
                    if len(out) >= limit:
                        break
        return out

    def _index_discard(self, k: bytes, entry: _PoolVote) -> None:
        """Drop one key from the per-tx index (under self._mtx)."""
        by_tx = self._by_tx.get(entry.vote.tx_hash)
        if by_tx is not None:
            by_tx.pop(k, None)
            if not by_tx:
                del self._by_tx[entry.vote.tx_hash]

    def remove(self, keys: list[bytes], cache_too: bool = False) -> None:
        """Remove votes by key (quorum purge path)."""
        with self._mtx:
            for k in keys:
                entry = self._votes.pop(k, None)
                if entry is not None:
                    self._votes_bytes -= entry.size
                    self._index_discard(k, entry)
                if cache_too:
                    self.cache.remove(k)
            self._log_compact()
            self._prio_compact()

    # -- update on commit (reference Update :329-359) --

    def update(self, height: int, votes: list[TxVote]) -> None:
        with self._mtx:
            self.height = height
            self._notified_txs_available = False
            self._txs_available.clear()
            for v in votes:
                k = vote_key(v)
                self.cache.push(k)  # committed votes stay cached
                entry = self._votes.pop(k, None)
                if entry is not None:
                    self._votes_bytes -= entry.size
                    self._index_discard(k, entry)
            self._log_compact()
            self._prio_compact()
            if len(self._votes) > 0:
                self._notify_txs_available()

    def flush(self) -> None:
        with self._mtx:
            self._votes.clear()
            self._by_tx.clear()
            self._log_base += len(self._log)
            self._log.clear()
            self._prio_log_base += len(self._prio_log)
            self._prio_log.clear()
            self._votes_bytes = 0
            self.cache.reset()
