"""Pending-state pools (reference mempool/ and txvotepool/).

- ``Mempool``: raw transactions awaiting block inclusion, plus keyed
  ``get_tx`` lookup used by the fast-path commit; a second instance serves
  as the **commitpool** holding fast-committed txs for block Vtxs
  (reference node/node.go:627-633).
- ``TxVotePool``: pending TxVotes with signature-keyed dedup, caps and WAL.

Both keep the reference's observable semantics (ordering, caps, cache,
availability signaling) without the CList idiom — an insertion-ordered
dict + condition variables serve the same contract for host-side code,
while the hot consumption path drains whole batches for the device kernel.
"""

from .mempool import ErrMempoolIsFull, ErrTxInCache, ErrTxTooLarge, Mempool, TxInfo
from .txvotepool import TxVotePool, UNKNOWN_PEER_ID

__all__ = [
    "ErrMempoolIsFull",
    "ErrTxInCache",
    "ErrTxTooLarge",
    "Mempool",
    "TxInfo",
    "TxVotePool",
    "UNKNOWN_PEER_ID",
]
