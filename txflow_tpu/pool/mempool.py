"""Mempool: ordered pool of raw txs (reference mempool/clist_mempool.go).

Forked-mempool behaviors preserved:
- ABCI CheckTx gate on ingest (app connection serialized by the proxy);
- sha256 LRU dedup cache, size/bytes caps, peer-sender tracking;
- ``get_tx(tx_key)`` lookup by sha256 — the fork's one addition
  (clist_mempool.go:171-177), used by TxFlow on quorum;
- reap by bytes/gas or by count; ``update`` on commit removes txs,
  with valid-but-uncommitted txs kept and recheck optional;
- TxsAvailable notification, once per height;
- optional WAL of accepted txs.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field

from ..crypto.hash import sha256
from ..trace.tracer import NULL_TRACER, SPAN_TX_INGEST
from ..utils.cache import make_lru
from ..utils.clock import monotonic
from ..utils.config import MempoolConfig
from ..utils.failpoints import FailpointError
from ..utils.wal import WAL
from .base import COMPACT_THRESHOLD, IngestLogPool

# mempool lanes (admission subsystem, admission/): priority txs keep
# committing at flat p50 under overload while bulk traffic sheds at the
# edges. Constants live HERE so admission can import them without the
# pool ever importing admission.
LANE_PRIORITY = 0
LANE_BULK = 1


class ErrTxInCache(Exception):
    pass


@dataclass
class ErrMempoolIsFull(Exception):
    num_txs: int
    max_txs: int
    txs_bytes: int
    max_txs_bytes: int

    def __str__(self):
        return (
            f"mempool is full: number of txs {self.num_txs} (max: {self.max_txs}), "
            f"total txs bytes {self.txs_bytes} (max: {self.max_txs_bytes})"
        )


@dataclass
class ErrTxTooLarge(Exception):
    max_size: int
    tx_size: int

    def __str__(self):
        return f"Tx too large. Max size is {self.max_size}, but got {self.tx_size}"


@dataclass
class TxInfo:
    sender_id: int = 0


@dataclass(slots=True)
class _MempoolTx:
    height: int
    gas_wanted: int
    tx: bytes
    senders: set[int] = field(default_factory=set)
    fast_path: bool = True  # app CheckTx verdict (ResponseCheckTx.fast_path)
    lane: int = LANE_BULK  # admission lane (classifier verdict at insert)


class Mempool(IngestLogPool):
    def __init__(
        self,
        config: MempoolConfig,
        proxy_app_conn=None,
        height: int = 0,
        pre_check=None,
        post_check=None,
        wal_path: str = "",
    ):
        super().__init__()  # _mtx/_cond/_seq + compacted ingest log
        self.config = config
        self.proxy_app = proxy_app_conn
        self.height = height
        self.pre_check = pre_check
        self.post_check = post_check
        self._txs: dict[bytes, _MempoolTx] = self._items  # tx_key -> entry
        self._txs_bytes = 0
        self.cache = make_lru(config.cache_size)
        # admission lanes: lane_of is the classifier hook (tx -> lane,
        # set by the node's AdmissionController; None = everything bulk).
        # The priority lane keeps its OWN compacted ingest log so the
        # sign/gossip walkers can serve priority txs first without
        # scanning past an arbitrarily deep bulk backlog.
        self.lane_of = None
        self._prio_log: list[bytes] = []
        self._prio_log_base = 0  # absolute position of _prio_log[0]
        self._lane_counts = [0, 0]  # live entries per lane (PRIORITY, BULK)
        # per-tx tracing (trace/tracer.py): the insert is where a tx's
        # e2e clock starts — wired by the node; NULL_TRACER = one
        # attribute check per insert
        self.tracer = NULL_TRACER
        self._txs_available = threading.Event()
        self._notified_txs_available = False
        self._notify_available = False
        self.wal: WAL | None = WAL(wal_path) if wal_path else None
        # disk-full/EIO degradation: a failed WAL append flags the pool
        # degraded (health "storage" section; admission sheds) and stops
        # further appends — ingest itself keeps working, crash-replay
        # durability is what was lost, and that must be LOUD, not fatal
        self.wal_degraded = False
        self.wal_errors = 0

    # -- introspection --

    def size(self) -> int:
        with self._mtx:
            return len(self._txs)

    def txs_bytes(self) -> int:
        with self._mtx:
            return self._txs_bytes

    def is_empty(self) -> bool:
        return self.size() == 0

    def txs_available(self) -> threading.Event:
        self._notify_available = True
        return self._txs_available

    def enable_txs_available(self) -> None:
        self._notify_available = True

    # -- ingest (reference CheckTx/CheckTxWithInfo :220-303) --

    def check_tx(
        self,
        tx: bytes,
        tx_info: TxInfo | None = None,
        write_wal: bool = True,
        key: bytes | None = None,
    ) -> None:
        """Raises on rejection; returns None when the tx entered the pool.

        key: sha256(tx) when the caller already has it (the commit path
        always does — vs.tx_key IS the mempool key), skipping a per-push
        hash (r4 profile).

        A socket-backed app conn is the exception path: its CheckTx is a
        round trip, and holding the pool lock across it stalled every
        reader (reap/drain/update/size) behind the socket — the
        lock-discipline finding this split fixes. The round trip runs
        UNLOCKED between an admission phase (caps + dedup-cache push,
        which reserves the key) and an insert phase that re-checks caps.
        A concurrent dup during the app call sees the cache reservation
        and gets ErrTxInCache, same verdict as the serialized path."""
        tx_info = tx_info or TxInfo()
        app = self.proxy_app
        if app is None or getattr(app, "is_local", False):
            with self._mtx:
                self._check_tx_locked(tx, tx_info, write_wal, key)
            return
        if key is None:
            key = sha256(tx)
        with self._mtx:
            self._admit_locked(tx, tx_info, key)
        try:
            res = app.check_tx_sync(tx)
        except BaseException:
            self.cache.remove(key)  # allow a retry after a conn failure
            raise
        if not res.is_ok:
            self.cache.remove(key)
            raise ValueError(f"rejected by app CheckTx (code {res.code}): {res.log}")
        with self._mtx:
            self._insert_checked_locked(tx, tx_info, write_wal, key, res)

    def check_tx_many(
        self,
        txs: list[bytes],
        tx_info: TxInfo | None = None,
        write_wal: bool = True,
    ) -> list[Exception | None]:
        """Batched ingest: same per-tx decisions/order as check_tx, errors
        returned instead of raised, bounded lock holds (64-tx groups, the
        txvotepool.check_tx_many pattern) so drains stay fair. The bench's
        seeding loop paid a lock acquire + notify per tx on the main
        thread (r5 instrumented profile: 32768 calls).

        Only a LOCAL (in-process) app conn may sit inside the lock
        groups — its CheckTx costs microseconds. Over a socket conn each
        CheckTx is a round trip, so 64 of them under one pool-lock hold
        would starve reap/drain/update for tens of ms (r5 review): that
        case falls back to the per-tx path, which releases the lock
        between app calls."""
        tx_info = tx_info or TxInfo()
        out: list[Exception | None] = [None] * len(txs)
        if self.proxy_app is not None and not getattr(
            self.proxy_app, "is_local", False
        ):
            for i, tx in enumerate(txs):
                try:
                    self.check_tx(tx, tx_info, write_wal)
                except Exception as e:
                    out[i] = e
            return out
        for base in range(0, len(txs), 64):
            accepted = False
            with self._mtx:
                for i, tx in enumerate(txs[base : base + 64], base):
                    try:
                        self._check_tx_locked(
                            tx, tx_info, write_wal, None, notify=False
                        )
                        accepted = True
                    except Exception as e:
                        out[i] = e
                # one waiter wakeup per lock group, not per tx (the
                # votepool batch path's pattern; a notify_all per item
                # measured ~1/3 of ingest cost, r5 microbench) — and only
                # when the group actually accepted something (an all-dup
                # group on an empty pool must not wake the proposer)
                if accepted:
                    self._log_notify()
                    self._notify_txs_available()
        return out

    def _admit_locked(self, tx: bytes, tx_info: TxInfo, key: bytes) -> None:
        """Admission phase: caps, dedup-cache reservation, pre_check.
        Raises on rejection; on success the key is RESERVED in the cache
        (dups now answer ErrTxInCache) and the caller owes either an
        insert or a cache.remove rollback."""
        if (
            len(self._txs) >= self.config.size
            or len(tx) + self._txs_bytes > self.config.max_txs_bytes
        ):
            raise ErrMempoolIsFull(
                len(self._txs), self.config.size, self._txs_bytes, self.config.max_txs_bytes
            )
        if not self.cache.push(key):
            entry = self._txs.get(key)
            if entry is not None:
                entry.senders.add(tx_info.sender_id)
            raise ErrTxInCache()
        if self.pre_check is not None:
            err = self.pre_check(tx)
            if err is not None:
                self.cache.remove(key)
                raise ValueError(f"rejected by pre_check: {err}")

    def _insert_checked_locked(
        self,
        tx: bytes,
        tx_info: TxInfo,
        write_wal: bool,
        key: bytes,
        res,
        notify: bool = True,
    ) -> None:
        """Insert phase: post_check, WAL, pool entry, notify. res is the
        app CheckTx response (None = no app). Re-checks caps — the
        unlocked app round trip may have let the pool fill."""
        if (
            len(self._txs) >= self.config.size
            or len(tx) + self._txs_bytes > self.config.max_txs_bytes
        ):
            self.cache.remove(key)
            raise ErrMempoolIsFull(
                len(self._txs), self.config.size, self._txs_bytes, self.config.max_txs_bytes
            )
        if self.post_check is not None:
            err = self.post_check(tx)
            if err is not None:
                self.cache.remove(key)
                raise ValueError(f"rejected by post_check: {err}")
        if self.wal is not None and write_wal and not self.wal_degraded:
            try:
                self.wal.write(tx)  # txlint: allow(lock-blocking) -- WAL append order must match insertion order; buffered write, fsync only if sync_on_write
            except (OSError, FailpointError):
                self.wal_degraded = True
                self.wal_errors += 1
        gas = res.gas_wanted if res is not None else 0
        fast_path = getattr(res, "fast_path", True) if res is not None else True
        lane = LANE_BULK
        if self.lane_of is not None:
            try:
                lane = self.lane_of(tx)
            except Exception:
                lane = LANE_BULK  # a hostile tx must not error the insert
            if lane != LANE_PRIORITY:
                lane = LANE_BULK
        entry = _MempoolTx(
            self.height, gas, tx, {tx_info.sender_id}, fast_path, lane
        )
        self._txs[key] = entry
        self._lane_counts[lane] += 1
        if lane == LANE_PRIORITY:
            self._prio_log.append(key)
        if notify:
            self._log_append(key)
        else:
            self._log_append_quiet(key)  # caller notifies per group
        self._txs_bytes += len(tx)
        tr = self.tracer
        if tr.active and tr.sampled_key(key):
            # anchor the e2e span at first local sight of the tx bytes;
            # the ingest marker makes the insert visible on the timeline
            t = monotonic()
            tx_hash = key.hex().upper()
            tr.anchor(tx_hash, t)
            tr.span(tx_hash, SPAN_TX_INGEST, t, t)
        if notify:
            self._notify_txs_available()

    def _check_tx_locked(
        self,
        tx: bytes,
        tx_info: TxInfo,
        write_wal: bool = True,
        key: bytes | None = None,
        notify: bool = True,
    ) -> None:
        """Single-lock-hold ingest: only valid when the app conn is local
        (in-process) or absent — check_tx/check_tx_many gate on is_local
        before entering this under the pool lock."""
        if key is None:
            key = sha256(tx)
        self._admit_locked(tx, tx_info, key)
        res = None
        if self.proxy_app is not None:
            try:
                res = self.proxy_app.check_tx_sync(tx)  # txlint: allow(lock-blocking) -- local in-process app only (is_local gated): microseconds, no socket
            except BaseException:
                self.cache.remove(key)
                raise
            if not res.is_ok:
                self.cache.remove(key)
                raise ValueError(f"rejected by app CheckTx (code {res.code}): {res.log}")
        self._insert_checked_locked(tx, tx_info, write_wal, key, res, notify)

    def _notify_txs_available(self) -> None:
        if self._notify_available and not self._notified_txs_available:
            self._notified_txs_available = True
            self._txs_available.set()

    # -- lookup (the fork's GetTx, clist_mempool.go:171-177) --

    def get_tx(self, tx_key: bytes) -> bytes | None:
        """Lock-free: the pool is content-addressed (key = sha256(tx)), so
        a key can only ever map to ONE byte string — a racing insert or
        purge makes this read equivalent to one taken a moment earlier or
        later, never a wrong value. dict.get is GIL-atomic; the commit
        path calls this per decision (r5 profile: the lock acquisition,
        contended by the ingest storm, cost more than the lookup)."""
        entry = self._txs.get(tx_key)
        return entry.tx if entry is not None else None

    def fast_path_of(self, tx_key: bytes) -> bool | None:
        """The app's CheckTx eligibility verdict for a pooled tx (None =
        not in the pool). Lock-free like get_tx: content-addressed, and
        the flag is immutable per entry."""
        entry = self._txs.get(tx_key)
        return entry.fast_path if entry is not None else None

    def has_sender(self, tx_key: bytes, sender_id: int) -> bool:
        with self._mtx:
            entry = self._txs.get(tx_key)
            return entry is not None and sender_id in entry.senders

    def lane_of_key(self, tx_key: bytes) -> int:
        """Admission lane of a pooled tx (LANE_BULK when unknown/gone).
        Lock-free like get_tx: content-addressed, and the lane verdict is
        immutable per entry. Votes inherit their tx's lane through this
        (TxVotePool.lane_of_vote), so the verify engine can drain
        priority-tx votes ahead of a deep bulk backlog."""
        entry = self._txs.get(tx_key)
        return entry.lane if entry is not None else LANE_BULK

    # -- reap (reference :306-355) --

    def _reap_order(self):
        """Iteration order for reaps (call under _mtx): priority-lane
        entries first, insertion order within each lane — block inclusion
        under overload must not strand the priority lane behind a full
        bulk backlog. The common no-priority case stays the plain dict
        walk (no copy)."""
        if self._lane_counts[LANE_PRIORITY] == 0:
            return self._txs.values()
        entries = list(self._txs.values())
        return [e for e in entries if e.lane == LANE_PRIORITY] + [
            e for e in entries if e.lane != LANE_PRIORITY
        ]

    def reap_max_bytes_max_gas(self, max_bytes: int, max_gas: int) -> list[bytes]:
        with self._mtx:
            out, total_bytes, total_gas = [], 0, 0
            for entry in self._reap_order():
                if max_bytes > -1 and total_bytes + len(entry.tx) > max_bytes:
                    break
                if max_gas > -1 and total_gas + entry.gas_wanted > max_gas:
                    break
                total_bytes += len(entry.tx)
                total_gas += entry.gas_wanted
                out.append(entry.tx)
            return out

    def reap_max_txs(self, n: int) -> list[bytes]:
        with self._mtx:
            if n < 0:
                n = len(self._txs)
            return [e.tx for e in list(self._reap_order())[:n]]

    def entries(self, after: int = 0, limit: int = -1) -> list[tuple[bytes, bytes]]:
        """Snapshot of (tx_key, tx) pairs in insertion order (gossip walk)."""
        with self._mtx:
            items = [(k, e.tx) for k, e in self._txs.items()]
        if limit >= 0:
            return items[after : after + limit]
        return items[after:]

    def entries_from(
        self, cursor: int, limit: int = 256
    ) -> tuple[list[tuple[bytes, bytes, int, bool, int]], int]:
        """Stable-cursor walk of live txs: (tx_key, tx, height,
        fast_path, lane) tuples; see IngestLogPool._entries_from for the
        cursor contract."""
        raw, pos = self._entries_from(cursor, limit)
        return [(k, e.tx, e.height, e.fast_path, e.lane) for k, e in raw], pos

    def priority_entries_from(
        self, cursor: int, limit: int = 256
    ) -> tuple[list[tuple[bytes, bytes, int, bool, int]], int]:
        """entries_from over the PRIORITY lane only: same tuple shape and
        cursor contract, but walking the priority ingest log — O(priority
        backlog), independent of how deep the bulk backlog is."""
        out: list[tuple[bytes, bytes, int, bool, int]] = []
        with self._mtx:
            pos = max(cursor, self._prio_log_base)
            while pos - self._prio_log_base < len(self._prio_log) and len(out) < limit:
                key = self._prio_log[pos - self._prio_log_base]
                e = self._txs.get(key)
                if e is not None and e.lane == LANE_PRIORITY:
                    out.append((key, e.tx, e.height, e.fast_path, e.lane))
                pos += 1
        return out, pos

    def lane_size(self, lane: int) -> int:
        """Live entries in one admission lane (O(1); admission headroom)."""
        with self._mtx:
            return self._lane_counts[lane]

    def _prio_compact(self) -> None:
        """_log_compact's twin for the priority log (call under _mtx)."""
        log = self._prio_log
        if len(log) - self._lane_counts[LANE_PRIORITY] < COMPACT_THRESHOLD:
            return
        n = 0
        while n < len(log) and log[n] not in self._txs:
            n += 1
        if n >= COMPACT_THRESHOLD:
            del log[:n]
            self._prio_log_base += n

    # -- update on commit (reference :358-422) --

    def lock(self) -> None:
        self._mtx.acquire()

    def unlock(self) -> None:
        self._mtx.release()

    def update(
        self,
        height: int,
        txs: list[bytes],
        deliver_results: list | None = None,
        pre_check=None,
        post_check=None,
        keys: list[bytes] | None = None,
    ) -> None:
        """Remove committed txs. Caller holds the lock (like the reference).

        keys: precomputed sha256 per tx (commit path: vs.tx_key)."""
        if pre_check is not None:
            self.pre_check = pre_check
        if post_check is not None:
            self.post_check = post_check
        self.height = height
        if self._notified_txs_available:
            # Event.clear is a lock+flag op — per-commit updates (fast
            # path, interval=1) shouldn't pay it when nothing is armed
            self._notified_txs_available = False
            self._txs_available.clear()
        for i, tx in enumerate(txs):
            key = keys[i] if keys is not None else sha256(tx)
            ok = deliver_results is None or (
                i < len(deliver_results) and deliver_results[i].is_ok
            )
            if ok:
                # valid committed txs stay cached so they cannot re-enter
                self.cache.push(key)
            else:
                # invalid txs may become valid later: allow resubmission
                self.cache.remove(key)
            entry = self._txs.pop(key, None)
            if entry is not None:
                self._txs_bytes -= len(entry.tx)
                self._lane_counts[entry.lane] -= 1
        self._log_compact()
        self._prio_compact()
        if len(self._txs) > 0:
            self._notify_txs_available()

    def push_committed_many(self, txs: list[bytes], keys: list[bytes]) -> None:
        """Commitpool bulk insert: caps + cache + insert under ONE lock,
        no app CheckTx (the txs are already executed — this pool only
        stages them for block inclusion, reference node/node.go commitpool
        wiring). Per-push check_tx lock churn on the committer thread
        measured ~10 µs/commit (r5 instrumented profile). Dups and a full
        pool drop silently, exactly like the per-push path's caller."""
        with self._mtx:
            for tx, key in zip(txs, keys):
                if (
                    len(self._txs) >= self.config.size
                    or len(tx) + self._txs_bytes > self.config.max_txs_bytes
                ):
                    continue  # this tx doesn't fit; a smaller one may
                if not self.cache.push(key):
                    continue
                self._txs[key] = _MempoolTx(self.height, 0, tx, {0})
                self._lane_counts[LANE_BULK] += 1
                self._log_append(key)
                self._txs_bytes += len(tx)
            if len(self._txs) > 0:
                self._notify_txs_available()

    def flush(self) -> None:
        with self._mtx:
            self._txs.clear()
            self._log_base += len(self._log)
            self._log.clear()
            self._prio_log_base += len(self._prio_log)
            self._prio_log.clear()
            self._lane_counts = [0, 0]
            self._txs_bytes = 0
            self.cache.reset()

    def init_wal(self, path: str) -> None:
        self.wal = WAL(path)

    def replay_wal(self) -> int:
        """Re-ingest txs from the WAL (crash recovery; reference mempool
        InitWAL semantics). Committed txs are filtered out afterwards by
        the caller (Handshaker/engine know what committed); returns count."""
        if self.wal is None:
            return 0
        n = 0
        for tx in self.wal.replay():
            try:
                self.check_tx(tx, write_wal=False)
                n += 1
            except Exception:
                continue  # dup/full/app-rejected: same as live ingest
        return n

    def close_wal(self) -> None:
        if self.wal is not None:
            self.wal.close()
            self.wal = None
