"""Shared pool plumbing: ingest log, seq wakeups, cursor walks.

Both pools (mempool, txvotepool) expose the same consumer protocol:

- ``seq()`` / ``wait_for_new(last_seq, timeout)`` — a monotonic ingest
  counter with condition-variable wakeups (the CList TxsWaitChan analog);
- ``entries_from(cursor, limit)`` — a stable-cursor walk over the ingest
  log (the CList pointer-walk analog, reference txvotepool/reactor.go:
  198-265): removals never shift a cursor.

The log is append-only but COMPACTED: once enough removed ("dead") keys
accumulate at its head, the dead prefix is dropped and a base offset
advances. Cursors are absolute positions, so a walker behind the new base
resumes at the base — it only skips entries that were already dead, which
the walk would have skipped anyway. This bounds memory where the naive
log grows forever at fast-path vote rates (the reference's CList frees
nodes once all walkers pass; this is the batched equivalent).
"""

from __future__ import annotations

import threading

from ..analysis.lockgraph import make_rlock
from ..analysis.racegraph import shared_field

# Compact when at least this many dead entries can be dropped at once.
COMPACT_THRESHOLD = 4096


class IngestLogPool:
    """Mixin-style base: subclasses store live items in ``self._items``
    (an insertion-ordered dict keyed by bytes) and call ``_log_append`` on
    accept / ``_log_compact`` after bulk removals, all under ``self._mtx``."""

    def __init__(self):
        self._mtx = make_rlock(f"pool.{type(self).__name__}._mtx")
        self._cond = threading.Condition(self._mtx)
        self._seq = 0
        self._log: list[bytes] = []
        self._log_base = 0  # absolute position of _log[0]
        self._items: dict[bytes, object] = {}
        # the ingest log + entry map, every reactor walk and engine drain
        # crosses threads through them
        self._sh_log = shared_field(f"pool.{type(self).__name__}.ingest_log")  # txlint: shared(self._mtx)

    # -- ingest bookkeeping (call under self._mtx) --

    def _log_append(self, key: bytes) -> None:
        self._sh_log.note_write()
        self._log.append(key)
        self._seq += 1
        self._cond.notify_all()

    def _log_append_quiet(self, key: bytes) -> None:
        """Append WITHOUT waking waiters — batch ingest paths append a
        whole lock-group and then call _log_notify once (a notify_all per
        vote measured as ~1/3 of the ingest cost, r5 microbench). Callers
        MUST follow with _log_notify before releasing the lock, or
        waiters sleep a full poll interval past available work."""
        self._sh_log.note_write()
        self._log.append(key)
        self._seq += 1

    def _log_notify(self) -> None:
        self._cond.notify_all()

    def _log_compact(self) -> None:
        """Drop the longest dead prefix once it crosses the threshold.

        Amortized O(1) per removal: the (O(prefix)) scan only runs when the
        log has at least COMPACT_THRESHOLD more entries than live items —
        scanning from 0 on EVERY bulk removal measured at 0.9 ms/call with
        a 16k-vote log (r3 step profile), serializing the commit path."""
        self._sh_log.note_write()
        log = self._log
        items = self._items
        if len(log) - len(items) < COMPACT_THRESHOLD:
            return
        n = 0
        while n < len(log) and log[n] not in items:
            n += 1
        if n >= COMPACT_THRESHOLD:
            del log[:n]
            self._log_base += n

    # -- consumer protocol --

    def seq(self) -> int:
        """Monotonic ingest counter; pairs with wait_for_new."""
        with self._mtx:
            return self._seq

    def wait_for_new(self, last_seq: int, timeout: float) -> int:
        """Block until an item arrives after last_seq (or timeout); returns
        the current seq. Fires on EVERY accepted item (consumers idle here
        instead of polling)."""
        with self._cond:
            if self._seq == last_seq:
                self._cond.wait(timeout)
            return self._seq

    def _entries_from(self, cursor: int, limit: int):
        """(list of (key, item), new_cursor): live entries only, in ingest
        order, from an absolute cursor. Call paths wrap this to shape the
        item tuple."""
        out = []
        with self._mtx:
            self._sh_log.note_read()
            pos = max(cursor, self._log_base)
            while pos - self._log_base < len(self._log) and len(out) < limit:
                key = self._log[pos - self._log_base]
                item = self._items.get(key)
                if item is not None:
                    out.append((key, item))
                pos += 1
        return out, pos
