"""txflow_tpu — a TPU-native aBFT consensus framework.

A brand-new framework with the capabilities of Fantom-foundation/go-txflow:
per-transaction commit via asynchronous validator vote aggregation (>2/3 of
stake), with a Tendermint-style block ticker as time-ordering fallback.

The hot path — ed25519 signature verification plus stake-weighted quorum
tally for thousands of in-flight transactions — runs as batched JAX/XLA
device kernels behind a ``VoteVerifier`` interface (see
``txflow_tpu.verifier``), instead of the reference's one-vote-at-a-time CPU
loop (reference: txflow/service.go:123-166 -> types/vote_set.go:81-131).

Layer map (mirrors SURVEY.md section 1):

- ``codec``     amino-compatible canonical binary encoding (types/codec.go)
- ``crypto``    host ed25519 + hashing/addresses (tendermint crypto)
- ``types``     TxVote/TxVoteSet/Commit/Block/ValidatorSet (types/)
- ``ops``       device kernels: GF(2^255-19) field, curve, batch verify, tally
- ``verifier``  VoteVerifier interface: scalar golden model + device impl
- ``parallel``  mesh/sharding of the vote-batch axis (shard_map/pjit)
- ``pool``      mempool + txvotepool (mempool/, txvotepool/)
- ``engine``    TxFlow aggregation service + TxExecutor (txflow/, txflowstate/)
- ``abci``      application interface + example apps (kvstore, counter)
- ``store``     tx/block/state stores over a KV DB (tx/, store/, state/store.go)
- ``state``     replicated chain state + BlockExecutor (state/)
- ``privval``   file-based signer with last-sign-state (privval/)
- ``consensus`` block-path BFT state machine + WAL replay (consensus/)
- ``net``       gossip transport: in-proc switch + reactors (p2p layer)
- ``node``      composition root (node/node.go)
- ``utils``     WAL, config, metrics, logging
"""

__version__ = "0.1.0"
