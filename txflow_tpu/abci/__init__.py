"""ABCI: the application interface (reference: tendermint abci, imported not forked).

The reference talks to the application over an ABCI client connection
(socket/grpc/local, node/node.go:576); test fixtures use
``proxy.NewLocalClientCreator(kvstore.NewApplication())``. Here the same
contract is an abstract ``Application`` plus a thread-safe ``AppConns``
proxy exposing the three logical connections (mempool / consensus / query)
with the same serialization guarantees a local ABCI client gives.
"""

from .types import (
    CodeTypeOK,
    RequestBeginBlock,
    RequestEndBlock,
    ResponseCheckTx,
    ResponseCommit,
    ResponseDeliverTx,
    ResponseEndBlock,
    ResponseInfo,
    ResponseQuery,
    ValidatorUpdate,
)
from .application import Application
from .proxy import AppConnConsensus, AppConnMempool, AppConnQuery, AppConns
from .kvstore import KVStoreApplication
from .counter import CounterApplication

__all__ = [
    "Application",
    "AppConns",
    "AppConnConsensus",
    "AppConnMempool",
    "AppConnQuery",
    "CodeTypeOK",
    "CounterApplication",
    "KVStoreApplication",
    "RequestBeginBlock",
    "RequestEndBlock",
    "ResponseCheckTx",
    "ResponseCommit",
    "ResponseDeliverTx",
    "ResponseEndBlock",
    "ResponseInfo",
    "ResponseQuery",
    "ValidatorUpdate",
]
