"""ABCI socket wire format (framework-native).

The reference drives apps across a process boundary through socket ABCI
connections created at node start (reference node/node.go:576,
createAndStartProxyAppConns) — apps in other processes/languages are the
point of ABCI. This rebuild's wire format keeps the same shape — varint
length-delimited frames, one request kind per method, a Flush fence —
but encodes with the framework's own deterministic codec primitives
instead of protobuf: frame = uvarint(len(payload)) ++ payload, payload =
kind byte ++ fields (length-prefixed where variable).

Messages (kind byte shared between request and response; EXCEPTION only
appears in responses):

| kind | request payload                      | response payload |
|------|--------------------------------------|------------------|
| 0    | — (EXCEPTION)                        | lp(error string) |
| 1    | ECHO: msg                            | msg              |
| 2    | FLUSH: empty                         | empty            |
| 3    | INFO: empty                          | lp(data) lp(version) uv(height) lp(app_hash) |
| 4    | INIT_CHAIN: uv(n) [lp(pub) uv(pow)]* | empty            |
| 5    | CHECK_TX: tx                         | uv(code) lp(data) lp(log) uv(gas) uv(block_only) |
| 6    | BEGIN_BLOCK: lp(hash) uv(height) lp(proposer) uv(n) [lp(addr) uv(h)]* | empty |
| 7    | DELIVER_TX: tx                       | uv(code) lp(data) lp(log) uv(n) [lp(k) lp(v)]* |
| 8    | END_BLOCK: uv(height)                | uv(n) [lp(pub) uv(pow)]* |
| 9    | COMMIT: empty                        | lp(app_hash)     |
| 10   | QUERY: lp(path) data                 | uv(code) lp(key) lp(value) lp(log) uv(height) |
"""

from __future__ import annotations

from ..codec.amino import length_prefixed, read_uvarint, uvarint
from .types import (
    RequestBeginBlock,
    RequestEndBlock,
    ResponseCheckTx,
    ResponseCommit,
    ResponseDeliverTx,
    ResponseEndBlock,
    ResponseInfo,
    ResponseQuery,
    ValidatorUpdate,
)

EXCEPTION = 0
ECHO = 1
FLUSH = 2
INFO = 3
INIT_CHAIN = 4
CHECK_TX = 5
BEGIN_BLOCK = 6
DELIVER_TX = 7
END_BLOCK = 8
COMMIT = 9
QUERY = 10

MAX_FRAME = 8 << 20  # an oversized frame is a protocol violation, not an OOM


def frame(payload: bytes) -> bytes:
    return uvarint(len(payload)) + payload


def read_frame(read_exact) -> bytes:
    """Read one frame via read_exact(n) -> bytes (raises on EOF)."""
    # uvarint length, byte at a time (length prefixes are tiny)
    shift = 0
    n = 0
    while True:
        b = read_exact(1)[0]
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 35:
            raise ValueError("frame length varint too long")
    if n > MAX_FRAME:
        raise ValueError(f"frame too large ({n} bytes)")
    return read_exact(n) if n else b""


def _lp_read(data: bytes, off: int) -> tuple[bytes, int]:
    n, off = read_uvarint(data, off)
    if off + n > len(data):
        raise ValueError("truncated length-prefixed field")
    return data[off : off + n], off + n


# -- requests --


def encode_request(kind: int, *, raw: bytes = b"", **kw) -> bytes:
    if kind in (ECHO, CHECK_TX, DELIVER_TX):
        return bytes([kind]) + raw
    if kind in (FLUSH, INFO, COMMIT):
        return bytes([kind])
    if kind == INIT_CHAIN:
        out = bytearray([kind])
        vals = kw["validators"]
        out += uvarint(len(vals))
        for v in vals:  # ValidatorUpdate-like or (pub, power) pair
            pub, power = (
                (v.pub_key, v.power) if hasattr(v, "pub_key") else v
            )
            out += length_prefixed(pub) + uvarint(power)
        return bytes(out)
    if kind == BEGIN_BLOCK:
        req: RequestBeginBlock = kw["req"]
        out = bytearray([kind])
        out += length_prefixed(req.hash)
        out += uvarint(req.height)
        out += length_prefixed(req.proposer_address)
        out += uvarint(len(req.byzantine_validators))
        for addr, h in req.byzantine_validators:
            out += length_prefixed(addr) + uvarint(h)
        return bytes(out)
    if kind == END_BLOCK:
        return bytes([kind]) + uvarint(kw["height"])
    if kind == QUERY:
        return bytes([kind]) + length_prefixed(kw["path"].encode()) + raw
    raise ValueError(f"unknown request kind {kind}")


def decode_request(payload: bytes):
    """-> (kind, dict of fields). Raises ValueError on malformed input
    (peer-facing decoder: total, never IndexError)."""
    if not payload:
        raise ValueError("empty request")
    kind, body = payload[0], payload[1:]
    if kind in (ECHO, CHECK_TX, DELIVER_TX):
        return kind, {"raw": body}
    if kind in (FLUSH, INFO, COMMIT):
        return kind, {}
    if kind == INIT_CHAIN:
        n, off = read_uvarint(body, 0)
        vals = []
        for _ in range(n):
            pub, off = _lp_read(body, off)
            power, off = read_uvarint(body, off)
            vals.append(ValidatorUpdate(pub, power))
        return kind, {"validators": vals}
    if kind == BEGIN_BLOCK:
        hash_, off = _lp_read(body, 0)
        height, off = read_uvarint(body, off)
        proposer, off = _lp_read(body, off)
        n, off = read_uvarint(body, off)
        byz = []
        for _ in range(n):
            addr, off = _lp_read(body, off)
            h, off = read_uvarint(body, off)
            byz.append((addr, h))
        return kind, {
            "req": RequestBeginBlock(
                hash=hash_, height=height, proposer_address=proposer,
                byzantine_validators=byz,
            )
        }
    if kind == END_BLOCK:
        height, _ = read_uvarint(body, 0)
        return kind, {"height": height}
    if kind == QUERY:
        path, off = _lp_read(body, 0)
        return kind, {"path": path.decode(), "raw": body[off:]}
    raise ValueError(f"unknown request kind {kind}")


# -- responses --


def encode_response(kind: int, res) -> bytes:
    if kind == EXCEPTION:
        return bytes([kind]) + length_prefixed(str(res).encode())
    if kind == ECHO:
        return bytes([kind]) + res
    if kind in (FLUSH, INIT_CHAIN, BEGIN_BLOCK):
        return bytes([kind])
    if kind == INFO:
        return (
            bytes([kind])
            + length_prefixed(res.data.encode())
            + length_prefixed(res.version.encode())
            + uvarint(res.last_block_height)
            + length_prefixed(res.last_block_app_hash)
        )
    if kind == CHECK_TX:
        return (
            bytes([kind])
            + uvarint(res.code)
            + length_prefixed(res.data or b"")
            + length_prefixed(res.log.encode())
            + uvarint(res.gas_wanted)
            + uvarint(0 if getattr(res, "fast_path", True) else 1)
        )
    if kind == DELIVER_TX:
        out = bytearray([kind])
        out += uvarint(res.code)
        out += length_prefixed(res.data or b"")
        out += length_prefixed(res.log.encode())
        tags = list(getattr(res, "tags", []) or [])
        out += uvarint(len(tags))
        for k, v in tags:
            out += length_prefixed(bytes(k)) + length_prefixed(bytes(v))
        return bytes(out)
    if kind == END_BLOCK:
        out = bytearray([kind])
        ups = res.validator_updates
        out += uvarint(len(ups))
        for u in ups:
            out += length_prefixed(u.pub_key) + uvarint(u.power)
        return bytes(out)
    if kind == COMMIT:
        return bytes([kind]) + length_prefixed(res.data or b"")
    if kind == QUERY:
        return (
            bytes([kind])
            + uvarint(res.code)
            + length_prefixed(res.key or b"")
            + length_prefixed(res.value or b"")
            + length_prefixed(res.log.encode())
            + uvarint(res.height)
        )
    raise ValueError(f"unknown response kind {kind}")


def decode_response(payload: bytes):
    """-> (kind, response object). EXCEPTION decodes to RuntimeError."""
    if not payload:
        raise ValueError("empty response")
    kind, body = payload[0], payload[1:]
    if kind == EXCEPTION:
        msg, _ = _lp_read(body, 0)
        return kind, RuntimeError(f"remote ABCI app: {msg.decode()}")
    if kind == ECHO:
        return kind, body
    if kind in (FLUSH, INIT_CHAIN, BEGIN_BLOCK):
        return kind, None
    if kind == INFO:
        data, off = _lp_read(body, 0)
        version, off = _lp_read(body, off)
        height, off = read_uvarint(body, off)
        app_hash, _ = _lp_read(body, off)
        return kind, ResponseInfo(
            data=data.decode(), version=version.decode(),
            last_block_height=height, last_block_app_hash=app_hash,
        )
    if kind == CHECK_TX:
        code, off = read_uvarint(body, 0)
        data, off = _lp_read(body, off)
        log, off = _lp_read(body, off)
        gas, off = read_uvarint(body, off)
        # block-only flag (0 = fast-path eligible); absent in frames from
        # older servers -> default eligible
        block_only = 0
        if off < len(body):
            block_only, _ = read_uvarint(body, off)
        return kind, ResponseCheckTx(
            code=code, data=data, log=log.decode(), gas_wanted=gas,
            fast_path=(block_only == 0),
        )
    if kind == DELIVER_TX:
        code, off = read_uvarint(body, 0)
        data, off = _lp_read(body, off)
        log, off = _lp_read(body, off)
        n, off = read_uvarint(body, off)
        tags = []
        for _ in range(n):
            k, off = _lp_read(body, off)
            v, off = _lp_read(body, off)
            tags.append((k, v))
        return kind, ResponseDeliverTx(
            code=code, data=data, log=log.decode(), tags=tags
        )
    if kind == END_BLOCK:
        n, off = read_uvarint(body, 0)
        ups = []
        for _ in range(n):
            pub, off = _lp_read(body, off)
            power, off = read_uvarint(body, off)
            ups.append(ValidatorUpdate(pub, power))
        return kind, ResponseEndBlock(validator_updates=ups)
    if kind == COMMIT:
        data, _ = _lp_read(body, 0)
        return kind, ResponseCommit(data=data)
    if kind == QUERY:
        code, off = read_uvarint(body, 0)
        key, off = _lp_read(body, off)
        value, off = _lp_read(body, off)
        log, off = _lp_read(body, off)
        height, _ = read_uvarint(body, off)
        return kind, ResponseQuery(
            code=code, key=key, value=value, log=log.decode(), height=height
        )
    raise ValueError(f"unknown response kind {kind}")
