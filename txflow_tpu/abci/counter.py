"""counter example app (reference test app: abci/example/counter).

With ``serial=True`` txs must be exactly the big-endian encoding of the
next integer — the reference pool tests use this to assert ordered reaping
(txvotepool/txvotepool_test.go:166).
"""

from __future__ import annotations

import struct

from .application import Application
from .types import ResponseCheckTx, ResponseCommit, ResponseDeliverTx


def _decode(tx: bytes) -> int:
    if len(tx) > 8:
        return -1
    return int.from_bytes(tx, "big")


class CounterApplication(Application):
    def __init__(self, serial: bool = False):
        self.serial = serial
        self.tx_count = 0
        self.check_count = 0

    def check_tx(self, tx: bytes) -> ResponseCheckTx:
        if self.serial:
            value = _decode(tx)
            if len(tx) > 8:
                return ResponseCheckTx(code=1, log=f"tx too large: {len(tx)} bytes")
            if value < self.tx_count:
                return ResponseCheckTx(
                    code=2,
                    log=f"invalid nonce: got {value}, expected >= {self.tx_count}",
                )
        self.check_count += 1
        return ResponseCheckTx()

    def deliver_tx(self, tx: bytes) -> ResponseDeliverTx:
        if self.serial:
            value = _decode(tx)
            if value != self.tx_count:
                return ResponseDeliverTx(
                    code=2,
                    log=f"invalid nonce: got {value}, expected {self.tx_count}",
                )
        self.tx_count += 1
        return ResponseDeliverTx()

    def commit(self) -> ResponseCommit:
        if self.tx_count == 0:
            return ResponseCommit()
        return ResponseCommit(data=struct.pack(">Q", self.tx_count))
